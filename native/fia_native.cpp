// Native data-path kernels for fia_tpu.
//
// The reference's data layer is numpy `loadtxt` + linear scans (its repo has
// no native code at all — SURVEY.md §2.4); at ML-20M-stress scale the host
// data path becomes the bottleneck ahead of the TPU, so the TSV rating
// parser and the CSR inverted-index builder are provided natively and
// exposed through ctypes (fia_tpu/data/native.py), with pure-numpy
// fallbacks when the shared library is absent.
//
// Build: make -C native   (produces libfia_native.so)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// Count data rows in a ratings TSV file: lines whose first non-blank
// character is a digit (headers/comments are not data — the parser
// skips them, and the two must agree). Returns -1 on IO error.
int64_t fia_count_rows(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    constexpr size_t BUF = 1 << 20;
    char* buf = static_cast<char*>(std::malloc(BUF));
    int64_t rows = 0;
    bool at_line_start = true;
    bool line_is_data = false;
    size_t got;
    while ((got = std::fread(buf, 1, BUF, f)) > 0) {
        for (size_t i = 0; i < got; ++i) {
            char c = buf[i];
            if (c == '\n') {
                if (line_is_data) ++rows;
                at_line_start = true;
                line_is_data = false;
            } else if (at_line_start && c != '\r' && c != ' ' && c != '\t') {
                line_is_data = (c >= '0' && c <= '9');
                at_line_start = false;
            }
        }
    }
    if (line_is_data) ++rows;
    std::free(buf);
    std::fclose(f);
    return rows;
}

// Parse up to max_rows "user \t item \t rating" lines into preallocated
// buffers. Returns the number of rows parsed, or -1 on IO error.
// Whitespace-tolerant; ratings may be integers or decimals.
int64_t fia_parse_tsv(const char* path, int64_t max_rows,
                      int32_t* users, int32_t* items, float* ratings) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    // Read whole file (rating files are <100 MB even at ML-20M scale).
    std::fseek(f, 0, SEEK_END);
    long size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    char* data = static_cast<char*>(std::malloc(size + 1));
    if (!data) { std::fclose(f); return -1; }
    size_t got = std::fread(data, 1, size, f);
    std::fclose(f);
    data[got] = '\0';

    const char* p = data;
    const char* end = data + got;
    int64_t n = 0;
    while (p < end && n < max_rows) {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n'))
            ++p;
        if (p >= end) break;
        // user — a line not starting with digits (header, comment) is
        // skipped, never emitted as a spurious (0, 0, 0.0) row
        int64_t u = 0;
        int u_digits = 0;
        while (p < end && *p >= '0' && *p <= '9') {
            u = u * 10 + (*p++ - '0');
            ++u_digits;
        }
        while (p < end && (*p == ' ' || *p == '\t')) ++p;
        // item
        int64_t it = 0;
        int i_digits = 0;
        while (p < end && *p >= '0' && *p <= '9') {
            it = it * 10 + (*p++ - '0');
            ++i_digits;
        }
        while (p < end && (*p == ' ' || *p == '\t')) ++p;
        // rating (int or decimal)
        double r = 0.0;
        int r_digits = 0;
        bool neg = false;
        if (p < end && (*p == '-' || *p == '+')) neg = (*p++ == '-');
        while (p < end && *p >= '0' && *p <= '9') {
            r = r * 10 + (*p++ - '0');
            ++r_digits;
        }
        if (p < end && *p == '.') {
            ++p;
            double scale = 0.1;
            while (p < end && *p >= '0' && *p <= '9') {
                r += (*p++ - '0') * scale;
                scale *= 0.1;
                ++r_digits;
            }
        }
        if (u_digits && i_digits && r_digits) {
            users[n] = static_cast<int32_t>(u);
            items[n] = static_cast<int32_t>(it);
            ratings[n] = static_cast<float>(neg ? -r : r);
            ++n;
        }
        while (p < end && *p != '\n') ++p;  // skip rest of line
    }
    std::free(data);
    return n;
}

// Build a CSR grouping of row positions by id (counting sort, stable).
// ids: (n,) int32 in [0, num_groups); indptr: (num_groups+1,) int64 out;
// indices: (n,) int64 out. Returns 0, or -1 if an id is out of range.
int32_t fia_build_csr(const int32_t* ids, int64_t n, int64_t num_groups,
                      int64_t* indptr, int64_t* indices) {
    std::memset(indptr, 0, sizeof(int64_t) * (num_groups + 1));
    for (int64_t i = 0; i < n; ++i) {
        int32_t g = ids[i];
        if (g < 0 || g >= num_groups) return -1;
        ++indptr[g + 1];
    }
    for (int64_t g = 0; g < num_groups; ++g) indptr[g + 1] += indptr[g];
    // stable fill using a moving cursor per group
    int64_t* cursor = static_cast<int64_t*>(
        std::malloc(sizeof(int64_t) * num_groups));
    std::memcpy(cursor, indptr, sizeof(int64_t) * num_groups);
    for (int64_t i = 0; i < n; ++i) {
        indices[cursor[ids[i]]++] = i;
    }
    std::free(cursor);
    return 0;
}

}  // extern "C"
