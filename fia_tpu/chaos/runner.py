"""The chaos engine: golden runs, armed runs, oracles, shrink, repro.

:class:`ChaosEngine` owns a root directory and a scenario registry.
For each scenario it computes one undisturbed *golden* run (cached —
compiled workload state lives on the scenario instance, so repeated
chaos runs pay XLA compiles once), then executes schedules against
fresh per-run workdirs with the fault plan armed, applies the oracle
battery, and on failure shrinks the schedule with ddmin and emits a
replayable JSON repro
(``python -m fia_tpu.cli.chaos --replay <repro.json>``).

Every run arms its plan with ``validate=True`` — a chaos schedule
naming an unregistered site is a bug in the schedule generator, not a
finding.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from fia_tpu import obs
from fia_tpu.chaos import oracles as ochk
from fia_tpu.chaos import schedule as sched
from fia_tpu.chaos.oracles import OracleFailure, RunRecord
from fia_tpu.chaos.scenarios import make_scenarios
from fia_tpu.chaos.shrink import ddmin
from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.utils import io

REPRO_MAGIC = "fia-chaos-repro-v1"


@dataclass
class ChaosReport:
    """One schedule's verdict (plus shrink artifacts on failure)."""

    schedule: sched.Schedule
    failures: list = field(default_factory=list)
    record: RunRecord | None = None
    shrunk: sched.Schedule | None = None
    repro_path: str | None = None

    @property
    def passed(self) -> bool:
        return not self.failures

    def to_dict(self) -> dict:
        return {
            "schedule": self.schedule.to_dict(),
            "passed": self.passed,
            "failures": [f.to_dict() for f in self.failures],
            "error": self.record.error if self.record else None,
            "events": list(self.record.events) if self.record else [],
            "shrunk": self.shrunk.to_dict() if self.shrunk else None,
            "repro_path": self.repro_path,
        }


class ChaosEngine:
    """Runs seeded schedules against scenarios and checks oracles."""

    def __init__(self, root: str, verbose: bool = False):
        self.root = root
        self.verbose = verbose
        self._classes = make_scenarios()
        self._scenarios: dict = {}  # name -> constructed instance
        self._goldens: dict = {}  # name -> golden outcome payload
        self._runs = 0
        os.makedirs(root, exist_ok=True)

    def _say(self, msg: str) -> None:
        if self.verbose:
            obs.diag("chaos", msg)

    def scenario(self, name: str):
        if name not in self._classes:
            raise ValueError(
                f"unknown scenario {name!r}; have {sorted(self._classes)}"
            )
        if name not in self._scenarios:
            self._scenarios[name] = self._classes[name]()
        return self._scenarios[name]

    def golden(self, name: str) -> dict:
        """The undisturbed run's outcome (computed once per scenario).

        The golden run executes with NO plan armed, in its own workdir;
        a failure here is a broken scenario, not a chaos finding, so it
        propagates.
        """
        if name not in self._goldens:
            scen = self.scenario(name)
            workdir = os.path.join(self.root, f"golden-{name}")
            self._say(f"golden run: {name}")
            events: list = []
            self._goldens[name] = scen.run(workdir, events)
        return self._goldens[name]

    def run_schedule(self, schedule: sched.Schedule
                     ) -> tuple[RunRecord, list]:
        """Execute one schedule; returns (record, oracle failures)."""
        scen = self.scenario(schedule.scenario)
        golden = self.golden(schedule.scenario)
        self._runs += 1
        workdir = os.path.join(
            self.root, f"run-{self._runs:04d}-{schedule.scenario}")
        events: list = []
        outcome = error = None
        with inject.active(*schedule.inject_faults(),
                           validate=True) as inj:
            try:
                inject.fire(sites.CHAOS_SCENARIO)
                outcome = scen.run(workdir, events)
            except Exception as e:
                error = {"kind": taxonomy.classify(e), "error": repr(e)}
        record = RunRecord(outcome=outcome, error=error, events=events,
                           report=inj.report(), workdir=workdir)
        failures = ochk.standard(golden, record, benign=schedule.benign)
        failures += scen.check(golden, record)
        self._say(
            f"{schedule.describe()} -> "
            + ("PASS" if not failures
               else f"FAIL ({', '.join(f.oracle for f in failures)})")
        )
        return record, failures

    def run(self, scenario_name: str, seed: int, n_faults: int,
            benign: bool = True, shrink: bool = True) -> ChaosReport:
        """Generate, run, and (on failure) shrink one seeded schedule."""
        scen = self.scenario(scenario_name)
        schedule = sched.generate(
            scenario_name, scen.domain(benign), seed, n_faults, benign)
        return self.run_report(schedule, shrink=shrink)

    def run_report(self, schedule: sched.Schedule,
                   shrink: bool = True) -> ChaosReport:
        record, failures = self.run_schedule(schedule)
        report = ChaosReport(schedule=schedule, failures=failures,
                             record=record)
        if failures and shrink and len(schedule.faults) > 1:
            report.shrunk = self.shrink(schedule, failures[0].oracle)
        elif failures and schedule.faults:
            report.shrunk = schedule
        if report.shrunk is not None:
            report.repro_path = self.write_repro(report)
        return report

    def shrink(self, schedule: sched.Schedule,
               target_oracle: str) -> sched.Schedule:
        """ddmin ``schedule`` down to a minimal plan still violating
        ``target_oracle`` (the first failure's stable id — shrinking
        against "any failure" can walk to an unrelated, weaker bug)."""
        self._say(f"shrinking against oracle {target_oracle!r} …")

        def still_fails(faults) -> bool:
            _, fls = self.run_schedule(schedule.with_faults(faults))
            return any(f.oracle == target_oracle for f in fls)

        minimal = ddmin(list(schedule.faults), still_fails)
        return schedule.with_faults(minimal)

    def write_repro(self, report: ChaosReport) -> str:
        """Publish the replayable repro JSON for a failed report."""
        shrunk = report.shrunk or report.schedule
        path = os.path.join(
            self.root,
            f"repro-{shrunk.scenario}-seed{shrunk.seed}.json")
        io.save_json_atomic(path, {
            "magic": REPRO_MAGIC,
            "schedule": shrunk.to_dict(),
            "original_schedule": report.schedule.to_dict(),
            "failures": [f.to_dict() for f in report.failures],
        }, indent=2)
        self._say(f"repro written: {path}")
        return path

    @staticmethod
    def load_repro(path: str) -> sched.Schedule:
        """The schedule inside a repro file (or a bare schedule JSON)."""
        import json

        with open(path) as f:
            d = json.load(f)
        if d.get("magic") == REPRO_MAGIC:
            d = d["schedule"]
        return sched.Schedule.from_dict(d)

    def replay(self, path: str) -> ChaosReport:
        """Re-run a repro file's schedule (no shrinking — it already is
        the minimal plan); the same failure must reproduce."""
        schedule = self.load_repro(path)
        record, failures = self.run_schedule(schedule)
        return ChaosReport(schedule=schedule, failures=failures,
                           record=record)
