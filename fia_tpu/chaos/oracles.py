"""End-to-end invariant oracles.

An oracle inspects one finished chaos run — its outcome payload, the
classified error that ended it (if any), the injector's fault
accounting, and the artifacts it left on disk — and returns
:class:`OracleFailure` records. The oracles encode the contracts the
reliability PRs promised:

- ``bit_identity`` — a run under a *benign* schedule (every fault has
  a bit-identity-preserving recovery) must produce outcomes
  bit-identical to the undisturbed golden run. Equality is on raw
  bytes + dtype + shape, not ``allclose``: the FIA fidelity story is
  "the fast path gives the same answer", and tolerance here would let
  silent-wrong-answer regressions hide inside it.
- ``classified_error`` — a run may *fail*, but only with an error the
  taxonomy classifies. An unclassified escape is a silent-wrong-answer
  hazard (nothing upstream knows how to recover from it).
- ``fault_accounting`` — armed ⇒ fired or reported: every scheduled
  fault either fired or the run ended early with a classified error
  (in which case unreached faults are expected). A complete run with
  unfired faults means the schedule did not test what it scripts.
- ``artifact_detectability`` — every artifact the run left under its
  original (non-quarantined) name either verifies or fails with a
  classified :class:`ArtifactIntegrityError`; nothing on disk can be
  parsed into garbage silently. Quarantined ``*.corrupt`` evidence is
  never re-verified (and never deleted — the run directory keeps it).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from fia_tpu.reliability.artifacts import (
    MANIFEST_SUFFIX,
    ArtifactIntegrityError,
    verify,
)


@dataclass
class OracleFailure:
    """One violated invariant: a stable oracle id plus evidence."""

    oracle: str
    detail: str

    def to_dict(self) -> dict:
        return {"oracle": self.oracle, "detail": self.detail}


@dataclass
class RunRecord:
    """What one scenario run produced (the oracles' input)."""

    outcome: dict | None  # name -> np.ndarray | str | int; None on error
    error: dict | None  # {"kind": taxonomy kind | None, "error": repr}
    events: list = field(default_factory=list)
    report: dict = field(default_factory=dict)  # Injector.report()
    workdir: str | None = None


def _value_diff(name: str, a, b) -> str | None:
    """A human-readable diff for one outcome entry, or None if
    bit-identical."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype != b.dtype:
            return f"{name}: dtype {a.dtype} != {b.dtype}"
        if a.shape != b.shape:
            return f"{name}: shape {a.shape} != {b.shape}"
        if a.tobytes() != b.tobytes():
            return f"{name}: bytes differ"
        return None
    if a != b:
        return f"{name}: {a!r} != {b!r}"
    return None


def compare_outcomes(golden: dict, got: dict) -> list[str]:
    """All bit-level differences between two outcome payloads."""
    diffs = []
    for name in sorted(set(golden) | set(got)):
        if name not in golden:
            diffs.append(f"{name}: unexpected (absent from golden)")
        elif name not in got:
            diffs.append(f"{name}: missing")
        else:
            d = _value_diff(name, golden[name], got[name])
            if d:
                diffs.append(d)
    return diffs


def bit_identity(golden: dict, record: RunRecord) -> list[OracleFailure]:
    if record.error is not None or record.outcome is None:
        return []  # a surfaced error is classified_error's business
    diffs = compare_outcomes(golden, record.outcome)
    if not diffs:
        return []
    head = "; ".join(diffs[:4]) + ("; …" if len(diffs) > 4 else "")
    return [OracleFailure(
        "bit_identity",
        f"{len(diffs)} outcome entr{'y' if len(diffs) == 1 else 'ies'} "
        f"differ from the golden run: {head}",
    )]


def classified_error(record: RunRecord) -> list[OracleFailure]:
    if record.error is None:
        return []
    if record.error.get("kind") is not None:
        return []
    return [OracleFailure(
        "classified_error",
        f"run died with an unclassified error: {record.error.get('error')}",
    )]


def fault_accounting(record: RunRecord) -> list[OracleFailure]:
    unfired = record.report.get("unfired", [])
    if not unfired or record.error is not None:
        return []
    desc = ", ".join(f"{s}@{at}:{k}" for s, at, k in unfired)
    return [OracleFailure(
        "fault_accounting",
        f"run completed but {len(unfired)} armed fault(s) never fired "
        f"({desc}) — the schedule's reachability assumptions are wrong",
    )]


def artifact_detectability(record: RunRecord) -> list[OracleFailure]:
    if not record.workdir or not os.path.isdir(record.workdir):
        return []
    failures = []
    for dirpath, _dirnames, filenames in os.walk(record.workdir):
        for name in filenames:
            if ".corrupt" in name or name.endswith(MANIFEST_SUFFIX):
                continue
            full = os.path.join(dirpath, name)
            if not os.path.exists(full + MANIFEST_SUFFIX):
                continue  # not published through the integrity layer
            try:
                verify(full)
            except ArtifactIntegrityError:
                pass  # detectable damage is the contract working
            except Exception as e:
                failures.append(OracleFailure(
                    "artifact_detectability",
                    f"{full}: verification crashed unclassified: {e!r}",
                ))
    return failures


def standard(golden: dict, record: RunRecord,
             benign: bool) -> list[OracleFailure]:
    """The oracle battery every scenario gets; ``bit_identity`` only
    applies to benign schedules (the full fault domain includes kinds
    whose recovery legitimately changes results — solver escalation,
    CPU rung — and kinds that kill the run)."""
    failures = []
    if benign:
        failures += bit_identity(golden, record)
    failures += classified_error(record)
    failures += fault_accounting(record)
    failures += artifact_detectability(record)
    return failures
