"""Chaos scenario engine: seeded fault schedules, end-to-end invariant
oracles, and automatic schedule shrinking.

Four PRs of reliability machinery (taxonomy/retry ladders, artifact
integrity, serving admission control, the lint-enforced site registry)
each carry a bit-identity or determinism contract that unit tests
exercise one injected fault at a time. Influence pipelines fail in
*composed* ways — solver state × checkpoint state × batching
("Scaling Up Influence Functions", arXiv:2112.03052) — so this package
turns those isolated harnesses into one continuously-exercised soak
layer:

- :mod:`~fia_tpu.chaos.schedule` — seeded, replayable fault schedules
  drawn from the checked-in site registry (multi-fault and
  repeated-fault compositions, JSON round-trip for repro files);
- :mod:`~fia_tpu.chaos.scenarios` — real end-to-end workloads
  (train→checkpoint→kill→resume, journaled ``query_many`` over a
  damaged disk tier, a serve stream under dispatch faults + overload)
  driven through the production Trainer/engine/service entry points
  under virtual time;
- :mod:`~fia_tpu.chaos.oracles` — invariants checked after every run:
  bit-identical results vs. an undisturbed golden run, classified
  errors only, armed ⇒ fired-or-reported fault accounting, on-disk
  artifact detectability;
- :mod:`~fia_tpu.chaos.shrink` — delta debugging (ddmin) reduces a
  failing schedule to a minimal reproducing fault sequence;
- :mod:`~fia_tpu.chaos.runner` — the engine tying them together and
  emitting replayable repro JSON
  (``python -m fia_tpu.cli.chaos --replay repro.json``).

Entry points: ``make chaos-smoke`` (fixed seed, CPU-bounded, fatal in
tier-1), ``make chaos-soak`` (seed-range sweep, not in tier-1), and
``python -m fia_tpu.cli.chaos`` for everything else.
"""

from fia_tpu.chaos.runner import ChaosEngine, ChaosReport  # noqa: F401
from fia_tpu.chaos.schedule import ChaosFault, Schedule, generate  # noqa: F401
