"""Schedule shrinking by delta debugging (Zeller's ddmin).

A failing chaos schedule found by a seed sweep typically carries
faults that have nothing to do with the violation. ``ddmin`` reduces
the fault list to a *1-minimal* subset — removing any single remaining
fault makes the failure disappear — by alternately re-running subsets
and their complements. The test predicate re-executes the scenario
(deterministic: same faults ⇒ same run), so the shrunk schedule is a
true repro, not a heuristic guess.
"""

from __future__ import annotations

from typing import Callable, Sequence


def _chunks(items: list, n: int) -> list[list]:
    k, out, start = len(items) / float(n), [], 0.0
    for _ in range(n):
        end = start + k
        out.append(items[int(start):int(end)])
        start = end
    return [c for c in out if c]


def ddmin(
    faults: Sequence,
    still_fails: Callable[[list], bool],
    max_tests: int = 64,
) -> list:
    """The minimal sublist of ``faults`` for which ``still_fails`` holds.

    Classic ddmin: try each of ``n`` chunks, then each complement;
    recurse on any reduction with granularity reset (subset) or
    decremented (complement); double granularity when nothing shrinks.
    ``max_tests`` bounds predicate evaluations — on exhaustion the best
    reduction found so far is returned (still failing, maybe not
    minimal). The caller guarantees ``still_fails(faults)`` is true.
    """
    current = list(faults)
    n = 2
    tests = 0
    while len(current) >= 2:
        chunks = _chunks(current, n)
        reduced = False
        for candidate_set, next_n in (
            (chunks, 2),  # subsets: reset granularity
            ([[f for c2 in chunks if c2 is not c for f in c2]
              for c in chunks], None),  # complements: n - 1
        ):
            for cand in candidate_set:
                if not cand or len(cand) == len(current):
                    continue
                tests += 1
                if tests > max_tests:
                    return current
                if still_fails(list(cand)):
                    current = list(cand)
                    n = next_n if next_n is not None else max(n - 1, 2)
                    reduced = True
                    break
            if reduced:
                break
        if not reduced:
            if n >= len(current):
                break
            n = min(len(current), 2 * n)
    return current
