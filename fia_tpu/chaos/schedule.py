"""Seeded, replayable fault schedules.

A *schedule* is the unit the chaos engine runs, shrinks, and replays:
an ordered set of ``(site, at, kind)`` faults drawn from a scenario's
declared fault domain — the injection sites the workload is guaranteed
to reach and the fault kinds meaningful there. Generation is a pure
function of ``(scenario, seed, n_faults, benign)``, so a schedule
printed in a failure report regenerates bit-identically anywhere, and
the JSON form (:meth:`Schedule.save` / :meth:`Schedule.load`) makes a
shrunk repro a file you can commit next to the bug it witnesses.

``benign=True`` restricts generation to each scenario's
*bit-identity-preserving* fault subset — kinds whose documented
recovery reproduces the undisturbed answer exactly (transient retry,
checkpoint walk-back, verified-cache self-heal, journaled deadline
resume). The full domain adds kinds whose recovery legitimately
changes results (solver escalation, CPU rung) or kills the run; those
schedules are checked against the weaker oracles only.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field, replace

from fia_tpu.reliability import inject, sites
from fia_tpu.utils import io

MAGIC = "fia-chaos-schedule-v1"

# A scenario's fault domain: site -> (kinds, max_at). ``max_at`` is the
# number of calls the workload is *guaranteed* to make at that site on
# any run (retries and resumes only add calls, never remove them), so
# every generated fault is reachable and the injector's armed ⇒
# fired-or-reported contract holds for complete runs.
Domain = dict


@dataclass(frozen=True, order=True)
class ChaosFault:
    """One scheduled fault: serializable mirror of ``inject.Fault``."""

    site: str
    at: int
    kind: str

    def to_inject(self) -> inject.Fault:
        return inject.Fault(sites.check(self.site), int(self.at), self.kind)


@dataclass(frozen=True)
class Schedule:
    """A seeded fault schedule bound to one scenario."""

    scenario: str
    seed: int
    faults: tuple = field(default_factory=tuple)  # tuple[ChaosFault, ...]
    benign: bool = True

    def inject_faults(self) -> list:
        return [f.to_inject() for f in self.faults]

    def describe(self) -> str:
        body = ", ".join(f"{f.site}@{f.at}:{f.kind}" for f in self.faults)
        return f"{self.scenario}/seed={self.seed} [{body or 'no faults'}]"

    def with_faults(self, faults) -> "Schedule":
        return replace(self, faults=tuple(faults))

    def to_dict(self) -> dict:
        return {
            "magic": MAGIC,
            "scenario": self.scenario,
            "seed": int(self.seed),
            "benign": bool(self.benign),
            "faults": [
                {"site": f.site, "at": int(f.at), "kind": f.kind}
                for f in self.faults
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Schedule":
        if d.get("magic") != MAGIC:
            raise ValueError(
                f"not a chaos schedule (magic {d.get('magic')!r}, "
                f"want {MAGIC!r})"
            )
        faults = tuple(
            ChaosFault(str(f["site"]), int(f["at"]), str(f["kind"]))
            for f in d.get("faults", ())
        )
        return cls(
            scenario=str(d["scenario"]), seed=int(d.get("seed", 0)),
            faults=faults, benign=bool(d.get("benign", True)),
        )

    def save(self, path: str) -> str:
        return io.save_json_atomic(path, self.to_dict(), indent=2)

    @classmethod
    def load(cls, path: str) -> "Schedule":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def generate(
    scenario_name: str,
    domain: Domain,
    seed: int,
    n_faults: int,
    benign: bool = True,
) -> Schedule:
    """A seeded schedule of ``n_faults`` faults over ``domain``.

    Pure function of its arguments (``random.Random(seed)``, no global
    state). Duplicate ``(site, at, channel)`` triples are rejected
    during sampling — the injector fires the *first* unfired match, so
    a duplicate would be armed but unreachable, violating the
    armed ⇒ fired-or-reported contract by construction.
    """
    rng = random.Random((seed, scenario_name, benign).__repr__())
    site_names = sorted(domain)
    taken: set = set()
    faults: list[ChaosFault] = []
    budget = max(int(n_faults), 0) * 8 + 8  # rejection-sampling bound
    while len(faults) < n_faults and budget > 0:
        budget -= 1
        site = rng.choice(site_names)
        kinds, max_at = domain[site]
        kind = rng.choice(list(kinds))
        at = rng.randrange(max(int(max_at), 1))
        key = (site, at, inject._channel(kind))
        if key in taken:
            continue
        taken.add(key)
        faults.append(ChaosFault(site, at, kind))
    return Schedule(
        scenario=scenario_name, seed=int(seed),
        faults=tuple(sorted(faults)), benign=benign,
    )
