"""End-to-end chaos scenarios over the production entry points.

Each scenario is a small, CPU-bounded workload driven through the REAL
Trainer / InfluenceEngine / InfluenceService code paths — not mocks —
with a declared *fault domain*: the injection sites the workload is
guaranteed to reach, the kinds meaningful there, and how many calls
each site is guaranteed to see (``max_at``). The benign domain is the
subset whose documented recovery is bit-identity-preserving; schedules
drawn from it must reproduce the undisturbed golden run exactly.

Scenario state that is safe to share across runs (compiled epoch fns,
engine jit caches) lives on the scenario instance so a smoke run pays
each XLA compile once; everything run-scoped (checkpoints, journals,
disk caches) lands in the per-run ``workdir``. All retry backoff runs
under a :class:`~fia_tpu.reliability.policy.VirtualClock` — a chaos
run never sleeps wall-clock time.

The two ``selftest`` scenarios are the harness's own fixtures: a
trivial retry-loop workload with a deliberately *broken* variant whose
retry path drops a unit's contribution. The broken one exists so tests
(and ``--scenario selftest-broken``) can watch the full
fail → shrink → replay pipeline end-to-end without touching jax.
"""

from __future__ import annotations

import os

import numpy as np

from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.reliability import policy as rpolicy
from fia_tpu.reliability.journal import Journal

# Transient device kinds whose retry recovery is bit-identical
# (functional inputs reused verbatim).
_TRANSIENT_KINDS = (taxonomy.WORKER, taxonomy.PREEMPTION, taxonomy.AMBIGUOUS)
# On-disk damage kinds; recovery is walk-back / self-heal — bit-identical.
_DAMAGE_KINDS = (inject.TORN, inject.BITFLIP, inject.STALE_MANIFEST)
# Kinds that kill a workload (classified surfacing, no bit-identity).
_KILL_KINDS = (taxonomy.OOM, taxonomy.HOST_OOM, taxonomy.DEADLINE)

# Shared tiny-MF workload shape (the repo's test convention).
_U, _I, _K = 30, 20, 4
_WD, _DAMP = 1e-2, 1e-3

# Backoff shaped like production training retry but able to absorb a
# worst-case smoke schedule (3 consecutive transient faults on one
# site); the VirtualClock makes the delays free.
_CHAOS_RETRY = rpolicy.RetryPolicy(
    max_attempts=4, base_delay=2.0, max_delay=30.0, jitter=0.25
)


def _toy_data(seed: int, n: int):
    rng = np.random.default_rng(seed)
    x = np.stack(
        [rng.integers(0, _U, n), rng.integers(0, _I, n)], axis=1
    ).astype(np.int32)
    y = rng.integers(1, 6, n).astype(np.float32)
    return x, y


class Scenario:
    """Base: a named workload with benign/full fault domains.

    ``run(workdir, events)`` executes the workload under whatever fault
    plan the runner armed and returns the outcome payload (name →
    array/str/int). It raises on unrecovered failure — classification
    is the runner's job.
    """

    name: str = "?"
    benign_domain: dict = {}
    full_domain: dict = {}

    def domain(self, benign: bool) -> dict:
        return self.benign_domain if benign else self.full_domain

    def run(self, workdir: str, events: list) -> dict:
        raise NotImplementedError

    def check(self, golden: dict, record) -> list:
        """Scenario-specific oracles beyond the standard battery."""
        return []


class SelftestScenario(Scenario):
    """Retry-loop counter workload — the harness validating itself.

    Six work units, each firing ``chaos.unit`` inside a production
    RetryPolicy under virtual time. Transient kinds retry to the same
    unit value (bit-identical); kill kinds surface classified.
    """

    name = "selftest"
    UNITS = 6
    broken = False
    benign_domain = {
        sites.CHAOS_UNIT: (_TRANSIENT_KINDS, UNITS),
    }
    full_domain = {
        sites.CHAOS_UNIT: (_TRANSIENT_KINDS + _KILL_KINDS, UNITS),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def run(self, workdir: str, events: list) -> dict:
        clock = rpolicy.VirtualClock()
        vals = []
        for u in range(self.UNITS):
            retried = []

            def work(u=u):
                inject.fire(sites.CHAOS_UNIT)
                return float(u * 3 + 1)

            v = _CHAOS_RETRY.run(
                work, clock=clock,
                on_retry=lambda kind, a, e: retried.append(kind),
            )
            if retried:
                events.append({"event": "unit_retried", "unit": u,
                               "kinds": list(retried)})
            if self.broken and retried:
                # The deliberately seeded bug (selftest-broken only): a
                # retried unit loses its contribution. The bit_identity
                # oracle must catch this and ddmin must shrink any
                # schedule that trips it to a single transient fault.
                v = 0.0
            vals.append(v)
        return {"units": np.asarray(vals, np.float64)}


class SelftestBrokenScenario(SelftestScenario):
    name = "selftest-broken"
    broken = True


class TrainResumeScenario(Scenario):
    """train → checkpoint → kill → restore → resume, bit-identically.

    Phase 1 trains to the kill step under rotated checkpointing, then
    the in-memory state is discarded (the kill). Phase 2 sweeps stale
    temps, restores the newest valid generation — walking back past
    damaged ones, all the way to from-scratch when every generation is
    corrupt — and finishes training. The absolute-step epoch keys and
    step masks make the final params bit-identical to an uninterrupted
    golden run from ANY valid restore point, which is exactly what the
    oracle asserts.
    """

    name = "train_resume"
    N, BATCH, STEPS, KILL, EVERY, KEEP = 400, 100, 40, 24, 8, 3
    # phase 1: 6 epoch dispatches; phase 2: >= 4 more (restore at the
    # kill step) — 10 guaranteed. Checkpoint publishes: 3 in phase 1,
    # >= 2 in phase 2.
    benign_domain = {
        sites.TRAINER_EPOCH: (_TRANSIENT_KINDS, 10),
        sites.CHECKPOINT_PUBLISH: (_DAMAGE_KINDS, 5),
    }
    full_domain = {
        sites.TRAINER_EPOCH: (_TRANSIENT_KINDS + (taxonomy.OOM,), 10),
        sites.CHECKPOINT_PUBLISH: (_DAMAGE_KINDS, 5),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        from fia_tpu.models import MF
        from fia_tpu.train.trainer import TrainConfig, Trainer

        import jax

        self.x, self.y = _toy_data(0, self.N)
        self.model = MF(_U, _I, _K, _WD)
        self.params0 = self.model.init_params(jax.random.PRNGKey(0))
        cfg = TrainConfig(batch_size=self.BATCH, num_steps=self.STEPS,
                          learning_rate=1e-2, seed=0)
        # one Trainer for every run/phase: the compiled epoch fn is
        # shared, and the VirtualClock absorbs retry backoff
        self.trainer = Trainer(self.model, cfg, retry_policy=_CHAOS_RETRY,
                               clock=rpolicy.VirtualClock())
        self.fingerprint = {"kind": "chaos-train", "seed": 0,
                            "steps": self.STEPS, "batch": self.BATCH}

    def _params_outcome(self, state) -> dict:
        import jax

        out = {"step": int(state.step)}
        for i, leaf in enumerate(jax.tree_util.tree_leaves(state.params)):
            out[f"param{i}"] = np.asarray(leaf)
        return out

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.train import checkpoint
        from fia_tpu.train.trainer import TrainState
        from fia_tpu.utils import io

        ckpt_dir = os.path.join(workdir, "ckpts")
        ck1 = checkpoint.PeriodicCheckpointer(
            ckpt_dir, every=self.EVERY, keep=self.KEEP,
            fingerprint=self.fingerprint)
        state = self.trainer.init_state(self.params0)
        # phase 1: train to the kill point, then discard state (the kill)
        self.trainer.fit(state, self.x, self.y, num_steps=self.KILL,
                         checkpointer=ck1)

        # phase 2: a fresh process would sweep temps, restore, resume
        io.sweep_stale_tmps(ckpt_dir)
        restored = checkpoint.restore_latest_valid(
            ckpt_dir, self.params0, self.trainer.init_state(self.params0).opt_state,
            fingerprint=self.fingerprint, verbose=False)
        if restored is None:
            # every generation corrupt: the ladder's last rung
            events.append({"event": "restore_exhausted",
                           "kind": "from_scratch"})
            state2 = self.trainer.init_state(self.params0)
        else:
            events.append({"event": "resumed", "step": int(restored[2])})
            state2 = TrainState(restored[0], restored[1], restored[2])
        ck2 = checkpoint.PeriodicCheckpointer(
            ckpt_dir, every=self.EVERY, keep=self.KEEP,
            fingerprint=self.fingerprint)
        ck2._last_step = state2.step
        final = self.trainer.fit(
            state2, self.x, self.y,
            num_steps=self.STEPS - int(state2.step), checkpointer=ck2)
        return self._params_outcome(final)


class QueryCacheScenario(Scenario):
    """Journaled ``query_many`` plus the verified iHVP disk cache.

    Part A runs a journaled multi-batch ``query_many`` with a resume
    loop: an injected ``deadline`` surfaces cleanly with completed
    batches banked, and the reopened journal finishes the remainder —
    the combined scores must be bit-identical to one undisturbed run.
    Part B exercises the disk-cache tier twice per point so a damaged
    entry is quarantined and self-heals into a clean recompute.
    """

    name = "query_cache"
    NPTS, BQ = 6, 2
    benign_domain = {
        # 3 guaranteed pipelined dispatches (part A)
        sites.ENGINE_DISPATCH_FLAT: (
            _TRANSIENT_KINDS[:2] + (taxonomy.DEADLINE,), 3),
        # 2 guaranteed first-publish cache entries (part B)
        sites.ENGINE_CACHE_PUBLISH: (_DAMAGE_KINDS, 2),
    }
    full_domain = {
        sites.ENGINE_DISPATCH_FLAT: (
            _TRANSIENT_KINDS + (taxonomy.OOM, taxonomy.DEADLINE), 3),
        sites.ENGINE_CACHE_PUBLISH: (_DAMAGE_KINDS, 2),
        sites.ENGINE_SOLVE: ((taxonomy.NAN,), 1),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        from fia_tpu.data.dataset import RatingDataset
        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.models import MF

        import jax

        x, y = _toy_data(0, 400)
        self.train = RatingDataset(x, y)
        self.model = MF(_U, _I, _K, _WD)
        params = self.model.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        self.pts = np.stack(
            [rng.integers(0, _U, self.NPTS), rng.integers(0, _I, self.NPTS)],
            axis=1).astype(np.int32)
        self.test_ds = RatingDataset(
            self.pts[:2].copy(), np.full(2, 4.0, np.float32))
        # one engine for every run (jit caches shared); the disk cache
        # tier is re-pointed into each run's workdir. The score kernel
        # is pinned (here and in every scenario below) rather than left
        # on 'auto': golden runs are BITWISE contracts, and auto
        # resolves per backend (pallas on TPU reorders accumulation).
        self.engine = InfluenceEngine(
            self.model, params, self.train, damping=_DAMP,
            model_name="chaos-mf", kernel="xla_analytic")

    def run(self, workdir: str, events: list) -> dict:
        eng = self.engine
        eng.cache_dir = os.path.join(workdir, "cache")
        jpath = os.path.join(workdir, "journal.jsonl")
        fp = eng.journal_fingerprint(self.pts, batch_queries=self.BQ)

        # part A: journaled query_many with a deadline-resume loop
        results = None
        for attempt in range(len(self.pts) + 2):
            j = Journal.open(jpath, fp, resume=os.path.exists(jpath),
                             fsync=False)
            try:
                results = eng.query_many(self.pts, batch_queries=self.BQ,
                                         journal=j)
                break
            except taxonomy.DeadlineExpired:
                events.append({"event": "deadline_resume",
                               "attempt": attempt})
            finally:
                j.close()
        if results is None:
            raise taxonomy.DeadlineExpired(
                "query_many never completed within the resume budget")

        out: dict = {}
        t = 0
        for r in results:
            for row in range(len(np.asarray(r.counts))):
                out[f"scores{t}"] = np.asarray(r.scores_of(row)).copy()
                t += 1
        out["points_done"] = t

        # part B: publish two cache entries, then re-read them — a
        # damaged entry must quarantine and self-heal to the same scores
        for k in range(2):
            first = eng.get_influence_on_test_loss([k], self.test_ds)
            healed = eng.get_influence_on_test_loss(
                [k], self.test_ds, force_refresh=False)
            out[f"cache{k}"] = np.asarray(healed).copy()
            if not np.array_equal(np.asarray(first), np.asarray(healed)):
                events.append({"event": "cache_heal_drift", "point": k})
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        failures = []
        for e in record.events:
            if e.get("event") == "cache_heal_drift":
                failures.append(OracleFailure(
                    "cache_self_heal",
                    f"healed cache entry for point {e['point']} is not "
                    "bit-identical to its first computation",
                ))
        if record.error is None and record.workdir:
            jpath = os.path.join(record.workdir, "journal.jsonl")
            if os.path.exists(jpath):
                fp = self.engine.journal_fingerprint(
                    self.pts, batch_queries=self.BQ)
                try:
                    j = Journal.open(jpath, fp, resume=True, fsync=False)
                    if j.corrupt_lines:
                        failures.append(OracleFailure(
                            "journal_consistency",
                            f"{j.corrupt_lines} corrupt journal line(s) "
                            "after a clean completion",
                        ))
                    j.close()
                except Exception as e:
                    failures.append(OracleFailure(
                        "journal_consistency",
                        f"journal reopen failed: {e!r}",
                    ))
        return failures


class ServeStreamScenario(Scenario):
    """A deterministic request stream under overload + dispatch faults.

    Two submit waves sized past the admission queue bound produce
    deterministic ``overload``/``invalid`` rejections; admitted keys
    resolve through hot/disk cache tiers and micro-batched dispatches.
    Benign schedules (disk-tier damage only) must reproduce the golden
    stream bit-identically; under dispatch faults the scenario oracle
    still requires every OK response to match golden byte-for-byte and
    every rejection to carry a classified or admission reason.
    """

    name = "serve_stream"
    MAX_BATCH, MAX_QUEUE, WAVE = 3, 6, 9
    mesh = None  # serve_stream_mesh shards dispatch over a device mesh
    # 4 guaranteed micro-batch dispatches; 10 disk-tier publishes on a
    # shed-free run (benign damage never sheds), but only the first
    # publish is guaranteed once full-domain dispatch faults can shed
    # whole batches.
    benign_domain = {
        sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 10),
    }
    full_domain = {
        sites.SERVE_DISPATCH: (
            (taxonomy.WORKER, taxonomy.OOM, taxonomy.DEADLINE), 4),
        sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 1),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        from fia_tpu.data.dataset import RatingDataset
        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.models import MF

        import jax

        x, y = _toy_data(0, 400)
        self.model = MF(_U, _I, _K, _WD)
        self.params = self.model.init_params(jax.random.PRNGKey(0))
        self.train_ds = RatingDataset(x, y)
        self.engine = InfluenceEngine(
            self.model, self.params, self.train_ds, damping=_DAMP,
            model_name="chaos-serve", kernel="xla_analytic")
        # 12 distinct keys; the stream below replays some of them
        rng = np.random.default_rng(2)
        flat = rng.choice(_U * _I, size=12, replace=False)
        self.keys = [(int(k // _I), int(k % _I)) for k in flat]

    def _stream(self):
        k = self.keys
        # wave 1: 6 distinct admits fill the queue, then one invalid id
        # and two duplicates shed as overload
        wave1 = k[:6] + [(-1, 5), k[0], k[1]]
        # wave 2: two hot-cache replays + 4 new keys admit, then 2 new
        # keys and a replay shed as overload
        wave2 = [k[0], k[1]] + k[6:10] + k[10:12] + [k[2]]
        return wave1 + wave2

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        eng = self.engine
        eng.cache_dir = os.path.join(workdir, "cache")
        svc = InfluenceService(
            engine=eng,
            config=ServeConfig(max_batch=self.MAX_BATCH,
                               max_queue=self.MAX_QUEUE,
                               mesh=self.mesh),
            clock=rpolicy.VirtualClock(),
        )
        from fia_tpu.serve.request import Request

        reqs = [Request(u, i, id=f"q{n}")
                for n, (u, i) in enumerate(self._stream())]
        responses = svc.run(reqs, drain_every=self.WAVE)
        out: dict = {}
        for r in responses:
            out[f"{r.id}:status"] = f"{r.status}/{r.reason or ''}"
            if r.ok:
                out[f"{r.id}:scores"] = np.asarray(r.scores).copy()
        stats = svc.cache.stats
        out["shed_batches"] = sum(
            1 for e in events if e.get("event") == "batch_shed")
        events.append({"event": "cache_stats",
                       "hits_hot": int(stats.hits_hot),
                       "hits_disk": int(stats.hits_disk)})
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure, _value_diff
        from fia_tpu.serve import admission

        if record.error is not None or record.outcome is None:
            return []
        failures = []
        got = record.outcome
        allowed = {
            taxonomy.OOM, taxonomy.HOST_OOM, taxonomy.AMBIGUOUS,
            taxonomy.WORKER, taxonomy.PREEMPTION, taxonomy.NAN,
            taxonomy.DEADLINE, taxonomy.DEVICE_LOST, taxonomy.HOST_LOST,
            admission.REASON_OVERLOAD, admission.REASON_INVALID,
            admission.REASON_DEGRADED,
        }
        for name, g in golden.items():
            if name.endswith(":status"):
                rid = name[:-len(":status")]
                gs = str(g)
                cs = str(got.get(name, "<missing>"))
                # admission decisions (overload/invalid) are a pure
                # function of the submit stream — faults cannot move them
                for adm in (admission.REASON_OVERLOAD,
                            admission.REASON_INVALID):
                    if (gs.endswith("/" + adm)) != (cs.endswith("/" + adm)):
                        failures.append(OracleFailure(
                            "admission_determinism",
                            f"{rid}: golden {gs} vs chaos {cs}",
                        ))
                if cs.startswith("rejected/"):
                    reason = cs.split("/", 1)[1]
                    if reason not in allowed:
                        failures.append(OracleFailure(
                            "classified_rejection",
                            f"{rid}: unclassified rejection {reason!r}",
                        ))
            elif name.endswith(":scores") and name in got:
                # every answer actually served must match golden bytes
                d = _value_diff(name, g, got[name])
                if d:
                    failures.append(OracleFailure("served_bit_identity", d))
        return failures


class ServeStreamMeshScenario(ServeStreamScenario):
    """The serve_stream workload with dispatch sharded over a 2-device
    ``data`` mesh (query-axis sharding, docs/design.md §15).

    Same request stream, admission bounds, and fault domain as
    ``serve_stream`` — a dispatch fault on a sharded micro-batch sheds
    exactly that batch, and admission stays a pure function of the
    submit stream. The mesh-specific oracle: every score actually
    served, in the golden run AND under faults, must be BIT-identical
    to a single-device reference stream computed fault-free at
    construction — sharding must never show through in results.
    Degrades to the single-device workload (with a ``mesh_skipped``
    event) when fewer than 2 devices exist, so the scenario stays
    runnable on any host.
    """

    name = "serve_stream_mesh"
    NDEV = 2

    def __init__(self):
        super().__init__()
        import jax

        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.parallel.mesh import make_mesh
        from fia_tpu.serve.request import Request
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        # single-device reference stream, computed fault-free before any
        # schedule is armed (no workdir: the disk tier stays off, as it
        # is for the first dispatch of every chaos run)
        ref_svc = InfluenceService(
            engine=self.engine,
            config=ServeConfig(max_batch=self.MAX_BATCH,
                               max_queue=self.MAX_QUEUE),
            clock=rpolicy.VirtualClock(),
        )
        reqs = [Request(u, i, id=f"q{n}")
                for n, (u, i) in enumerate(self._stream())]
        self.ref = {
            r.id: np.asarray(r.scores).copy()
            for r in ref_svc.run(reqs, drain_every=self.WAVE) if r.ok
        }
        if jax.device_count() >= self.NDEV:
            self.mesh = make_mesh(self.NDEV)
            self.engine = InfluenceEngine(
                self.model, self.params, self.train_ds, damping=_DAMP,
                model_name="chaos-serve-mesh", mesh=self.mesh,
                kernel="xla_analytic")

    def run(self, workdir: str, events: list) -> dict:
        if self.mesh is None:
            import jax

            events.append({"event": "mesh_skipped",
                           "devices": int(jax.device_count())})
        return super().run(workdir, events)

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        failures = super().check(golden, record)
        outcomes = [("golden", golden)]
        if record.error is None and record.outcome is not None:
            outcomes.append(("chaos", record.outcome))
        for label, out in outcomes:
            for name, v in out.items():
                if not name.endswith(":scores"):
                    continue
                rid = name[: -len(":scores")]
                ref = self.ref.get(rid)
                if ref is None:
                    failures.append(OracleFailure(
                        "mesh_single_device_identity",
                        f"{label} run served {rid}, which the "
                        "single-device reference rejected",
                    ))
                elif not np.array_equal(np.asarray(v), ref):
                    failures.append(OracleFailure(
                        "mesh_single_device_identity",
                        f"{label} run: scores for {rid} diverge from "
                        "the single-device reference (sharded dispatch "
                        "must be bit-identical)",
                    ))
        return failures


class DeviceLossRecoveryScenario(ServeStreamScenario):
    """Kill mesh devices mid-wave; the service must shrink and recover.

    The serve_stream workload over a 4-device ``data`` mesh, with
    ``device_lost`` faults armed at ``serve.dispatch``. Each loss must
    trigger the mesh-shrink recovery (docs/design.md §18): rebuild over
    the survivors, AOT re-arm, re-dispatch the failed batch — so a
    BENIGN schedule of losses sheds *nothing* and reproduces the golden
    stream bit-identically, through up to three consecutive shrinks
    (4 → 3 → 2 → 1 devices; ``max_at=3`` keeps every benign schedule
    within what four devices can absorb). Scenario oracles:

    - ``shrunk_mesh_identity`` — every score served, golden AND chaos,
      matches a fault-free single-device reference bit-for-bit; the
      mesh size the run ended on must never show through in results.
    - ``no_unclassified_errors`` — a run may die or shed, but only
      classified: an unclassified escape or rejection reason means the
      recovery path leaked a raw backend error.

    Degrades to the meshless workload when fewer than 4 devices exist
    (``mesh_skipped`` event): ``device_lost`` then has nothing to
    shrink and sheds classified, so it moves to the FULL domain only.
    """

    name = "device_loss_recovery"
    NDEV = 4

    def __init__(self):
        super().__init__()
        import jax

        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.parallel.mesh import make_mesh
        from fia_tpu.serve.request import Request
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        # fault-free single-device reference stream (same pattern as
        # serve_stream_mesh: computed before any schedule is armed)
        ref_svc = InfluenceService(
            engine=self.engine,
            config=ServeConfig(max_batch=self.MAX_BATCH,
                               max_queue=self.MAX_QUEUE),
            clock=rpolicy.VirtualClock(),
        )
        reqs = [Request(u, i, id=f"q{n}")
                for n, (u, i) in enumerate(self._stream())]
        self.ref = {
            r.id: np.asarray(r.scores).copy()
            for r in ref_svc.run(reqs, drain_every=self.WAVE) if r.ok
        }
        if jax.device_count() >= self.NDEV:
            self.mesh = make_mesh(self.NDEV)
            self.engine = InfluenceEngine(
                self.model, self.params, self.train_ds, damping=_DAMP,
                model_name="chaos-devloss", mesh=self.mesh,
                kernel="xla_analytic")
        # Domains are per-instance: device loss is benign (recovery is
        # a bit-identical re-dispatch) only when there is a mesh to
        # shrink. mesh.rebuild is deliberately NOT in any domain — the
        # site is reachable only after a loss fires, so arming it
        # directly would violate the armed ⇒ fired contract;
        # rebuild-time faults are unit-tested instead.
        if self.mesh is not None:
            self.benign_domain = {
                sites.SERVE_DISPATCH: ((taxonomy.DEVICE_LOST,), 3),
                sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 10),
            }
        else:
            self.benign_domain = dict(ServeStreamScenario.benign_domain)
        self.full_domain = {
            sites.SERVE_DISPATCH: (
                (taxonomy.WORKER, taxonomy.OOM, taxonomy.DEADLINE,
                 taxonomy.DEVICE_LOST), 4),
            sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 1),
            sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
        }

    def run(self, workdir: str, events: list) -> dict:
        import jax

        from fia_tpu.parallel.mesh import mesh_fingerprint

        if self.mesh is None:
            events.append({"event": "mesh_skipped",
                           "devices": int(jax.device_count())})
        elif (mesh_fingerprint(self.engine.mesh)
                != mesh_fingerprint(self.mesh)):
            # a prior run's recovery left the shared engine on a shrunk
            # mesh; restore the full topology so every run starts equal
            self.engine.rebuild_mesh(self.mesh)
        out = super().run(workdir, events)
        if self.mesh is not None:
            # recovery accounting goes in events, NOT the outcome — the
            # golden run never shrinks, and benign runs must stay
            # bit-identical to it in outcome space
            events.append({
                "event": "mesh_after",
                "devices": int(self.engine.mesh.devices.size),
                "shrunk": int(self.NDEV - self.engine.mesh.devices.size),
            })
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        failures = super().check(golden, record)
        if record.error is not None and record.error.get("kind") is None:
            failures.append(OracleFailure(
                "no_unclassified_errors",
                f"run died unclassified: {record.error.get('error')}",
            ))
        outcomes = [("golden", golden)]
        if record.error is None and record.outcome is not None:
            outcomes.append(("chaos", record.outcome))
        for label, out in outcomes:
            for name, v in out.items():
                # rejection-reason classification is covered by the
                # parent's classified_rejection oracle; here: identity
                if name.endswith(":scores"):
                    rid = name[: -len(":scores")]
                    ref = self.ref.get(rid)
                    if ref is None:
                        failures.append(OracleFailure(
                            "shrunk_mesh_identity",
                            f"{label} run served {rid}, which the "
                            "single-device reference rejected",
                        ))
                    elif not np.array_equal(np.asarray(v), ref):
                        failures.append(OracleFailure(
                            "shrunk_mesh_identity",
                            f"{label} run: scores for {rid} diverge "
                            "from the fault-free single-device "
                            "reference (mesh-shrink recovery must be "
                            "bit-identical)",
                        ))
        return failures


class HostLossRecoveryScenario(DeviceLossRecoveryScenario):
    """Kill whole hosts mid-wave; the pod must shrink by hosts and
    recover.

    The device-loss workload scaled to a pod stand-in: 8 devices under
    a 4-host virtual overlay (2 devices per host,
    ``parallel.mesh.virtual_hosts``), with ``host_lost`` faults armed at
    ``serve.dispatch``. Each loss must trigger the host-granular
    mesh-shrink recovery (docs/design.md §25): drop the lost host's
    ENTIRE device group via ``surviving_mesh(..., unnamed="host")``,
    rebuild, AOT re-arm, re-dispatch — so a benign schedule of up to
    three host losses (8 → 6 → 4 → 2 devices) sheds nothing and stays
    bit-identical to the fault-free single-device reference. Oracles
    are inherited: ``shrunk_mesh_identity`` and
    ``no_unclassified_errors`` — a host loss that escapes unclassified
    or perturbs a single served byte is the failure this scenario
    exists to catch. ``mesh.rebuild_multihost`` is deliberately NOT in
    any domain for the same armed ⇒ fired reason as ``mesh.rebuild``.

    Degrades to the meshless workload when fewer than 8 devices exist
    (``mesh_skipped`` event), where ``host_lost`` sheds classified and
    so moves to the FULL domain only.
    """

    name = "host_loss_recovery"
    NDEV = 8
    NHOSTS = 4

    def __init__(self):
        super().__init__()
        # super() built the 8-device mesh (or skipped it) and armed
        # DEVICE_LOST; re-arm with HOST_LOST — same sites, same caps,
        # host-granular evidence.
        if self.mesh is not None:
            self.benign_domain = {
                sites.SERVE_DISPATCH: ((taxonomy.HOST_LOST,), 3),
                sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 10),
            }
        self.full_domain = {
            sites.SERVE_DISPATCH: (
                (taxonomy.WORKER, taxonomy.OOM, taxonomy.DEADLINE,
                 taxonomy.HOST_LOST), 4),
            sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 1),
            sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
        }

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.parallel import mesh as pmesh

        if self.mesh is None:
            return super().run(workdir, events)
        # the virtual-host overlay is scoped to the run so other
        # scenarios in the same battery see real process indices
        overlay = {
            int(d.id): int(d.id) // (self.NDEV // self.NHOSTS)
            for d in self.mesh.devices.flat
        }
        with pmesh.virtual_hosts(overlay):
            out = super().run(workdir, events)
            events.append({
                "event": "hosts_after",
                "hosts": int(len(pmesh.mesh_hosts(self.engine.mesh))),
            })
        return out


class FactorBankScenario(Scenario):
    """Factor-bank publish → verified load → O(1) hit serving → miss
    fall-through, under artifact damage and load faults.

    Each run republishes the (fixed, precomputed) bank into its workdir
    — the ``factor.publish`` damage point — then a ``precomputed``
    engine attempts the verified load (``engine.factor_load``) and
    serves every banked pair plus a few unbanked ones, one query at a
    time. A torn/bit-rotted/stale-manifest bank must quarantine and
    degrade to the solver ladder; a transient load fault must degrade
    the same way; and in every case each served answer must be
    byte-identical to one of the two fault-free references computed at
    construction (bank hit or bank-less ladder — anything else is a
    silent wrong answer). Miss-pair scores go into the outcome payload:
    they are served by the ladder regardless of bank health, so benign
    schedules must reproduce them bit-identically.
    """

    name = "factor_bank"
    NPAIRS, NMISS = 8, 3
    benign_domain = {
        sites.FACTOR_PUBLISH: (_DAMAGE_KINDS, 1),
        sites.ENGINE_FACTOR_LOAD: (_TRANSIENT_KINDS, 1),
    }
    full_domain = {
        sites.FACTOR_PUBLISH: (_DAMAGE_KINDS, 1),
        sites.ENGINE_FACTOR_LOAD: (
            _TRANSIENT_KINDS + (taxonomy.HOST_OOM,), 1),
        sites.ENGINE_SOLVE: ((taxonomy.NAN,), 1),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        import tempfile

        import jax

        from fia_tpu.data.dataset import RatingDataset
        from fia_tpu.influence import factor as fbank
        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.models import MF

        x, y = _toy_data(0, 400)
        self.model = MF(_U, _I, _K, _WD)
        params = self.model.init_params(jax.random.PRNGKey(0))
        train = RatingDataset(x, y)
        builder = InfluenceEngine(
            self.model, params, train, damping=_DAMP,
            model_name="chaos-factor", lissa_depth=30,
            kernel="xla_analytic")
        pairs = fbank.select_hot_pairs(
            builder.index, max_entries=self.NPAIRS,
            top_users=4, top_items=4)
        # the bank content is fixed; runs only re-PUBLISH it (that is
        # where the damage channel bites), never re-factorize
        self.bank = fbank.build_bank(builder, pairs)
        self.fp = fbank.bank_fingerprint(
            "chaos-factor", self.model.block_size, _DAMP,
            *builder._train_host)
        self.pairs = [(int(u), int(i)) for u, i in pairs]
        banked = set(self.pairs)
        self.miss_pairs = [
            (int(u), int(i))
            for u, i in zip(x[:, 0], x[:, 1])
            if (int(u), int(i)) not in banked
        ][: self.NMISS]

        # one precomputed engine for every run (compiled programs are
        # shared); its cache_dir/bank state is re-pointed per run
        self.eng = InfluenceEngine(
            self.model, params, train, damping=_DAMP,
            solver="precomputed", cache_dir=tempfile.mkdtemp(
                prefix="fia-chaos-factor-init-"),
            model_name="chaos-factor", lissa_depth=30,
            kernel="xla_analytic")

        # fault-free references: bank-hit bytes and bank-less ladder
        # bytes per pair, each queried alone (T=1) so per-pair results
        # are independent of what else is in a batch
        path = fbank.default_bank_path(self.eng.cache_dir, "chaos-factor")
        fbank.publish_bank(self.bank, path, self.fp)
        assert self.eng.ensure_factor_bank() == len(self.bank)
        self.ref_bank = [
            self._one(self.eng, p).tobytes() for p in self.pairs
        ]
        # the rung a rejected bank degrades to — since the certified
        # rung landed that is ``sampled``, not lissa (bitwise-exact
        # here: toy counts sit below the default sample cap)
        ladder = InfluenceEngine(
            self.model, params, train, damping=_DAMP,
            solver=rpolicy.next_solver("precomputed"),
            model_name="chaos-factor", lissa_depth=30,
            kernel="xla_analytic")
        self.ref_ladder = [
            self._one(ladder, p).tobytes() for p in self.pairs
        ]

    @staticmethod
    def _one(engine, pair) -> np.ndarray:
        res = engine.query_batch(np.asarray([pair], np.int64))
        return np.asarray(res.scores_of(0))

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.influence import factor as fbank

        eng = self.eng
        eng.cache_dir = os.path.join(workdir, "cache")
        eng.unload_factor_bank()
        eng.solver = "precomputed"  # undo any sticky prior escalation
        path = fbank.default_bank_path(eng.cache_dir, eng.model_name)
        fbank.publish_bank(self.bank, path, self.fp)
        n = eng.ensure_factor_bank()
        events.append({"event": "bank_loaded", "entries": int(n)})

        for k, pair in enumerate(self.pairs):
            b = self._one(eng, pair).tobytes()
            via = ("bank" if b == self.ref_bank[k]
                   else "ladder" if b == self.ref_ladder[k]
                   else "neither")
            events.append({"event": "pair_served", "pair": k, "via": via})

        out: dict = {}
        for k, pair in enumerate(self.miss_pairs):
            out[f"miss{k}"] = self._one(eng, pair).copy()
        out["pairs_total"] = len(self.pairs)
        events.append({"event": "bank_stats", **eng.bank_stats()})
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        if record.error is not None or record.outcome is None:
            return []
        failures = []
        entries = next(
            (e["entries"] for e in record.events
             if e.get("event") == "bank_loaded"), 0)
        for e in record.events:
            if e.get("event") != "pair_served":
                continue
            if e["via"] == "neither":
                failures.append(OracleFailure(
                    "factor_serving_integrity",
                    f"pair {e['pair']}: served scores match neither the "
                    "bank reference nor the ladder reference "
                    "(silent wrong answer)",
                ))
            elif entries == 0 and e["via"] != "ladder":
                failures.append(OracleFailure(
                    "factor_fall_through",
                    f"pair {e['pair']} served via {e['via']} with no "
                    "bank loaded — a rejected bank must degrade to the "
                    "solver ladder",
                ))
        return failures


class UpdateWhileServingScenario(Scenario):
    """Streaming updates (``FIAModel.apply_updates``) under live serving,
    mid-update kills, and swap faults — docs/design.md §17.

    The train set is split into two non-interacting communities; both
    updates land entirely in community A, so community-B probes are
    provably outside every footprint. Three fault-free reference states
    (base, after update 1, after both) are served T=1 at construction;
    every probe answered during a chaos run must match the reference of
    the state it was admitted under, byte-for-byte:

    - pre/mid/post waves pin serving to base / post-1 / post-2 state;
    - a ticket submitted BEFORE update 2 and drained after it must
      answer on its admission epoch (the fenced post-1 state);
    - untouched (community-B) probes must be bit-identical in every
      wave — the local-update projection at work;
    - a rolled-back attempt must leave serving answering the old state,
      and the retry (resuming the attempt's checkpoints) must commit to
      the same bytes as the uninterrupted golden run;
    - a committed swap must re-key untouched cache entries, never
      wholesale-flush (``swap_stats`` oracle).
    """

    name = "update_while_serving"
    BASE_STEPS, STEPS, EVERY = 24, 16, 4
    # community A: users 0-14 x items 0-9; community B: the rest. The
    # update rows below stay inside A, so B probes are untouched by
    # construction (footprint reach cannot cross communities).
    TOUCHED = ((2, 3), (5, 1), (11, 8))
    UNTOUCHED = ((16, 12), (22, 17), (28, 11))
    FENCE = (2, 3)
    # each update fires stream.update once and stream.swap once on a
    # fault-free attempt: 2 guaranteed calls per site across the two
    # updates; the retry budget (4 attempts/update) absorbs a worst-case
    # 3-fault smoke schedule on one site with one attempt to spare
    benign_domain = {
        sites.STREAM_UPDATE: (_TRANSIENT_KINDS, 2),
        sites.STREAM_SWAP: (_TRANSIENT_KINDS, 2),
    }
    full_domain = {
        sites.STREAM_UPDATE: (_TRANSIENT_KINDS + _KILL_KINDS, 2),
        sites.STREAM_SWAP: (_TRANSIENT_KINDS + _KILL_KINDS, 2),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    @staticmethod
    def _community_data(seed: int, n: int):
        rng = np.random.default_rng(seed)
        half = n // 2
        xa = np.stack([rng.integers(0, 15, half),
                       rng.integers(0, 10, half)], axis=1)
        xb = np.stack([rng.integers(15, _U, n - half),
                       rng.integers(10, _I, n - half)], axis=1)
        x = np.concatenate([xa, xb]).astype(np.int32)
        y = rng.integers(1, 6, n).astype(np.float32)
        return x, y

    def __init__(self):
        import tempfile

        from fia_tpu.api import FIAModel
        from fia_tpu.data.dataset import RatingDataset

        x, y = self._community_data(0, 240)
        self.fm = FIAModel(
            "MF", _U, _I, _K, _WD, batch_size=50,
            data_sets={"train": RatingDataset(x, y)},
            initial_learning_rate=1e-2, damping=_DAMP,
            train_dir=tempfile.mkdtemp(prefix="fia-chaos-stream-init-"),
            model_name="chaos-stream", solver="direct", seed=0,
        )
        # virtual time everywhere: retry backoff and staleness timers
        # must never sleep wall-clock in a chaos run
        self.fm._trainer.clock = rpolicy.VirtualClock()
        self.fm.train(self.BASE_STEPS, save_checkpoints=False,
                      verbose=False)
        self.base_state = self.fm.state
        self.base_train = self.fm.data_sets["train"]
        # both update batches live strictly inside community A; update 2
        # touches user 2 so the FENCE probe distinguishes mid from post
        self.upd1 = (np.array([[2, 3], [5, 1], [11, 8]], np.int32),
                     np.array([5.0, 4.0, 3.0], np.float32))
        self.upd2 = (np.array([[2, 5], [7, 2], [13, 6]], np.int32),
                     np.array([2.0, 5.0, 4.0], np.float32))

        # fault-free per-state references, each probe served alone (T=1)
        # so bytes are independent of batch composition
        self.ref_old = self._snapshot_refs()
        assert self.fm.apply_updates(*self.upd1, steps=self.STEPS,
                                     checkpoint_every=self.EVERY).committed
        self.ref_mid = self._snapshot_refs()
        assert self.fm.apply_updates(*self.upd2, steps=self.STEPS,
                                     checkpoint_every=self.EVERY).committed
        self.ref_new = self._snapshot_refs()
        self._reset()
        for p in self.UNTOUCHED:
            # the projection guarantee surgical invalidation rests on
            assert self.ref_old[p] == self.ref_mid[p] == self.ref_new[p], (
                f"untouched probe {p} moved across a footprinted update")
        assert self.ref_old[self.FENCE] != self.ref_mid[self.FENCE]
        assert self.ref_mid[self.FENCE] != self.ref_new[self.FENCE]

    def _reset(self):
        self.fm.state = self.base_state
        self.fm.data_sets["train"] = self.base_train
        self.fm._engines.clear()

    def _service(self):
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        return InfluenceService.from_model(
            self.fm, config=ServeConfig(), clock=rpolicy.VirtualClock())

    def _one(self, svc, pair, rid):
        from fia_tpu.serve.request import Request

        return svc.run([Request(pair[0], pair[1], id=rid)],
                       drain_every=1)[0]

    def _snapshot_refs(self) -> dict:
        svc = self._service()
        return {
            p: np.asarray(self._one(svc, p, f"ref{k}").scores).tobytes()
            for k, p in enumerate(self.TOUCHED + self.UNTOUCHED)
        }

    def _wave(self, svc, wave: str, refs: dict, out: dict,
              events: list) -> None:
        for k, p in enumerate(self.TOUCHED + self.UNTOUCHED):
            r = self._one(svc, p, f"{wave}{k}")
            match = bool(r.ok) and (
                np.asarray(r.scores).tobytes() == refs[p])
            events.append({"event": "probe_served", "wave": wave,
                           "probe": k, "match": match})
            if r.ok:
                out[f"{wave}{k}:scores"] = np.asarray(r.scores).copy()

    def _apply(self, svc, upd, events: list, tag: int,
               probe_on_rollback: bool):
        """One update under the chaos retry budget; a rolled-back
        attempt leaves its checkpoints behind, so the retry resumes."""
        for attempt in range(_CHAOS_RETRY.max_attempts):
            r = self.fm.apply_updates(*upd, steps=self.STEPS,
                                      checkpoint_every=self.EVERY)
            if r.committed:
                if attempt:
                    events.append({"event": "update_retried",
                                   "update": tag,
                                   "attempts": attempt + 1})
                return r
            events.append({"event": "update_rolled_back", "update": tag,
                           "reason": r.reason,
                           "resumed_step": r.resumed_step})
            if probe_on_rollback:
                # rollback must keep answering — on the OLD state
                pr = self._one(svc, self.FENCE, f"rb{tag}-{attempt}")
                events.append({
                    "event": "post_rollback_serve", "update": tag,
                    "ok": bool(pr.ok) and (
                        np.asarray(pr.scores).tobytes()
                        == self.ref_old[self.FENCE]),
                })
        raise taxonomy.DeadlineExpired(
            f"update {tag} never committed within the retry budget")

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.serve.request import Request

        self._reset()
        self.fm.train_dir = os.path.join(workdir, "train")
        svc = self._service()
        out: dict = {}

        self._wave(svc, "pre", self.ref_old, out, events)
        r1 = self._apply(svc, self.upd1, events, 1,
                         probe_on_rollback=True)
        self._wave(svc, "mid", self.ref_mid, out, events)

        # epoch fence: admitted before update 2, drained after — must
        # answer on its admission state whatever the update does
        assert svc.submit(Request(*self.FENCE, id="fence")) is None
        r2 = self._apply(svc, self.upd2, events, 2,
                         probe_on_rollback=False)
        fr = next(r for r in svc.drain() if r.id == "fence")
        events.append({"event": "probe_served", "wave": "fence",
                       "probe": 0,
                       "match": bool(fr.ok) and (
                           np.asarray(fr.scores).tobytes()
                           == self.ref_mid[self.FENCE])})
        if fr.ok:
            out["fence:scores"] = np.asarray(fr.scores).copy()
        self._wave(svc, "post", self.ref_new, out, events)

        st = svc.cache.stats
        events.append({"event": "swap_stats",
                       "rekeyed": int(st.rekeyed),
                       "rekey_dropped": int(st.rekey_dropped),
                       "disk_rekeyed": int(st.disk_rekeyed),
                       "disk_rekey_dropped": int(st.disk_rekey_dropped)})
        out["update1"] = r1.status
        out["update2"] = r2.status
        out["epochs"] = int(svc.epoch)
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        if record.error is not None or record.outcome is None:
            return []
        failures = []
        for e in record.events:
            if e.get("event") == "probe_served" and not e["match"]:
                failures.append(OracleFailure(
                    "epoch_serving_integrity",
                    f"wave {e['wave']} probe {e['probe']}: served bytes "
                    "do not match the reference of the state the request "
                    "was admitted under (stale or half-swapped answer)",
                ))
            elif e.get("event") == "post_rollback_serve" and not e["ok"]:
                failures.append(OracleFailure(
                    "rollback_keeps_serving",
                    f"after a rolled-back update {e['update']}, serving "
                    "did not answer bit-identically on the old state",
                ))
        stats = next((e for e in record.events
                      if e.get("event") == "swap_stats"), None)
        if stats is not None and (
                stats["rekeyed"] + stats["disk_rekeyed"]) == 0:
            failures.append(OracleFailure(
                "surgical_invalidation",
                "no cache entry survived the swaps by re-keying — the "
                "untouched community-B blocks must ride through a "
                "footprinted update without recompute",
            ))
        return failures


class UnlearnWhileServingScenario(Scenario):
    """An audited unlearning plan (``audit.plan.apply_plan``) flowing
    through the live epoch-fenced loop under serve traffic, mid-apply
    kills, and swap faults — docs/design.md §23.

    Structurally the unlearning twin of ``update_while_serving``: same
    two-community train set, but the deltas are REMOVALS chosen by a
    real reverse sweep (``audit.reverse.reverse_topk`` over community-A
    test points → ``build_plan``), not hand-picked appends. Apply 1 is
    a ``remove`` plan, apply 2 a ``reweight`` plan built against the
    shrunk post-removal set — exercising the stale-plan row-count gate
    on the retry path too (a rollback must restore the train set or the
    retry is refused as stale). Sweeping A-community test points keeps
    every plan row inside A by construction (a B row shares no user or
    item with an A test pair, so its sweep score is exactly zero and
    the ``only_negative`` filter drops it); construction asserts this,
    so community-B probes are provably outside both footprints' READ
    reach (stream/footprint.py). Oracles as in the update twin:

    - every probe byte-matches the reference of its admission state;
    - community-B probes are bit-identical in every wave;
    - a rolled-back apply keeps serving the old state; the retry
      (resuming the attempt's checkpoints) commits the golden bytes;
    - committed swaps re-key untouched entries, never wholesale-flush;
    - plan identity is deterministic: plan ids and predicted deltas
      must replay exactly against the golden run (``plan_determinism``).
    """

    name = "unlearn_while_serving"
    BASE_STEPS, STEPS, EVERY = 24, 16, 4
    # sweep provenance: test points inside community A (users 0-14 x
    # items 0-9); B probes below are untouched by construction
    TESTPTS = ((2, 3), (5, 1), (11, 8), (7, 2), (13, 6), (4, 4))
    TOUCHED = ((2, 3), (5, 1), (11, 8))
    UNTOUCHED = ((16, 12), (22, 17), (28, 11))
    FENCE = (2, 3)
    PLAN_ROWS = 3
    # each apply fires audit.apply once and stream.swap once on a
    # fault-free attempt: 2 guaranteed calls per site across the two
    # plans; the retry budget absorbs a worst-case 3-fault schedule
    benign_domain = {
        sites.AUDIT_APPLY: (_TRANSIENT_KINDS, 2),
        sites.STREAM_SWAP: (_TRANSIENT_KINDS, 2),
    }
    full_domain = {
        sites.AUDIT_APPLY: (_TRANSIENT_KINDS + _KILL_KINDS, 2),
        sites.STREAM_SWAP: (_TRANSIENT_KINDS + _KILL_KINDS, 2),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        import tempfile

        from fia_tpu.api import FIAModel
        from fia_tpu.audit.plan import build_plan
        from fia_tpu.audit.reverse import reverse_topk
        from fia_tpu.data.dataset import RatingDataset

        x, y = UpdateWhileServingScenario._community_data(1, 240)
        self.fm = FIAModel(
            "MF", _U, _I, _K, _WD, batch_size=50,
            data_sets={"train": RatingDataset(x, y)},
            initial_learning_rate=1e-2, damping=_DAMP,
            train_dir=tempfile.mkdtemp(prefix="fia-chaos-unlearn-init-"),
            model_name="chaos-unlearn", solver="direct", seed=0,
        )
        self.fm._trainer.clock = rpolicy.VirtualClock()
        self.fm.train(self.BASE_STEPS, save_checkpoints=False,
                      verbose=False)
        self.base_state = self.fm.state
        self.base_train = self.fm.data_sets["train"]

        pts = np.asarray(self.TESTPTS, np.int64)
        ty = np.asarray(self.base_train.y[:len(pts)], np.float32)

        def _plan(action):
            sweep = reverse_topk(self.fm, pts, ty, k=16)
            return build_plan(self.fm, sweep, action=action,
                              max_rows=self.PLAN_ROWS)

        # fault-free golden pass: plans + per-state references, each
        # probe served alone (T=1) so bytes are batch-independent
        self.ref_old = self._snapshot_refs()
        self.plan1 = _plan("remove")
        rx = np.asarray(self.base_train.x, np.int64)[self.plan1.row_ids]
        assert bool(np.all(rx[:, 0] < 15) and np.all(rx[:, 1] < 10)), (
            "sweep surfaced a community-B row for A-only test points")
        assert self._apply_plan(self.plan1).committed
        self.ref_mid = self._snapshot_refs()
        # the reweight plan is built against the SHRUNK train set — its
        # row-count stamp is what the stale-plan gate checks on retry
        self.plan2 = _plan("reweight")
        assert self._apply_plan(self.plan2).committed
        self.ref_new = self._snapshot_refs()
        self._reset()
        for p in self.UNTOUCHED:
            # both footprints' READ reach stops at the community border
            assert self.ref_old[p] == self.ref_mid[p] == self.ref_new[p], (
                f"untouched probe {p} moved across an unlearning apply")
        assert self.ref_old[self.FENCE] != self.ref_mid[self.FENCE]
        assert self.ref_mid[self.FENCE] != self.ref_new[self.FENCE]

    def _apply_plan(self, plan):
        from fia_tpu.audit.plan import apply_plan

        return apply_plan(self.fm, plan, steps=self.STEPS,
                          checkpoint_every=self.EVERY)

    def _reset(self):
        self.fm.state = self.base_state
        self.fm.data_sets["train"] = self.base_train
        self.fm._engines.clear()

    def _service(self):
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        return InfluenceService.from_model(
            self.fm, config=ServeConfig(), clock=rpolicy.VirtualClock())

    def _one(self, svc, pair, rid):
        from fia_tpu.serve.request import Request

        return svc.run([Request(pair[0], pair[1], id=rid)],
                       drain_every=1)[0]

    def _snapshot_refs(self) -> dict:
        svc = self._service()
        return {
            p: np.asarray(self._one(svc, p, f"ref{k}").scores).tobytes()
            for k, p in enumerate(self.TOUCHED + self.UNTOUCHED)
        }

    def _wave(self, svc, wave: str, refs: dict, out: dict,
              events: list) -> None:
        for k, p in enumerate(self.TOUCHED + self.UNTOUCHED):
            r = self._one(svc, p, f"{wave}{k}")
            match = bool(r.ok) and (
                np.asarray(r.scores).tobytes() == refs[p])
            events.append({"event": "probe_served", "wave": wave,
                           "probe": k, "match": match})
            if r.ok:
                out[f"{wave}{k}:scores"] = np.asarray(r.scores).copy()

    def _apply(self, svc, plan, events: list, tag: int,
               probe_on_rollback: bool):
        """One plan apply under the chaos retry budget; a rolled-back
        attempt restores the pre-apply train set (else the retry would
        be refused as a stale plan) and leaves its checkpoints behind,
        so the retry resumes rather than restarts."""
        for attempt in range(_CHAOS_RETRY.max_attempts):
            r = self._apply_plan(plan)
            if r.committed:
                if attempt:
                    events.append({"event": "apply_retried",
                                   "plan": plan.plan_id,
                                   "attempts": attempt + 1})
                return r
            events.append({"event": "apply_rolled_back", "apply": tag,
                           "plan": plan.plan_id, "reason": r.reason,
                           "resumed_step": r.resumed_step})
            if probe_on_rollback:
                pr = self._one(svc, self.FENCE, f"rb{tag}-{attempt}")
                events.append({
                    "event": "post_rollback_serve", "update": tag,
                    "ok": bool(pr.ok) and (
                        np.asarray(pr.scores).tobytes()
                        == self.ref_old[self.FENCE]),
                })
        raise taxonomy.DeadlineExpired(
            f"plan {plan.plan_id} never committed within the retry budget")

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.serve.request import Request

        self._reset()
        self.fm.train_dir = os.path.join(workdir, "train")
        svc = self._service()
        out: dict = {}

        self._wave(svc, "pre", self.ref_old, out, events)
        r1 = self._apply(svc, self.plan1, events, 1,
                         probe_on_rollback=True)
        self._wave(svc, "mid", self.ref_mid, out, events)

        # epoch fence: admitted before the reweight apply, drained
        # after — must answer on its admission state (post-removal)
        assert svc.submit(Request(*self.FENCE, id="fence")) is None
        r2 = self._apply(svc, self.plan2, events, 2,
                         probe_on_rollback=False)
        fr = next(r for r in svc.drain() if r.id == "fence")
        events.append({"event": "probe_served", "wave": "fence",
                       "probe": 0,
                       "match": bool(fr.ok) and (
                           np.asarray(fr.scores).tobytes()
                           == self.ref_mid[self.FENCE])})
        if fr.ok:
            out["fence:scores"] = np.asarray(fr.scores).copy()
        self._wave(svc, "post", self.ref_new, out, events)

        st = svc.cache.stats
        events.append({"event": "swap_stats",
                       "rekeyed": int(st.rekeyed),
                       "rekey_dropped": int(st.rekey_dropped),
                       "disk_rekeyed": int(st.disk_rekeyed),
                       "disk_rekey_dropped": int(st.disk_rekey_dropped)})
        out["apply1"] = r1.status
        out["apply2"] = r2.status
        out["plan1"] = self.plan1.plan_id
        out["plan2"] = self.plan2.plan_id
        out["predicted_delta1"] = round(self.plan1.predicted_delta, 6)
        out["predicted_delta2"] = round(self.plan2.predicted_delta, 6)
        out["train_rows"] = len(self.fm.data_sets["train"].x)
        out["epochs"] = int(svc.epoch)
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        if record.error is not None or record.outcome is None:
            return []
        failures = []
        for e in record.events:
            if e.get("event") == "probe_served" and not e["match"]:
                failures.append(OracleFailure(
                    "epoch_serving_integrity",
                    f"wave {e['wave']} probe {e['probe']}: served bytes "
                    "do not match the reference of the state the request "
                    "was admitted under (stale or half-swapped answer)",
                ))
            elif e.get("event") == "post_rollback_serve" and not e["ok"]:
                failures.append(OracleFailure(
                    "rollback_keeps_serving",
                    f"after a rolled-back apply {e['update']}, serving "
                    "did not answer bit-identically on the old state",
                ))
        for key in ("plan1", "plan2", "predicted_delta1",
                    "predicted_delta2", "train_rows"):
            if record.outcome.get(key) != golden.get(key):
                failures.append(OracleFailure(
                    "plan_determinism",
                    f"{key} diverged from the golden run: "
                    f"{record.outcome.get(key)!r} != {golden.get(key)!r} "
                    "— plan identity must be a pure function of the "
                    "sweep, not of the fault schedule",
                ))
        stats = next((e for e in record.events
                      if e.get("event") == "swap_stats"), None)
        if stats is not None and (
                stats["rekeyed"] + stats["disk_rekeyed"]) == 0:
            failures.append(OracleFailure(
                "surgical_invalidation",
                "no cache entry survived the swaps by re-keying — the "
                "untouched community-B blocks must ride through a "
                "footprinted unlearning apply without recompute",
            ))
        return failures


class ServeBrownoutScenario(Scenario):
    """Certified-approximate serving through a forced brownout episode
    (docs/design.md §22, docs/reliability.md "Degraded modes").

    Wave A serves four cold misses in ``full`` mode — real dispatches
    plus disk-tier publishes (the damage point; no wave-A key is ever
    re-read, so benign damage is invisible to the outcome). A synthetic
    sick-backend drain signal is then fed to the health controller —
    deterministic, identical in golden and chaos runs — forcing
    ``full → bank_preferred``. Wave B mixes two banked pairs (exact
    O(1) bank hits) with four unbanked misses that must be ANSWERED
    from the certified ``sampled`` rung, ``approx=True`` with a stamped
    error bound, instead of shed ``degraded``.

    The scenario oracle (``certified_approx_integrity``) holds every
    approx answer to its own certificate: |served − direct reference|
    must stay within the stamped bound. A transient fault at
    ``engine.sampled_solve`` escalates the whole sampled micro-batch
    one ladder rung — those answers must then byte-match the
    escalation-rung reference (computed per micro-batch, since the
    fallback re-solves the batch verbatim) and drop the approx stamp.
    Either way, an in-bounds query is never rejected ``degraded``.
    """

    name = "serve_brownout"
    MAX_BATCH = 3
    NWARM, NBANK, NAPPROX = 4, 2, 4
    SAMPLED_CAP = 16  # < typical block count: genuinely subsampled
    # wave-A misses publish 4 disk entries; approx answers never
    # publish, so damage is bounded by the exact-path dispatches
    benign_domain = {
        sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 4),
    }
    # 2 guaranteed sampled dispatches (NAPPROX=4 misses, micro-batches
    # of MAX_BATCH=3): the fire seam's call index is the dispatch
    # ordinal, and an escalated batch still lets the next one dispatch
    full_domain = {
        sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 4),
        sites.ENGINE_SAMPLED_SOLVE: (_TRANSIENT_KINDS, 2),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        import tempfile

        import jax

        from fia_tpu.data.dataset import RatingDataset
        from fia_tpu.influence import factor as fbank
        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.models import MF

        x, y = _toy_data(3, 400)
        self.model = MF(_U, _I, _K, _WD)
        params = self.model.init_params(jax.random.PRNGKey(0))
        train = RatingDataset(x, y)
        self.eng = InfluenceEngine(
            self.model, params, train, damping=_DAMP,
            solver="precomputed", cache_dir=tempfile.mkdtemp(
                prefix="fia-chaos-brownout-init-"),
            model_name="chaos-brownout", lissa_depth=30,
            kernel="xla_analytic", sampled_cap=self.SAMPLED_CAP)
        pairs = fbank.select_hot_pairs(
            self.eng.index, max_entries=self.NBANK + 2,
            top_users=4, top_items=4)
        self.bank = fbank.build_bank(self.eng, pairs)
        self.fp = fbank.bank_fingerprint(
            "chaos-brownout", self.model.block_size, _DAMP,
            *self.eng._train_host)
        banked = {(int(u), int(i)) for u, i in pairs}
        self.bank_pairs = sorted(banked)[: self.NBANK]
        fresh: list = []
        for u, i in zip(x[:, 0], x[:, 1]):
            p = (int(u), int(i))
            if p not in banked and p not in fresh:
                fresh.append(p)
        self.warm_pairs = fresh[: self.NWARM]
        self.approx_pairs = fresh[self.NWARM: self.NWARM + self.NAPPROX]

        # fault-free references for the oracle: the exact answer each
        # certificate bounds against, and the escalation-rung bytes a
        # fault-escalated sampled batch must reproduce — per service
        # micro-batch, because escalation re-solves the batch verbatim
        direct = InfluenceEngine(
            self.model, params, train, damping=_DAMP, solver="direct",
            model_name="chaos-brownout", kernel="xla_analytic")
        ladder = InfluenceEngine(
            self.model, params, train, damping=_DAMP,
            solver=rpolicy.next_solver("sampled") or "direct",
            model_name="chaos-brownout", lissa_depth=30,
            kernel="xla_analytic")
        self.ref_direct: dict = {}
        self.ref_ladder: dict = {}
        for lo in range(0, self.NAPPROX, self.MAX_BATCH):
            chunk = self.approx_pairs[lo: lo + self.MAX_BATCH]
            pts = np.asarray(chunk, np.int64)
            res_d = direct.query_batch(pts)
            res_l = ladder.query_batch(pts)
            for t, p in enumerate(chunk):
                self.ref_direct[p] = np.asarray(
                    res_d.scores_of(t)).copy()
                self.ref_ladder[p] = np.asarray(
                    res_l.scores_of(t)).tobytes()

    def run(self, workdir: str, events: list) -> dict:
        from fia_tpu.influence import factor as fbank
        from fia_tpu.serve.health import MODE_BANK_PREFERRED, HealthConfig
        from fia_tpu.serve.request import Request
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        eng = self.eng
        eng.cache_dir = os.path.join(workdir, "cache")
        eng.unload_factor_bank()
        eng.solver = "precomputed"  # undo any sticky prior escalation
        path = fbank.default_bank_path(eng.cache_dir, eng.model_name)
        fbank.publish_bank(self.bank, path, self.fp)
        if eng.ensure_factor_bank() == 0:
            raise RuntimeError("serve_brownout: factor bank not loaded")
        svc = InfluenceService(
            engine=eng,
            config=ServeConfig(
                max_batch=self.MAX_BATCH,
                health=HealthConfig(
                    window=4, err_degrade=0.5, err_cache_only=2.0,
                    err_recover=0.25, min_evidence=2, queue_hold=3,
                    hold=8),
            ),
            clock=rpolicy.VirtualClock(),
        )
        # wave A: cold misses in full mode (dispatches + publishes)
        for j, p in enumerate(self.warm_pairs):
            svc.submit(Request(*p, id=f"w{j}"))
        res_a = svc.drain()
        # the forced episode: one synthetic sick-backend drain signal —
        # min_evidence=2 is met and the windowed error rate crosses
        # err_degrade, so the ladder steps to bank_preferred; hold=8
        # keeps it there for the remainder of the run
        svc.health.observe(errors=8, dispatches=8, queue_depth=0,
                           queue_cap=svc.admission.max_queue)
        if svc.health.mode != MODE_BANK_PREFERRED:
            raise RuntimeError(
                f"forced brownout did not engage ({svc.health.mode})")
        events.append({"event": "brownout_forced",
                       "mode": svc.health.mode})
        # wave B: bank hits + unbanked misses under bank_preferred
        for j, p in enumerate(self.bank_pairs):
            svc.submit(Request(*p, id=f"b{j}"))
        for j, p in enumerate(self.approx_pairs):
            svc.submit(Request(*p, id=f"a{j}"))
        res_b = svc.drain()

        out: dict = {"mode": svc.health.mode}
        for r in res_a + res_b:
            out[f"{r.id}:status"] = f"{r.status}/{r.reason or ''}"
            out[f"{r.id}:approx"] = int(bool(r.approx))
            out[f"{r.id}:err"] = (float(r.err_bound)
                                  if r.err_bound is not None else -1.0)
            if r.ok:
                out[f"{r.id}:scores"] = np.asarray(r.scores).copy()
        roll = svc.rollup()
        out["answered_approx"] = int(roll["answered_approx"])
        out["rejected_degraded"] = int(
            roll["rejected"].get("degraded", 0))
        events.append({"event": "serve_rollup",
                       "answered_approx": int(roll["answered_approx"]),
                       "modes": roll["modes"]})
        return out

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure

        if record.error is not None or record.outcome is None:
            return []
        got = record.outcome
        failures = []
        for j, p in enumerate(self.approx_pairs):
            rid = f"a{j}"
            status = str(got.get(f"{rid}:status", "<missing>"))
            if status != "ok/":
                failures.append(OracleFailure(
                    "certified_approx_integrity",
                    f"{rid}: in-bounds brownout miss not answered "
                    f"(got {status}) — certified approx serving must "
                    "replace the degraded shed",
                ))
                continue
            scores = np.asarray(got[f"{rid}:scores"])
            if got.get(f"{rid}:approx"):
                eb = float(got.get(f"{rid}:err", -1.0))
                ref = self.ref_direct[p]
                diff = (float(np.max(np.abs(scores - ref)))
                        if scores.size else 0.0)
                if eb < 0.0:
                    failures.append(OracleFailure(
                        "certified_approx_integrity",
                        f"{rid}: approx answer with no stamped "
                        "err_bound",
                    ))
                elif diff > eb + 1e-6:
                    failures.append(OracleFailure(
                        "certified_approx_integrity",
                        f"{rid}: served score error {diff:.3e} exceeds "
                        f"the stamped certificate {eb:.3e}",
                    ))
            elif scores.tobytes() != self.ref_ladder[p]:
                # a sampled-solve fault escalates the whole micro-batch
                # one rung; an un-stamped answer matching neither
                # reference is the silent-wrong-answer class
                failures.append(OracleFailure(
                    "certified_approx_integrity",
                    f"{rid}: un-stamped answer does not byte-match the "
                    "escalation-rung reference (silent wrong answer)",
                ))
        degraded = int(got.get("rejected_degraded", 0))
        if degraded:
            failures.append(OracleFailure(
                "certified_approx_integrity",
                f"{degraded} request(s) shed 'degraded' while the "
                "sampled rung was allowed to answer them",
            ))
        return failures


class ServeMultitenantScenario(Scenario):
    """Multi-tenant overload survival: quotas, fair queueing, and the
    class-aware brownout under a 2× scavenger flood
    (docs/reliability.md "Multi-tenant serving & fairness").

    Three phases over one service on a deterministic tick clock:

    - **U (unloaded)**: an interactive-only wave establishes the
      unloaded interactive latency baseline.
    - **O (overload)**: a scavenger flood at 2× its queue quota (the
      excess must shed class-tagged ``overload`` without consuming
      interactive headroom — every interactive/batch submit after the
      flood still admits), mixed with interactive + batch traffic. A
      *scripted* ``serve.dispatch`` fault — identical in golden and
      chaos runs — kills exactly the first scavenger batch: because
      batches are class-pure, the shed hits only scavenger waiters.
    - **B (brownout)**: a synthetic sick-backend signal (min_evidence
      is set far above what organic traffic can accumulate, so
      injected faults can never move the ladder — the transition log's
      mode path is fault-invariant) forces ``bank_preferred``.
      Interactive misses must still be answered EXACT (the class-aware
      ladder leaves interactive at full until severity 2) while
      scavenger misses come back certified-approximate.

    Scenario oracles: **starvation_bound** (every admitted request
    resolves within STARVATION_BOUND_S of virtual queue wait, even
    while lower-priority work dispatches), **class_isolation**
    (interactive p99 under the flood within ISOLATION_FACTOR of its
    unloaded p99), **class_batch_purity** (every dispatched batch id —
    served or shed — carries exactly one class), admission/quota
    determinism, classified rejections, and the brownout ladder's
    (from, to, tick) path vs golden. Benign schedules additionally get
    whole-outcome bit identity from the standard battery.
    """

    name = "serve_multitenant"
    MAX_BATCH, MAX_QUEUE = 3, 12
    N_UNLOADED = 4          # phase-U interactive wave
    N_FLOOD = 13            # scavenger submits (quota cap is 6 → 2×+)
    N_OVER_I, N_OVER_B = 4, 2  # interactive/batch riding the flood
    N_BROWN = 3             # per-class phase-B misses
    FAULT_ORDINAL = 3       # phase-O dispatch #3 = first scavenger batch
    STARVATION_BOUND_S = 1.0   # virtual seconds (ticks of 1e-3)
    ISOLATION_FACTOR = 3.0
    # exact-path publishes on a shed-free run: 4 (U) + 9 (O, the shed
    # scavenger batch never publishes) + 3 (B) = 16; approx answers
    # never publish. Damage is invisible to the outcome (no key is
    # ever re-read), so the benign domain stays bit-identical.
    benign_domain = {
        sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 12),
    }
    # every planned batch fires serve.dispatch before its device call
    # (9 fires on the undisturbed run: 2 U + 5 O + 2 B); injected
    # faults shed exactly the class-pure batch they land on
    full_domain = {
        sites.SERVE_DISPATCH: (
            (taxonomy.WORKER, taxonomy.OOM, taxonomy.DEADLINE), 6),
        sites.SERVE_CACHE_PUBLISH: (_DAMAGE_KINDS, 4),
        sites.CHAOS_SCENARIO: ((taxonomy.WORKER,), 1),
    }

    def __init__(self):
        import jax

        from fia_tpu.data.dataset import RatingDataset
        from fia_tpu.influence.engine import InfluenceEngine
        from fia_tpu.models import MF

        x, y = _toy_data(7, 400)
        self.model = MF(_U, _I, _K, _WD)
        self.params = self.model.init_params(jax.random.PRNGKey(0))
        self.train_ds = RatingDataset(x, y)
        self.engine = InfluenceEngine(
            self.model, self.params, self.train_ds, damping=_DAMP,
            model_name="chaos-multitenant", kernel="xla_analytic")
        rng = np.random.default_rng(11)
        flat = rng.choice(_U * _I, size=32, replace=False)
        keys = [(int(k // _I), int(k % _I)) for k in flat]
        it = iter(keys)

        def take(n):
            return [next(it) for _ in range(n)]

        self.unloaded_keys = take(self.N_UNLOADED)
        self.flood_keys = take(self.N_FLOOD)
        self.over_i_keys = take(self.N_OVER_I)
        self.over_b_keys = take(self.N_OVER_B)
        self.brown_i_keys = take(self.N_BROWN)
        self.brown_s_keys = take(self.N_BROWN)

    class _TickClock:
        """Deterministic monotonic stand-in: every read advances one
        fixed tick, so queue waits measure dispatch ORDER (the thing
        fair queueing controls), identically across replays."""

        def __init__(self):
            self.t = 0.0

        def __call__(self):
            self.t += 1e-3
            return self.t

    def run(self, workdir: str, events: list) -> dict:
        import json

        from fia_tpu.serve.health import MODE_BANK_PREFERRED, HealthConfig
        from fia_tpu.serve.request import Request
        from fia_tpu.serve.service import InfluenceService, ServeConfig

        eng = self.engine
        eng.cache_dir = os.path.join(workdir, "cache")
        svc = InfluenceService(
            engine=eng,
            config=ServeConfig(
                max_batch=self.MAX_BATCH, max_queue=self.MAX_QUEUE,
                dispatch_window=1,  # scripted fault needs query_batch
                class_quotas={"scavenger": 0.5},
                health=HealthConfig(
                    window=4, err_degrade=0.5, err_cache_only=2.0,
                    err_recover=0.25, min_evidence=50, queue_hold=3,
                    hold=8),
            ),
            clock=self._TickClock(),
        )
        responses = []

        # phase U: unloaded interactive baseline
        for j, p in enumerate(self.unloaded_keys):
            svc.submit(Request(*p, id=f"u{j}", cls="interactive",
                               tenant="t-int"))
        responses += svc.drain()

        # phase O: 2× scavenger flood + interactive/batch riders. The
        # flood goes FIRST: its quota rejections prove it cannot eat
        # the headroom the later interactive/batch submits then use.
        for j, p in enumerate(self.flood_keys):
            r = svc.submit(Request(*p, id=f"s{j}", cls="scavenger",
                                   tenant="t-scav"))
            if r is not None:
                responses.append(r)
        for j, p in enumerate(self.over_i_keys):
            svc.submit(Request(*p, id=f"i{j}", cls="interactive",
                               tenant="t-int"))
        for j, p in enumerate(self.over_b_keys):
            svc.submit(Request(*p, id=f"m{j}", cls="batch",
                               tenant="t-bulk"))
        # scripted serve.dispatch fault, part of the workload itself
        # (identical in golden and chaos runs): the FAULT_ORDINAL-th
        # exact dispatch of this drain is the first scavenger batch —
        # interactive/batch dispatch ahead of it under DRR priority
        orig_qb = eng.query_batch
        calls = {"n": 0}

        def scripted(pts):
            n = calls["n"]
            calls["n"] += 1
            if n == self.FAULT_ORDINAL:
                raise taxonomy.DeadlineExpired(
                    "scripted chaos fault: scavenger batch dispatch")
            return orig_qb(pts)

        eng.query_batch = scripted
        try:
            responses += svc.drain()
        finally:
            eng.query_batch = orig_qb

        # phase B: forced brownout (deterministic synthetic signal; 60
        # dispatches of evidence meets min_evidence=50 on its own —
        # organic drains never can)
        svc.health.observe(errors=60, dispatches=60, queue_depth=0,
                           queue_cap=svc.admission.max_queue)
        if svc.health.mode != MODE_BANK_PREFERRED:
            raise RuntimeError(
                f"forced brownout did not engage ({svc.health.mode})")
        events.append({"event": "brownout_forced",
                       "mode": svc.health.mode})
        for j, p in enumerate(self.brown_i_keys):
            svc.submit(Request(*p, id=f"bi{j}", cls="interactive",
                               tenant="t-int"))
        for j, p in enumerate(self.brown_s_keys):
            svc.submit(Request(*p, id=f"bs{j}", cls="scavenger",
                               tenant="t-scav"))
        responses += svc.drain()

        out: dict = {"mode": svc.health.mode}
        for r in responses:
            out[f"{r.id}:status"] = f"{r.status}/{r.reason or ''}"
            out[f"{r.id}:class"] = r.cls
            out[f"{r.id}:wait"] = float(r.queue_wait_s)
            out[f"{r.id}:batch"] = (-1 if r.batch_id is None
                                    else int(r.batch_id))
            out[f"{r.id}:approx"] = int(bool(r.approx))
            if r.ok:
                out[f"{r.id}:scores"] = np.asarray(r.scores).copy()
        # the ladder's mode path must replay identically even under
        # injected dispatch faults (signal VALUES may differ there;
        # benign bit-identity covers the full log)
        out["transitions"] = json.dumps(
            [(t["from"], t["to"], t["tick"])
             for t in svc.health.transitions])
        roll = svc.rollup()
        out["answered_approx"] = int(roll["answered_approx"])
        events.append({"event": "serve_rollup",
                       "classes": roll["classes"],
                       "rejected": roll["rejected"]})
        return out

    def _ids(self):
        return (
            [f"u{j}" for j in range(self.N_UNLOADED)]
            + [f"s{j}" for j in range(self.N_FLOOD)]
            + [f"i{j}" for j in range(self.N_OVER_I)]
            + [f"m{j}" for j in range(self.N_OVER_B)]
            + [f"bi{j}" for j in range(self.N_BROWN)]
            + [f"bs{j}" for j in range(self.N_BROWN)]
        )

    def check(self, golden: dict, record) -> list:
        from fia_tpu.chaos.oracles import OracleFailure
        from fia_tpu.serve import admission

        if record.error is not None or record.outcome is None:
            return []
        got = record.outcome
        failures = []
        allowed = {
            taxonomy.OOM, taxonomy.HOST_OOM, taxonomy.AMBIGUOUS,
            taxonomy.WORKER, taxonomy.PREEMPTION, taxonomy.NAN,
            taxonomy.DEADLINE, taxonomy.DEVICE_LOST, taxonomy.HOST_LOST,
            admission.REASON_OVERLOAD, admission.REASON_INVALID,
            admission.REASON_DEGRADED,
        }
        admission_reasons = ("/" + admission.REASON_OVERLOAD,
                            "/" + admission.REASON_INVALID)
        waits_unloaded, waits_overload = [], []
        by_batch: dict[int, set] = {}
        for rid in self._ids():
            status = str(got.get(f"{rid}:status", "<missing>"))
            if status == "<missing>":
                failures.append(OracleFailure(
                    "starvation_bound",
                    f"{rid}: admitted request never resolved",
                ))
                continue
            gs = str(golden.get(f"{rid}:status", "<missing>"))
            # admission decisions are a pure function of the submit
            # stream + quotas — faults cannot move them
            for adm in admission_reasons:
                if gs.endswith(adm) != status.endswith(adm):
                    failures.append(OracleFailure(
                        "admission_determinism",
                        f"{rid}: golden {gs} vs chaos {status}",
                    ))
            if status.startswith("rejected/"):
                reason = status.split("/", 1)[1]
                if reason not in allowed:
                    failures.append(OracleFailure(
                        "classified_rejection",
                        f"{rid}: unclassified rejection {reason!r}",
                    ))
                if reason in (admission.REASON_OVERLOAD,
                              admission.REASON_INVALID):
                    continue  # refused at the door: no wait to bound
            # admitted (served, or admitted-then-shed): bounded wait
            wait = float(got.get(f"{rid}:wait", 0.0))
            if wait > self.STARVATION_BOUND_S:
                failures.append(OracleFailure(
                    "starvation_bound",
                    f"{rid}: queue wait {wait:.3f}s exceeds the "
                    f"{self.STARVATION_BOUND_S}s starvation bound",
                ))
            if rid.startswith("u"):
                waits_unloaded.append(wait)
            elif rid.startswith("i"):
                waits_overload.append(wait)
            bid = int(got.get(f"{rid}:batch", -1))
            if bid >= 0:
                by_batch.setdefault(bid, set()).add(
                    str(got.get(f"{rid}:class")))
        for bid, classes in sorted(by_batch.items()):
            if len(classes) != 1:
                failures.append(OracleFailure(
                    "class_batch_purity",
                    f"batch {bid} mixes classes {sorted(classes)} — "
                    "a fault there cannot shed a single class",
                ))
        if waits_unloaded and waits_overload:
            p99_u = float(np.percentile(waits_unloaded, 99))
            p99_o = float(np.percentile(waits_overload, 99))
            if p99_o > self.ISOLATION_FACTOR * max(p99_u, 1e-9):
                failures.append(OracleFailure(
                    "class_isolation",
                    f"interactive p99 under 2× scavenger overload "
                    f"({p99_o:.4f}s) exceeds {self.ISOLATION_FACTOR}× "
                    f"its unloaded p99 ({p99_u:.4f}s)",
                ))
        if str(got.get("transitions")) != str(golden.get("transitions")):
            failures.append(OracleFailure(
                "brownout_replay",
                f"ladder mode path diverged: golden "
                f"{golden.get('transitions')} vs chaos "
                f"{got.get('transitions')}",
            ))
        # the class-aware ladder: phase-B interactive misses answer
        # EXACT; scavenger misses answer certified-approximate (an
        # injected dispatch fault may shed them classified instead —
        # but an answered one must carry the right stamp)
        for j in range(self.N_BROWN):
            bi = f"bi{j}"
            if (str(got.get(f"{bi}:status")) == "ok/"
                    and int(got.get(f"{bi}:approx", 0))):
                failures.append(OracleFailure(
                    "class_aware_brownout",
                    f"{bi}: interactive answered approximate at "
                    "severity 1 — interactive degrades only at "
                    "severity 2",
                ))
            bs = f"bs{j}"
            if (str(got.get(f"{bs}:status")) == "ok/"
                    and not int(got.get(f"{bs}:approx", 0))):
                failures.append(OracleFailure(
                    "class_aware_brownout",
                    f"{bs}: scavenger brownout miss answered exact — "
                    "the sampled rung must absorb scavenger work "
                    "first",
                ))
        return failures


def make_scenarios() -> dict:
    """Fresh scenario registry (instances are lazily constructed so the
    selftest path never imports jax)."""
    return {
        SelftestScenario.name: SelftestScenario,
        SelftestBrokenScenario.name: SelftestBrokenScenario,
        TrainResumeScenario.name: TrainResumeScenario,
        QueryCacheScenario.name: QueryCacheScenario,
        ServeStreamScenario.name: ServeStreamScenario,
        ServeStreamMeshScenario.name: ServeStreamMeshScenario,
        DeviceLossRecoveryScenario.name: DeviceLossRecoveryScenario,
        HostLossRecoveryScenario.name: HostLossRecoveryScenario,
        FactorBankScenario.name: FactorBankScenario,
        UpdateWhileServingScenario.name: UpdateWhileServingScenario,
        UnlearnWhileServingScenario.name: UnlearnWhileServingScenario,
        ServeBrownoutScenario.name: ServeBrownoutScenario,
        ServeMultitenantScenario.name: ServeMultitenantScenario,
    }


SCENARIO_NAMES = tuple(make_scenarios())
