"""Subsampled block-Hessian estimation with a concentration certificate.

The ``sampled`` solver rung (docs/design.md §22) sits between the
``precomputed`` bank and ``lissa`` on the degradation ladder
(``reliability/policy.py``): instead of accumulating the block Hessian
over *every* related training row of a query, it accumulates over a
fixed-size subsample and serves the resulting iHVP with an explicit
per-query error bound. "Faithful and Fast Influence Function via
Advanced Sampling" (arXiv:2510.26776) motivates the estimator;
arXiv:2409.17357 the error-controlled serving policy built on top.

Estimator. With ``n`` related rows and a sample of ``m`` positions,
each sampled row carries weight ``n/m`` in the Hessian accumulation
(Horvitz–Thompson), so ``E[H_m] = H`` and the unsampled score pass —
which always runs over ALL rows — is untouched. At ``m == n`` the
weights collapse to 1 and the program is bit-identical to the exact
flat path's Hessian.

Certificate. Write the per-row Hessian action on the solved vector
``x`` as ``h_s(x) = wv_s g_s (g_s·x) + ab_s e_s (C x)`` so that
``H x = (2/n) Σ_s h_s(x) + (rdiag + damping) ⊙ x``. The sampled
Hessian's defect is then ``ΔH x = 2 (mean_S h − mean_all h)``, a
mean-of-samples deviation whose scale is estimated by the sample
standard deviation ``σ̂`` of ``h_s(x)`` over the sampled rows:

    ‖ΔH x‖ ≲ 2 z σ̂ fpc / √m,   fpc = √((n − m)/(n − 1))

(finite-population correction; zero at ``m == n``, i.e. the bound is
exactly 0 when nothing was left out). Pushing through the inverse with
``λ_min(H) ≥ damping`` gives the iHVP error, and the fused score form
``score_s = wv_s (2 e_s (g_s·ihvp) + reg_dot) / n`` turns that into a
per-row score bound via segment maxima (``score_error_bound``).

Host-side sampling is deterministic: positions are drawn from a Philox
stream keyed on the (u, i) pair itself, so a query's sample — and its
served score and bound — is reproducible across dispatches, batch
compositions, and processes.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# Confidence multiplier for the one-sided deviation estimate: ~3-sigma,
# validated empirically by the bench fidelity gate (|sampled − direct|
# within the stamped bound on >= 99% of a fixed-seed query slice).
CONFIDENCE_Z = 3.0

# Philox key-domain separator so the sampler's stream can never collide
# with data-generation or training streams keyed on small integers.
SAMPLE_DOMAIN = 0x5AE1

# Default per-query Hessian sample cap (rows). Queries with fewer
# related rows than the cap are exact (err_bound == 0).
DEFAULT_CAP = 64


def sample_weights(
    pairs: np.ndarray,
    counts: np.ndarray,
    s_pad: int,
    cap: int,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-dispatch Hessian sample-weight vector, host-side.

    ``pairs`` is the (T, 2) int query array, ``counts`` the (T,)
    related-row counts in flat-row order (query t's rows occupy the
    contiguous span ``[offset_t, offset_t + n_t)`` of the concatenated
    postings, exactly the layout ``_flat_prelude`` reconstructs on
    device). Returns ``(ws, m)``: ``ws`` is the (s_pad,) float32 weight
    vector — ``n_t / m_t`` at sampled positions, 0 elsewhere (including
    every pad row) — and ``m`` the (T,) int32 sample sizes.
    """
    total = int(np.sum(counts))
    if total > s_pad:
        raise ValueError(f"flat rows {total} exceed s_pad {s_pad}")
    ws = np.zeros(s_pad, np.float32)
    m = np.zeros(len(counts), np.int32)
    off = 0
    for t, n in enumerate(int(c) for c in counts):
        mt = min(n, int(cap))
        m[t] = mt
        if mt >= n:
            ws[off:off + n] = 1.0
        elif mt > 0:
            u, i = int(pairs[t][0]), int(pairs[t][1])
            # 2x64-bit Philox key: (domain ‖ seed, u ‖ i)
            gen = np.random.Generator(np.random.Philox(
                key=np.array(
                    [(SAMPLE_DOMAIN << 32) ^ (seed & 0xFFFFFFFF),
                     ((u & 0xFFFFFFFF) << 32) | (i & 0xFFFFFFFF)],
                    dtype=np.uint64)))
            idx = gen.choice(n, size=mt, replace=False)
            ws[off + idx] = np.float32(n) / np.float32(mt)
        off += n
    return ws, m


def segment_sample_std(
    h: jnp.ndarray,
    ws: jnp.ndarray,
    t: jnp.ndarray,
    m: jnp.ndarray,
    num_segments: int,
) -> jnp.ndarray:
    """``σ̂_t``: per-query sample std of the per-row vectors ``h_s``
    over the sampled rows (``ws > 0``), jit-safe.

    ``h`` is (S, d), ``ws`` (S,), ``t`` (S,) segment ids, ``m`` (T,)
    sample sizes. Pad rows carry ``ws == 0`` and drop out of every sum.
    """
    mask = (ws > 0).astype(h.dtype)
    cnt = jnp.maximum(m.astype(h.dtype), 1.0)
    mu = jax.ops.segment_sum(h * mask[:, None], t, num_segments)
    mu = mu / cnt[:, None]
    diff = (h - mu[t]) * mask[:, None]
    ss = jax.ops.segment_sum(jnp.sum(diff * diff, axis=1), t,
                             num_segments)
    dof = jnp.maximum(m.astype(h.dtype) - 1.0, 1.0)
    return jnp.sqrt(ss / dof)


def ihvp_error_bound(
    sigma: jnp.ndarray,
    m: jnp.ndarray,
    n: jnp.ndarray,
    lam,
) -> jnp.ndarray:
    """``‖x_m − x‖`` bound per query from the sample deviation.

    ``2 z σ̂ fpc / (√m · λ)`` — the 2 is the Hessian's ``2/n`` loss
    convention, ``lam`` lower-bounds ``λ_min(H)`` (a scalar damping
    floor or a per-query measured spectrum), and the finite-population
    correction zeroes the bound at ``m == n``.
    """
    mf = jnp.maximum(m.astype(sigma.dtype), 1.0)
    nf = jnp.maximum(n.astype(sigma.dtype), 1.0)
    fpc = jnp.sqrt(jnp.clip(nf - mf, 0.0, None)
                   / jnp.maximum(nf - 1.0, 1.0))
    return 2.0 * CONFIDENCE_Z * sigma * fpc / (jnp.sqrt(mf) * lam)


def score_error_bound(
    gmax: jnp.ndarray,
    wmax: jnp.ndarray,
    regnorm: jnp.ndarray,
    err_ihvp: jnp.ndarray,
    n: jnp.ndarray,
) -> jnp.ndarray:
    """Per-query bound on ``max_s |score_s − score_s^exact|``.

    From the fused score form ``wv (2 e (g·x) + reg_dot) / n``:
    ``gmax`` is the segment max of ``wv_s · 2|e_s| · ‖g_s‖``, ``wmax``
    the segment max of ``wv_s``, ``regnorm = ‖rdiag ⊙ θ_t‖`` (the
    ``reg_dot`` term's Lipschitz constant in ``x``).
    """
    nf = jnp.maximum(n.astype(err_ihvp.dtype), 1.0)
    return (gmax + wmax * regnorm) * err_ihvp / nf
