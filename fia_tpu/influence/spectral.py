"""Hessian spectrum tools.

The reference ships a ``find_eigvals_of_hessian`` whose power-iteration
loop was deleted (it reads ``norm_val`` before assignment,
``genericNeuralNet.py:768-808`` — dead code). This is the working
equivalent: power iteration for the dominant eigenvalue, with a shifted
second pass for the smallest, usable on any matrix-free HVP (block or
full-parameter); plus exact eigenvalues for materialised block Hessians.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def power_iteration(
    hvp: Callable[[jnp.ndarray], jnp.ndarray],
    dim: int,
    num_iters: int = 100,
    key=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(eigval, eigvec) of the dominant eigenpair of the symmetric
    operator ``hvp``."""
    if key is None:
        key = jax.random.PRNGKey(0)
    v0 = jax.random.normal(key, (dim,))
    v0 = v0 / jnp.linalg.norm(v0)

    def body(_, v):
        w = hvp(v)
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

    v = lax.fori_loop(0, num_iters, body, v0)
    lam = jnp.vdot(v, hvp(v))
    return lam, v


def extreme_eigvals(
    hvp: Callable[[jnp.ndarray], jnp.ndarray],
    dim: int,
    num_iters: int = 100,
    key=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(largest, smallest) eigenvalues of the symmetric operator.

    Second extreme via the spectral shift H' = H − λ_d I (reference's
    intended approach, per the surviving scaffolding at
    ``genericNeuralNet.py:786-806``). Power iteration converges to the
    dominant-*magnitude* eigenvalue, so λ_d may be the most-negative one
    (indefinite Hessians away from an optimum); the two passes together
    always yield both extremes — order them by value, not by pass.
    """
    lam_dom, _ = power_iteration(hvp, dim, num_iters, key)

    def shifted(v):
        return hvp(v) - lam_dom * v

    lam_shift, _ = power_iteration(shifted, dim, num_iters, key)
    other = lam_shift + lam_dom
    return jnp.maximum(lam_dom, other), jnp.minimum(lam_dom, other)


def block_hessian_eigvals(H: jnp.ndarray) -> jnp.ndarray:
    """Exact spectrum of a materialised (tiny) block Hessian."""
    return jnp.linalg.eigvalsh(H)


def lissa_tuning(
    hvp: Callable[[jnp.ndarray], jnp.ndarray],
    dim: int,
    scale_floor: float = 0.0,
    num_iters: int = 100,
    shift_margin: float = 1.5,
    scale_margin: float = 1.2,
    key=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Spectrum-derived ``(scale, shift)`` for the LiSSA recursion.

    The recursion cur ← v + cur − H(cur)/scale converges iff every
    eigenvalue of H/scale lies in (0, 2): λ_max bounds the scale from
    below, and a *negative* λ_min (indefinite block Hessian — reachable
    away from an optimum through the residual cross term) diverges at
    ANY scale, so it must be shifted out first. Both extremes come from
    one two-pass power iteration (:func:`extreme_eigvals`); the derived
    operator is H + shift·I with

        shift = shift_margin · max(−λ_min, 0)  (PD blocks: shift = 0)
        scale = max(scale_floor, scale_margin · (λ_max + shift))

    The caller's recursion on the shifted operator then converges to
    (H + shift·I)⁻¹ v — the shift-damped inverse, the standard
    indefinite-case regularisation — while PD blocks keep the exact
    semantics at the cost of ~2·num_iters extra HVPs (nothing against
    a 10k-deep recursion). jit- and vmap-friendly.

    The margins are deliberately generous: power-iteration Rayleigh
    quotients approach the extremes from *inside* the spectrum, and a
    shift even a few percent short leaves a residual negative
    eigenvalue whose (1 + |λ|/scale)^depth growth is finite-but-huge —
    plausible-looking garbage the engine's NaN ladder cannot catch.
    Over-shifting merely damps a solve that is already in the
    approximation regime, and over-scaling only slows convergence
    (second order against a 10k-deep recursion), so both knobs err
    wide.
    """
    lam_max, lam_min = extreme_eigvals(hvp, dim, num_iters=num_iters,
                                       key=key)
    shift = shift_margin * jnp.maximum(-lam_min, 0.0)
    scale = jnp.maximum(scale_floor, scale_margin * (lam_max + shift))
    return scale, shift
