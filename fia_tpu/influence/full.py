"""Full-parameter (non-block) influence engine.

Capability parity with the generic engine in the reference
(``genericNeuralNet.py:503-740``): inverse-HVPs in the FULL parameter
space via minibatched LiSSA or CG over the whole training set, and
Koh-&-Liang influence of any training row on the test loss
(``predicted_loss_diff_j = (H^-1 v) · ∇_θ L(z_j) / N``; the reference's
scoring loop is commented out at ``genericNeuralNet.py:740-764`` — this
is the working version).

TPU-native choices:
  - the train set is sharded along a mesh 'data' axis; the HVP's mean
    gradient then psums across devices automatically under jit.
  - scoring all N train rows needs no per-example full gradients: for a
    fixed direction u, dot(∇L_j, u) for every j is ONE forward-mode
    ``jvp`` of the per-example-loss vector (O(N·k) instead of O(N·|θ|)).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fia_tpu import obs
from fia_tpu.data.dataset import RatingDataset
from fia_tpu.influence import solvers
from fia_tpu.reliability import inject, sites
from fia_tpu.reliability import policy as rpolicy


class FullInfluenceEngine:
    def __init__(
        self,
        model,
        params,
        train: RatingDataset,
        damping: float = 1e-6,
        solver: str = "cg",
        cg_maxiter: int = 100,
        cg_tol: float = 1e-8,
        lissa_scale: float = 10.0,
        lissa_depth: int = 10_000,  # reference depth, genericNeuralNet.py:544
        lissa_batch: int = 0,  # 0 = full-batch HVPs inside LiSSA
        lissa_samples: int = 1,  # averaged recursions; >1 only reduces
        #   variance when lissa_batch > 0 makes the HVPs stochastic
        hvp_batch: int = 0,  # 0 = one full-batch HVP program; >0 = scan
        mesh: Mesh | None = None,
        residual_guard: float | None = None,
    ):
        if solver not in rpolicy.FULL_SOLVERS:
            # the factor bank holds (2k+2)-wide BLOCK inverses; the
            # full-parameter Hessian it would need here cannot even be
            # materialised, so 'precomputed' (and 'direct'/'schulz')
            # must be resolved away via resolve_solver(...,
            # supported=FULL_SOLVERS) before reaching this constructor
            raise ValueError(
                f"unknown solver {solver!r} for the full-parameter "
                f"engine (supported: {rpolicy.FULL_SOLVERS}); route "
                "requests through policy.resolve_solver"
            )
        self.model = model
        self.damping = float(damping)
        self.solver = solver
        self.cg_maxiter = int(cg_maxiter)
        self.cg_tol = float(cg_tol)
        self.lissa_scale = float(lissa_scale)
        self.lissa_depth = int(lissa_depth)
        self.lissa_batch = int(lissa_batch)
        self.lissa_samples = int(lissa_samples)
        # Divergence guard for get_inverse_hvp: when set, a solve whose
        # relative residual exceeds this (or is non-finite) escalates
        # down the lissa -> cg ladder instead of shipping a silently
        # wrong answer. None = NaN screening only (the residual costs
        # one extra full-data HVP per solve).
        self.residual_guard = (
            None if residual_guard is None else float(residual_guard)
        )
        self.mesh = mesh

        # flat layout derived from HOST copies before any cross-process
        # placement: ravel runs plain (non-jit) ops, which global arrays
        # don't support — the flat vector is then placed globally below.
        # np.asarray also accepts already-global fully-replicated params
        # (e.g. a trained state handed over from a multi-host Trainer).
        flat, unravel = ravel_pytree(
            jax.tree_util.tree_map(np.asarray, params)
        )
        self._unravel = unravel
        self.num_params = flat.shape[0]

        self.train_x = jnp.asarray(train.x)
        self.train_y = jnp.asarray(train.y)
        self._multihost = False
        if mesh is not None:
            from fia_tpu.parallel.distributed import put_global, spans_processes

            n = train.num_examples
            # divisibility is only needed along the sharded 'data' axis —
            # n % devices.size would needlessly drop rows on 2-D meshes
            drop = n % mesh.shape["data"]
            if drop:  # keep shards equal; influence over N-drop rows
                self.train_x = self.train_x[: n - drop]
                self.train_y = self.train_y[: n - drop]
            self._multihost = spans_processes(mesh)
            if self._multihost:
                # every process holds the same host copies; build global
                # arrays (device_put cannot target non-addressable devices)
                self.train_x = put_global(
                    mesh, np.asarray(self.train_x), P("data")
                )
                self.train_y = put_global(
                    mesh, np.asarray(self.train_y), P("data")
                )
                params = put_global(mesh, params, P())
                flat = put_global(mesh, np.asarray(flat), P())
            else:
                shard = NamedSharding(mesh, P("data"))
                self.train_x = jax.device_put(self.train_x, shard)
                self.train_y = jax.device_put(self.train_y, shard)
                params = jax.tree_util.tree_map(
                    lambda a: jax.device_put(
                        jnp.asarray(a), NamedSharding(mesh, P())
                    ),
                    params,
                )
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self._flat0 = jnp.asarray(flat)
        self.num_train = int(self.train_x.shape[0])

        # Chunked HVP: one full-batch double-backprop program over
        # ML-20M-scale train sets peaks at O(N) residual activations; a
        # lax.scan over row chunks bounds the live set to one chunk.
        # Chunks are gathered in-program from the resident train tensors
        # (no second copy of the train set); the ragged tail re-reads
        # row 0 at weight 0, which the summed chunk loss ignores exactly.
        self.hvp_batch = int(hvp_batch)
        if self.hvp_batch > 0:
            # a chunk larger than the train set would only add dead rows
            b = max(1, min(self.hvp_batch, self.num_train))
            if mesh is not None:
                # each chunk's row axis is sharded across 'data'
                b = -(-b // mesh.shape["data"]) * mesh.shape["data"]
            self.hvp_batch = b
        # AOT-compiled query-path executables, armed by precompile():
        # keyed by (program name, call geometry); call sites consult
        # this before the method-level jits so a warmed engine pays no
        # trace-or-compile on its first real query.
        self._aot = {}

    # -- core pieces -------------------------------------------------------
    # The jitted entry points take flat0/train tensors as ARGUMENTS, not
    # closures: a jit may not close over cross-process global arrays.

    def _chunk_rows(self, train_x, train_y, ci, b):
        """Gather row chunk ci of b rows from the resident train tensors.

        Shared by the chunked HVP and chunked scoring scans: the ragged
        tail re-reads row 0 (callers mask it — `_hvp_of` by weight, the
        scoring path by slicing the stacked output), and each chunk's
        row axis is sharding-constrained onto 'data' under a mesh.
        Returns (x, y, valid_mask_f32).
        """
        n = self.num_train
        gidx = ci * b + jnp.arange(b, dtype=jnp.int32)
        idx = jnp.where(gidx < n, gidx, 0)
        x, y = train_x[idx], train_y[idx]
        w = (gidx < n).astype(jnp.float32)
        if self.mesh is not None:
            c = lambda a: jax.lax.with_sharding_constraint(
                a, NamedSharding(
                    self.mesh, P("data", *([None] * (a.ndim - 1)))
                )
            )
            x, y, w = c(x), c(y), c(w)
        return x, y, w

    def _hvp_of(self, flat0, train_x, train_y, v):
        n = self.num_train
        if self.hvp_batch <= 0 or self.hvp_batch >= n:

            def total(fvec):
                return self.model.loss(self._unravel(fvec), train_x, train_y)

            hv = jax.jvp(jax.grad(total), (flat0,), (v,))[1]
            return hv + self.damping * v
        b = self.hvp_batch
        nb = -(-n // b)

        def chunk_hvp(acc, ci):
            x, y, w = self._chunk_rows(train_x, train_y, ci, b)

            def loss_sum(fvec):
                p = self._unravel(fvec)
                return jnp.sum(self.model.indiv_loss(p, x, y) * w)

            hv = jax.jvp(jax.grad(loss_sum), (flat0,), (v,))[1]
            return acc + hv, None

        err_hv = jax.lax.scan(
            chunk_hvp, jnp.zeros_like(v), jnp.arange(nb, dtype=jnp.int32)
        )[0] / n
        reg_hv = jax.jvp(
            jax.grad(lambda f: self.model.reg_loss(self._unravel(f))),
            (flat0,), (v,),
        )[1]
        return err_hv + reg_hv + self.damping * v

    def _hvp(self, v):
        """Single-host convenience wrapper (spectral probes, tests)."""
        return self._hvp_of(self._flat0, self.train_x, self.train_y, v)

    def _lissa_sample_hvp(self, flat0, train_x, train_y, key):
        n = self.num_train
        b = self.lissa_batch

        def sample_hvp(j, v):
            idx = jax.random.randint(jax.random.fold_in(key, j), (b,), 0, n)
            x, y = train_x[idx], train_y[idx]

            def loss(fvec):
                return self.model.loss(self._unravel(fvec), x, y)

            hv = jax.jvp(jax.grad(loss), (flat0,), (v,))[1]
            return hv + self.damping * v

        return sample_hvp

    @partial(jax.jit, static_argnums=0)
    def _test_loss_grad_jit(self, flat0, tx, ty):
        def loss(fvec):
            return self.model.loss_no_reg(self._unravel(fvec), tx, ty)

        return jax.grad(loss)(flat0)

    def test_loss_grad(self, test_x, test_y):
        """v = ∇_θ of the mean test loss WITHOUT regularisation
        (reference ``grad_loss_no_reg_op``, genericNeuralNet.py:154).

        Method-level jit (shape-keyed cache reuse across calls — a fresh
        ``jax.jit(closure)`` per call would recompile every time) with
        test data as arguments; jit rather than eager grad because
        multi-process global params only support compiled SPMD programs.
        """
        tx = jnp.asarray(np.asarray(test_x))
        ty = jnp.asarray(np.asarray(test_y))
        exe = self._aot.get(("test_loss_grad", tuple(tx.shape)))
        if exe is not None:
            return exe(self._flat0, self._aot_in(tx), self._aot_in(ty))
        return self._test_loss_grad_jit(self._flat0, tx, ty)

    @partial(jax.jit, static_argnums=(0, 6))
    def _solve(self, v, seed, flat0, train_x, train_y, solver):
        # ``solver`` is an explicit static operand (NOT read off self):
        # self is a static arg too, so a mutated self.solver would never
        # retrace — the degradation ladder must be able to re-solve with
        # the next rung and actually get it.
        hvp = lambda w: self._hvp_of(flat0, train_x, train_y, w)
        if solver == "cg":
            return solvers.solve_cg(
                hvp, v, maxiter=self.cg_maxiter, tol=self.cg_tol
            )
        elif solver == "lissa":
            sample = (
                self._lissa_sample_hvp(flat0, train_x, train_y,
                                       jax.random.PRNGKey(seed))
                if self.lissa_batch
                else None
            )
            return solvers.solve_lissa(
                hvp,
                v,
                scale=self.lissa_scale,
                recursion_depth=self.lissa_depth,
                sample_hvp=sample,
                num_samples=self.lissa_samples if self.lissa_batch else 1,
            )
        raise ValueError(f"unknown solver {solver!r}")

    def get_inverse_hvp(self, v, seed: int = 0):
        """Solve H x = v, guarded against silent solver divergence.

        The fetched solution is screened for non-finite values (the
        LiSSA recursion "succeeds" into a NaN buffer when scale is
        beaten by the spectrum) and — when ``residual_guard`` is set —
        for relative residual above the guard. Either finding escalates
        down the full-engine ladder (``lissa -> cg``; CG's best-iterate
        freeze cannot diverge) and re-solves. Escalation is sticky: a
        spectrum that beat LiSSA once will beat it again next call.
        """
        v = jnp.asarray(v)
        solver = self.solver
        while True:
            exe = self._aot.get(("solve", solver))
            if exe is not None:
                x = exe(self._aot_in(v), np.uint32(seed), self._flat0,
                        self.train_x, self.train_y)
            else:
                x = self._solve(v, np.uint32(seed), self._flat0,
                                self.train_x, self.train_y, solver)
            # fault-injection site: corrupts the *screened* host copy,
            # so recovery runs exactly as for a real diverged solve
            xh = inject.corrupt(sites.FULL_SOLVE, np.asarray(self._fetch(x)))
            bad = not np.isfinite(xh).all()
            reason = "non-finite inverse-HVP"
            if not bad and self.residual_guard is not None:
                rr = self.relative_residual(v, x)
                if not np.isfinite(rr) or rr > self.residual_guard:
                    bad = True
                    reason = (f"relative residual {rr:.3g} over guard "
                              f"{self.residual_guard:g}")
            if not bad:
                return x
            nxt = rpolicy.next_solver(solver, rpolicy.FULL_SOLVER_FALLBACK)
            if nxt is None:
                obs.diag("reliability",
                         f"{reason} from {solver!r} with no "
                         "fallback rung left; returning as-is")
                return x
            obs.diag("reliability",
                     f"{reason} from {solver!r}; escalating "
                     f"solver to {nxt!r}")
            obs.REGISTRY.counter(
                "engine.solver_escalations",
                **{"from": solver, "to": nxt}
            ).inc()
            self.solver = solver = nxt

    @partial(jax.jit, static_argnums=0)
    def _residual_jit(self, v, x, flat0, train_x, train_y):
        return solvers.relative_residual(
            lambda w: self._hvp_of(flat0, train_x, train_y, w), v, x
        )

    def relative_residual(self, v, x) -> float:
        """Relative residual ‖Hx − v‖/‖v‖ of a solve, at one extra HVP.

        The quality number the reference's ``fmin_ncg`` path tracks via
        ``avextol`` (``genericNeuralNet.py:646-664``) but never reports;
        truncated solves (e.g. the ML-20M maxiter-10 stress probe) carry
        this so "all finite" is not their only quality statement
        (r3 VERDICT item 6).
        """
        return float(self._residual_jit(
            jnp.asarray(v), jnp.asarray(x), self._flat0,
            self.train_x, self.train_y,
        ))

    @partial(jax.jit, static_argnums=0)
    def _score_all(self, u, flat0, train_x, train_y):
        """dot(∇_θ L_total(z_j), u) / N for every train row j.

        Per-example total loss = own squared error + full regulariser, so
        the dot splits into a forward-mode jvp of the per-example error
        vector plus a constant ∇reg·u term. When ``hvp_batch`` is set,
        the jvp scans row chunks exactly like ``_hvp_of``: one
        full-train jvp materialises (N, k) primal+tangent embedding
        gathers, and the TPU (8,128) tile layout pads the k=16 minor
        axis 8x — 4 x 9.54G temporaries = 38.4G for ML-20M, the
        observed stress OOM (output/stress_full_space.log, 2026-07-31).
        """
        n = self.num_train
        reg_dot = jax.jvp(
            lambda f: self.model.reg_loss(self._unravel(f)), (flat0,), (u,)
        )[1]
        if self.hvp_batch <= 0 or self.hvp_batch >= n:

            def indiv(fvec):
                p = self._unravel(fvec)
                return self.model.indiv_loss(p, train_x, train_y)

            _, err_dots = jax.jvp(indiv, (flat0,), (u,))
            return (err_dots + reg_dot) / n

        b = self.hvp_batch
        nb = -(-n // b)

        def chunk_dots(carry, ci):
            x, y, _ = self._chunk_rows(train_x, train_y, ci, b)

            def indiv(fvec):
                p = self._unravel(fvec)
                return self.model.indiv_loss(p, x, y)

            _, dots = jax.jvp(indiv, (flat0,), (u,))
            return carry, dots

        dots = jax.lax.scan(
            chunk_dots, None, jnp.arange(nb, dtype=jnp.int32)
        )[1]
        # ragged-tail rows re-read row 0; the slice drops their dots
        return (dots.reshape(nb * b)[:n] + reg_dot) / n

    def _aot_in(self, x):
        """Place a per-call operand for an AOT executable.

        Compiled executables are strict about input placement: mesh
        engines lower their programs with replicated input shardings
        (precompile), so host-fresh operands (test batches, solve
        directions) are re-placed to that layout here. No-op without a
        mesh, and a no-copy no-op for arrays already so placed.
        """
        if self.mesh is None:
            return x
        return jax.device_put(x, NamedSharding(self.mesh, P()))

    def _fetch(self, arr) -> np.ndarray:
        """Host copy of a (possibly cross-process sharded) result."""
        if self._multihost:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
        return np.asarray(arr)

    # -- public API --------------------------------------------------------
    def _score_all_run(self, u):
        """_score_all through the AOT executable when armed."""
        exe = self._aot.get(("score_all",))
        if exe is not None:
            return exe(self._aot_in(u), self._flat0,
                       self.train_x, self.train_y)
        return self._score_all(u, self._flat0, self.train_x, self.train_y)

    def get_influence_on_test_loss(self, test_x, test_y, seed: int = 0):
        """Predicted test-LOSS change per removed train row, (N,)."""
        v = self.test_loss_grad(test_x, test_y)
        ihvp = self.get_inverse_hvp(v, seed=seed)
        return self._fetch(self._score_all_run(ihvp))

    @partial(jax.jit, static_argnums=0)
    def _pred_grad_jit(self, flat0, tx):
        def pred(fvec):
            return jnp.mean(self.model.predict(self._unravel(fvec), tx))

        return jax.grad(pred)(flat0)

    def get_influence_on_test_prediction(
        self, test_x, seed: int = 0, return_residual: bool = False
    ):
        """Predicted test-PREDICTION change per removed train row (the
        quantity FIA approximates in the block subspace).

        ``return_residual``: also return the solve's relative residual
        ‖Hx − v‖/‖v‖ (one extra chunked HVP) — the quality statement
        truncated stress solves must carry.
        """
        tx = jnp.asarray(np.asarray(test_x))
        exe = self._aot.get(("pred_grad", tuple(tx.shape)))
        if exe is not None:
            v = exe(self._flat0, self._aot_in(tx))
        else:
            v = self._pred_grad_jit(self._flat0, tx)
        ihvp = self.get_inverse_hvp(v, seed=seed)
        scores = self._fetch(self._score_all_run(ihvp))
        if return_residual:
            return scores, self.relative_residual(v, ihvp)
        return scores

    def precompile(self, n_test: int = 1) -> dict:
        """AOT pre-lower + compile the query-path programs
        (``jax.jit(...).lower(...).compile()``) for ``n_test``-row test
        batches, so a warmed engine's first query pays no
        trace-or-compile: the test/prediction gradient, the iHVP solve
        at the current solver rung, and the all-rows scoring jvp.
        Single-process mesh engines lower with their replicated input
        shardings baked in (r7; per-call operands re-placed by
        ``_aot_in``); cross-process engines stay on the jit path — AOT
        there buys nothing, one process compiles either way.

        Returns ``{"compiled": [names], "cached": [names], "seconds"}``.
        """
        if self._multihost:
            return {"compiled": [], "cached": [], "seconds": 0.0}
        t0 = time.perf_counter()
        cls = type(self)
        flat = self._flat0
        rep = (
            None if self.mesh is None
            else NamedSharding(self.mesh, P())
        )
        sds = lambda shape, dtype: (
            jax.ShapeDtypeStruct(shape, dtype) if rep is None
            else jax.ShapeDtypeStruct(shape, dtype, sharding=rep)
        )
        v = sds(flat.shape, flat.dtype)
        tx = sds(
            (n_test,) + tuple(self.train_x.shape[1:]), self.train_x.dtype
        )
        ty = sds((n_test,), self.train_y.dtype)
        jobs = {
            ("test_loss_grad", tuple(tx.shape)): lambda: cls
            ._test_loss_grad_jit.lower(self, flat, tx, ty),
            ("pred_grad", tuple(tx.shape)): lambda: cls
            ._pred_grad_jit.lower(self, flat, tx),
            ("solve", self.solver): lambda: cls._solve.lower(
                self, v, np.uint32(0), flat, self.train_x, self.train_y,
                self.solver,
            ),
            ("score_all",): lambda: cls._score_all.lower(
                self, v, flat, self.train_x, self.train_y
            ),
        }
        compiled, cached = [], []
        for key, build in jobs.items():
            if key in self._aot:
                cached.append(key[0])
                continue
            self._aot[key] = build().compile()
            compiled.append(key[0])
        return {"compiled": compiled, "cached": cached,
                "seconds": time.perf_counter() - t0}
