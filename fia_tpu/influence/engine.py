"""The FIA influence engine.

End-to-end equivalent of the reference's
``get_influence_on_test_loss`` override (``matrix_factorization.py:
164-251`` / ``NCF.py:193-280``): for a test interaction (u*, i*), compute
the block-restricted inverse-HVP and score every related training row's
influence on the test *prediction*.

Where the reference mutates its TF graph per test point and loops
``sess.run`` per training row, this engine compiles ONE pure function of
the test batch. Two implementations:

- flat (single-device default): every query's related rows on one flat
  axis, Gauss-Newton block Hessians accumulated by segment — device
  work scales with rows actually scored (``_flat_fn``);
- padded: per-query ``vmap`` at a common pad — required for meshes
  (query batch sharded data-parallel over ICI, params replicated),
  CG/LiSSA solvers, and models without the Gauss-Newton hooks.

Both gather related sets on device from resident CSR postings and ship
compact outputs in a single host round trip (see docs/design.md §2).
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fia_tpu import obs
from fia_tpu.data.dataset import RatingDataset
from fia_tpu.data.index import InteractionIndex, bucketed_pad
from fia_tpu.influence import grads as G
from fia_tpu.influence import hvp as H
from fia_tpu.influence import kernels as K
from fia_tpu.influence import sampled as sampled_mod
from fia_tpu.influence import solvers
from fia_tpu.influence import spectral
from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.reliability import policy as rpolicy
from fia_tpu.reliability.journal import Journal  # noqa: F401 (re-export)


class InfluenceResult:
    """Batched influence query results (T test points, P padded rows).

    The flat path stores results PACKED (one flat score array in query
    order plus counts) and synthesizes the padded ``scores``/
    ``related_idx``/``related_mask`` views lazily on first access —
    building (T, P) padded host arrays was a measurable share of query
    latency, and the common consumers (``scores_of``/``related_of``)
    never need them.
    """

    def __init__(self, scores=None, related_idx=None, related_mask=None,
                 counts=None, ihvp=None, test_grad=None,
                 packed=None, test_points=None, index=None, pad=None,
                 err_bound=None, approx=False):
        self.counts = counts
        self.ihvp = ihvp
        self.test_grad = test_grad
        # Certified-approximate payloads (solver='sampled', docs/design
        # §22): err_bound is a (T,) per-query bound on the max per-row
        # score error (0 for exactly-solved queries), approx marks a
        # result carrying at least one subsampled answer. None/False on
        # every exact path, so downstream consumers can treat absence
        # as exactness.
        self.err_bound = None if err_bound is None else np.asarray(err_bound)
        self.approx = bool(approx)
        self._scores = scores
        self._related_idx = related_idx
        self._related_mask = related_mask
        self._packed = packed
        self._test_points = test_points
        self._index = index
        self._pad = pad
        self._offsets = None
        if packed is not None:
            self._offsets = np.concatenate(
                [[0], np.cumsum(np.asarray(counts, np.int64))]
            )

    # -- padded views (lazy for packed results) ---------------------------
    def _materialize(self):
        rel_idx, rel_mask, _ = self._index.related_padded(
            self._test_points, pad_to=self._pad
        )
        T = len(self._test_points)
        scores = np.zeros((T, self._pad), np.float32)
        scores[rel_mask] = self._packed
        self._scores = scores
        self._related_idx = rel_idx
        self._related_mask = rel_mask

    @property
    def scores(self) -> np.ndarray:  # (T, P), 0 on padding
        if self._scores is None:
            self._materialize()
        return self._scores

    @property
    def related_idx(self) -> np.ndarray:  # (T, P) train-row ids
        if self._related_idx is None:
            self._materialize()
        return self._related_idx

    @property
    def related_mask(self) -> np.ndarray:  # (T, P) bool
        if self._related_mask is None:
            self._materialize()
        return self._related_mask

    # -- per-query accessors (no padding required) ------------------------
    def scores_of(self, t: int) -> np.ndarray:
        """Unpadded scores for test point t (reference return value)."""
        if self._packed is not None:
            return self._packed[self._offsets[t] : self._offsets[t + 1]]
        return self.scores[t, : self.counts[t]]

    def related_of(self, t: int) -> np.ndarray:
        if self._packed is not None:
            u, i = (int(v) for v in self._test_points[t])
            return self._index.related(u, i)
        return self.related_idx[t, : self.counts[t]]


def _classify_device_failure(e: Exception) -> str | None:
    """Classify a dispatch/compile failure for the adaptive retry layer.

    Delegates to the unified taxonomy in
    :mod:`fia_tpu.reliability.taxonomy` — the classifier grew up here
    (r3/r4; the per-kind histories live in that module's docstring) and
    was lifted out so the trainer, distributed runtime and CLI drivers
    share exactly the same signatures. The name stays importable from
    this module: it is the documented seam tests and operators key on.

    Returns a :class:`~fia_tpu.reliability.taxonomy.FaultKind` string
    (``"oom"`` / ``"ambiguous"`` / ``"worker"`` / ``"preemption"`` /
    ``"host_oom"`` / ``"nan"`` / ``"deadline"``) or ``None`` for
    unrelated failures, which callers must re-raise.
    """
    return taxonomy.classify(e)


# Kinds the padded adaptive layer knows how to absorb; anything else
# surfaces (host_oom/nan/deadline have their own dedicated layers).
_ADAPTIVE_KINDS = frozenset(
    {taxonomy.OOM, taxonomy.AMBIGUOUS, taxonomy.WORKER, taxonomy.PREEMPTION}
)


def _concat_results(parts: list["InfluenceResult"]) -> "InfluenceResult":
    """Stitch same-pad chunked query results back into one batch result.

    Valid only for chunks of one logical batch dispatched at a common
    pad (the adaptive path guarantees this): packed scores concatenate
    in query order because per-query postings are contiguous, and the
    dense (T, P) views share a width.
    """
    counts = np.concatenate([p.counts for p in parts])
    ihvp = np.concatenate([p.ihvp for p in parts])
    test_grad = np.concatenate([p.test_grad for p in parts])
    # error bounds stitch like every other per-query array; parts
    # without one are exact (bound 0)
    err = None
    if any(p.err_bound is not None for p in parts):
        err = np.concatenate([
            p.err_bound if p.err_bound is not None
            else np.zeros(len(p.counts), np.float32)
            for p in parts
        ])
    approx = any(p.approx for p in parts)
    if parts[0]._packed is not None:
        return InfluenceResult(
            counts=counts,
            ihvp=ihvp,
            test_grad=test_grad,
            packed=np.concatenate([p._packed for p in parts]),
            test_points=np.concatenate([p._test_points for p in parts]),
            index=parts[0]._index,
            pad=max(p._pad for p in parts),
            err_bound=err,
            approx=approx,
        )
    return InfluenceResult(
        np.concatenate([p.scores for p in parts]),
        np.concatenate([p.related_idx for p in parts]),
        np.concatenate([p.related_mask for p in parts]),
        counts,
        ihvp,
        test_grad,
        err_bound=err,
        approx=approx,
    )


class InfluenceEngine:
    """Block-restricted (FIA) influence over a trained model.

    Args:
      model: a LatentFactorModel.
      params: trained parameter pytree.
      train: the training RatingDataset.
      damping: Hessian damping λ (reference default 1e-6, RQ1.py:20).
      solver: 'direct' (materialise + LU solve; exact, TPU-fast default),
        'cg' (matrix-free, fmin_ncg-equivalent on this quadratic),
        'lissa', 'schulz' (matmul-only Newton–Schulz inversion,
        beyond-reference option), 'precomputed' (factor-bank tier), or
        'sampled' (certified subsampled rung: Hessian over at most
        ``sampled_cap`` related rows per query, answers stamped with a
        concentration error bound, over-tolerance queries escalated one
        ladder rung — docs/design.md §22).
      mesh: optional jax Mesh with a 'data' axis; query batches are then
        sharded across it. With a 2-D ('data', 'model') mesh, pass
        ``shard_tables=True`` to row-shard the embedding tables over the
        'model' axis (stress configs whose tables exceed one device).
      cache_dir: if set, inverse-HVPs are cached as npz files keyed like
        the reference (``matrix_factorization.py:210-222``).
    """

    def __init__(
        self,
        model,
        params,
        train: RatingDataset,
        damping: float = 1e-6,
        solver: str = "direct",
        cg_maxiter: int = 100,
        cg_tol: float = 1e-10,
        lissa_scale: float = 10.0,
        lissa_depth: int = 10_000,  # reference depth, genericNeuralNet.py:544
        mesh: Mesh | None = None,
        cache_dir: str | None = None,
        model_name: str = "model",
        pad_bucket: int = 128,
        shard_tables: bool = False,
        hessian_mode: str = "auto",
        group_queries: bool = False,
        pad_policy: str = "batch",
        impl: str = "auto",
        flat_chunk: int = 2048,
        flat_accum: str = "auto",
        row_features: str = "auto",
        cpu_fallback: bool = True,
        query_bucket: int = 64,
        kernel: str = "auto",
        lissa_tune: str = "spectral",
        sampled_cap: int = sampled_mod.DEFAULT_CAP,
        sampled_tol: float = float("inf"),
    ):
        if solver not in ("direct", "cg", "lissa", "schulz",
                          "precomputed", "sampled"):
            raise ValueError(f"unknown solver {solver!r}")
        self.model = model
        # Score-kernel variant for the flat/bank paths (influence/kernels/):
        # 'auto' resolves to the fused Pallas kernel on TPU (models with
        # a kernel family), the pure-XLA analytic twin elsewhere — op-
        # for-op the historical score stage, so CPU golden runs are
        # untouched — and the vmapped-autodiff reference for models
        # without hooks. Explicit variants are for parity/bench runs;
        # resolve_variant rejects impossible requests loudly.
        if kernel not in ("auto",) + K.VARIANTS:
            raise ValueError(f"unknown kernel {kernel!r}")
        self.kernel = kernel
        # Row-sharded tables never meet the Pallas kernel: its score
        # stage re-fetches embedding rows from fully-resident tables
        # inside the kernel (kernels/common.onehot_fetch) — exactly the
        # any-device-reads-any-row pattern sharding removes. 'auto'
        # therefore resolves as on a non-TPU backend (the XLA analytic
        # twin, whose score stage consumes the pre-gathered g), and an
        # explicit 'pallas' request is rejected loudly rather than
        # silently served from unsharded tables.
        if shard_tables and kernel == "pallas":
            raise ValueError(
                "kernel='pallas' is incompatible with shard_tables: the "
                "fused kernel re-fetches rows from fully-resident tables"
            )
        self._kernel_variant = K.resolve_variant(
            kernel, model, backend="cpu" if shard_tables else None
        )
        # LiSSA tuning on the solver-ladder miss path: 'spectral' runs
        # extreme_eigvals on the block HVP and derives (scale, shift)
        # covering BOTH spectrum ends — indefinite blocks (λ_min < 0,
        # reachable away from an optimum through the e·C cross term)
        # make the Neumann recursion diverge at ANY scale, so the shift
        # lifts the operator PD first; 'static' keeps the configured
        # scale (plus solve_lissa's λ_max-only auto_scale guard).
        if lissa_tune not in ("spectral", "static"):
            raise ValueError(f"unknown lissa_tune {lissa_tune!r}")
        self.lissa_tune = lissa_tune
        if shard_tables and (mesh is None or "model" not in mesh.axis_names):
            raise ValueError("shard_tables requires a mesh with a 'model' axis")
        # Fused per-train-row feature table for the flat path (see
        # models/base.py hook doc). Chip A/Bs (roofline --ab feat,
        # output/roofline_{mf,ncf}_feat*.json, r4) measured it a WASH
        # on both models once the block_row_grads hook and the
        # single-gather row construction landed — the per-dispatch
        # gathers it fuses were no longer the binding traffic — so
        # 'auto' resolves to OFF (no HBM spent on a neutral cache);
        # 'on' forces the table (gated to models defining the hooks,
        # ids < 2^24 for exact float-packed comparison, and a 2 GB
        # physical budget — the minor axis tiles to a 128 multiple).
        if row_features not in ("auto", "on", "off"):
            raise ValueError(f"unknown row_features {row_features!r}")
        self.row_features = row_features
        self._rowfeat = None
        # Host copies survive a TPU worker crash/restart (the r3 k=256
        # failure mode kills every device buffer this client holds);
        # _upload_device_state rebuilds the device state from them.
        self._params_host = jax.tree_util.tree_map(np.asarray, params)
        self._train_host = (np.asarray(train.x), np.asarray(train.y))
        self._shard_tables = shard_tables
        self.mesh = mesh
        self._multihost = False
        if mesh is not None:
            from fia_tpu.parallel.distributed import spans_processes

            self._multihost = spans_processes(mesh)
        self.index = InteractionIndex(train.x, model.num_users, model.num_items)
        self._upload_device_state()
        self.damping = float(damping)
        self.solver = solver
        self.cg_maxiter = int(cg_maxiter)
        self.cg_tol = float(cg_tol)
        self.lissa_scale = float(lissa_scale)
        self.lissa_depth = int(lissa_depth)
        self.mesh = mesh
        self.cache_dir = cache_dir
        self.model_name = model_name
        self.pad_bucket = int(pad_bucket)
        # (A Pallas fused-scoring kernel existed through r1; retired r2
        # after a measured A/B loss to both XLA paths — BASELINE.md §4.)
        # Direct-solver Hessian build: 'analytic' uses the model's
        # closed-form block Hessian (when it defines one), 'autodiff'
        # materialises it by batched HVPs over the identity. Measured:
        # analytic is ~9x faster on CPU, but on TPU XLA fuses the
        # identity-batched HVP into one program that beats the
        # many-small-reduction closed form — so 'auto' picks by backend.
        if hessian_mode not in ("auto", "analytic", "autodiff"):
            raise ValueError(f"unknown hessian_mode {hessian_mode!r}")
        self.hessian_mode = hessian_mode
        if hessian_mode == "analytic" and model.block_hessian is None:
            raise ValueError(
                f"{type(model).__name__} defines no closed-form block_hessian"
            )
        self._analytic_hessian = model.block_hessian is not None and (
            hessian_mode == "analytic"
            or (hessian_mode == "auto" and jax.default_backend() != "tpu")
        )
        # Optional per-bucket batch splitting. Measured on the v5e chip:
        # one big dispatch at the batch's max pad beats many small
        # per-bucket dispatches (small vmap batches underutilise the
        # device and each dispatch carries fixed host/transfer cost), so
        # the default is a single pad; grouping helps only when query
        # batches are huge and degree distributions extremely skewed.
        self.group_queries = bool(group_queries)
        # 'batch': pad to the batch's max related count (least compute;
        # recompiles when a new batch's max lands in a new bucket).
        # 'dataset': pad every batch to the dataset-wide ceiling
        # (max user degree + max item degree) — one compiled program
        # serves all batches, for varied/streaming query workloads.
        if pad_policy not in ("batch", "dataset"):
            raise ValueError(f"unknown pad_policy {pad_policy!r}")
        self.pad_policy = pad_policy
        # 'flat' = segment-sum path (device work ∝ actual related rows,
        # not padded rows — see _flat_fn); 'padded' = per-query vmap at a
        # common pad. 'auto' picks flat whenever eligible (single device,
        # direct solver, model defines the Gauss-Newton hooks).
        if impl not in ("auto", "flat", "padded"):
            raise ValueError(f"unknown impl {impl!r}")
        self.impl = impl
        # flat-path Hessian accumulation chunk: bounds the (chunk, d, d)
        # outer-product buffer; larger chunks = fewer sequential scan
        # steps at more VMEM/HBM (2048 ~ 9.5 MB at d=34). Rounded down to
        # a power of two so it always divides the power-of-two S pad.
        self.flat_chunk = 1 << max(0, int(flat_chunk).bit_length() - 1)
        # d-aware clamp: the accumulation buffer is (chunk, d, d) — at
        # k=256 (d=514) the default 2048-chunk makes it 2.2 GB, which
        # crashed the TPU worker at RUNTIME twice in r3 (RQ2 k=256,
        # "kernel fault", not an XLA OOM). Cap chunk at the largest
        # power of two keeping the buffer <= 64M fp32 elements (256 MB)
        # — no floor: flooring at 128 would re-cross the crash size
        # for blocks beyond d≈707. d=34/64 reference blocks are
        # untouched (cap >> 2048).
        d_blk = int(model.block_size)
        cap_elems = 64_000_000 // max(d_blk * d_blk, 1)
        cap = 1 << max(0, cap_elems.bit_length() - 1) if cap_elems else 1
        self.flat_chunk = max(1, min(self.flat_chunk, cap))
        # Flat-path per-query Hessian segment reduction: 'scan' is the
        # scatter-add form (VPU serial, memory-lean), 'onehot' the
        # (T, chunk) @ (chunk, d²) matmul form (MXU; chip A/B winner,
        # BASELINE §4.3). 'auto' picks onehot on TPU, scan elsewhere
        # (CPU has no MXU to feed — the one-hot multiplies are pure
        # waste there).
        if flat_accum not in ("auto", "scan", "onehot"):
            raise ValueError(f"unknown flat_accum {flat_accum!r}")
        self.flat_accum = flat_accum
        # Flat-path query-axis bucket: every dispatch pads its (T, 2)
        # query ids to bucketed_pad(T, query_bucket) by duplicating the
        # trailing pair, so mixed-size streams reuse a handful of
        # compiled geometries AND the batched block solve always runs
        # at a canonical batch size. The second property is a
        # bit-exactness contract, not a perf tweak: the batched LU
        # behind jnp.linalg.solve selects kernels by batch size (CPU
        # measurably flips low bits below batch ~16), so without a
        # canonical solve geometry `query_many` chunking would not be
        # bit-identical to one full dispatch (tests/test_dispatch.py
        # pins the equivalence). 0 disables (exact-T programs).
        self.query_bucket = max(0, int(query_bucket))
        self._jitted = {}  # pad length -> compiled batched query
        # (t_pad, s_pad) geometry -> AOT-compiled flat executable
        # (jax.jit(...).lower(...).compile()), armed by precompile_flat
        # at warmup so steady-state dispatches never trace or compile.
        self._aot = {}
        # Memory-adaptive padded-path state (_query_padded_adaptive):
        # the largest (queries x pad) cell count that dispatched
        # successfully, and the smallest that exhausted device memory.
        # Shared across pads — the dominant temporaries scale with
        # T x pad x block_dim, so cells transfer between pad buckets —
        # and persisted across processes (utils/memlimits.py) so a
        # fresh process does not re-pay the failing compile that
        # taught a previous one the device's envelope.
        self._cells_ok = 0
        # _cells_bad: the effective in-process ceiling — min over every
        # failure observed, whatever its class. _cells_bad_hard: min
        # over explicit RESOURCE_EXHAUSTED failures only; this is the
        # ONLY value the cross-process cache ever receives — a generic
        # tunnel-500 (possibly a transient fault) chunks this engine
        # but must not degrade every later process (r3 advisor
        # finding). Tracked separately so an ambiguous fault at a small
        # size cannot shadow a genuine OOM ceiling learned earlier.
        self._cells_bad = 1 << 62
        self._cells_bad_hard = 1 << 62
        # Largest successful dispatch that contradicted a recorded
        # ceiling (success at >= cells_bad); the persistence layer then
        # clears stale cached ceilings <= this size. 0 = none.
        self._cleared_bad = 0
        self._memkey = None
        # Last rung of the query degradation ladder: when device-side
        # recovery is exhausted (worker keeps dying at single-query
        # dispatches), rebuild the engine from its host copies on the
        # CPU backend and finish there — slow but correct beats dead.
        self.cpu_fallback = bool(cpu_fallback)
        self._is_cpu_fallback = False
        self._cpu_engine: "InfluenceEngine | None" = None
        # Precomputed factor-bank tier (solver='precomputed'): hot
        # (u, i) pairs answer from factorized block inverses published
        # offline (cli/factor.py -> influence/factor.py), one
        # triangular-solve/matvec inside the flat dispatch; everything
        # else — missing entry, stale params digest, damaged artifact,
        # mesh/hook ineligibility — falls through to a config-identical
        # delegate at the next ladder rung (policy.QUERY_SOLVER_FALLBACK).
        self._bank = None
        self._bank_lookup: dict | None = None
        self._bank_device = None  # (factor (N,d,d), kind (N,)) on device
        self._bank_load_attempted = False
        self._bank_dropped_stale = 0
        self._bank_hits = 0
        self._bank_misses = 0
        self._bank_delegate: "InfluenceEngine | None" = None
        # Certified subsampled rung (solver='sampled', docs/design.md
        # §22): Hessian accumulation over <= sampled_cap related rows
        # per query (the score pass still covers every row), answers
        # stamped with a concentration error bound. Queries whose bound
        # exceeds sampled_tol escalate one ladder rung through a
        # config-identical delegate — the adaptive cost/accuracy policy.
        self.sampled_cap = max(1, int(sampled_cap))
        self.sampled_tol = float(sampled_tol)
        self._sampled_delegate: "InfluenceEngine | None" = None
        self._approx_sibling: "InfluenceEngine | None" = None

    def _upload_device_state(self) -> None:
        """(Re)build every device-resident tensor from host copies.

        Called at construction, and again by :meth:`_reset_device_state`
        after a TPU worker crash. CSR postings live on device: related
        sets are gathered inside the jitted query, so per-batch
        host→device traffic is just the (T, 2) test points — not (T, P)
        padded index/mask arrays, whose transfer dominated end-to-end
        latency on tunnel/PCIe-attached hosts (measured 1.2 s of a
        1.36 s 256-query batch at P=3584). On a cross-process mesh
        every jit operand must be a global array; params (unless
        table-sharded) and train tensors are replicated.
        """
        inject.fire(sites.ENGINE_UPLOAD)
        mesh = self.mesh
        self.params = jax.tree_util.tree_map(jnp.asarray, self._params_host)
        if self._sharded_now():
            from fia_tpu.parallel.sharded import shard_model_params

            # pad_rows: the flat hot path gathers block rows through a
            # shard_map collective, which needs row counts divisible by
            # the 'model' axis; the zero pad rows are unreachable by
            # real ids and exactly neutral to predictions, regularizer
            # sums, and the per-leaf sum/norm params fingerprint.
            self.params = shard_model_params(
                mesh, self.params, self.model, pad_rows=True
            )
        self.train_x = jnp.asarray(self._train_host[0])
        self.train_y = jnp.asarray(self._train_host[1])
        self._postings = tuple(
            jnp.asarray(a, jnp.int32) for a in self.index.postings()
        )
        self._rowfeat = None
        if self._want_row_features():
            x, y = self._train_host
            step = 1 << 21  # bound the build's activation peak
            parts = [
                self.model.build_row_features(
                    self.params, jnp.asarray(x[s: s + step], jnp.int32),
                    jnp.asarray(y[s: s + step]),
                )
                for s in range(0, len(x), step)
            ]
            self._rowfeat = (
                parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            )
        if self._multihost:
            from fia_tpu.parallel.distributed import put_global

            if not self._shard_tables:
                self.params = put_global(mesh, self.params, P())
            self.train_x = put_global(mesh, self.train_x, P())
            self.train_y = put_global(mesh, self.train_y, P())
            self._postings = tuple(
                put_global(mesh, a, P()) for a in self._postings
            )
            if self._rowfeat is not None:
                self._rowfeat = put_global(mesh, self._rowfeat, P())

    def _sharded_now(self) -> bool:
        """Tables row-sharded on the CURRENT mesh. A ``shard_tables``
        engine re-homed by :meth:`rebuild_mesh` onto a mesh without a
        non-trivial 'model' axis (``surviving_mesh`` collapses to
        trailing-axis 1 when survivors can't fill a model group, and
        ``None`` is the single-device last rung) degrades to replicated
        placement — the tables must then fit one device, which degraded
        mode accepts over dying."""
        return (
            self._shard_tables
            and self.mesh is not None
            and "model" in self.mesh.axis_names
            and int(self.mesh.shape["model"]) > 1
        )

    def _want_row_features(self) -> bool:
        if (
            self.model.build_row_features is None
            or self.row_features == "off"
            # table-sharded params: the fused table would replicate what
            # sharding just split — defeats the point at those scales
            or self._shard_tables
        ):
            return False
        if max(self.model.num_users, self.model.num_items) >= (1 << 24):
            return False  # float-packed ids must compare exactly
        if self.row_features != "on":
            return False  # 'auto' = off: measured neutral (chip A/B r4)
        n = len(self._train_host[0])
        padded = -(-int(self.model.row_feature_dim) // 128) * 128
        # 2 GB physical budget: (N, F) stores as (N, ceil(F/128)*128)
        return n * padded * 4 <= (2 << 30)

    def _reset_device_state(self, max_wait_s: float = 120.0) -> None:
        """Recover from a TPU worker crash/restart ("kernel fault").

        Every device buffer this client held (params, train tensors,
        postings, in-flight outputs) died with the worker, and compiled
        executables bound to the dead client state cannot be trusted —
        drop them and re-upload. Host-side state (index, learned memory
        envelope, result caches) survives untouched, so recovery costs
        one re-upload plus recompiles of whatever runs next.

        The worker takes seconds to come back after a crash — the r4
        k=256 retry died AGAIN at ``device_put`` time because the
        re-upload raced the restart — so upload failures that still
        carry the worker-death (or preemption) signature back off
        exponentially up to ``max_wait_s`` before surfacing. The
        schedule is a reliability :class:`RetryPolicy` under a
        :class:`Deadline` — deterministic jitter, replayable under
        fault injection.
        """
        self._jitted.clear()
        self._aot.clear()  # AOT executables bound the dead client too
        # 8 attempts at 2s base / x2 growth / 30s cap spans ~120s of
        # backoff — the observed worker-restart envelope.
        pol = rpolicy.RetryPolicy(
            max_attempts=8, base_delay=2.0, max_delay=30.0, jitter=0.25
        )
        pol.run(
            self._upload_device_state,
            retry_on=(taxonomy.WORKER, taxonomy.PREEMPTION),
            deadline=rpolicy.Deadline(max_wait_s),
        )

    def rebuild_mesh(self, mesh, max_wait_s: float = 120.0) -> None:
        """Re-home the engine on a different (usually shrunken) mesh.

        The ``device_lost`` recovery move: unlike a worker crash
        (:meth:`_reset_device_state`, same topology), the dead device is
        not coming back — the service hands us the surviving mesh
        (:func:`fia_tpu.parallel.mesh.surviving_mesh`) and we re-place
        every device-resident tensor on it from the host copies. Every
        compiled executable is dropped: AOT keys embed the mesh
        fingerprint (:meth:`_aot_key`), so the caller re-arms the
        planned geometries with :meth:`precompile_flat` and steady
        state stays zero-compile on the new topology. Results are
        unchanged by construction — ``_mesh_plan`` gives each shard the
        exact single-device program, so scores are bit-identical across
        mesh sizes (docs/design.md §15).

        Passing ``mesh=None`` re-homes onto the default single device —
        the last rung before giving up entirely.
        """
        from fia_tpu.parallel.mesh import mesh_hosts

        inject.fire(sites.MESH_REBUILD)
        nhosts = 0 if mesh is None else len(mesh_hosts(mesh))
        if nhosts > 1:
            # Cross-host rebuilds carry extra failure surface (DCN
            # re-placement against hosts that may themselves be
            # settling), so they get their own injection site on top of
            # the generic one.
            inject.fire(sites.MESH_REBUILD_MULTIHOST)
        obs.REGISTRY.counter("engine.mesh_rebuilds").inc()
        obs.event("mesh.rebuild",
                  ndev=1 if mesh is None else int(mesh.devices.size),
                  nhosts=nhosts)
        self.mesh = mesh
        self._multihost = False
        if mesh is not None:
            from fia_tpu.parallel.distributed import spans_processes

            self._multihost = spans_processes(mesh)
        self._jitted.clear()
        self._aot.clear()
        # Survivor devices may themselves be settling after the fabric
        # event — re-placement retries under the same envelope as the
        # worker-restart path.
        pol = rpolicy.RetryPolicy(
            max_attempts=8, base_delay=2.0, max_delay=30.0, jitter=0.25
        )
        pol.run(
            self._upload_device_state,
            retry_on=(taxonomy.WORKER, taxonomy.PREEMPTION),
            deadline=rpolicy.Deadline(max_wait_s),
        )
        if self._bank is not None:
            self._place_bank()
        if self._bank_delegate is not None:
            self._bank_delegate.rebuild_mesh(mesh, max_wait_s=max_wait_s)

    # -- the pure per-test-point query ------------------------------------
    def _query_one(self, params, train_x, train_y, postings, u, i, test_x,
                   *, pad: int):
        model = self.model
        # Device-side related-set gather: user postings first, then item
        # postings, duplicates kept — exactly the reference's ordering
        # (``matrix_factorization.py:315-322``) and InteractionIndex
        # .related()'s, so host-side result unpadding stays aligned.
        uoff, urows, ioff, irows = postings
        nu = uoff[u + 1] - uoff[u]
        ni = ioff[i + 1] - ioff[i]
        p = jnp.arange(pad, dtype=jnp.int32)
        gu = urows[jnp.clip(uoff[u] + p, 0, urows.shape[0] - 1)]
        gi = irows[jnp.clip(ioff[i] + (p - nu), 0, irows.shape[0] - 1)]
        rel_idx = jnp.where(p < nu, gu, gi)
        rel_mask = p < nu + ni
        rel_x = train_x[rel_idx]
        rel_y = train_y[rel_idx]
        w = rel_mask.astype(jnp.float32)
        count = jnp.sum(w)

        # v = ∇_block r̂(u*, i*)  (test-side vector)
        v = G.block_prediction_grad(model, params, u, i, test_x[None, :])

        hvp = H.make_block_hvp(model, params, u, i, rel_x, rel_y, w, self.damping)
        if self.solver in ("direct", "schulz"):
            d = model.block_size
            if self._analytic_hessian:
                Hmat = model.block_hessian(params, u, i, rel_x, rel_y, w)
                Hmat = Hmat + self.damping * jnp.eye(d, dtype=jnp.float32)
            else:
                Hmat = H.materialize_block_hessian(
                    model, params, u, i, rel_x, rel_y, w, self.damping
                )
            if self.solver == "schulz":
                # same knobs as CG; an unreachably tight tol is safe (the
                # solver's best-iterate/divergence guard caps iterations)
                ihvp = solvers.solve_schulz(
                    Hmat, v, maxiter=self.cg_maxiter, tol=self.cg_tol
                )
            else:
                ihvp = solvers.solve_direct(Hmat, v)
        elif self.solver == "cg":
            ihvp = solvers.solve_cg(hvp, v, maxiter=self.cg_maxiter, tol=self.cg_tol)
        elif self.lissa_tune == "spectral":
            # Spectrum-aware tuning (the bank-miss rung lands here:
            # QUERY_SOLVER_FALLBACK['precomputed'] == 'lissa'):
            # extreme_eigvals gives BOTH ends of the block spectrum, so
            # besides lifting scale past λ_max (which solve_lissa's own
            # guard also does) an indefinite block — λ_min < 0 through
            # the e·C cross term away from an optimum, where the
            # recursion diverges at ANY scale — gets a PD shift folded
            # into the operator; the result then solves the
            # shift-damped system (H + shift·I)x = v, finite where the
            # static config NaNs. PD blocks see shift ≈ 0 and keep the
            # reference semantics.
            scale, shift = spectral.lissa_tuning(
                hvp, model.block_size, scale_floor=self.lissa_scale
            )
            ihvp = solvers.solve_lissa(
                lambda x_, _s=shift: hvp(x_) + _s * x_, v, scale=scale,
                recursion_depth=self.lissa_depth, auto_scale=False,
            )
        else:
            # no num_samples here: the block HVP is DETERMINISTIC (full
            # related set every step), so averaged recursions would be
            # bit-identical — multi-sample averaging lives on the full
            # engine, whose minibatched sample_hvp is stochastic
            ihvp = solvers.solve_lissa(
                hvp, v, scale=self.lissa_scale,
                recursion_depth=self.lissa_depth,
            )

        # One vmapped per-example-gradient batch + one matvec replaces the
        # reference's per-row sess.run scoring loop.
        per_ex = G.per_example_block_loss_grads(model, params, u, i, rel_x, rel_y)
        scores = (per_ex @ ihvp) / jnp.maximum(count, 1.0)
        scores = jnp.where(rel_mask, scores, 0.0)
        return scores, ihvp, v, rel_mask

    def _batched(self, pad: int):
        if pad not in self._jitted:
            inner = jax.vmap(
                partial(self._query_one, pad=pad),
                in_axes=(None, None, None, None, 0, 0, 0),
            )

            def fn(*a):
                scores, ihvp, v, _ = inner(*a)
                return scores, ihvp, v

            self._jitted[pad] = jax.jit(fn)
        return self._jitted[pad]

    # -- flat segment-sum query path --------------------------------------
    @staticmethod
    def _flat_prelude(s_pad: int):
        """The flat program's integer prelude, shared by ``_flat_fn``,
        ``_bank_fn``, and the sharded out_fn's rel-id recomputation (all
        three must produce the IDENTICAL row layout — integer ops, so
        sharing the code makes that exact by construction). Maps a
        ``(T, 2)`` query block + CSR postings to per-flat-position
        ``(u, i, counts, t, row, wv, ut, it)``: segment ids ``t``, the
        owning train-row index ``row``, validity weights ``wv``, and
        the per-row owning-query ids ``ut``/``it``."""

        def prelude(tx, postings):
            T = tx.shape[0]
            u, i = tx[:, 0], tx[:, 1]
            uoff, urows, ioff, irows = postings
            nu = uoff[u + 1] - uoff[u]
            ni = ioff[i + 1] - ioff[i]
            counts = nu + ni
            off = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32),
                 jnp.cumsum(counts, dtype=jnp.int32)]
            )
            total = off[-1]

            s = jnp.arange(s_pad, dtype=jnp.int32)
            # segment ids by scatter+cumsum, not searchsorted: the
            # binary search lowers to ~log2(T) serialized S-wide gather
            # rounds, the scan to one T-element scatter + one VPU
            # cumsum. Duplicate offsets (empty segments) accumulate in
            # the scatter and the cumsum skips them correctly.
            t = jnp.clip(
                jnp.cumsum(
                    jnp.zeros((s_pad,), jnp.int32)
                    .at[off[1:T]]
                    .add(1, mode="drop")
                ),
                0, T - 1,
            )
            pos = s - off[t]
            valid = s < total
            ut, it = u[t], i[t]
            # ONE flat-row gather from the concatenated postings (item
            # lists offset past the user lists) instead of gathering
            # both lists and selecting — halves the dominant random-
            # access traffic of the row construction
            cat_rows = jnp.concatenate([urows, irows])
            base = jnp.where(
                pos < nu[t],
                uoff[ut] + pos,
                urows.shape[0] + ioff[it] + pos - nu[t],
            )
            row = cat_rows[jnp.clip(base, 0, cat_rows.shape[0] - 1)]
            wv = valid.astype(jnp.float32)
            return u, i, counts, t, row, wv, ut, it

        return prelude

    def _flat_fn(self, s_pad: int, stage: str = "scores",
                 donate: bool = False):
        """All queries' related rows concatenated into one flat (S,)
        axis; per-query Hessians accumulated by segment reduction.

        The padded per-query layout wastes compute proportionally to
        max/mean related-set skew (~10× on ML-1M: pad 3584 vs mean 356);
        here device work scales with the ACTUAL total row count. Requires
        the model's Gauss-Newton hooks (``block_cross_const`` /
        ``block_reg_diag``, see models/base.py) and the direct solver.
        Outputs are identical in layout to ``_batched_packed``: flat
        scores in query order (user postings then item postings), plus
        (T, d) ihvp and test vectors.

        ``stage`` truncates the program for roofline accounting
        (scripts/roofline.py): "grads" stops after the per-row block
        gradients, "hessian" after the segment-reduced Hessians,
        "solve" after the batched solves; "scores" (default) is the
        full program. Stages are cumulative prefixes of one program, so
        best-of-N time differences attribute device cost per stage.

        Under a mesh the SAME single-device body runs per query shard:
        ``_dispatch_flat`` packs the batch into a ``(ndev, t_loc, 2)``
        scratch placed along the 'data' axis, and the compiled program
        is a vmap of this body over the shard axis — embarrassingly
        parallel, zero hot-path collectives (each query's Hessian only
        reads its own related rows), and bit-identical to the
        single-device program because every shard executes the exact
        accumulation order the single-device geometry would
        (docs/design.md §15).
        """
        use_feat = self._rowfeat is not None
        variant = self._kernel_variant
        sharded = self._sharded_now()
        key = ("flat", s_pad, stage, use_feat, donate, variant, sharded)
        if key in self._jitted:
            return self._jitted[key]
        if stage not in ("grads", "hessian", "solve", "scores"):
            raise ValueError(f"unknown stage {stage!r}")
        model = self.model
        mesh = self.mesh
        prelude = self._flat_prelude(s_pad)
        d = model.block_size
        # chunk must divide S; flat_chunk is a power of two and S a
        # multiple of the bucket floor, so the gcd is their largest
        # common chunking (≥ 2048 whenever flat_chunk ≥ 2048). Under a
        # mesh s_pad is the PER-SHARD row pad (same bucketing), so the
        # same gcd applies shard-locally.
        import math

        chunk = math.gcd(s_pad, self.flat_chunk)

        def fn(params, train_x, train_y, postings, tx, rowfeat,
               grel=None, gqry=None):
            T = tx.shape[0]
            u, i, counts, t, row, wv, ut, it = prelude(tx, postings)

            # Per-flat-row prediction gradients w.r.t. the owning
            # query's block (the J of the Gauss-Newton form), residual
            # e, and the user/item match masks. Three tiers, fastest
            # first:
            #  - fused row-feature table: ONE wide gather; every other
            #    per-row gather reads a full (8, 128) tile for <=16
            #    useful values — XLA's cost model put the multi-gather
            #    grads stage at 39 GB accessed vs ~1.5 GB fused
            #    (output/roofline_mf.json, r4)
            #  - kernels.row_grads: the analytic block_row_grads hook
            #    (one batched program over gathered inputs), or the
            #    vmapped-autodiff reference — S single-row graphs,
            #    measured 92% of MF flat-query device time (BENCH r4
            #    device_split) — per the engine's kernel variant.
            # The Hessian stage consumes g tile-by-tile either way; the
            # 'pallas' variant re-forms gradients in VMEM for the SCORE
            # stage only (influence/kernels/), so g below still feeds
            # the accumulation.
            if use_feat:
                feat = rowfeat[row]
                g, e, ma, mb = model.grads_from_row_features(feat, ut, it)
                ab = wv * ma * mb
                rel_x = train_x[row] if variant == "pallas" else None
            elif grel is not None:
                # row-sharded tables: the per-row block gradients and
                # residuals come from rows the dispatching out_fn
                # gathered ONCE via the 'model'-axis collective
                # (parallel/sharded.gather_table_rows); the hook is
                # op-for-op the row_grads + predict pair below, so the
                # sharded program stays bitwise the replicated one.
                rel_x = train_x[row]
                rel_y = train_y[row]
                g, e = model.grads_from_rows(
                    params, grel, rel_x, rel_y, ut, it
                )
                ab = wv * (rel_x[:, 0] == ut) * (rel_x[:, 1] == it)
            else:
                rel_x = train_x[row]
                rel_y = train_y[row]
                g = K.row_grads(model, variant, params, ut, it, rel_x)
                e = model.predict(params, rel_x) - rel_y
                ab = wv * (rel_x[:, 0] == ut) * (rel_x[:, 1] == it)
            if stage == "grads":
                return g, e

            # H_t = (2/n_t) Σ_{s∈t} w (g gᵀ + a b e C) + diag(reg) + λI,
            # accumulated in chunks so the outer-product buffer stays small

            onehot = self.flat_accum == "onehot" or (
                self.flat_accum == "auto"
                and jax.default_backend() == "tpu"
            )

            def accum(g_r, t_r, w_r, abe_r):
                """Chunked scan: (nc, chunk, ...) -> (T, d, d), (T,)."""

                def body_scatter(carry, args):
                    acc, s_abe = carry
                    gc, tc, wc, ac = args
                    outer = (gc * wc[:, None])[:, :, None] * gc[:, None, :]
                    return (acc.at[tc].add(outer), s_abe.at[tc].add(ac)), None

                def body_onehot(carry, args):
                    # Segment reduction as one (T, chunk) @ (chunk, d²)
                    # matmul: scatter-adds serialize on the VPU
                    # (row-at-a-time accumulate), while the one-hot
                    # contraction rides the MXU — the wasted multiplies
                    # on zero one-hot entries are far cheaper than the
                    # scatter's serialization (chip A/B, BASELINE §4.3).
                    # fp32 einsum: Hessian entries accumulate hundreds
                    # of rows; bf16 mantissas would cost real fidelity.
                    acc, s_abe = carry
                    gc, tc, wc, ac = args
                    oh = (
                        tc[:, None] == jnp.arange(T, dtype=tc.dtype)[None, :]
                    ).astype(jnp.float32)  # (chunk, T)
                    outer = (
                        (gc * wc[:, None])[:, :, None] * gc[:, None, :]
                    ).reshape(-1, d * d)
                    Hc = jax.lax.dot_general(
                        oh, outer,
                        (((0,), (0,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST,
                    )  # (T, d²)
                    # elementwise-masked sum, not oh.T @ ac: a default-
                    # precision matmul would round abe to bf16 on TPU
                    # while the Hessian contraction above runs HIGHEST
                    return (
                        acc + Hc.reshape(T, d, d),
                        s_abe + jnp.sum(oh * ac[:, None], axis=0),
                    ), None

                (acc, s_abe), _ = jax.lax.scan(
                    body_onehot if onehot else body_scatter,
                    (jnp.zeros((T, d, d), jnp.float32),
                     jnp.zeros((T,), jnp.float32)),
                    (g_r, t_r, w_r, abe_r),
                )
                return acc, s_abe

            nc = s_pad // chunk
            HH, sum_abe = accum(
                g.reshape(nc, chunk, d), t.reshape(nc, chunk),
                wv.reshape(nc, chunk), (ab * e).reshape(nc, chunk),
            )
            n_t = jnp.maximum(counts.astype(jnp.float32), 1.0)
            C = model.block_cross_const(params)
            rdiag = model.block_reg_diag(params)
            H = (2.0 / n_t)[:, None, None] * (
                HH + sum_abe[:, None, None] * C[None]
            ) + jnp.diag(rdiag + self.damping)[None]
            if stage == "hessian":
                return H

            if gqry is not None:
                # Row-sharded query-side math: per-query "mini params"
                # substitute each table with its single gathered row
                # (leading axis 1), so the IDENTICAL autodiff /
                # extract_block graphs run at (u, i) = (0, 0) on row 0
                # — the jnp.where masks in block_predict select the
                # block branch in both programs (the test point IS the
                # query pair), so v and θ are bitwise the replicated
                # path's.
                zx = jnp.zeros((1, 2), jnp.int32)

                def mini(qr):
                    return {
                        kk: (qr[kk][None] if kk in qr else vv)
                        for kk, vv in params.items()
                    }

                v = jax.vmap(
                    lambda qr: G.block_prediction_grad(
                        model, mini(qr), 0, 0, zx
                    )
                )(gqry)
            else:
                v = jax.vmap(
                    lambda uu, ii, xj: G.block_prediction_grad(
                        model, params, uu, ii, xj[None, :]
                    )
                )(u, i, tx)
            ihvp = jax.vmap(solvers.solve_direct)(H, v)
            if stage == "solve":
                return ihvp, v

            # score_s = ∇_block L(z_s) · ihvp_t / n_t, with the per-example
            # loss gradient 2 e g + wd·θ̃ (θ̃ = decayed block dims)
            if gqry is not None:
                theta = jax.vmap(
                    lambda qr: model.flatten_block(
                        model.extract_block(mini(qr), 0, 0)
                    )
                )(gqry)
            else:
                theta = jax.vmap(
                    lambda uu, ii: model.flatten_block(
                        model.extract_block(params, uu, ii)
                    )
                )(u, i)
            reg_dot = jnp.sum(theta * rdiag[None] * ihvp, axis=1)  # (T,)
            scores = K.fused_scores(
                model, variant, params, ut, it, t, rel_x, e, wv,
                ihvp, reg_dot, n_t, g=g,
            )
            return scores, ihvp, v

        if mesh is None:
            out_fn = fn
        elif sharded:
            from fia_tpu.parallel import sharded as SH

            def out_fn(params, train_x, train_y, postings, txs, rowfeat):
                # Same (ndev, t_loc, 2) query-shard layout as the
                # replicated branch below — but the tables live
                # row-sharded over 'model', so the block rows every
                # per-query op needs are fetched FIRST: the flat rel
                # ids are recomputed per shard with the SAME integer
                # prelude the body runs (exact by construction), then
                # two gather_table_rows collectives (rel rows on the
                # s_pad axis, query rows on the t_loc axis) move
                # exactly the needed rows onto each query's data
                # shard. Everything downstream is shard-local — the
                # only hot-path collectives are the two gathers
                # (docs/design.md §20).
                txs = jax.lax.with_sharding_constraint(
                    txs, NamedSharding(mesh, P("data", None, None))
                )
                rel = jax.vmap(
                    # prelude()[4] is the flat train-row index
                    lambda t: train_x[prelude(t, postings)[4]]
                )(txs)
                grel = SH.gather_table_rows(
                    mesh, model, params, rel[..., 0], rel[..., 1]
                )
                gqry = SH.gather_table_rows(
                    mesh, model, params, txs[..., 0], txs[..., 1]
                )
                out = jax.vmap(
                    lambda t, gr, gq: fn(params, train_x, train_y,
                                         postings, t, rowfeat,
                                         grel=gr, gqry=gq)
                )(txs, grel, gqry)
                return jax.tree_util.tree_map(
                    lambda a: jax.lax.with_sharding_constraint(
                        a, NamedSharding(
                            mesh, P("data", *([None] * (a.ndim - 1)))
                        )
                    ),
                    out,
                )
        else:
            def out_fn(params, train_x, train_y, postings, txs, rowfeat):
                # (ndev, t_loc, 2) query shards placed along 'data' by
                # _dispatch_flat: vmap the single-device body over the
                # shard axis and pin every output's leading dim to the
                # same placement, so GSPMD partitions the whole program
                # shard-locally (each device runs exactly the
                # single-device geometry on its own queries) and the
                # host fetch sees a deterministic layout.
                txs = jax.lax.with_sharding_constraint(
                    txs, NamedSharding(mesh, P("data", None, None))
                )
                out = jax.vmap(
                    lambda t: fn(params, train_x, train_y, postings, t,
                                 rowfeat)
                )(txs)
                return jax.tree_util.tree_map(
                    lambda a: jax.lax.with_sharding_constraint(
                        a, NamedSharding(
                            mesh, P("data", *([None] * (a.ndim - 1)))
                        )
                    ),
                    out,
                )

        # Donating the query-id scratch — the only per-dispatch
        # host→device operand — lets XLA reuse its buffer instead of
        # allocating one per dispatch (every other operand is resident).
        self._jitted[key] = (
            jax.jit(out_fn, donate_argnums=(4,)) if donate
            else jax.jit(out_fn)
        )
        return self._jitted[key]

    def _flat_eligible(self) -> bool:
        return (
            # meshes (single- or multi-process) shard the QUERY axis:
            # each device runs the single-device program on its own
            # query shard (_mesh_plan / _dispatch_flat), so mesh
            # results stay bit-identical to one device; multi-host
            # output assembly rides the same process allgather as the
            # padded path (r3 VERDICT item 5 — the fast path covers pods)
            self.solver == "direct"
            and not self.group_queries
            # the flat path always builds the Hessian from the analytic
            # GN hooks — an explicit 'autodiff' request must be honored
            and self.hessian_mode != "autodiff"
            # 'dataset' promises one compiled program and a uniform
            # output pad across batches — a padded-path contract
            and self.pad_policy == "batch"
            and self.model.block_cross_const is not None
            and self.model.block_reg_diag is not None
            # row-sharded tables additionally need the pre-gathered-row
            # gradient hook (the sharded body never indexes a table)
            and (not self._sharded_now()
                 or self.model.grads_from_rows is not None)
        )

    def _query_pad(self, T: int) -> int:
        """Query-axis pad of a flat dispatch (see ``query_bucket``).

        Under a mesh this is the PER-SHARD pad: ``_mesh_plan`` calls it
        on the shard's query count, so every shard solves at the same
        canonical batch size as a single-device dispatch of that count
        (the bit-exactness contract of ``query_bucket``).
        """
        if self.query_bucket <= 0:
            return T
        return bucketed_pad(T, self.query_bucket)

    def _s_pad_for(self, total: int) -> int:
        """Flat-axis pad for ``total`` related rows.

        Geometric bucketing (~12.5% granule): pure powers of two waste
        up to ~50% device work on padded rows (measured 44% on ML-1M
        256-query batches — the flat program is compute-bound, so
        padding is wall-clock). The power-of-two floor keeps S a
        multiple of every flat_chunk ≤ floor (the scan reshape needs
        chunk | S). Under a mesh this buckets each shard's OWN row
        total (``_mesh_plan`` takes the max across shards).
        """
        return bucketed_pad(total, 2048)

    def _mesh_plan(self, counts: np.ndarray, T: int):
        """Query-axis shard plan of one mesh dispatch.

        The batch splits into ``ndev`` contiguous shards of ``q`` real
        queries (the last possibly ragged or empty); every shard pads
        its query axis to a common ``t_loc`` and its flat row axis to a
        common ``s_loc`` — the max over shards of the single-device
        bucketing — so each device executes exactly the single-device
        program geometry on its slice. Returns
        ``(ndev, q, t_loc, s_loc)``.
        """
        ndev = int(self.mesh.shape["data"])
        q = -(-max(int(T), 1) // ndev)
        t_loc = self._query_pad(q)
        counts = np.asarray(counts, np.int64)
        s_loc = 1
        for k in range(ndev):
            tot = int(counts[k * q: (k + 1) * q].sum())
            s_loc = max(s_loc, self._s_pad_for(max(tot, 1)))
        return ndev, q, t_loc, s_loc

    def flat_geometry(self, test_points: np.ndarray) -> tuple[int, int]:
        """``(t_pad, s_pad)`` compile geometry of the flat dispatch these
        points would issue — what :meth:`precompile_flat` must arm so
        the dispatch itself never traces or compiles. Under a mesh both
        numbers are PER-SHARD (the executable's shapes carry a leading
        ``ndev`` shard axis on top of them)."""
        test_points = np.asarray(test_points)
        if test_points.ndim == 1:
            test_points = test_points[None, :]
        counts = self.index.counts_batch(test_points)
        if self.mesh is not None:
            _, _, t_loc, s_loc = self._mesh_plan(
                counts, int(test_points.shape[0])
            )
            return (t_loc, s_loc)
        return (
            self._query_pad(int(test_points.shape[0])),
            self._s_pad_for(int(counts.sum())),
        )

    def _donate_scratch(self) -> bool:
        # CPU ignores donation (with a warning per dispatch).
        # Single-process meshes donate since r7: the scratch is placed
        # with exactly the NamedSharding the executable was lowered
        # with, so the donated layout always matches (pinned by
        # tests/test_mesh_dispatch.py). Multi-host keeps the undonated
        # path — per-process pieces of a make_array_from_callback
        # global carry no such layout guarantee.
        return jax.default_backend() != "cpu" and not self._multihost

    def _mesh_fp(self):
        from fia_tpu.parallel.mesh import mesh_fingerprint

        return mesh_fingerprint(self.mesh)

    def active_kernel_variant(self) -> str:
        """The resolved score-kernel variant ('pallas' /
        'xla_analytic' / 'vmap_autodiff') — bench/serve report it so
        perf trajectories across kernel generations stay comparable."""
        return self._kernel_variant

    def _aot_key(self, t_pad: int, s_pad: int):
        # mesh fingerprint LAST: warmup/compiled_geometries index the
        # geometry as (k[1], k[2]) — appending keeps those stable; the
        # kernel variant sits before it so a variant flip (e.g. a
        # post-recovery CPU rebuild) can never serve a stale executable,
        # and the table-placement flag likewise (rebuild_mesh can flip
        # a shard_tables engine between sharded and replicated programs)
        return ("flat", t_pad, s_pad, self._rowfeat is not None,
                self._donate_scratch(), self._kernel_variant,
                self._sharded_now(), self._mesh_fp())

    def precompile_flat(self, geometries) -> dict:
        """AOT pre-lower + compile flat programs for ``(t_pad, s_pad)``
        geometries (``jax.jit(...).lower(...).compile()``) ahead of any
        dispatch, so a warmed engine never pays trace-or-compile on the
        hot path. Geometries come from :meth:`flat_geometry` over the
        planned batches (serve warmup) or an explicit list. No-op when
        the flat path is ineligible. Returns the compile inventory:
        ``{"compiled": [[t,s],...], "cached": [...], "seconds": float}``.
        """
        if not (self.impl in ("auto", "flat") and self._flat_eligible()):
            return {"compiled": [], "cached": [], "seconds": 0.0}
        t0 = time.perf_counter()
        compiled, cached = [], []
        # backend-compile events fired inside .compile() attach to this
        # span via compilemon's obs mirror — AOT-key attribution
        with obs.span("engine.precompile") as _osp:
            self._precompile_geometries(geometries, compiled, cached)
            _osp.set(compiled=len(compiled), cached=len(cached))
        return {"compiled": compiled, "cached": cached,
                "seconds": time.perf_counter() - t0}

    def _precompile_geometries(self, geometries, compiled, cached):
        for t_pad, s_pad in geometries:
            t_pad, s_pad = int(t_pad), int(s_pad)
            key = self._aot_key(t_pad, s_pad)
            if key in self._aot:
                cached.append([t_pad, s_pad])
                continue
            fn = self._flat_fn(s_pad, donate=self._donate_scratch())
            params_in = self.params
            if self.mesh is not None:
                # lower WITH the dispatch-time input shardings: the AOT
                # executable is strict about operand placement, and
                # baking the NamedSharding in keeps steady state
                # zero-compile on any device count (compilemon-pinned).
                # Row-sharded tables (shard_tables) lower as sharded
                # ShapeDtypeStructs carrying each resident leaf's
                # NamedSharding — the lowering never touches the real
                # buffers; un-placed leaves (the replicated-mesh case,
                # whose params live uncommitted on one device) lower as
                # plain specs and keep jit's free placement.
                ndev = int(self.mesh.shape["data"])
                tx = jax.ShapeDtypeStruct(
                    (ndev, t_pad, 2), jnp.int32,
                    sharding=NamedSharding(self.mesh, P("data", None, None)),
                )
                if self._sharded_now():
                    params_in = jax.tree_util.tree_map(
                        lambda a: jax.ShapeDtypeStruct(
                            a.shape, a.dtype, sharding=a.sharding
                        ),
                        self.params,
                    )
            else:
                tx = jax.ShapeDtypeStruct((t_pad, 2), jnp.int32)
            self._aot[key] = fn.lower(
                params_in, self.train_x, self.train_y, self._postings,
                tx, self._rowfeat,
            ).compile()
            compiled.append([t_pad, s_pad])

    def compiled_geometries(self) -> dict:
        """Compiled flat-program inventory (bench/serve reporting):
        AOT ``[t_pad, s_pad]`` pairs plus jit cache keys."""
        return {
            "aot": sorted([k[1], k[2]] for k in self._aot),
            "jit": sorted(str(k) for k in self._jitted),
        }

    def _flat_exec(self, t_pad: int, s_pad: int):
        """The executable for one dispatch geometry: the AOT program
        when :meth:`precompile_flat` armed one, else the jit-cached
        program (which compiles on first call)."""
        exe = self._aot.get(self._aot_key(t_pad, s_pad))
        if exe is not None:
            obs.REGISTRY.counter("engine.aot_hits").inc()
            return exe
        # jit path: compiles on first call for this geometry — the
        # compile itself shows up via compilemon's obs mirror
        obs.REGISTRY.counter("engine.aot_misses").inc()
        return self._flat_fn(s_pad, donate=self._donate_scratch())

    def _dispatch_flat(self, test_points: np.ndarray, pad_to: int | None):
        """Enqueue one flat query program; returns an opaque handle for
        :meth:`_finalize_flat`. Dispatch is async — the device starts
        crunching while the host moves on.

        Span-only wrapper: the dispatch body lives in
        ``_dispatch_flat_inner`` (the function registered on the
        FIA204/205 dispatch path in analysis/config.py)."""
        with obs.span("engine.dispatch_flat",
                      n=int(len(test_points))) as sp:
            handle = self._dispatch_flat_inner(test_points, pad_to)
            shards = handle[4]
            if shards is not None:
                sp.set(ndev=shards[0], t_loc=shards[2])
            return handle

    def _dispatch_flat_inner(self, test_points: np.ndarray,
                             pad_to: int | None):
        inject.fire(sites.ENGINE_DISPATCH_FLAT)
        counts = self.index.counts_batch(test_points)
        tx_np = np.ascontiguousarray(np.asarray(test_points, np.int64))
        T = tx_np.shape[0]
        pad = bucketed_pad(
            counts.max() if counts.size else 1, self.pad_bucket, pad_to
        )
        if self.mesh is not None:
            ndev, q, t_loc, s_loc = self._mesh_plan(counts, T)
            # Pack the batch into (ndev, t_loc, 2): shard k takes
            # queries [k*q, (k+1)*q), short/empty shards duplicating
            # their trailing real pair (the batch's last pair when the
            # shard is past the ragged end) — exactly the single-device
            # query-axis padding, so pad rows' flat positions land past
            # each shard's real total and _assemble_packed slices them
            # away per shard.
            sh = np.empty((ndev, t_loc, 2), np.int64)
            for k in range(ndev):
                rows = tx_np[k * q: (k + 1) * q]
                if rows.shape[0] == 0:
                    rows = tx_np[-1:]
                if rows.shape[0] < t_loc:
                    rows = np.concatenate(
                        [rows,
                         np.repeat(rows[-1:], t_loc - rows.shape[0], axis=0)]
                    )
                sh[k] = rows
            # the one sanctioned host→device transfer of the dispatch:
            # placed along 'data' so each device receives only its own
            # shard (works single- and multi-process)
            from fia_tpu.parallel.distributed import put_global

            tx = put_global(
                self.mesh, sh.astype(np.int32), P("data", None, None)
            )
            out = self._flat_exec(t_loc, s_loc)(
                self.params, self.train_x, self.train_y, self._postings,
                tx, self._rowfeat,
            )
            return (test_points, counts, out, pad, (ndev, q, t_loc))
        total = int(counts.sum())
        s_pad = self._s_pad_for(total)
        t_pad = self._query_pad(T)
        if t_pad > T:
            # Query-axis padding: duplicate the trailing (u, i) pair up
            # to the bucket. Pad rows take flat positions AFTER the real
            # total (their segment offsets start at off[T]), so real
            # scores are untouched and _assemble_packed's [:T] slice
            # recovers bit-identical payloads; pad rows past s_pad are
            # simply truncated (their garbage Hessians stay PD via the
            # damping diagonal and are sliced away with everything else).
            tx_np = np.concatenate(
                [tx_np, np.repeat(tx_np[-1:], t_pad - T, axis=0)]
            )
        tx = jnp.asarray(tx_np, jnp.int32)
        out = self._flat_exec(t_pad, s_pad)(
            self.params, self.train_x, self.train_y, self._postings, tx,
            self._rowfeat,
        )
        return (test_points, counts, out, pad, None)

    def _finalize_flat(self, handle) -> InfluenceResult:
        test_points, counts, out, pad, shards = handle
        return self._assemble_packed(test_points, counts, out, pad,
                                     shards=shards)

    def _query_flat(
        self,
        test_points: np.ndarray,
        pad_to: int | None = None,
        _depth: int = 0,
    ) -> InfluenceResult:
        try:
            return self._finalize_flat(
                self._dispatch_flat(test_points, pad_to)
            )
        except Exception as e:
            T = len(test_points)
            cls = _classify_device_failure(e)
            if cls == taxonomy.PREEMPTION and _depth < 3:
                # Preemption carries no size evidence: rebuild (the
                # reset's own backoff waits out the reclaim window) and
                # retry at the SAME size. _depth bounds a permanently
                # reclaimed slice.
                self._reset_device_state()
                return self._query_flat(test_points, pad_to, _depth + 1)
            if cls != taxonomy.WORKER or _depth >= 3 or T <= 1:
                if cls in _ADAPTIVE_KINDS:
                    # Ladder exhausted on a device-side fault: last rung
                    # is the CPU backend (None when unavailable/disabled).
                    cpu = self._query_on_cpu(test_points, pad_to)
                    if cpu is not None:
                        return cpu
                raise
            # Bounded retry-at-half after a TPU worker crash (the r3
            # k=256 failure: 64-query batches killed the worker twice,
            # 32 succeeded — BASELINE §4.1). The crash destroyed every
            # device buffer, so rebuild state first; depth 3 bounds a
            # persistent fault to ~log2 retries before surfacing.
            self._reset_device_state()
            h = (T + 1) // 2
            return _concat_results([
                self._query_flat(test_points[:h], pad_to, _depth + 1),
                self._query_flat(test_points[h:], pad_to, _depth + 1),
            ])

    def _query_on_cpu(
        self, test_points: np.ndarray, pad_to: int | None
    ) -> InfluenceResult | None:
        """Final degradation rung: answer the query on the CPU backend.

        Rebuilds a single-device engine from the host copies that
        survive any device failure (``_params_host``/``_train_host``)
        under ``jax.default_device(cpu)``. Returns ``None`` when the
        rung does not apply (disabled, already the fallback, mesh
        engines whose global arrays have no CPU analogue, or no CPU
        backend) so callers surface the original failure instead.
        """
        if not self.cpu_fallback or self._is_cpu_fallback:
            return None
        if self.mesh is not None:
            return None
        try:
            cpu0 = jax.local_devices(backend="cpu")[0]
        except Exception:
            return None
        if self._cpu_engine is None:
            obs.diag(
                "reliability",
                "device-side recovery exhausted; "
                "degrading to the CPU backend for this query",
            )
            with jax.default_device(cpu0):
                eng = InfluenceEngine(
                    self.model,
                    self._params_host,
                    RatingDataset(*self._train_host),
                    damping=self.damping,
                    solver=self.solver,
                    cg_maxiter=self.cg_maxiter,
                    cg_tol=self.cg_tol,
                    lissa_scale=self.lissa_scale,
                    lissa_depth=self.lissa_depth,
                    model_name=self.model_name + "-cpufb",
                    pad_bucket=self.pad_bucket,
                    hessian_mode="auto",
                    impl="auto",
                    # never interpret-mode Pallas in production: a
                    # forced-pallas engine degrades to the XLA twin on
                    # the CPU rung ('auto' resolves it there)
                    kernel="auto" if self.kernel == "pallas" else self.kernel,
                    lissa_tune=self.lissa_tune,
                    sampled_cap=self.sampled_cap,
                    sampled_tol=self.sampled_tol,
                )
                eng._is_cpu_fallback = True
            self._cpu_engine = eng
        with jax.default_device(cpu0):
            return self._cpu_engine.query_batch(test_points, pad_to=pad_to)

    def _wide_block_cap(self) -> bool:
        """Proactive dispatch cap for very wide blocks: the d=514
        (k=256) 64-query flat program kills the TPU worker outright (a
        runtime/kernel fault, not an XLA OOM — reproduced 6x across
        r3/r4, BASELINE §4.1) while 32-query dispatches are measured
        safe, and k=128 at 256 queries is fine, so the guard keys on
        block width alone. The reactive crash recovery (worker-
        signature classify -> state rebuild -> retry-at-half) still
        absorbs anything the cap misses, but a production k=256 sweep
        should not have to crash twice to find the safe size. Scoped
        to the TPU backend and the flat path, the only territory the
        fault was ever observed in."""
        return (int(self.model.block_size) >= 512
                and jax.default_backend() == "tpu")

    def query_many(
        self,
        test_points: np.ndarray,
        batch_queries: int = 256,
        pad_to: int | None = None,
        window: int = 4,
        journal: "Journal | None" = None,
        deadline: "rpolicy.Deadline | None" = None,
    ) -> list[InfluenceResult]:
        """Pipelined large workloads: split into query batches, keep up
        to ``window`` device programs in flight, and finalize in order.

        Host-side result assembly + transfer is ~40% of a single batch's
        latency on tunnel-attached hosts (BASELINE.md §4); dispatching
        batch r+1 before fetching batch r overlaps that host work with
        device compute. Falls back to sequential :meth:`query_batch`
        whenever the flat path is ineligible. The bounded window caps
        device-resident output buffers for very long workloads.

        ``journal``: a reliability :class:`Journal` (open it against
        :meth:`journal_fingerprint`); each finalized batch is recorded
        durably, and batches already journaled are reconstructed from
        the journal instead of recomputed — a killed workload resumes
        where it stopped. ``deadline``: a reliability ``Deadline``;
        expiry between batches raises ``DeadlineExpired`` with every
        completed batch already journaled (a clean, resumable stop).
        """
        test_points = np.asarray(test_points)
        if test_points.ndim == 1:
            test_points = test_points[None, :]
        if self._wide_block_cap():
            batch_queries = min(batch_queries, 32)
        batches = [
            test_points[i : i + batch_queries]
            for i in range(0, len(test_points), batch_queries)
        ]
        results: list[InfluenceResult | None] = [None] * len(batches)
        todo: list[int] = []
        for k in range(len(batches)):
            if journal is not None and journal.done(f"batch:{k}"):
                results[k] = self._result_from_journal(
                    journal.get(f"batch:{k}")
                )
            else:
                todo.append(k)

        def bank(k: int, res: InfluenceResult) -> None:
            results[k] = res
            if journal is not None:
                journal.record(f"batch:{k}", self._journal_payload(res))

        if not (self.impl in ("auto", "flat") and self._flat_eligible()):
            for k in todo:
                if deadline is not None:
                    deadline.check("query_many (sequential)")
                bank(k, self.query_batch(batches[k], pad_to=pad_to))
            return results
        done = 0  # finalize order == dispatch order == batch order
        try:
            inflight: list = []
            for k in todo:
                if deadline is not None:
                    deadline.check("query_many (dispatch)")
                inflight.append((k, self._dispatch_flat(batches[k], pad_to)))
                if len(inflight) >= max(1, window):
                    j, h = inflight.pop(0)
                    bank(j, self._finalize_flat(h))
                    done += 1
            while inflight:
                j, h = inflight.pop(0)
                bank(j, self._finalize_flat(h))
                done += 1
        except Exception as e:
            if _classify_device_failure(e) not in (
                taxonomy.WORKER, taxonomy.PREEMPTION
            ):
                raise
            # A worker crash/preemption kills every in-flight dispatch
            # at once. Rebuild device state and run the unfinalized
            # remainder sequentially through _query_flat, whose own
            # bounded halving (and CPU last rung) absorbs a recurring
            # crash; already-finalized results are host numpy and stay
            # valid — and journaled, when a journal is attached.
            self._reset_device_state()
            for k in todo[done:]:
                bank(k, self._query_flat(batches[k], pad_to))
        return results

    # -- resumable-execution plumbing --------------------------------------
    def journal_fingerprint(self, test_points: np.ndarray,
                            batch_queries: int = 256,
                            pad_to: int | None = None, **extra) -> dict:
        """Identity of a :meth:`query_many` workload for journal binding.

        Two runs share journal progress iff model/solver/config, the
        test-point stream AND the batch split agree — anything less and
        a resumed run would stitch batches computed under a different
        regime. ``extra`` lets callers fold in their own provenance.
        """
        import hashlib

        tp = np.ascontiguousarray(np.asarray(test_points, np.int64))
        return {
            "kind": "query_many",
            "model": self.model_name,
            "solver": self.solver,
            "damping": repr(self.damping),
            "pad_bucket": self.pad_bucket,
            # part of the numeric identity: the query-axis pad sets the
            # batched-solve geometry, so results journaled under one
            # bucket must not resume a run under another
            "query_bucket": self.query_bucket,
            "batch_queries": int(batch_queries),
            "pad_to": None if pad_to is None else int(pad_to),
            "n_points": int(tp.shape[0]) if tp.ndim > 1 else 1,
            "points_sha1": hashlib.sha1(tp.tobytes()).hexdigest(),
            **extra,
        }

    def _journal_payload(self, res: InfluenceResult) -> dict:
        """JSON-packable form of one batch result (exact round-trip)."""
        base = {
            "counts": np.asarray(res.counts),
            "ihvp": np.asarray(res.ihvp),
            "test_grad": np.asarray(res.test_grad),
        }
        if res.err_bound is not None:
            base["err_bound"] = np.asarray(res.err_bound)
            base["approx"] = np.asarray(res.approx)
        if res._packed is not None:
            base.update(
                fmt="packed",
                packed=np.asarray(res._packed),
                test_points=np.asarray(res._test_points),
                pad=int(res._pad),
            )
        else:
            base.update(
                fmt="dense",
                scores=np.asarray(res.scores),
                related_idx=np.asarray(res.related_idx),
                related_mask=np.asarray(res.related_mask),
            )
        return base

    def _result_from_journal(self, p: dict) -> InfluenceResult:
        err = p["err_bound"] if "err_bound" in p else None
        approx = bool(np.asarray(p["approx"])) if "approx" in p else False
        if p["fmt"] == "packed":
            return InfluenceResult(
                counts=p["counts"], ihvp=p["ihvp"],
                test_grad=p["test_grad"], packed=p["packed"],
                test_points=p["test_points"], index=self.index,
                pad=int(p["pad"]),
                err_bound=err, approx=approx,
            )
        return InfluenceResult(
            p["scores"], p["related_idx"], p["related_mask"],
            p["counts"], p["ihvp"], p["test_grad"],
            err_bound=err, approx=approx,
        )

    def _assemble_packed(self, test_points, counts, out, pad: int,
                         shards=None) -> InfluenceResult:
        """Wrap flat device outputs as a packed (lazily padded) result.

        One device_get for all outputs (separate per-array fetches
        serialise into host round trips). The padded (T, P) views are
        synthesized on first access from the host CSR, whose
        contiguous-prefix mask rows consume the packed scores in device
        order (user postings then item postings) — consumers reading
        ``scores_of``/``related_of`` never pay for padding.

        ``shards`` is the mesh dispatch's ``(ndev, q, t_loc)`` plan:
        outputs then carry a leading shard axis and each shard's REAL
        prefix (its own query count / row total) is sliced out and
        concatenated back into stream order — the host-side inverse of
        ``_dispatch_flat``'s shard packing.
        """
        if self._multihost:
            # outputs live partly on non-addressable devices; gather
            # every process a full host copy (same path as the padded
            # engine's multi-host fetch at _query_padded)
            from jax.experimental import multihost_utils

            packed, ihvp, v = multihost_utils.process_allgather(
                out, tiled=True
            )
        else:
            packed, ihvp, v = jax.device_get(out)
        T = int(np.asarray(counts).shape[0])
        if shards is not None:
            ndev, q, _ = shards
            cum = np.concatenate(
                [[0], np.cumsum(np.asarray(counts, np.int64))]
            )
            pk, ih, vv = [], [], []
            for k in range(ndev):
                lo, hi = min(k * q, T), min((k + 1) * q, T)
                if hi == lo:  # empty trailing shard (duplicate work)
                    continue
                pk.append(np.asarray(packed)[k, : int(cum[hi] - cum[lo])])
                ih.append(np.asarray(ihvp)[k, : hi - lo])
                vv.append(np.asarray(v)[k, : hi - lo])
            packed = np.concatenate(pk)
            ihvp = np.concatenate(ih)
            v = np.concatenate(vv)
        else:
            # Query-axis pad rows (duplicated trailing queries appended
            # by _dispatch_flat) slice away here; their flat rows
            # already sit past `total` in the packed scores.
            ihvp = np.asarray(ihvp)[:T]
            v = np.asarray(v)[:T]
        # NaN injection site: a diverged solve returns a "successful"
        # buffer — corruption (and detection) happens on the fetched
        # host payload, exactly like the real failure mode.
        ihvp = inject.corrupt(sites.ENGINE_SOLVE, np.asarray(ihvp))
        total = int(counts.sum())
        return InfluenceResult(
            counts=counts,
            ihvp=ihvp,
            test_grad=v,
            packed=np.asarray(packed)[:total],
            test_points=np.asarray(test_points),
            index=self.index,
            pad=pad,
        )

    def _batched_packed(self, pad: int, s: int):
        """Single-device fast path: compact the (T, P) padded scores into
        a flat (S,) valid-only array *on device* before they cross the
        host link. With skewed related-set sizes the padded matrix is
        mostly zeros (mean/max count ≈ 1/10 on ML-1M), so this cuts
        device→host traffic ~10× — the dominant cost of a steady-state
        query batch on tunnel/PCIe-attached hosts."""
        key = (pad, s)
        if key not in self._jitted:
            inner = jax.vmap(
                partial(self._query_one, pad=pad),
                in_axes=(None, None, None, None, 0, 0, 0),
            )

            def fn(params, train_x, train_y, postings, u, i, tx):
                scores, ihvp, v, mask = inner(params, train_x, train_y,
                                              postings, u, i, tx)
                fm = mask.reshape(-1)
                pos = jnp.cumsum(fm) - 1
                packed = (
                    jnp.zeros((s,), scores.dtype)
                    .at[jnp.where(fm, pos, s)]
                    .set(scores.reshape(-1), mode="drop")
                )
                return packed, ihvp, v

            self._jitted[key] = jax.jit(fn)
        return self._jitted[key]

    # -- precomputed factor-bank tier --------------------------------------
    def block_hessians(self, pairs: np.ndarray,
                       batch_queries: int = 512) -> np.ndarray:
        """Damped block Hessians for explicit (u, i) pairs, (N, d, d)
        host numpy — the factor-bank build's input.

        Rides the flat mega-batch program's ``hessian`` stage (ONE
        fused dispatch per ``batch_queries`` chunk, mesh-sharded when
        the engine carries a mesh) whenever the model's Gauss-Newton
        hooks allow; models without the hooks (or an explicit
        ``hessian_mode='autodiff'``) fall back to a vmapped per-pair
        materialisation over the padded related sets.
        """
        pairs = np.asarray(pairs, np.int64)
        if pairs.ndim == 1:
            pairs = pairs[None, :]
        gn_ok = (
            self.model.block_cross_const is not None
            and self.model.block_reg_diag is not None
            and self.hessian_mode != "autodiff"
        )
        out = []
        for s0 in range(0, len(pairs), max(int(batch_queries), 1)):
            chunk = pairs[s0: s0 + max(int(batch_queries), 1)]
            out.append(
                self._block_hessians_flat(chunk) if gn_ok
                else self._block_hessians_padded(chunk)
            )
        return out[0] if len(out) == 1 else np.concatenate(out)

    def _block_hessians_flat(self, chunk: np.ndarray) -> np.ndarray:
        counts = self.index.counts_batch(chunk)
        tx_np = np.ascontiguousarray(chunk)
        T = tx_np.shape[0]
        if self.mesh is not None:
            # same shard packing as _dispatch_flat: contiguous query
            # shards along 'data', trailing-pair duplication per shard
            ndev, q, t_loc, s_loc = self._mesh_plan(counts, T)
            sh = np.empty((ndev, t_loc, 2), np.int64)
            for k in range(ndev):
                rows = tx_np[k * q: (k + 1) * q]
                if rows.shape[0] == 0:
                    rows = tx_np[-1:]
                if rows.shape[0] < t_loc:
                    rows = np.concatenate(
                        [rows,
                         np.repeat(rows[-1:], t_loc - rows.shape[0], axis=0)]
                    )
                sh[k] = rows
            from fia_tpu.parallel.distributed import put_global

            tx = put_global(
                self.mesh, sh.astype(np.int32), P("data", None, None)
            )
            hess = self._flat_fn(s_loc, "hessian")(
                self.params, self.train_x, self.train_y, self._postings,
                tx, self._rowfeat,
            )
            if self._multihost:
                from jax.experimental import multihost_utils

                hess = multihost_utils.process_allgather(hess, tiled=True)
            hess = np.asarray(jax.device_get(hess))
            parts = []
            for k in range(ndev):
                lo, hi = min(k * q, T), min((k + 1) * q, T)
                if hi > lo:
                    parts.append(hess[k, : hi - lo])
            return np.concatenate(parts)
        t_pad = self._query_pad(T)
        if t_pad > T:
            tx_np = np.concatenate(
                [tx_np, np.repeat(tx_np[-1:], t_pad - T, axis=0)]
            )
        s_pad = self._s_pad_for(int(counts.sum()))
        hess = self._flat_fn(s_pad, "hessian")(
            self.params, self.train_x, self.train_y, self._postings,
            jnp.asarray(tx_np, jnp.int32), self._rowfeat,
        )
        return np.asarray(jax.device_get(hess))[:T]

    def _block_hessians_padded(self, chunk: np.ndarray) -> np.ndarray:
        idx, mask, _ = self.index.related_padded(
            chunk, bucket=self.pad_bucket
        )
        model, damping = self.model, self.damping
        d = int(model.block_size)

        def one(uu, ii, ridx, m):
            rel_x = self.train_x[ridx]
            rel_y = self.train_y[ridx]
            w = m.astype(jnp.float32)
            if self._analytic_hessian:
                Hm = model.block_hessian(
                    self.params, uu, ii, rel_x, rel_y, w
                )
                return Hm + damping * jnp.eye(d, dtype=jnp.float32)
            return H.materialize_block_hessian(
                model, self.params, uu, ii, rel_x, rel_y, w, damping
            )

        hess = jax.jit(jax.vmap(one))(
            jnp.asarray(chunk[:, 0], jnp.int32),
            jnp.asarray(chunk[:, 1], jnp.int32),
            jnp.asarray(idx, jnp.int32),
            jnp.asarray(mask),
        )
        return np.asarray(jax.device_get(hess))

    def factor_bank_path(self) -> str | None:
        """Default on-disk bank location (None without a cache_dir)."""
        if self.cache_dir is None:
            return None
        from fia_tpu.influence import factor as fbank

        return fbank.default_bank_path(self.cache_dir, self.model_name)

    def load_factor_bank(self, path: str | None = None) -> int:
        """Load (or reload) the factor bank device-resident.

        A *verified* load: artifact checksum + config/train fingerprint
        first (corrupt banks quarantine as ``*.corrupt``), then the
        per-entry ``dep_crc`` revalidation against the live params —
        stale entries are dropped before the bank ever serves. Any
        integrity failure or taxonomy-classified fault degrades to "no
        bank" (every query falls through the ladder); unclassified
        errors surface. Returns the number of servable entries.
        """
        from fia_tpu.influence import factor as fbank
        from fia_tpu.reliability import artifacts

        self._bank_load_attempted = True
        self._bank = None
        self._bank_lookup = None
        self._bank_device = None
        if path is None:
            path = self.factor_bank_path()
        if path is None or not os.path.exists(path):
            return 0
        try:
            inject.fire(sites.ENGINE_FACTOR_LOAD)
            bank, dropped = fbank.load_bank(path, self)
        except artifacts.ArtifactIntegrityError as e:
            obs.diag(
                "reliability",
                f"factor bank rejected ({e.reason}); "
                "queries fall through the solver ladder",
            )
            return 0
        except Exception as e:
            if taxonomy.classify(e) is None:
                raise
            obs.diag(
                "reliability",
                "factor bank load failed transiently; "
                "serving without the bank",
            )
            return 0
        self._bank_dropped_stale = int(dropped)
        if len(bank) == 0:
            return 0
        self._bank = bank
        self._bank_lookup = bank.lookup()
        self._place_bank()
        return len(bank)

    def _place_bank(self) -> None:
        """(Re)place the loaded bank device-resident.

        Under a mesh the factor/kind arrays are *explicitly replicated*
        with the mesh's own placement (``put_global``) rather than a
        bare ``jnp.asarray`` (which lands them on device 0 only):
        placement-aware residency — every device owns the full bank, so
        a bank hit on any query shard reads local factors and never
        cross-fetches state another device owns. With row-sharded
        tables the same applies to the block rows the hit program
        needs: those arrive through the dispatch's gather collective,
        never by reaching into another shard's table."""
        if self._bank is None:
            self._bank_device = None
            return
        fac = jnp.asarray(self._bank.factor)
        knd = jnp.asarray(self._bank.kind.astype(np.int32))
        if self.mesh is not None:
            from fia_tpu.parallel.distributed import put_global

            fac = put_global(self.mesh, fac, P())
            knd = put_global(self.mesh, knd, P())
        self._bank_device = (fac, knd)

    def ensure_factor_bank(self) -> int:
        """Load the bank once, lazily; returns servable entry count."""
        if not self._bank_load_attempted:
            with obs.span("engine.bank_load") as sp:
                self.load_factor_bank()
                sp.set(entries=0 if self._bank is None
                       else len(self._bank))
        return 0 if self._bank is None else len(self._bank)

    def unload_factor_bank(self) -> None:
        """Forget any loaded bank and reset the bank counters (test /
        chaos hook): the next :meth:`ensure_factor_bank` re-attempts the
        verified load, and the miss delegate restarts its solver ladder
        (keeping its compiled programs)."""
        self._bank = None
        self._bank_lookup = None
        self._bank_device = None
        self._bank_load_attempted = False
        self._bank_hits = 0
        self._bank_misses = 0
        self._bank_dropped_stale = 0
        if self._bank_delegate is not None:
            self._bank_delegate.solver = (
                rpolicy.next_solver("precomputed") or "direct"
            )

    def bank_contains(self, u: int, i: int) -> bool:
        return bool(self._bank_lookup) and (
            (int(u), int(i)) in self._bank_lookup
        )

    def bank_stats(self) -> dict:
        """Per-engine bank counters (bench/serve reporting)."""
        return {
            "entries": 0 if self._bank is None else len(self._bank),
            "hits": int(self._bank_hits),
            "misses": int(self._bank_misses),
            "dropped_stale": int(self._bank_dropped_stale),
        }

    def _miss_delegate(self) -> "InfluenceEngine":
        """Bank misses serve from a private engine at the next ladder
        rung — config-identical except solver and cache_dir, so a miss
        is bit-identical to a bank-less engine at that rung (the
        fall-through fidelity contract factor_smoke pins)."""
        if self._bank_delegate is None:
            self._bank_delegate = InfluenceEngine(
                self.model,
                self._params_host,
                RatingDataset(*self._train_host),
                damping=self.damping,
                solver=rpolicy.next_solver("precomputed") or "direct",
                cg_maxiter=self.cg_maxiter,
                cg_tol=self.cg_tol,
                lissa_scale=self.lissa_scale,
                lissa_depth=self.lissa_depth,
                mesh=self.mesh,
                cache_dir=None,
                model_name=self.model_name,
                pad_bucket=self.pad_bucket,
                shard_tables=self._shard_tables,
                hessian_mode=self.hessian_mode,
                group_queries=self.group_queries,
                pad_policy=self.pad_policy,
                impl=self.impl,
                flat_chunk=self.flat_chunk,
                flat_accum=self.flat_accum,
                row_features=self.row_features,
                cpu_fallback=self.cpu_fallback,
                query_bucket=self.query_bucket,
                kernel=self.kernel,
                lissa_tune=self.lissa_tune,
                sampled_cap=self.sampled_cap,
                sampled_tol=self.sampled_tol,
            )
        return self._bank_delegate

    def _bank_serving_eligible(self) -> bool:
        # the bank hit program is the flat prelude + a bank gather: it
        # needs the same GN hooks the flat path needs. Single-process
        # meshes serve hits too (r13): the bank replicates per device
        # (_place_bank) and the hit program query-shards exactly like
        # _dispatch_flat, with the same sharded-table gather when the
        # tables are row-sharded. Multi-host keeps the delegate route
        # (the allgather layout of a second program family isn't worth
        # the hit-rate at pod scale yet).
        return (
            not self._multihost
            and self._bank_device is not None
            and self.hessian_mode != "autodiff"
            and not self.group_queries
            and self.pad_policy == "batch"
            and self.model.block_cross_const is not None
            and self.model.block_reg_diag is not None
            and (not self._sharded_now()
                 or self.model.grads_from_rows is not None)
        )

    def _bank_fn(self, s_pad: int):
        """Flat scoring program for bank hits: the ``_flat_fn`` prelude
        (segment ids, one flat row gather, per-row block grads) with the
        Hessian accumulation and batched solve replaced by one gather
        from the device-resident bank plus a triangular solve (Cholesky
        entries) / matvec (explicit-inverse entries) per query — the
        O(1)-per-query hot path. Scores section is byte-for-byte the
        flat program's, so hit results keep the packed layout and the
        assembly/corruption seams downstream."""
        use_feat = self._rowfeat is not None
        variant = self._kernel_variant
        sharded = self._sharded_now()
        key = ("flatbank", s_pad, use_feat, variant, sharded)
        if key in self._jitted:
            return self._jitted[key]
        from jax.scipy.linalg import cho_solve

        model = self.model
        mesh = self.mesh
        prelude = self._flat_prelude(s_pad)

        def fn(params, train_x, train_y, postings, tx, rowfeat,
               bfac, bknd, bidx, grel=None, gqry=None):
            u, i, counts, t, row, wv, ut, it = prelude(tx, postings)
            if use_feat:
                feat = rowfeat[row]
                g, e, _, _ = model.grads_from_row_features(feat, ut, it)
                rel_x = train_x[row] if variant == "pallas" else None
            elif grel is not None:
                # row-sharded tables: rows pre-gathered by the
                # dispatching out_fn (see _flat_fn) — op-for-op the
                # row_grads + predict pair below
                rel_x = train_x[row]
                rel_y = train_y[row]
                g, e = model.grads_from_rows(
                    params, grel, rel_x, rel_y, ut, it
                )
            else:
                rel_x = train_x[row]
                rel_y = train_y[row]
                # no Hessian stage on the bank hot path: under the
                # fused kernel the (S, d) gradient matrix is never
                # formed at all — rows rebuild inside VMEM tiles
                g = (
                    None if variant == "pallas"
                    else K.row_grads(model, variant, params, ut, it, rel_x)
                )
                e = model.predict(params, rel_x) - rel_y

            if gqry is not None:
                # per-query mini params from gathered rows (see
                # _flat_fn's sharded branch — bitwise the same graphs)
                zx = jnp.zeros((1, 2), jnp.int32)

                def mini(qr):
                    return {
                        kk: (qr[kk][None] if kk in qr else vv)
                        for kk, vv in params.items()
                    }

                v = jax.vmap(
                    lambda qr: G.block_prediction_grad(
                        model, mini(qr), 0, 0, zx
                    )
                )(gqry)
            else:
                v = jax.vmap(
                    lambda uu, ii, xj: G.block_prediction_grad(
                        model, params, uu, ii, xj[None, :]
                    )
                )(u, i, tx)
            Fsel = bfac[bidx]  # (T, d, d): L or H^-1 per entry kind
            ksel = bknd[bidx]
            chol = jax.vmap(
                lambda Lt, vt: cho_solve((Lt, True), vt)
            )(Fsel, v)
            mv = jnp.einsum("tij,tj->ti", Fsel, v)
            ihvp = jnp.where((ksel == 1)[:, None], mv, chol)

            n_t = jnp.maximum(counts.astype(jnp.float32), 1.0)
            rdiag = model.block_reg_diag(params)
            if gqry is not None:
                theta = jax.vmap(
                    lambda qr: model.flatten_block(
                        model.extract_block(mini(qr), 0, 0)
                    )
                )(gqry)
            else:
                theta = jax.vmap(
                    lambda uu, ii: model.flatten_block(
                        model.extract_block(params, uu, ii)
                    )
                )(u, i)
            reg_dot = jnp.sum(theta * rdiag[None] * ihvp, axis=1)
            scores = K.fused_scores(
                model, variant, params, ut, it, t, rel_x, e, wv,
                ihvp, reg_dot, n_t, g=g,
            )
            return scores, ihvp, v

        if mesh is None:
            out_fn = fn
        else:
            def out_fn(params, train_x, train_y, postings, txs, rowfeat,
                       bfac, bknd, bidxs):
                # (ndev, t_loc, 2) query shards + (ndev, t_loc) bank
                # rows along 'data' (packed by _query_bank_hits, same
                # layout as _dispatch_flat); the bank itself is
                # replicated per device (_place_bank), so Fsel gathers
                # are shard-local. With row-sharded tables the block
                # rows arrive by the same two gather collectives as
                # the flat program.
                txs = jax.lax.with_sharding_constraint(
                    txs, NamedSharding(mesh, P("data", None, None))
                )
                bidxs = jax.lax.with_sharding_constraint(
                    bidxs, NamedSharding(mesh, P("data", None))
                )
                if sharded:
                    from fia_tpu.parallel import sharded as SH

                    rel = jax.vmap(
                        lambda t: train_x[prelude(t, postings)[4]]
                    )(txs)
                    grel = SH.gather_table_rows(
                        mesh, model, params, rel[..., 0], rel[..., 1]
                    )
                    gqry = SH.gather_table_rows(
                        mesh, model, params, txs[..., 0], txs[..., 1]
                    )
                    out = jax.vmap(
                        lambda t, b, gr, gq: fn(
                            params, train_x, train_y, postings, t,
                            rowfeat, bfac, bknd, b, grel=gr, gqry=gq,
                        )
                    )(txs, bidxs, grel, gqry)
                else:
                    out = jax.vmap(
                        lambda t, b: fn(params, train_x, train_y,
                                        postings, t, rowfeat, bfac,
                                        bknd, b)
                    )(txs, bidxs)
                return jax.tree_util.tree_map(
                    lambda a: jax.lax.with_sharding_constraint(
                        a, NamedSharding(
                            mesh, P("data", *([None] * (a.ndim - 1)))
                        )
                    ),
                    out,
                )

        self._jitted[key] = jax.jit(out_fn)
        return self._jitted[key]

    def _query_bank_hits(self, points: np.ndarray, rows: np.ndarray,
                         pad_to: int | None) -> InfluenceResult:
        """One bank-hit dispatch (every point has a bank row). On a
        classified device fault the points re-route through the miss
        delegate — the O(1) tier must never cost availability."""
        try:
            inject.fire(sites.ENGINE_DISPATCH_FLAT)
            counts = self.index.counts_batch(points)
            tx_np = np.ascontiguousarray(np.asarray(points, np.int64))
            ridx = np.asarray(rows, np.int64)
            T = tx_np.shape[0]
            t_pad = self._query_pad(T)
            bfac, bknd = self._bank_device
            pad = bucketed_pad(
                counts.max() if counts.size else 1, self.pad_bucket, pad_to
            )
            if self.mesh is not None:
                # same (ndev, t_loc, 2) shard packing as _dispatch_flat,
                # plus the parallel (ndev, t_loc) bank-row ids (the
                # factor bank itself is replicated per device by
                # _place_bank, so each shard gathers its own factors)
                ndev, q, t_loc, s_loc = self._mesh_plan(counts, T)
                sh = np.empty((ndev, t_loc, 2), np.int64)
                sb = np.empty((ndev, t_loc), np.int64)
                for k in range(ndev):
                    rows_k = tx_np[k * q: (k + 1) * q]
                    ridx_k = ridx[k * q: (k + 1) * q]
                    if rows_k.shape[0] == 0:
                        rows_k = tx_np[-1:]
                        ridx_k = ridx[-1:]
                    if rows_k.shape[0] < t_loc:
                        n = t_loc - rows_k.shape[0]
                        rows_k = np.concatenate(
                            [rows_k, np.repeat(rows_k[-1:], n, axis=0)]
                        )
                        ridx_k = np.concatenate(
                            [ridx_k, np.repeat(ridx_k[-1:], n)]
                        )
                    sh[k] = rows_k
                    sb[k] = ridx_k
                from fia_tpu.parallel.distributed import put_global

                tx = put_global(
                    self.mesh, sh.astype(np.int32), P("data", None, None)
                )
                bx = put_global(
                    self.mesh, sb.astype(np.int32), P("data", None)
                )
                out = self._bank_fn(s_loc)(
                    self.params, self.train_x, self.train_y,
                    self._postings, tx, self._rowfeat, bfac, bknd, bx,
                )
                return self._assemble_packed(
                    points, counts, out, pad, shards=(ndev, q, t_loc)
                )
            if t_pad > T:
                # same trailing-pair duplication as _dispatch_flat: pad
                # queries' flat rows land past `total` and slice away
                tx_np = np.concatenate(
                    [tx_np, np.repeat(tx_np[-1:], t_pad - T, axis=0)]
                )
                ridx = np.concatenate(
                    [ridx, np.repeat(ridx[-1:], t_pad - T)]
                )
            s_pad = self._s_pad_for(int(counts.sum()))
            out = self._bank_fn(s_pad)(
                self.params, self.train_x, self.train_y, self._postings,
                jnp.asarray(tx_np, jnp.int32), self._rowfeat,
                bfac, bknd, jnp.asarray(ridx, jnp.int32),
            )
            return self._assemble_packed(points, counts, out, pad)
        except Exception as e:
            if _classify_device_failure(e) is None:
                raise
            self._bank_hits -= len(points)
            self._bank_misses += len(points)
            obs.REGISTRY.counter(
                "engine.bank_hit_fallbacks").inc(len(points))
            return self._miss_delegate().query_batch(points, pad_to=pad_to)

    def _merge_stream(self, test_points, hits, misses,
                      pad_to: int | None) -> InfluenceResult:
        """Stitch hit/miss sub-results back into stream order as one
        packed result (``hits``/``misses`` are (positions, result))."""
        counts = self.index.counts_batch(test_points)
        T = len(test_points)
        d = int(self.model.block_size)
        ihvp = np.zeros((T, d), np.float32)
        tg = np.zeros((T, d), np.float32)
        off = np.concatenate(
            [[0], np.cumsum(counts.astype(np.int64))]
        )
        packed = np.zeros(int(off[-1]), np.float32)
        # sub-results from the sampled rung carry per-query bounds;
        # positions from exact sub-results keep bound 0
        approx = any(res.approx for _, res in (hits, misses))
        err = np.zeros(T, np.float32) if approx else None
        for idxs, res in (hits, misses):
            for r, tpos in enumerate(idxs):
                packed[off[tpos]: off[tpos + 1]] = res.scores_of(r)
                ihvp[tpos] = res.ihvp[r]
                tg[tpos] = res.test_grad[r]
                if err is not None and res.err_bound is not None:
                    err[tpos] = res.err_bound[r]
        pad = bucketed_pad(
            counts.max() if counts.size else 1, self.pad_bucket, pad_to
        )
        return InfluenceResult(
            counts=counts, ihvp=ihvp, test_grad=tg, packed=packed,
            test_points=np.asarray(test_points), index=self.index, pad=pad,
            err_bound=err, approx=approx,
        )

    def _query_precomputed(self, test_points: np.ndarray,
                           pad_to: int | None) -> InfluenceResult:
        """The ``precomputed`` rung: bank hits in one O(1)-per-query
        dispatch, everything else through the delegate at the next
        ladder rung (docs/design.md §16)."""
        self.ensure_factor_bank()
        T = test_points.shape[0]
        if not self._bank_serving_eligible():
            self._bank_misses += T
            obs.REGISTRY.counter("engine.bank_misses").inc(T)
            return self._miss_delegate().query_batch(
                test_points, pad_to=pad_to
            )
        lut = self._bank_lookup
        rows = np.fromiter(
            (lut.get((int(u), int(i)), -1) for u, i in test_points),
            np.int64, count=T,
        )
        hit = rows >= 0
        nh = int(np.count_nonzero(hit))
        self._bank_hits += nh
        self._bank_misses += T - nh
        obs.REGISTRY.counter("engine.bank_hits").inc(nh)
        obs.REGISTRY.counter("engine.bank_misses").inc(T - nh)
        obs.event("bank.partition", hits=nh, misses=T - nh)
        if nh == T:
            return self._query_bank_hits(test_points, rows, pad_to)
        if nh == 0:
            return self._miss_delegate().query_batch(
                test_points, pad_to=pad_to
            )
        hi = np.flatnonzero(hit)
        mi = np.flatnonzero(~hit)
        res_h = self._query_bank_hits(test_points[hi], rows[hi], pad_to)
        res_m = self._miss_delegate().query_batch(
            test_points[mi], pad_to=pad_to
        )
        return self._merge_stream(test_points, (hi, res_h), (mi, res_m),
                                  pad_to)

    # -- certified subsampled rung (solver='sampled') ----------------------
    def _sampled_eligible(self) -> bool:
        """The sampled program is the single-device flat body with a
        Horvitz–Thompson-weighted Hessian accumulation; mesh engines
        escalate one rung through the delegate rather than grow a third
        sharded program family (the rung exists to serve cheap bounded
        answers, which a mesh-size batch does not need)."""
        return (
            self.mesh is None
            and not self.group_queries
            and self.hessian_mode != "autodiff"
            and self.pad_policy == "batch"
            and self.model.block_cross_const is not None
            and self.model.block_reg_diag is not None
        )

    def _sampled_fn(self, s_pad: int):
        """Fused subsampled query program (docs/design.md §22).

        The flat body (``_flat_fn``) with the Hessian accumulated over
        the host-sampled row subset only — ``ws`` carries the ``n/m``
        Horvitz–Thompson weights, 0 off-sample — while the score pass
        still covers EVERY related row, plus the per-query
        concentration certificate (influence/sampled.py). At
        ``m == n`` the weights are all 1 and the program is bitwise the
        exact flat program with a zero bound. Outputs
        ``(scores, ihvp, v, err_bound)``.
        """
        use_feat = self._rowfeat is not None
        variant = self._kernel_variant
        key = ("sampled", s_pad, use_feat, variant)
        if key in self._jitted:
            return self._jitted[key]
        import math

        model = self.model
        prelude = self._flat_prelude(s_pad)
        d = model.block_size
        chunk = math.gcd(s_pad, self.flat_chunk)

        def fn(params, train_x, train_y, postings, tx, rowfeat, ws, msz):
            T = tx.shape[0]
            u, i, counts, t, row, wv, ut, it = prelude(tx, postings)

            if use_feat:
                feat = rowfeat[row]
                g, e, ma, mb = model.grads_from_row_features(feat, ut, it)
                ab = wv * ma * mb
                rel_x = train_x[row] if variant == "pallas" else None
            else:
                rel_x = train_x[row]
                rel_y = train_y[row]
                g = K.row_grads(model, variant, params, ut, it, rel_x)
                e = model.predict(params, rel_x) - rel_y
                ab = wv * (rel_x[:, 0] == ut) * (rel_x[:, 1] == it)

            onehot = self.flat_accum == "onehot" or (
                self.flat_accum == "auto"
                and jax.default_backend() == "tpu"
            )

            def accum(g_r, t_r, w_r, abe_r):
                def body_scatter(carry, args):
                    acc, s_abe = carry
                    gc, tc, wc, ac = args
                    outer = (gc * wc[:, None])[:, :, None] * gc[:, None, :]
                    return (acc.at[tc].add(outer),
                            s_abe.at[tc].add(ac)), None

                def body_onehot(carry, args):
                    acc, s_abe = carry
                    gc, tc, wc, ac = args
                    oh = (
                        tc[:, None]
                        == jnp.arange(T, dtype=tc.dtype)[None, :]
                    ).astype(jnp.float32)
                    outer = (
                        (gc * wc[:, None])[:, :, None] * gc[:, None, :]
                    ).reshape(-1, d * d)
                    Hc = jax.lax.dot_general(
                        oh, outer,
                        (((0,), (0,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST,
                    )
                    return (
                        acc + Hc.reshape(T, d, d),
                        s_abe + jnp.sum(oh * ac[:, None], axis=0),
                    ), None

                (acc, s_abe), _ = jax.lax.scan(
                    body_onehot if onehot else body_scatter,
                    (jnp.zeros((T, d, d), jnp.float32),
                     jnp.zeros((T,), jnp.float32)),
                    (g_r, t_r, w_r, abe_r),
                )
                return acc, s_abe

            nc = s_pad // chunk
            # the ONLY divergence from the flat body: sample weights on
            # both Hessian terms (wv folds into ws — off-sample rows
            # carry 0 — so E[H_m] = H and m == n is bitwise exact)
            HH, sum_abe = accum(
                g.reshape(nc, chunk, d), t.reshape(nc, chunk),
                (wv * ws).reshape(nc, chunk),
                (ab * ws * e).reshape(nc, chunk),
            )
            n_t = jnp.maximum(counts.astype(jnp.float32), 1.0)
            C = model.block_cross_const(params)
            rdiag = model.block_reg_diag(params)
            H = (2.0 / n_t)[:, None, None] * (
                HH + sum_abe[:, None, None] * C[None]
            ) + jnp.diag(rdiag + self.damping)[None]

            v = jax.vmap(
                lambda uu, ii, xj: G.block_prediction_grad(
                    model, params, uu, ii, xj[None, :]
                )
            )(u, i, tx)
            ihvp = jax.vmap(solvers.solve_direct)(H, v)

            theta = jax.vmap(
                lambda uu, ii: model.flatten_block(
                    model.extract_block(params, uu, ii)
                )
            )(u, i)
            reg_dot = jnp.sum(theta * rdiag[None] * ihvp, axis=1)
            scores = K.fused_scores(
                model, variant, params, ut, it, t, rel_x, e, wv,
                ihvp, reg_dot, n_t, g=g,
            )

            # certificate: sample deviation of the per-row Hessian
            # action h_s(x) on the solved vector, pushed through the
            # inverse and the score form (influence/sampled.py)
            gx = jnp.einsum("sd,sd->s", g, ihvp[t])
            Cx = ihvp @ C.T
            h = wv[:, None] * g * gx[:, None] + (ab * e)[:, None] * Cx[t]
            sigma = sampled_mod.segment_sample_std(h, ws, t, msz, T)
            # λ_min(H_m) in place of the raw damping floor: the GN part
            # contributes real positive curvature, and the measured
            # spectrum tightens the bound by the same factor (d is the
            # tiny block size, so the batched eigvalsh is a rounding
            # error next to the accumulation)
            lam = jnp.maximum(
                jnp.linalg.eigvalsh(H)[:, 0], self.damping
            )
            err_ihvp = sampled_mod.ihvp_error_bound(
                sigma, msz, counts, lam
            )
            gnorm = jnp.sqrt(jnp.sum(g * g, axis=1))
            # segment maxima clamp at 0: an empty segment (a pair with
            # no postings) yields -inf, and its bound must read 0
            gmax = jnp.maximum(
                jax.ops.segment_max(wv * 2.0 * jnp.abs(e) * gnorm, t, T),
                0.0,
            )
            wmax = jnp.maximum(jax.ops.segment_max(wv, t, T), 0.0)
            regnorm = jnp.sqrt(jnp.sum((theta * rdiag[None]) ** 2, axis=1))
            err = sampled_mod.score_error_bound(
                gmax, wmax, regnorm, err_ihvp, n_t
            )
            return scores, ihvp, v, err

        self._jitted[key] = jax.jit(fn)
        return self._jitted[key]

    def _sampled_fallback(self) -> "InfluenceEngine":
        """Escalation target of the sampled rung: a config-identical
        engine one ladder rung down (``sampled → lissa``), shared
        across batches so its compiled programs amortize."""
        if self._sampled_delegate is None:
            self._sampled_delegate = InfluenceEngine(
                self.model,
                self._params_host,
                RatingDataset(*self._train_host),
                damping=self.damping,
                solver=rpolicy.next_solver("sampled") or "direct",
                cg_maxiter=self.cg_maxiter,
                cg_tol=self.cg_tol,
                lissa_scale=self.lissa_scale,
                lissa_depth=self.lissa_depth,
                mesh=self.mesh,
                cache_dir=None,
                model_name=self.model_name,
                pad_bucket=self.pad_bucket,
                shard_tables=self._shard_tables,
                hessian_mode=self.hessian_mode,
                group_queries=self.group_queries,
                pad_policy=self.pad_policy,
                impl=self.impl,
                flat_chunk=self.flat_chunk,
                flat_accum=self.flat_accum,
                row_features=self.row_features,
                cpu_fallback=self.cpu_fallback,
                query_bucket=self.query_bucket,
                kernel=self.kernel,
                lissa_tune=self.lissa_tune,
            )
        return self._sampled_delegate

    def approx_sibling(self) -> "InfluenceEngine":
        """A config-identical engine at the ``sampled`` rung, cache-less.

        The serving brownout path (serve/service.py) answers
        ``bank_preferred`` misses from this sibling instead of shedding
        them: same model state, same knobs, ``solver='sampled'`` and no
        disk cache — so a certified approximate answer can never be
        written under (or read from) the exact solver's cache keys.
        Shared per engine so the sibling's compiled programs amortize
        across brownout episodes; an engine already on the sampled rung
        is its own sibling.
        """
        if self.solver == "sampled":
            return self
        if self._approx_sibling is None:
            self._approx_sibling = InfluenceEngine(
                self.model,
                self._params_host,
                RatingDataset(*self._train_host),
                damping=self.damping,
                solver="sampled",
                cg_maxiter=self.cg_maxiter,
                cg_tol=self.cg_tol,
                lissa_scale=self.lissa_scale,
                lissa_depth=self.lissa_depth,
                mesh=self.mesh,
                cache_dir=None,
                model_name=self.model_name,
                pad_bucket=self.pad_bucket,
                shard_tables=self._shard_tables,
                hessian_mode=self.hessian_mode,
                group_queries=self.group_queries,
                pad_policy=self.pad_policy,
                impl=self.impl,
                flat_chunk=self.flat_chunk,
                flat_accum=self.flat_accum,
                row_features=self.row_features,
                cpu_fallback=self.cpu_fallback,
                query_bucket=self.query_bucket,
                kernel=self.kernel,
                lissa_tune=self.lissa_tune,
                sampled_cap=self.sampled_cap,
                sampled_tol=self.sampled_tol,
            )
        return self._approx_sibling

    def _result_take(self, res: InfluenceResult, idxs: np.ndarray,
                     test_points: np.ndarray) -> InfluenceResult:
        """Restrict a packed result to the query positions ``idxs``
        (stream order preserved) — the sampled rung keeps the
        in-tolerance slice of a batch while escalated queries recompute."""
        off = res._offsets
        packed = (
            np.concatenate(
                [res._packed[off[t]: off[t + 1]] for t in idxs]
            )
            if len(idxs)
            else np.zeros(0, np.float32)
        )
        return InfluenceResult(
            counts=res.counts[idxs], ihvp=res.ihvp[idxs],
            test_grad=res.test_grad[idxs], packed=packed,
            test_points=np.asarray(test_points)[idxs], index=self.index,
            pad=res._pad,
            err_bound=None if res.err_bound is None
            else res.err_bound[idxs],
            approx=res.approx,
        )

    def _query_sampled(self, test_points: np.ndarray,
                       pad_to: int | None) -> InfluenceResult:
        """The ``sampled`` rung: one fused subsampled dispatch for the
        whole batch; queries whose certificate exceeds ``sampled_tol``
        escalate one ladder rung (docs/design.md §22) — the per-query
        cost/accuracy policy."""
        T = test_points.shape[0]
        if not self._sampled_eligible():
            obs.REGISTRY.counter(
                "engine.sampled_escalations", reason="ineligible"
            ).inc(T)
            return self._sampled_fallback().query_batch(
                test_points, pad_to=pad_to
            )
        try:
            res = self._dispatch_sampled(test_points, pad_to)
        except Exception as e:
            cls = _classify_device_failure(e)
            if cls is None:
                raise
            # one-shot degradation on any classified device fault: the
            # fallback engine owns the full retry/CPU ladder
            obs.REGISTRY.counter(
                "engine.sampled_escalations", reason=cls
            ).inc(T)
            self._reset_device_state()
            return self._sampled_fallback().query_batch(
                test_points, pad_to=pad_to
            )
        err = res.err_bound
        over = np.flatnonzero(err > self.sampled_tol)
        obs.REGISTRY.counter("engine.sampled_queries").inc(T)
        obs.event("engine.sampled", queries=T, escalated=int(len(over)),
                  err_max=float(err.max()) if T else 0.0)
        if len(over) == 0:
            return res
        obs.REGISTRY.counter(
            "engine.sampled_escalations", reason="tolerance"
        ).inc(int(len(over)))
        res_e = self._sampled_fallback().query_batch(
            test_points[over], pad_to=pad_to
        )
        keep = np.flatnonzero(err <= self.sampled_tol)
        if len(keep) == 0:
            return res_e
        sub = self._result_take(res, keep, test_points)
        return self._merge_stream(test_points, (keep, sub),
                                  (over, res_e), pad_to)

    def _dispatch_sampled(self, test_points: np.ndarray,
                          pad_to: int | None) -> InfluenceResult:
        inject.fire(sites.ENGINE_SAMPLED_SOLVE)
        counts = self.index.counts_batch(test_points)
        tx_np = np.ascontiguousarray(np.asarray(test_points, np.int64))
        T = tx_np.shape[0]
        pad = bucketed_pad(
            counts.max() if counts.size else 1, self.pad_bucket, pad_to
        )
        t_pad = self._query_pad(T)
        if t_pad > T:
            # same trailing-pair padding as _dispatch_flat: pad rows'
            # flat positions land past the real total
            tx_np = np.concatenate(
                [tx_np, np.repeat(tx_np[-1:], t_pad - T, axis=0)]
            )
        pcounts = (self.index.counts_batch(tx_np)
                   if t_pad > T else counts)
        s_pad = self._s_pad_for(int(pcounts.sum()))
        # deterministic per-(u, i) Philox sample, batch-composition
        # independent: the same pair always serves the same answer/bound
        ws_np, m_np = sampled_mod.sample_weights(
            tx_np, pcounts, s_pad, self.sampled_cap
        )
        with obs.span("engine.dispatch_sampled", n=int(T)):
            out = self._sampled_fn(s_pad)(
                self.params, self.train_x, self.train_y, self._postings,
                jnp.asarray(tx_np, jnp.int32), self._rowfeat,
                jnp.asarray(ws_np), jnp.asarray(m_np),
            )
            packed, ihvp, v, err = jax.device_get(out)
        # same payload seam as the exact path (sites.ENGINE_SOLVE, the
        # fetched iHVP host buffer) — engine.sampled_solve stays a pure
        # raise seam so its call index is the dispatch ordinal, which
        # chaos schedules rely on
        ihvp = inject.corrupt(sites.ENGINE_SOLVE, np.asarray(ihvp)[:T])
        return InfluenceResult(
            counts=counts,
            ihvp=ihvp,
            test_grad=np.asarray(v)[:T],
            packed=np.asarray(packed)[: int(counts.sum())],
            test_points=np.asarray(test_points),
            index=self.index,
            pad=pad,
            err_bound=np.asarray(err)[:T],
            approx=True,
        )

    # -- public API --------------------------------------------------------
    def query_batch(
        self,
        test_points: np.ndarray,
        test_ratings: np.ndarray | None = None,
        pad_to: int | None = None,
    ) -> InfluenceResult:
        """Influence of related training rows on each test prediction.

        Args:
          test_points: (T, 2) int array of (user, item) pairs.
          test_ratings: unused by the prediction-influence path (the test
            vector is ∇r̂, not ∇loss); accepted for API symmetry.
          pad_to: force a single fixed pad length (disables grouping).

        Results are screened for non-finite payloads (the iHVP
        silent-wrong-answer class: a diverged LiSSA/Schulz recursion
        returns a "successful" NaN buffer). On detection the engine
        escalates down the solver degradation ladder
        (``lissa → cg → direct``, ``schulz → direct``) and recomputes —
        see :meth:`_nan_ladder`.
        """
        t0 = time.perf_counter()
        with obs.span("engine.query", solver_requested=self.solver) as sp:
            res = self._query_batch_impl(test_points, pad_to)
            res = self._nan_ladder(
                res, lambda: self._query_batch_impl(test_points, pad_to)
            )
            # final attrs: the ladder may have escalated self.solver
            sp.set(solver=self.solver, kernel=self._kernel_variant,
                   n=int(np.asarray(test_points).reshape(-1, 2).shape[0]))
        obs.REGISTRY.counter(
            "engine.queries_total", solver=self.solver).inc()
        obs.REGISTRY.histogram(
            "engine.query_us", solver=self.solver
        ).observe((time.perf_counter() - t0) * 1e6)
        return res

    def _nan_ladder(self, res: InfluenceResult, recompute) -> InfluenceResult:
        """Escalate the solver until the payload is finite (or the
        ladder bottoms out at the exact direct solve).

        Escalation is sticky — the engine keeps the more robust solver
        for subsequent batches (the block spectrum that diverged once
        will diverge again) — and drops compiled programs, since the
        solver choice is baked into the traced query functions.
        """
        while taxonomy.classify_payload(
            res.ihvp, res.test_grad, res._packed, res._scores
        ) is not None:
            nxt = rpolicy.next_solver(self.solver)
            if nxt is None:
                obs.diag(
                    "reliability",
                    "non-finite influence payload from the "
                    f"{self.solver!r} solver with no fallback rung left; "
                    "returning as-is (check damping/conditioning)",
                )
                return res
            obs.diag(
                "reliability",
                "non-finite influence payload from "
                f"{self.solver!r}; escalating solver to {nxt!r}",
            )
            obs.event("solver.escalate",
                      **{"from": self.solver, "to": nxt})
            obs.REGISTRY.counter(
                "engine.solver_escalations",
                **{"from": self.solver, "to": nxt}
            ).inc()
            self.solver = nxt
            self._jitted.clear()
            self._aot.clear()  # the solver is baked into AOT programs
            res = recompute()
        return res

    def _query_batch_impl(
        self,
        test_points: np.ndarray,
        pad_to: int | None = None,
    ) -> InfluenceResult:
        test_points = np.asarray(test_points)
        if test_points.ndim == 1:
            test_points = test_points[None, :]
        T = test_points.shape[0]

        if self.solver == "precomputed":
            return self._query_precomputed(test_points, pad_to)

        if self.solver == "sampled":
            return self._query_sampled(test_points, pad_to)

        if self.impl in ("auto", "flat") and self._flat_eligible():
            if self._wide_block_cap() and T > 32:
                # Ride query_many's windowed pipeline (overlapped
                # dispatch/fetch + its own crash fallback) rather than
                # serialize 32-query fetch cycles here; sub-results
                # stitch across differing pads (_concat_results takes
                # the max).
                return _concat_results(
                    self.query_many(test_points, batch_queries=32,
                                    pad_to=pad_to)
                )
            return self._query_flat(test_points, pad_to)
        if self.impl == "flat":
            raise ValueError(
                "impl='flat' requires the direct solver, a model defining "
                "the Gauss-Newton hooks, pad_policy='batch', and no "
                "explicit hessian_mode='autodiff'"
            )

        if self.group_queries and pad_to is None and T > 1:
            counts = self.index.counts_batch(test_points).astype(np.int64)
            pads = np.array(
                [bucketed_pad(int(c), self.pad_bucket) for c in counts]
            )
            uniq = np.unique(pads)
            if len(uniq) > 1:
                P = int(uniq.max())
                scores = np.zeros((T, P), np.float32)
                rel_idx = np.zeros((T, P), np.int32)
                rel_mask = np.zeros((T, P), bool)
                out_counts = np.zeros(T, np.int32)
                ihvp = test_grad = None
                for p in uniq:
                    sel = np.flatnonzero(pads == p)
                    r = self._query_padded_adaptive(test_points[sel], int(p))
                    if ihvp is None:
                        d = r.ihvp.shape[1]
                        ihvp = np.zeros((T, d), np.float32)
                        test_grad = np.zeros((T, d), np.float32)
                    w = r.scores.shape[1]
                    scores[sel, :w] = r.scores
                    rel_idx[sel, :w] = r.related_idx
                    rel_mask[sel, :w] = r.related_mask
                    out_counts[sel] = r.counts
                    ihvp[sel] = r.ihvp
                    test_grad[sel] = r.test_grad
                return InfluenceResult(scores, rel_idx, rel_mask,
                                       out_counts, ihvp, test_grad)
        return self._query_padded_adaptive(test_points, pad_to)

    def _memlimits_seed(self) -> None:
        """Adopt the cross-process learned memory envelope (lazy)."""
        if self._memkey is not None:
            return
        from fia_tpu.utils import memlimits

        d = int(
            self.model.flatten_block(
                self.model.extract_block(self.params, 0, 0)
            ).size
        )
        ndev = self.mesh.devices.size if self.mesh is not None else 1
        self._memkey = memlimits.key(
            jax.default_backend(), ndev, self.model_name, d
        )
        ok, bad = memlimits.load(self._memkey)
        self._cells_ok = max(self._cells_ok, ok)
        # cached ceilings were persisted only for explicit OOMs, so a
        # loaded bad is hard evidence (still clearable by a success)
        self._cells_bad = min(self._cells_bad, bad)
        self._cells_bad_hard = min(self._cells_bad_hard, bad)
        if self._cells_ok >= self._cells_bad:
            # Inconsistent merged records (e.g. a transient tunnel
            # failure persisted a bad below a genuine ok, or the cache
            # travelled between differently-sized chips). Trust the
            # failure: deriving chunks from a poisoned ok would
            # re-dispatch a recorded-failing size — a 40-66 s failing
            # compile per batch, the exact cost this cache avoids.
            self._cells_ok = self._cells_bad // 2

    def _record_ok(self, cells: int) -> None:
        self._cells_ok = max(self._cells_ok, cells)
        if cells >= self._cells_bad_hard:
            # A success at/above a recorded failing size is direct
            # evidence that record was wrong (a transient fault misread
            # as memory pressure). Clear it — and remember the success
            # size so the persisted copy is cleared too (r3 advisor
            # finding: a stale ceiling otherwise degrades every later
            # process until the cache file is hand-deleted).
            self._cells_bad_hard = 1 << 62
            self._cleared_bad = max(self._cleared_bad, cells)
        if cells >= self._cells_bad:
            # Ambiguous ceilings <= the success are refuted as well;
            # any surviving hard ceiling (> cells) stays binding.
            self._cells_bad = self._cells_bad_hard
            self._cleared_bad = max(self._cleared_bad, cells)

    def _record_bad(self, cells: int, definite: bool) -> None:
        self._cells_bad = min(self._cells_bad, cells)
        if definite:
            self._cells_bad_hard = min(self._cells_bad_hard, cells)
        self._cells_ok = min(self._cells_ok, self._cells_bad // 2)

    def _dispatch_padded_resilient(
        self, test_points: np.ndarray, pad: int | None,
        s_pad: int | None = None,
    ) -> InfluenceResult:
        """One padded dispatch; ambiguous tunnel failures retry once.

        A generic tunnel HTTP 500 is as likely a transient fault as a
        wrapped OOM; halving straight away costs a fresh 40-66 s XLA
        compile at the new shape AND (before r4) taught the envelope a
        false ceiling. One same-size retry is free when the fault was
        transient (the compile is already cached) and bounded when it
        was real. Definite OOMs skip the retry — re-dispatching a size
        the backend just measured as over-memory cannot succeed.
        """
        try:
            return self._query_padded(test_points, pad, s_pad)
        except Exception as e:
            if _classify_device_failure(e) != "ambiguous":
                raise
            return self._query_padded(test_points, pad, s_pad)

    def _query_padded_adaptive(
        self, test_points: np.ndarray, pad_to: int | None
    ) -> InfluenceResult:
        """Memory-envelope bookkeeping around :meth:`_adaptive_run`."""
        from fia_tpu.utils import memlimits

        self._memlimits_seed()
        state0 = (self._cells_ok, self._cells_bad_hard, self._cleared_bad)
        try:
            return self._adaptive_run(test_points, pad_to)
        finally:
            state1 = (self._cells_ok, self._cells_bad_hard,
                      self._cleared_bad)
            if state1 != state0:
                try:
                    # Only hard (RESOURCE_EXHAUSTED) ceilings reach the
                    # shared cache: persisting a possibly-transient
                    # tunnel fault would degrade all future processes
                    # (min-merge never forgets). A contradicted ceiling
                    # is actively cleared instead.
                    memlimits.update(
                        self._memkey,
                        self._cells_ok,
                        self._cells_bad_hard,
                        clear_bad_at=self._cleared_bad or None,
                    )
                    self._cleared_bad = 0
                except Exception:
                    # Envelope persistence must never replace a
                    # successful query result (this runs in a finally).
                    pass

    def _adaptive_run(
        self, test_points: np.ndarray, pad_to: int | None
    ) -> InfluenceResult:
        """Dispatch a padded query batch, splitting it when HBM runs out.

        A (T, pad) padded program's temporaries scale with T x pad x
        block_dim; big NCF batches can exceed a 16G chip (a 256-query
        batch at pad 4608 needed 16.06G). On a memory failure the batch
        is re-dispatched in halved query chunks at the SAME pad (so
        chunks share one compiled program and concatenate exactly);
        the working/failing cell counts persist on the engine, so later
        batches — including other pad buckets — pre-chunk instead of
        repeating the failing compile.
        """
        test_points = np.asarray(test_points)
        T = test_points.shape[0]
        counts = self.index.counts_batch(test_points)
        m = counts.max() if counts.size else 1
        if pad_to is None and self.pad_policy == "dataset":
            m = self.index.max_related_count()
        pad = bucketed_pad(m, self.pad_bucket, pad_to)

        chunk = T
        if self._cells_bad < (1 << 62) and (
            T * pad >= self._cells_bad
            or (self._cells_ok and T * pad > self._cells_ok)
        ):
            # Memory pressure has been observed on this engine: never
            # attempt an untested larger size — a failed dispatch costs
            # a full XLA compile (40-66 s through the tunnel) before the
            # error surfaces. Stay at the known-good cell count.
            good = self._cells_ok // pad
            chunk = good if good else max(1, (self._cells_bad // pad) // 2)
            chunk = max(1, min(T, chunk))
            if chunk < T:
                # Power-of-two floor: a chunk that doesn't divide T
                # leaves a different-shaped remainder dispatch, and each
                # new shape is a fresh 40-66 s XLA compile through the
                # tunnel (T is a power of two in every real workload).
                chunk = 1 << (chunk.bit_length() - 1)
        # Preemptions carry no size evidence: rebuild and retry at the
        # SAME size, bounded so a permanently reclaimed slice surfaces.
        preempt_left = 3
        if chunk >= T:
            try:
                out = self._dispatch_padded_resilient(test_points, pad)
            except Exception as e:
                cls = _classify_device_failure(e)
                if cls not in _ADAPTIVE_KINDS or (
                    T <= 1 and cls != taxonomy.PREEMPTION
                ):
                    raise
                if cls == taxonomy.PREEMPTION:
                    preempt_left -= 1
                    if preempt_left < 0:
                        raise
                    self._reset_device_state()
                    # fall into the chunked loop at the same size
                elif cls == taxonomy.WORKER:
                    # not memory evidence — rebuild the dead device
                    # state and halve, teaching the envelope nothing
                    self._reset_device_state()
                    chunk = max(1, T // 2)
                else:
                    self._record_bad(T * pad, cls == taxonomy.OOM)
                    chunk = max(1, T // 2)
            else:
                # Record fast-path successes too: otherwise one
                # misclassified transient failure would permanently
                # over-chunk sizes that had dispatched fine for hours.
                self._record_ok(T * pad)
                return out

        # Shared packed-output pad for every chunk of this batch: each
        # distinct (pad, s) pair is a fresh XLA compile, and letting
        # every chunk bucket its own total burned one ~7-14 s compile
        # per chunk per batch on chunked NCF A/B rounds (r4,
        # output/ab_impls_ncf_r4.log). The sliding-window max bounds
        # ANY contiguous chunk of the current size, so halving mid-loop
        # just recomputes it.
        cum = np.concatenate([[0], np.cumsum(counts, dtype=np.int64)])

        def shared_s(c: int) -> int | None:
            if self.mesh is not None:
                return None  # packed output path is single-device only
            win = int((cum[min(c, T):] - cum[: T - min(c, T) + 1]).max())
            return bucketed_pad(max(win, 1), 1024)

        parts: list[InfluenceResult] = []
        start = 0
        s_shared = shared_s(chunk)
        prev_chunk = chunk
        while start < T:
            if chunk != prev_chunk:
                s_shared = shared_s(chunk)
                prev_chunk = chunk
            n = min(chunk, T - start)
            try:
                parts.append(
                    self._dispatch_padded_resilient(
                        test_points[start : start + n], pad, s_shared
                    )
                )
            except Exception as e:
                cls = _classify_device_failure(e)
                if cls == taxonomy.PREEMPTION:
                    preempt_left -= 1
                    if preempt_left < 0:
                        raise
                    self._reset_device_state()
                    continue  # same size: no size evidence
                if n <= 1 or cls not in _ADAPTIVE_KINDS:
                    raise
                if cls == taxonomy.WORKER:
                    self._reset_device_state()
                else:
                    self._record_bad(n * pad, cls == taxonomy.OOM)
                chunk = max(1, n // 2)
                continue
            self._record_ok(n * pad)
            start += n
        return parts[0] if len(parts) == 1 else _concat_results(parts)

    def _query_padded(
        self, test_points: np.ndarray, pad_to: int | None,
        s_pad: int | None = None,
    ) -> InfluenceResult:
        """One device dispatch at a single pad length.

        ``s_pad``: caller-shared packed-output length (must be >= this
        batch's related-row total); chunked dispatches of one batch
        pass a common value so they share one compiled program.
        """
        inject.fire(sites.ENGINE_DISPATCH_PADDED)
        counts = self.index.counts_batch(test_points)
        m = counts.max() if counts.size else 1
        if pad_to is None and self.pad_policy == "dataset":
            m = self.index.max_related_count()
        pad = bucketed_pad(m, self.pad_bucket, pad_to)

        u = jnp.asarray(test_points[:, 0], jnp.int32)
        i = jnp.asarray(test_points[:, 1], jnp.int32)
        tx = jnp.asarray(test_points, jnp.int32)
        T = test_points.shape[0]

        if self.mesh is not None:
            from fia_tpu.parallel.distributed import put_global

            n = self.mesh.devices.size
            pad_T = (-T) % n
            if pad_T:
                u = jnp.concatenate([u, jnp.repeat(u[-1:], pad_T)])
                i = jnp.concatenate([i, jnp.repeat(i[-1:], pad_T)])
                tx = jnp.concatenate([tx, jnp.repeat(tx[-1:], pad_T, axis=0)])
            u, i, tx = (
                put_global(self.mesh, a, P("data", *([None] * (a.ndim - 1))))
                for a in (u, i, tx)
            )

        if self.mesh is None:
            # Packed-output fast path (see _batched_packed). S rounds up
            # to a geometric bucket: logarithmic compile count at
            # ≤12.5% padding waste in the packed transfer (vs ~5× above
            # it for the unpacked (T, P) copy).
            total = int(counts.sum())
            s = bucketed_pad(total, 1024) if s_pad is None else int(s_pad)
            out = self._batched_packed(pad, s)(
                self.params, self.train_x, self.train_y, self._postings,
                u, i, tx,
            )
            return self._assemble_packed(test_points, counts, out, pad)

        out = self._batched(pad)(
            self.params, self.train_x, self.train_y, self._postings, u, i, tx
        )
        if self._multihost:
            # Data-sharded outputs span non-addressable devices; gather
            # every process a full host copy before the host fetch below.
            from jax.experimental import multihost_utils

            scores, ihvp, v = multihost_utils.process_allgather(
                out, tiled=True
            )
        else:
            scores, ihvp, v = jax.device_get(out)
        ihvp = inject.corrupt(sites.ENGINE_SOLVE, np.asarray(ihvp))
        # Result row ids/mask come from the host CSR (same ordering as the
        # device gather: user postings then item postings) — cheap, and it
        # avoids shipping (T, P) int/bool arrays back over the interconnect.
        rel_idx, rel_mask, _ = self.index.related_padded(test_points, pad_to=pad)
        return InfluenceResult(
            scores=np.asarray(scores)[:T],
            related_idx=rel_idx,
            related_mask=rel_mask,
            counts=counts,
            ihvp=np.asarray(ihvp)[:T],
            test_grad=np.asarray(v)[:T],
        )

    def get_influence_on_test_loss(
        self,
        test_indices,
        test_ds: RatingDataset,
        force_refresh: bool = True,
        test_description=None,
    ) -> np.ndarray:
        """Reference-signature convenience: one test index at a time.

        Returns predicted rating diffs for the related training rows of
        ``test_ds.x[test_indices[0]]`` (reference
        ``matrix_factorization.py:164-251``), caching the inverse-HVP to
        npz when ``cache_dir`` is set.
        """
        assert len(test_indices) == 1
        t = int(test_indices[0])
        point = test_ds.x[t]

        cache = None
        if self.cache_dir is not None:
            desc = test_description if test_description is not None else [t]
            cache = os.path.join(
                self.cache_dir,
                f"{self.model_name}-{self.solver}-normal_loss-test-{desc}.npz",
            )
        stale = False
        if cache is not None and not force_refresh and os.path.exists(cache):
            # cache hit (reference genericNeuralNet.py:724-735): reuse the
            # stored solve instead of recomputing; scores are stored too
            # since this engine fuses solving and scoring in one program.
            # The filename key (reference-shaped) doesn't identify the
            # trained params, so a fingerprint guards against serving
            # scores from a different checkpoint. The hit is verified
            # through the artifact integrity layer first: a corrupt file
            # (torn write, bit rot) is quarantined as *.corrupt and
            # treated as a miss — recompute, then publish a clean entry.
            from fia_tpu.reliability import artifacts

            try:
                hit = artifacts.load_npz(cache, require_manifest=False)
                if "scores" in hit and (
                    "params_fp" in hit
                    and self._fingerprint_matches(hit["params_fp"])
                ):
                    return hit["scores"]
            except artifacts.ArtifactIntegrityError:
                pass
            stale = True
        res = self.query_batch(point[None, :])
        if cache is not None and (
            force_refresh or stale or not os.path.exists(cache)
        ):
            from fia_tpu.reliability import artifacts

            artifacts.publish_npz(
                cache,
                dict(inverse_hvp=res.ihvp[0], scores=res.scores_of(0),
                     params_fp=self._params_fingerprint()),
                fingerprint={"model_key": self.model_name,
                             "solver": self.solver},
                site=sites.ENGINE_CACHE_PUBLISH,
            )
        return res.scores_of(0)

    def _params_fingerprint(self) -> np.ndarray:
        """Cache-validation identity: per-leaf sum and L2 norm of the
        checkpoint (order-stable via tree flatten; computed on device so
        sharded embedding tables aren't gathered to host just for two
        scalars) plus the solve configuration — the cache filename keys
        the solver name but not damping/tolerances, and stale scores
        from a different solve setup must not be served. The training
        set is fingerprinted too (row count plus position-weighted x/y
        checksums): identical params over different train data — e.g. a
        leave-one-out subset — must not serve each other's scores."""
        if getattr(self, "_params_fp", None) is None:
            stats = [
                s
                for leaf in jax.tree_util.tree_leaves(self.params)
                for s in (jnp.sum(leaf), jnp.linalg.norm(jnp.ravel(leaf)))
            ]
            # Train-set checksums are computed on HOST in float64 with
            # np.sum (pairwise, BLAS-free, deterministic) and compared
            # EXACTLY: at ML-scale the position-weighted dots are ~1e14,
            # where any relative tolerance swallows a one-row delta —
            # the exact case (LOO subset vs full set) this guards.
            hx, hy = self._train_host
            n = hx.shape[0]
            pos = ((np.arange(n) % 997) + 1).astype(np.float64)
            tstats = [
                float(n),
                float(np.sum(hx[:, 0].astype(np.float64) * pos)),
                float(np.sum(hx[:, 1].astype(np.float64) * pos)),
                float(np.sum(hy.astype(np.float64) * pos)),
            ]
            cfg = [self.damping, self.cg_tol, float(self.cg_maxiter),
                   self.lissa_scale, float(self.lissa_depth)]
            self._params_fp = np.concatenate([
                np.asarray(jax.device_get(jnp.stack(stats)), np.float64),
                np.asarray(tstats + cfg, np.float64),
            ])
        return self._params_fp

    # train stats + solve cfg at the fingerprint tail (exact-match part)
    _FP_EXACT_TAIL = 9

    def _fingerprint_matches(self, stored) -> bool:
        """Params stats tolerate cross-backend reduction noise
        (allclose); train checksums and solve config must match EXACTLY
        (see _params_fingerprint on why tolerances would unguard LOO)."""
        fp = self._params_fingerprint()
        stored = np.asarray(stored)
        if stored.shape != fp.shape:
            return False
        k = fp.shape[0] - self._FP_EXACT_TAIL
        return bool(
            np.allclose(stored[:k], fp[:k])
            and np.array_equal(stored[k:], fp[k:])
        )

    def related_indices(self, test_point) -> np.ndarray:
        u, i = int(test_point[0]), int(test_point[1])
        return self.index.related(u, i)
