"""Hessian-vector products.

The reference builds HVPs by graph-level double backprop
(``src/influence/hessians.py:68-119``) and, for FIA, slices both sides
to the (user, item) block (``matrix_factorization.py:324-351``),
evaluating on the related training rows only with damping added after
accumulation (``matrix_factorization.py:288-308``). Here the same math is
forward-over-reverse ``jvp(grad(f))`` — one fused XLA computation, no
graph surgery — over the functionally-substituted block.
"""

from __future__ import annotations

import jax
import jax.flatten_util
import jax.numpy as jnp


def make_block_hvp(model, params, u, i, x, y, w, damping: float):
    """Returns hvp(v) for the damped block Hessian of the total loss.

    H = ∇²_block [ masked-mean MSE over rows (x, y, w) + L2 reg ], and
    hvp(v) = H v + damping * v  (damping after accumulation, matching
    ``matrix_factorization.py:306``). v is a flat (d,) vector.
    """
    block0 = model.extract_block(params, u, i)
    bvec0 = model.flatten_block(block0)

    def total(bvec):
        block = model.unflatten_block(bvec, block0)
        return model.block_loss(params, block, u, i, x, y, w)

    grad_fn = jax.grad(total)

    def hvp(v):
        hv = jax.jvp(grad_fn, (bvec0,), (v,))[1]
        return hv + damping * v

    return hvp


def materialize_block_hessian(model, params, u, i, x, y, w, damping: float):
    """Dense damped block Hessian (d, d).

    The FIA block is tiny (2k+2 or 4k), so materialising H via one
    batched HVP over the identity and solving directly is both exact and
    faster on TPU than an iterative solve — this is the default solver's
    workhorse.
    """
    hvp = make_block_hvp(model, params, u, i, x, y, w, damping)
    d = model.block_size
    return jax.vmap(hvp)(jnp.eye(d, dtype=jnp.float32))


def materialize_full_hessian(model, params, x, y, w=None, damping: float = 0.0):
    """Dense Hessian of the total loss over ALL parameters, shape (D, D).

    Working equivalent of the reference's dead ``hessians.hessians``
    (``src/influence/hessians.py:125-181`` — broken: uses the removed
    ``array_ops.unpack/pack``). Rows/columns follow
    ``jax.flatten_util.ravel_pytree`` order over the parameter pytree.
    Only sensible for small D — used by tests to validate HVPs against
    an explicit Hessian.
    """
    flat0, unravel = jax.flatten_util.ravel_pytree(params)

    def total(flat):
        return model.loss(unravel(flat), x, y, w)

    Hmat = jax.hessian(total)(flat0)
    if damping:
        Hmat = Hmat + damping * jnp.eye(flat0.shape[0], dtype=Hmat.dtype)
    return Hmat


def make_full_hvp(model, params, x, y, w=None, damping: float = 0.0):
    """hvp(v) over the FULL parameter pytree (generic engine path).

    Equivalent of the reference's full-space ``hessian_vector_product``
    (``hessians.py:68-119``) fed with train batches
    (``genericNeuralNet.py:547-594``). v is a pytree like params.
    """
    grad_fn = jax.grad(lambda p: model.loss(p, x, y, w))

    def hvp(v):
        hv = jax.jvp(grad_fn, (params,), (v,))[1]
        if damping:
            hv = jax.tree_util.tree_map(lambda a, b: a + damping * b, hv, v)
        return hv

    return hvp
