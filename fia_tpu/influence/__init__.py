from fia_tpu.influence.engine import InfluenceEngine, InfluenceResult  # noqa: F401
from fia_tpu.influence import grads, hvp, solvers  # noqa: F401
