"""Inverse-HVP solvers.

The reference solves H x = v by minimising the quadratic
½ xᵀHx − vᵀx with ``scipy.optimize.fmin_ncg`` (host round-trip per HVP,
``matrix_factorization.py:419-433``) or by the LiSSA recursion
(``genericNeuralNet.py:511-544``). The system here is PSD (damped
Gauss-Newton-ish block Hessian), so:

  - ``solve_direct``: materialise the tiny block Hessian and LU-solve
    (see its docstring for why not Cholesky). Exact; the TPU-fast
    default for FIA blocks (d = 2k+2 or 4k).
  - ``solve_cg``: matrix-free conjugate gradients under ``lax.while_loop``
    (device-resident; equivalent to fmin_ncg's quadratic minimisation in
    exact arithmetic). For large d / full-parameter systems.
  - ``solve_lissa``: the stochastic Neumann-series recursion
    cur ← v + (1−λ)·cur − H(cur)/scale, result cur/scale, matching the
    reference's update (``genericNeuralNet.py:533``).
  - ``solve_schulz``: matmul-only Newton–Schulz inversion of the
    materialised block Hessian (beyond-reference option; HyperINF,
    arXiv:2410.05090).

All solvers are jit- and vmap-friendly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def solve_direct(H: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Solve H x = v for dense H via LU.

    LU rather than Cholesky: at a well-trained optimum the damped block
    Hessian is PD, but away from it the MSE Hessian's second-order term
    can make H indefinite (Cholesky would silently produce NaNs).
    """
    return jnp.linalg.solve(H, v)


def relative_residual(
    hvp: Callable[[jnp.ndarray], jnp.ndarray],
    v: jnp.ndarray,
    x: jnp.ndarray,
) -> jnp.ndarray:
    """Relative residual ‖Hx − v‖ / ‖v‖ of a candidate solve.

    The one-number solve-quality statement shared by the reliability
    divergence guards (engine NaN ladder, FullInfluenceEngine
    ``residual_guard``) and the stress probes — costs a single extra
    HVP. jit- and vmap-friendly; returns a 0-d array.
    """
    r = hvp(x) - v
    return jnp.linalg.norm(r) / jnp.maximum(jnp.linalg.norm(v), 1e-30)


def solve_cg(
    hvp: Callable[[jnp.ndarray], jnp.ndarray],
    v: jnp.ndarray,
    maxiter: int = 100,
    tol: float = 1e-10,
    x0: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Conjugate gradients on H x = v with a matrix-free hvp.

    Stopping: ||r||² ≤ tol · max(||v||², tiny), or maxiter (the reference
    caps fmin_ncg at 100 iterations, ``matrix_factorization.py:431``).
    Runs entirely on device; batches cleanly under vmap.
    """
    x = jnp.zeros_like(v) if x0 is None else x0
    r = v - hvp(x)
    p = r
    rs = jnp.vdot(r, r)
    threshold = tol * jnp.maximum(jnp.vdot(v, v), 1e-30)

    def cond(state):
        _, _, _, rs, it = state
        return jnp.logical_and(rs > threshold, it < maxiter)

    def body(state):
        # Under vmap the loop keeps running until ALL lanes converge, so
        # converged lanes must freeze (their p·Hp -> 0 would give 0/0).
        # A lane hitting negative curvature (H not PD away from an
        # optimum) also freezes, Newton-CG style: keep the current x.
        x, r, p, rs, it = state
        hp = hvp(p)
        denom = jnp.vdot(p, hp)
        stop = jnp.logical_or(rs <= threshold, denom <= 0.0)
        alpha = jnp.where(stop, 0.0, rs / jnp.where(denom != 0.0, denom, 1.0))
        x = x + alpha * p
        r = r - alpha * hp
        rs_new = jnp.where(stop, rs, jnp.vdot(r, r))
        beta = jnp.where(stop, 0.0, rs_new / jnp.where(rs != 0.0, rs, 1.0))
        p = jnp.where(stop, p, r + beta * p)
        # force the loop to exit for frozen lanes by zeroing their rs
        rs_new = jnp.where(jnp.logical_and(denom <= 0.0, rs > threshold),
                           jnp.zeros_like(rs_new), rs_new)
        return x, r, p, rs_new, it + 1

    x, *_ = lax.while_loop(cond, body, (x, r, p, rs, jnp.int32(0)))
    return x


def solve_schulz(
    H: jnp.ndarray, v: jnp.ndarray, maxiter: int = 128, tol: float = 1e-6
) -> jnp.ndarray:
    """Hyperpower (Newton–Schulz) solve: iterate X ← X(2I − HX), x = Xv.

    Matmul-only inversion of the materialised block Hessian — maps
    straight onto the MXU (no triangular solves, no host loops) and
    converges quadratically from X₀ = Hᵀ/(‖H‖₁‖H‖∞), which satisfies
    ‖I − HX₀‖ < 1 for any nonsingular H. The Schulz-iteration approach
    to influence-function inverses follows HyperINF (arXiv:2410.05090);
    here the FIA block system is small (d = 2k+2 / 4k), so the (d, d)
    iterates are cheap and batch cleanly under vmap over query batches.

    Iterations run until the RMS of the residual matrix I − HX drops
    below ``tol`` (the solve error obeys ‖Hx − v‖ ≤ ‖I − HX‖·‖v‖), up
    to ``maxiter``; convergence needs ≈ 2·log₂(κ(H)) + 6 iterations,
    with a long flat plateau first when κ is large (slow modes shrink
    below float32 resolution per step), so a plateau must NOT stop the
    loop. Beyond κ ~ 1/eps(float32) no 32-bit solver can reach tol and
    the quadratic iteration amplifies rounding instead — the loop
    tracks the best iterate and exits on material divergence (residual
    doubling, or NaN), returning that best (never NaN). Iterating past
    convergence keeps the best iterate, so lanes of mixed conditioning
    under a vmapped while_loop are safe.
    """
    d = H.shape[-1]
    eye = jnp.eye(d, dtype=H.dtype)
    norm1 = jnp.max(jnp.sum(jnp.abs(H), axis=-2))
    norminf = jnp.max(jnp.sum(jnp.abs(H), axis=-1))
    X0 = H.T / jnp.maximum(norm1 * norminf, 1e-30)

    # full fp32 matmuls: the TPU MXU's default bf16 accumulation floors
    # the residual around 1e-2 — the plateau phase then never ends and
    # the divergence guard returns a barely-improved X0
    mm = lambda a, b: jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)

    def resid(X):
        R = eye - mm(H, X)
        return jnp.sqrt(jnp.mean(jnp.square(R)))

    r0 = resid(X0)

    def cond(state):
        _, _, r_best, r_cur, it = state
        ok = jnp.isfinite(r_cur) & (r_cur < 2.0 * r_best)
        return (r_best > tol) & ok & (it < maxiter)

    def body(state):
        X_cur, X_best, r_best, _, it = state
        X_new = mm(X_cur, 2.0 * eye - mm(H, X_cur))
        r_new = resid(X_new)
        better = jnp.isfinite(r_new) & (r_new < r_best)
        X_best = jnp.where(better, X_new, X_best)
        r_best = jnp.where(better, r_new, r_best)
        return X_new, X_best, r_best, r_new, it + 1

    _, X, *_ = lax.while_loop(cond, body, (X0, X0, r0, r0, jnp.int32(0)))
    return mm(X, v)


def solve_lissa(
    hvp: Callable[[jnp.ndarray], jnp.ndarray],
    v: jnp.ndarray,
    scale: float = 10.0,
    damping: float = 0.0,
    recursion_depth: int = 1000,
    num_samples: int = 1,
    sample_hvp: Callable[[int, jnp.ndarray], jnp.ndarray] | None = None,
    auto_scale: bool = True,
) -> jnp.ndarray:
    """LiSSA inverse-HVP estimate.

    ``sample_hvp(j, x)``, when given, evaluates the HVP on the j-th
    stochastic minibatch (the reference's minibatched variant,
    ``genericNeuralNet.py:524-533``); otherwise the deterministic ``hvp``
    is used every step. Defaults mirror the reference: scale 10, LiSSA
    damping 0 (the Hessian damping lives inside ``hvp``).

    The recursion only converges when λ_max(H) < 2·scale; the reference
    silently NaNs past that (observed: NCF blocks whose GMF cross term
    pushes λ_max past 20 at the default scale 10). ``auto_scale`` keeps
    the reference semantics whenever they are valid — the estimator's
    fixed point is (H/scale)⁻¹·v/scale = H⁻¹v for EVERY valid scale, so
    raising it never changes the answer — by estimating λ_max with a
    32-step power iteration (cost: 32 extra HVPs against a 10k-deep
    recursion) and lifting scale to 1.05·λ_max only where the
    configured value would diverge.
    """
    if auto_scale:
        # estimate on the DETERMINISTIC hvp even in the minibatched
        # variant — a single minibatch's λ_max says nothing about the
        # batch the recursion will step on next; the full-data estimate
        # is representative, and the stochastic case takes a wider
        # margin to cover batch-to-batch curvature spread
        nv = jnp.linalg.norm(v)
        w0 = jnp.where(nv > 0, v / jnp.maximum(nv, 1e-30),
                       jnp.ones_like(v) / np.sqrt(v.size))

        def pit(_, st):
            w, _ = st
            hw = hvp(w)
            lam = jnp.linalg.norm(hw)
            return hw / jnp.maximum(lam, 1e-30), lam

        _, lam = lax.fori_loop(0, 32, pit, (w0, jnp.zeros(())))
        margin = 1.05 if sample_hvp is None else 1.5
        scale = jnp.maximum(scale, margin * lam)

    def one_sample(i, acc):
        def body(j, cur):
            # offset by the sample index so repetitions draw distinct
            # minibatch sequences (the reference re-fills per repetition,
            # genericNeuralNet.py:516-533) — without it every "sample"
            # would be bit-identical and the averaging a no-op
            hv = (
                sample_hvp(i * recursion_depth + j, cur)
                if sample_hvp is not None
                else hvp(cur)
            )
            return v + (1.0 - damping) * cur - hv / scale

        cur = lax.fori_loop(0, recursion_depth, body, v)
        return acc + cur / scale

    acc = lax.fori_loop(0, num_samples, one_sample, jnp.zeros_like(v))
    return acc / num_samples
