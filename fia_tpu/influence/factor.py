"""Precomputed factorized iHVP tier: the factor bank.

FIA's per-query Hessian is a tiny (2k+2 / 4k) block, yet the solver
ladder (``lissa → schulz → cg → direct``) estimates and inverts it from
scratch on every serve-cache miss. This module precomputes factorized
inverse-Hessian blocks for HOT (user, item) pairs offline — following
the low-rank factorization of LoRIF (arXiv:2601.21929) and the
Schulz-iteration refinement of HyperINF (arXiv:2410.05090) — so a
hot-path query collapses to one triangular solve / matvec inside the
engine's existing flat dispatch (the ``precomputed`` solver rung;
docs/design.md §16).

The bank lifecycle is **select → factorize → publish → load →
invalidate**:

- :func:`select_hot_pairs` ranks users/items by interaction degree (the
  serving hot set is degree-skewed by construction) and crosses the
  heads into candidate pairs.
- :func:`build_bank` computes the pairs' damped block Hessians in one
  fused mega-batch dispatch (``InfluenceEngine.block_hessians``, the
  flat program's ``hessian`` stage — AOT/mesh machinery included) and
  factorizes them: batched Cholesky where the block is numerically PD,
  a clamped-eigendecomposition inverse (low-rank + diagonal form) for
  near-singular blocks, with optional Schulz polish of that fallback.
- :func:`publish_bank` persists the bank through the artifact integrity
  layer (fsync'd atomic npz + checksummed manifest, fault site
  ``factor.publish``) under a config fingerprint binding model key,
  block width, damping, and the exact train set.
- :func:`load_bank` is a *verified* read: manifest checksum +
  fingerprint first, then a per-entry ``dep_crc`` revalidation against
  the CURRENT params/train state — a stale entry (any touched
  parameter row or train row) is dropped at load, never served.
- :func:`refresh_bank` is the surgical invalidation pass
  (``FIAModel._invalidate``): after a params change it keeps exactly
  the entries whose dependency digests still match (their Hessians are
  provably unchanged, so their factors stay valid) and republishes the
  survivors under the new fingerprint.

``dep_crc`` is the per-entry params fingerprint: a digest over exactly
the inputs the entry's Hessian and scores read — the parameter rows of
every user/item appearing in the pair's related set, all non-embedding
(global) parameters, the related rows' (x, y) bytes, and the solve
constants. Anything else can change freely without touching the entry.
"""

from __future__ import annotations

import hashlib
import os
import struct

import numpy as np

from fia_tpu.reliability import artifacts, sites

# Bump when the npz layout or dep_crc recipe changes: a bank written by
# an older recipe must miss cleanly (fingerprint-mismatch), not serve
# entries validated under different rules.
BANK_VERSION = 1

# Cholesky acceptance: min(diag(L)) must clear this fraction of
# max(diag(L)), else the block is treated as near-singular and the
# clamped-eigendecomposition fallback owns the entry.
_RCOND = 1e-6

KIND_CHOLESKY = 0  # factor holds L with H = L Lᵀ (lower)
KIND_INVERSE = 1   # factor holds an explicit approximate H⁻¹


class FactorBank:
    """An immutable set of factorized block inverses keyed by (u, i).

    Arrays (all host numpy, row ``n`` describes pair ``pairs[n]``):
      pairs   (N, 2) int32 — the (user, item) pairs covered
      kind    (N,)  uint8  — KIND_CHOLESKY or KIND_INVERSE
      factor  (N, d, d) float32 — L or H⁻¹ per ``kind``
      dep_crc (N,)  uint64 — per-entry dependency digest (see module doc)
    """

    def __init__(self, pairs, kind, factor, dep_crc):
        self.pairs = np.ascontiguousarray(np.asarray(pairs, np.int32))
        self.kind = np.ascontiguousarray(np.asarray(kind, np.uint8))
        self.factor = np.ascontiguousarray(np.asarray(factor, np.float32))
        self.dep_crc = np.ascontiguousarray(np.asarray(dep_crc, np.uint64))
        n = len(self.pairs)
        if not (len(self.kind) == len(self.factor) == len(self.dep_crc) == n):
            raise ValueError("factor bank arrays disagree on entry count")

    def __len__(self) -> int:
        return len(self.pairs)

    @property
    def block_d(self) -> int:
        return int(self.factor.shape[-1]) if len(self) else 0

    def lookup(self) -> dict:
        """Host hit-test map {(u, i): row}."""
        return {
            (int(u), int(i)): n for n, (u, i) in enumerate(self.pairs)
        }

    def take(self, mask: np.ndarray) -> "FactorBank":
        mask = np.asarray(mask, bool)
        return FactorBank(self.pairs[mask], self.kind[mask],
                          self.factor[mask], self.dep_crc[mask])

    @staticmethod
    def empty(block_d: int) -> "FactorBank":
        d = int(block_d)
        return FactorBank(
            np.zeros((0, 2), np.int32), np.zeros((0,), np.uint8),
            np.zeros((0, d, d), np.float32), np.zeros((0,), np.uint64),
        )


def default_bank_path(cache_dir: str, model_name: str) -> str:
    """Canonical on-disk location of a model's bank (the third serve
    cache tier lives beside the per-query disk tier)."""
    return os.path.join(cache_dir, "factor", f"{model_name}-bank.npz")


def bank_fingerprint(model_name: str, block_d: int, damping: float,
                     train_x: np.ndarray, train_y: np.ndarray) -> dict:
    """Manifest fingerprint binding a bank to its config + train set.

    Params freshness is deliberately NOT here — that is per-entry
    ``dep_crc`` territory, so a params update can invalidate entries
    surgically instead of voiding the whole artifact.
    """
    # normalize to RatingDataset's canonical dtypes so the digest is
    # identical whether the caller holds raw arrays or engine state
    x = np.ascontiguousarray(np.asarray(train_x, np.int32))
    y = np.ascontiguousarray(np.asarray(train_y, np.float32))
    return {
        "kind": "factor-bank",
        "version": BANK_VERSION,
        "model_key": str(model_name),
        "block_d": int(block_d),
        "damping": repr(float(damping)),
        "train_sha1": hashlib.sha1(x.tobytes() + y.tobytes()).hexdigest(),
    }


# -- hot-pair selection ----------------------------------------------------

def select_hot_pairs(index, max_entries: int = 1024,
                     top_users: int = 64, top_items: int = 64) -> np.ndarray:
    """Candidate (u, i) pairs for the bank, hottest first.

    Degree is the hotness signal the interaction index already holds:
    rank users and items by interaction count, cross the two heads, and
    score each pair by the product of its degrees (the classic
    popularity-traffic proxy — a serve stream drawn from the empirical
    interaction distribution hits these pairs first). Deterministic:
    ties break by ascending id. Returns (N, 2) int32, N ≤ max_entries.
    """
    du = np.asarray(index.user_degrees(), np.int64)
    di = np.asarray(index.item_degrees(), np.int64)
    # stable argsort on negated degree: ties resolve by ascending id
    users = np.argsort(-du, kind="stable")[: max(int(top_users), 0)]
    items = np.argsort(-di, kind="stable")[: max(int(top_items), 0)]
    users = users[du[users] > 0]
    items = items[di[items] > 0]
    if users.size == 0 or items.size == 0:
        return np.zeros((0, 2), np.int32)
    uu, ii = np.meshgrid(users, items, indexing="ij")
    pairs = np.stack([uu.ravel(), ii.ravel()], axis=1)
    score = du[pairs[:, 0]] * di[pairs[:, 1]]
    order = np.lexsort((pairs[:, 1], pairs[:, 0], -score))
    pairs = pairs[order][: max(int(max_entries), 0)]
    return np.ascontiguousarray(pairs, np.int32)


# -- per-entry dependency digests ------------------------------------------

def _classify_leaves(model, params_host) -> list:
    """Parameter leaves tagged by their keying axis.

    A leaf whose leading dimension equals ``num_users`` is user-keyed
    (its row u feeds only queries touching user u), ``num_items``
    item-keyed; anything else — including the ambiguous case where the
    leaf matches BOTH table sizes — is hashed per entry along every
    matching axis (ambiguity costs digest bytes, never correctness).
    Returns ``[(name, arr, tags)]`` sorted by the pytree key path.
    """
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(params_host)
    out = []
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        tags = set()
        if arr.ndim >= 1 and arr.shape[0] == int(model.num_users):
            tags.add("user")
        if arr.ndim >= 1 and arr.shape[0] == int(model.num_items):
            tags.add("item")
        if not tags:
            tags.add("global")
        out.append((jax.tree_util.keystr(path), arr, tags))
    out.sort(key=lambda t: t[0])
    return out


def dep_crcs(model, params_host, train_x, train_y, index,
             pairs: np.ndarray, damping: float) -> np.ndarray:
    """Per-pair dependency digests under the CURRENT params/train state.

    Covers exactly what the (u, i) block Hessian and its scores read:
    the parameter rows of every user/item id appearing in the pair's
    related set (plus u and i themselves), every global leaf, the
    related rows' (x, y) values in gather order, and the solve
    constants (damping, block width, weight decay). An entry whose
    stored digest equals the fresh one is provably untouched by
    whatever changed — the basis of surgical invalidation.
    """
    pairs = np.asarray(pairs, np.int64)
    # same dtype normalization as bank_fingerprint: digest-stable across
    # raw-array and RatingDataset-canonicalized callers
    x = np.ascontiguousarray(np.asarray(train_x, np.int32))
    y = np.ascontiguousarray(np.asarray(train_y, np.float32))
    leaves = _classify_leaves(model, params_host)

    seed = hashlib.blake2b(digest_size=16)
    seed.update(struct.pack("<iid", int(model.block_size), BANK_VERSION,
                            float(damping)))
    seed.update(struct.pack("<d", float(model.weight_decay)))
    for name, arr, tags in leaves:
        if "global" in tags:
            seed.update(name.encode())
            seed.update(np.ascontiguousarray(arr).tobytes())
    seed_digest = seed.digest()

    out = np.empty(len(pairs), np.uint64)
    for n, (u, i) in enumerate(pairs):
        u, i = int(u), int(i)
        urows = np.asarray(index.rows_of_user(u), np.int64)
        irows = np.asarray(index.rows_of_item(i), np.int64)
        rel = np.concatenate([urows, irows])
        users = np.unique(np.concatenate([[u], x[irows, 0]]))
        items = np.unique(np.concatenate([[i], x[urows, 1]]))
        h = hashlib.blake2b(digest_size=8)
        h.update(seed_digest)
        h.update(struct.pack("<qq", u, i))
        for name, arr, tags in leaves:
            if "user" in tags:
                h.update(np.ascontiguousarray(arr[users]).tobytes())
            if "item" in tags:
                h.update(np.ascontiguousarray(arr[items]).tobytes())
        h.update(rel.tobytes())
        h.update(np.ascontiguousarray(x[rel]).tobytes())
        h.update(np.ascontiguousarray(y[rel]).tobytes())
        out[n] = np.uint64(
            int.from_bytes(h.digest(), "little", signed=False)
        )
    return out


# -- factorization ---------------------------------------------------------

def factorize(H, schulz_polish: bool = False, schulz_iters: int = 8,
              rcond: float = _RCOND):
    """Factorize a batch of damped block Hessians.

    Batched Cholesky first — H is damped Gauss-Newton, PD at any
    well-trained optimum, and ``cho_solve`` at query time is the
    cheapest exact solve there is. Rows where the factorization fails
    numerically (non-finite L, or a diagonal spread past ``rcond`` —
    the away-from-optimum indefinite case solve_direct's LU guards
    against) fall back to a clamped eigendecomposition: eigenvalue
    MAGNITUDES floored at ``rcond·|λ|_max`` (signs preserved — the
    ladder's direct rung LU-solves the indefinite system as-is) and
    inverted, i.e. a low-rank (well-conditioned eigenspace) +
    diagonal-floor inverse. With
    ``schulz_polish`` the fallback inverse is refined by best-iterate
    Newton–Schulz steps X ← X(2I − HX) (HyperINF, arXiv:2410.05090),
    which sharpens the clamped modes where H was merely ill-conditioned
    rather than truly singular.

    Returns ``(kind (N,) uint8, factor (N, d, d) float32)`` as numpy.
    """
    import jax
    import jax.numpy as jnp

    H = jnp.asarray(H, jnp.float32)
    if H.ndim == 2:
        H = H[None]
    d = H.shape[-1]
    eye = jnp.eye(d, dtype=jnp.float32)

    L = jnp.linalg.cholesky(H)
    diag = jnp.diagonal(L, axis1=-2, axis2=-1)
    ok = jnp.all(jnp.isfinite(L), axis=(-2, -1)) & (
        jnp.min(diag, axis=-1)
        > rcond * jnp.maximum(jnp.max(diag, axis=-1), 1e-30)
    )

    # sign-PRESERVING magnitude floor: away from the optimum the block
    # Hessian is legitimately indefinite and solve_direct answers with
    # a plain LU solve of that indefinite system — flipping a healthy
    # negative eigenvalue positive would diverge from the ladder's
    # ground truth. Only near-zero magnitudes get regularized.
    w, V = jnp.linalg.eigh(H)
    aw = jnp.abs(w)
    floor = jnp.maximum(
        rcond * jnp.max(aw, axis=-1, keepdims=True), 1e-12
    )
    wc = jnp.where(w < 0, -1.0, 1.0) * jnp.maximum(aw, floor)
    Hinv = jnp.einsum("nij,nj,nkj->nik", V, 1.0 / wc, V)

    if schulz_polish and int(schulz_iters) > 0:
        mm = lambda a, b: jnp.matmul(
            a, b, precision=jax.lax.Precision.HIGHEST
        )

        def resid(X):
            R = eye[None] - mm(H, X)
            return jnp.sqrt(jnp.mean(jnp.square(R), axis=(-2, -1)))

        best, r_best = Hinv, resid(Hinv)
        X = Hinv
        for _ in range(int(schulz_iters)):
            X = mm(X, 2.0 * eye[None] - mm(H, X))
            r = resid(X)
            better = jnp.isfinite(r) & (r < r_best)
            best = jnp.where(better[:, None, None], X, best)
            r_best = jnp.where(better, r, r_best)
        Hinv = best

    factor = jnp.where(ok[:, None, None], jnp.nan_to_num(L), Hinv)
    kind = jnp.where(ok, KIND_CHOLESKY, KIND_INVERSE).astype(jnp.uint8)
    return (np.asarray(jax.device_get(kind), np.uint8),
            np.asarray(jax.device_get(factor), np.float32))


# -- build / publish / load / refresh --------------------------------------

def build_bank(engine, pairs: np.ndarray, batch_queries: int = 512,
               schulz_polish: bool = False) -> FactorBank:
    """Factorize ``pairs``' damped block Hessians into a bank.

    The Hessians come from ONE fused mega-batch dispatch per
    ``batch_queries`` chunk (:meth:`InfluenceEngine.block_hessians`,
    the flat program's ``hessian`` stage — mesh-sharded when the engine
    carries a mesh), so the offline pass rides the same AOT'd machinery
    as online queries.
    """
    pairs = np.asarray(pairs, np.int64)
    if pairs.size == 0:
        return FactorBank.empty(engine.model.block_size)
    H = engine.block_hessians(pairs, batch_queries=batch_queries)
    kind, factor = factorize(H, schulz_polish=schulz_polish)
    crc = dep_crcs(engine.model, engine._params_host,
                   engine._train_host[0], engine._train_host[1],
                   engine.index, pairs, engine.damping)
    return FactorBank(pairs, kind, factor, crc)


def publish_bank(bank: FactorBank, path: str, fingerprint: dict) -> str:
    """Durably publish a bank through the artifact integrity layer
    (fault site ``factor.publish``; torn/bitflip/stale-manifest damage
    is detected and quarantined on the next verified load)."""
    return artifacts.publish_npz(
        path,
        {
            "pairs": bank.pairs,
            "kind": bank.kind,
            "factor": bank.factor,
            "dep_crc": bank.dep_crc,
        },
        fingerprint=fingerprint,
        site=sites.FACTOR_PUBLISH,
    )


def _bank_from_raw(raw: dict, path: str) -> FactorBank:
    try:
        return FactorBank(raw["pairs"], raw["kind"], raw["factor"],
                          raw["dep_crc"])
    except (KeyError, ValueError) as e:
        # checksum passed but the payload is not a bank (foreign writer
        # under our name): quarantine like any unreadable artifact
        artifacts.quarantine(path, f"bank-malformed: {e}")
        raise artifacts.ArtifactIntegrityError(
            path, "unreadable", f"bank-malformed: {e}"
        )


def load_bank(path: str, engine) -> tuple[FactorBank, int]:
    """Verified bank load against the CURRENT engine state.

    Integrity first (checksum + config/train fingerprint; corrupt files
    quarantine as ``*.corrupt`` and read as a miss), then the per-entry
    ``dep_crc`` revalidation: entries whose digests no longer match the
    live params/train state are dropped HERE — a stale entry under a
    new params fingerprint is structurally unservable. Returns
    ``(bank_of_survivors, n_dropped)``; raises
    :class:`~fia_tpu.reliability.artifacts.ArtifactIntegrityError` on
    integrity failure (callers treat it as "no bank").
    """
    fp = bank_fingerprint(engine.model_name, engine.model.block_size,
                          engine.damping, *engine._train_host)
    raw = artifacts.load_npz(path, expected_fingerprint=fp,
                             require_manifest=True)
    bank = _bank_from_raw(raw, path)
    if len(bank) == 0:
        return bank, 0
    fresh = dep_crcs(engine.model, engine._params_host,
                     engine._train_host[0], engine._train_host[1],
                     engine.index, bank.pairs, engine.damping)
    keep = fresh == bank.dep_crc
    return bank.take(keep), int(np.count_nonzero(~keep))


def refresh_bank(model, params_host, train_x, train_y, index, damping,
                 path: str, model_name: str) -> dict:
    """Surgical invalidation after a params/train change.

    Re-digests every published entry under the NEW state and
    republishes exactly the survivors (their inputs are unchanged, so
    their factors are still the factors of the current Hessians — no
    recompute needed) under the new fingerprint. Touched entries are
    dropped. Returns ``{"kept": int, "dropped": int}``; a missing or
    corrupt bank is a no-op (corruption quarantines as usual).
    """
    if not os.path.exists(path):
        return {"kept": 0, "dropped": 0}
    try:
        # integrity-only read: the OLD fingerprint is unknowable here
        # (that is the point of the refresh), dep_crc does the params
        # half of the validation below
        raw = artifacts.load_npz(path, require_manifest=True)
        bank = _bank_from_raw(raw, path)
    except artifacts.ArtifactIntegrityError:
        return {"kept": 0, "dropped": 0}
    if len(bank):
        fresh = dep_crcs(model, params_host, train_x, train_y, index,
                         bank.pairs, damping)
        keep = fresh == bank.dep_crc
        dropped = int(np.count_nonzero(~keep))
        bank = bank.take(keep)
    else:
        dropped = 0
    fp = bank_fingerprint(model_name, model.block_size, damping,
                          train_x, train_y)
    publish_bank(bank, path, fp)
    return {"kept": len(bank), "dropped": dropped}
