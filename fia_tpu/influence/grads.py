"""Gradient primitives for influence analysis.

Replaces the reference's graph-level ``tf.gradients`` ops and the
one-``sess.run``-per-train-row scoring loop
(``matrix_factorization.py:240-246``) with vmapped per-example gradients.
All functions return *flattened* block vectors (d = model.block_size) so
solvers and scoring are plain linear algebra.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def block_prediction_grad(model, params, u, i, x):
    """∇_block of the mean predicted rating over rows ``x``.

    This is the FIA test-side vector v (reference ``grad_loss_r`` sliced
    by ``get_test_grad``, ``genericNeuralNet.py:155`` +
    ``matrix_factorization.py:152-162, 253-286``).
    """
    block0 = model.extract_block(params, u, i)

    def mean_pred(bvec):
        block = model.unflatten_block(bvec, block0)
        return jnp.mean(model.block_predict(params, block, u, i, x))

    return jax.grad(mean_pred)(model.flatten_block(block0))


def block_loss_grad(model, params, u, i, x, y, w=None):
    """∇_block of the total loss ((masked-)mean MSE + L2) over rows x."""
    block0 = model.extract_block(params, u, i)

    def total(bvec):
        block = model.unflatten_block(bvec, block0)
        return model.block_loss(params, block, u, i, x, y, w)

    return jax.grad(total)(model.flatten_block(block0))


def per_example_block_loss_grads(model, params, u, i, x, y):
    """(B, d) matrix of ∇_block L(z_j) for each row j fed alone.

    Matches the reference's per-row feeds of ``grad_total_loss_op``
    sliced to the block (``matrix_factorization.py:240-246``): each row's
    loss is its own squared error plus the *full* regulariser, so every
    row's gradient carries the same wd * θ_block term.
    """
    block0 = model.extract_block(params, u, i)
    bvec0 = model.flatten_block(block0)

    def one(xj, yj):
        def total(bvec):
            block = model.unflatten_block(bvec, block0)
            return model.block_loss(params, block, u, i, xj[None, :], yj[None])

        return jax.grad(total)(bvec0)

    return jax.vmap(one)(x, y)


def autodiff_row_grads(model, params, u, i, x):
    """(B, d) per-row block Jacobian by vmapped single-row autodiff.

    The *definitional* form of ``block_row_grads``: row j's gradient is
    ``jax.grad`` of its own one-row prediction w.r.t. the flattened
    block. Every faster path — the model's closed-form hook, the fused
    Pallas score kernels (influence/kernels/) — is parity-tested
    against this function, so it must stay the plain-AD transcription
    of the definition. ``u``/``i`` may be scalars or (B,) per-row
    query ids aligned with ``x`` (the flat engine's layout).
    """
    u_arr = jnp.asarray(u)
    per_row_ids = u_arr.ndim > 0

    def one(xj, uu, ii):
        block0 = model.extract_block(params, uu, ii)

        def pred(bvec):
            block = model.unflatten_block(bvec, block0)
            return model.block_predict(params, block, uu, ii, xj[None, :])[0]

        return jax.grad(pred)(model.flatten_block(block0))

    if per_row_ids:
        return jax.vmap(one)(x, u, i)
    return jax.vmap(lambda xj: one(xj, u, i))(x)


def per_example_block_prediction_grads(model, params, u, i, x):
    """(B, d) matrix of g_j = ∇_block r̂(z_j), one row per example.

    The Jacobian of the prediction w.r.t. the block — the J in
    Gauss-Newton block-Hessian forms (H = (2/n) Jᵀ W J + corrections),
    exact for models whose prediction is piecewise-linear in the block.
    Routes through the model's ``block_row_grads`` hook when defined
    (one batched program instead of B vmapped single-row graphs — see
    models/base.py hook doc); :func:`autodiff_row_grads` remains the
    definition the hook is regression-tested against.
    """
    if model.block_row_grads is not None:
        return model.block_row_grads(params, u, i, x)
    return autodiff_row_grads(model, params, u, i, x)


def per_example_full_loss_grads(model, params, x, y):
    """(B,) pytree-of-stacked per-example full-parameter loss gradients."""

    def one(xj, yj):
        return jax.grad(lambda p: model.loss(p, xj[None, :], yj[None]))(params)

    return jax.vmap(one)(x, y)


def full_loss_grad(model, params, x, y, w=None):
    return jax.grad(lambda p: model.loss(p, x, y, w))(params)


def full_loss_no_reg_grad(model, params, x, y, w=None):
    return jax.grad(lambda p: model.loss_no_reg(p, x, y, w))(params)
