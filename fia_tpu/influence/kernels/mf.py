"""Fused Pallas score kernel for the MF block geometry.

The MF per-row block gradient is closed-form gathers + masks
(models/mf.py ``block_row_grads``):

    g_j = [a_j Q[i_j] ; b_j P[u_j] ; a_j ; b_j],  d = 2k + 2

so the score dot g_j · ihvp_t splits into two masked k-row dots plus
two bias picks — no (S, d) matrix needed. Each grid step streams one
(TILE, 2k) tile of pre-gathered raw rows ``[Q[i_j] | P[u_j]]`` plus
the (TILE, 4) scalar pack through VMEM, one-hot-fetches its queries'
``[ihvp | reg_dot | n_t]`` rows on the MXU, and writes the finished
(TILE, 1) score column; the gradient exists only in registers.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from fia_tpu.influence.kernels import common


def _kernel(rows_ref, scal_ref, t_ref, B_ref, out_ref, *, k: int, d: int,
            t_pad: int):
    P = common.onehot_fetch(t_ref[...], B_ref, t_pad)  # (TILE, d + 2)
    rows = rows_ref[...]
    scal = scal_ref[...]
    e, wv, a, b = scal[:, 0], scal[:, 1], scal[:, 2], scal[:, 3]
    # g · ihvp, term by term: the pu slice of the iHVP dots the row's
    # item embedding (and vice versa), biases pick single entries
    gdot = a * (jnp.sum(rows[:, :k] * P[:, :k], axis=1) + P[:, 2 * k]) + b * (
        jnp.sum(rows[:, k:] * P[:, k : 2 * k], axis=1) + P[:, 2 * k + 1]
    )
    out_ref[...] = common.score_epilogue(gdot, e, wv, P, d)[:, None]


def fused_scores(model, params, ut, it, t, rel_x, e, wv, ihvp, reg_dot, n_t):
    """(S,) fused scores for the MF geometry (see package doc for the
    operand contract)."""
    k = int(model.embedding_size)
    d = int(model.block_size)
    t_pad = ihvp.shape[0]
    rows = model.kernel_row_inputs(params, rel_x)  # (S, 2k) [Q[i]|P[u]]
    a = (rel_x[:, 0] == ut).astype(jnp.float32)
    b = (rel_x[:, 1] == it).astype(jnp.float32)
    scal = common.pack_scalars(e, wv, a, b)
    t2 = t.astype(jnp.int32)[:, None]
    B = common.query_matrix(ihvp, reg_dot, n_t)

    S = rows.shape[0]
    s_pad = common.pad_rows(S)
    # fialint: disable=FIA202 -- static shape ints; pad choice is per-geometry
    if s_pad != S:
        # zero-padded rows carry wv = 0 and segment 0 — they fetch a
        # real B row and score exactly 0, then slice away
        pad = [(0, s_pad - S), (0, 0)]
        rows = jnp.pad(rows, pad)
        scal = jnp.pad(scal, pad)
        t2 = jnp.pad(t2, pad)

    def block_specs(pl, tile):
        return [
            pl.BlockSpec((tile, 2 * k), lambda s: (s, 0)),
            pl.BlockSpec((tile, 4), lambda s: (s, 0)),
            pl.BlockSpec((tile, 1), lambda s: (s, 0)),
            pl.BlockSpec((t_pad, d + 2), lambda s: (0, 0)),
        ]

    out = common.run_tiled(
        functools.partial(_kernel, k=k, d=d, t_pad=t_pad),
        s_pad,
        t_pad,
        (rows, scal, t2, B),
        block_specs,
        interpret=common.interpret_mode(),
    )
    return out[:S, 0]
