"""Shared plumbing for the fused Pallas score kernels.

Both block geometries (kernels/mf.py, kernels/ncf.py) run the same
harness: the flat row axis S is cut into row tiles that Pallas
pipelines through VMEM, per-row float operands travel as one packed
(S, 4) matrix ``[e, wv, a, b]``, segment ids as an (S, 1) int32
column, and every per-QUERY operand — the (T, d) iHVP, the (T,)
regulariser dot and segment size — as one augmented
``B = [ihvp | reg_dot | n_t]`` (T, d + 2) matrix resident in VMEM for
every grid step. Inside the kernel a row tile fetches its queries'
rows of B with a one-hot (TILE, T) @ (T, d+2) MXU matmul — the same
one-hot-over-scatter trade the engine's Hessian accumulation uses
(engine.py ``body_onehot``) — so the (S, d) gather-expand of the iHVP
never exists in HBM. See docs/design.md §19 for the memory plan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# One-hot staging buffer budget: TILE · T fp32 elements per grid step.
# 2^20 elements = 4 MB — comfortably inside a ~16 MB VMEM core budget
# next to the row tile, B, and the geometry's weight operands.
_ONEHOT_BUDGET_ELEMS = 1 << 20
_MAX_TILE = 512
_SUBLANE = 8  # fp32 sublane quantum; row tiles stay multiples of it


def pick_tile(s_pad: int, t_pad: int) -> int:
    """Largest power-of-two row tile that divides ``s_pad``, capped by
    the one-hot VMEM budget for this query count. The flat pad is a
    multiple of 2048 in production (engine `_s_pad_for`), so this is
    normally 512 at T ≤ 2048 and halves as T grows."""
    budget = max(_SUBLANE, _ONEHOT_BUDGET_ELEMS // max(int(t_pad), 1))
    tile = 1
    while (
        tile * 2 <= min(_MAX_TILE, budget)
        and s_pad % (tile * 2) == 0
    ):
        tile *= 2
    return tile


def pad_rows(s: int) -> int:
    """Round the flat row count up to the sublane quantum so direct
    (test-sized) invocations tile cleanly; padded rows carry wv = 0 and
    score 0 by construction."""
    return -(-s // _SUBLANE) * _SUBLANE


def pack_scalars(e, wv, a, b) -> jnp.ndarray:
    """(S, 4) fp32 per-row operand pack: residual, validity, user and
    item match masks — one streamed input instead of four 1-wide ones."""
    return jnp.stack(
        [
            e.astype(jnp.float32),
            wv.astype(jnp.float32),
            a.astype(jnp.float32),
            b.astype(jnp.float32),
        ],
        axis=1,
    )


def query_matrix(ihvp, reg_dot, n_t) -> jnp.ndarray:
    """Augmented per-query operand ``B = [ihvp | reg_dot | n_t]``,
    (T, d + 2). The kernel divides by the n_t column (rather than
    multiplying by a precomputed reciprocal) to keep the epilogue the
    same arithmetic as the XLA twin's ``/ n_t[t]``."""
    return jnp.concatenate(
        [ihvp, reg_dot[:, None], n_t[:, None]], axis=1
    ).astype(jnp.float32)


def interpret_mode() -> bool:
    """Pallas interpret mode everywhere but real TPUs: the kernels are
    *testable* on CPU (parity vs the XLA twin, tests/test_kernels.py)
    without pretending interpret execution is a serving path."""
    return jax.default_backend() != "tpu"


def onehot_fetch(t_col, B_ref, t_pad: int) -> jnp.ndarray:
    """(TILE, d+2) per-row rows of B via a one-hot MXU matmul.

    ``t_col`` is the (TILE, 1) int32 segment-id block;
    ``broadcasted_iota`` rather than 1-D ``iota`` — TPU requires ≥ 2D
    iota (see /opt/skills/guides/pallas_guide.md).
    """
    onehot = (
        t_col == jax.lax.broadcasted_iota(jnp.int32, (t_col.shape[0], t_pad), 1)
    ).astype(jnp.float32)
    return jnp.dot(onehot, B_ref[...], preferred_element_type=jnp.float32)


def score_epilogue(gdot, e, wv, P, d: int) -> jnp.ndarray:
    """Shared kernel epilogue: (TILE,) scores from the per-row
    gradient·iHVP dot and the fetched B rows —
    wv · (2 e gdot + reg_dot) / n_t."""
    reg_dot = P[:, d]
    n_t = P[:, d + 1]
    return wv * (2.0 * e * gdot + reg_dot) / n_t


def run_tiled(kernel_body, s_pad: int, t_pad: int, inputs, block_specs,
              *, interpret: bool):
    """``pallas_call`` harness shared by the geometries: grid over row
    tiles, (S, 1) fp32 score output."""
    from jax.experimental import pallas as pl

    tile = pick_tile(s_pad, t_pad)
    grid = (s_pad // tile,)
    return pl.pallas_call(
        kernel_body,
        grid=grid,
        in_specs=block_specs(pl, tile),
        out_specs=pl.BlockSpec((tile, 1), lambda s: (s, 0)),
        out_shape=jax.ShapeDtypeStruct((s_pad, 1), jnp.float32),
        interpret=interpret,
    )(*inputs)
