"""Fused score kernels for the flat influence path.

The score stage of the flat program computes, for every flat related
row s owned by query t,

    score_s = wv_s * (2 e_s (g_s · ihvp_t) + reg_dot_t) / n_t

with g_s the row's closed-form block gradient. The vmapped-autodiff
form of that stage (S single-row grad graphs feeding an (S, d)
matrix in HBM, then an (S, d) gather-expand of ihvp) was measured at
~90% of the flat query's device program ("Scaling Up Influence
Functions", arXiv:2112.03052, finds the same wall at pod scale). This
package collapses it into one of three interchangeable *variants*:

  - ``pallas``: one fused Pallas TPU kernel per block geometry
    (``kernels/mf.py`` / ``kernels/ncf.py``) — raw embedding rows
    stream through VMEM tiles, per-row gradients form *in registers*
    from the closed-form block losses, and per-query operands arrive
    by an in-kernel one-hot MXU matmul, so neither the (S, d) gradient
    matrix nor the (S, d) ihvp expansion ever touches HBM. On non-TPU
    backends the same kernel runs under ``interpret=True`` (tests
    only — production CPU serves the XLA twin).
  - ``xla_analytic``: the pure-XLA twin — the model's
    ``block_row_grads`` hook plus the reference einsum, op-for-op the
    engine's historical default, so it is the always-available
    fallback AND the bit-exactness anchor for golden runs.
  - ``vmap_autodiff``: the definitional reference (vmapped
    ``jax.grad`` over single-row graphs) every faster variant is
    parity-tested against (tests/test_kernels.py).

Selection is engine-level (``InfluenceEngine(kernel=...)``) and folds
into both the jit cache keys and the AOT ``_aot_key``, so
``precompile_flat`` / mesh dispatch / ``rebuild_mesh`` keep their
zero-steady-state-compile contract per variant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fia_tpu.influence.grads import autodiff_row_grads

VARIANTS = ("pallas", "xla_analytic", "vmap_autodiff")

_PALLAS_FAMILIES = ("mf", "ncf")


def supports_pallas(model) -> bool:
    """A fused Pallas kernel exists for this block geometry: the model
    declares a kernel family this package implements and the gather /
    closed-form hooks the kernel body needs."""
    return (
        getattr(model, "kernel_family", None) in _PALLAS_FAMILIES
        and model.kernel_row_inputs is not None
        and model.block_row_grads is not None
    )


def resolve_variant(requested: str, model, backend: str | None = None) -> str:
    """Resolve an engine-level ``kernel`` request to a concrete variant.

    ``auto`` picks the fused Pallas kernel on TPU when the model's
    geometry has one, the XLA analytic twin when the model defines
    ``block_row_grads`` (every non-TPU production backend — interpret
    mode is a test vehicle, not a serving path), and the autodiff
    reference otherwise. Explicit requests are honored or rejected
    loudly — a silently substituted variant would invalidate a parity
    run without telling anyone.
    """
    if backend is None:
        backend = jax.default_backend()
    if requested == "auto":
        if backend == "tpu" and supports_pallas(model):
            return "pallas"
        if model.block_row_grads is not None:
            return "xla_analytic"
        return "vmap_autodiff"
    if requested not in VARIANTS:
        raise ValueError(f"unknown kernel variant {requested!r}")
    if requested == "pallas" and not supports_pallas(model):
        raise ValueError(
            f"{type(model).__name__} has no fused Pallas score kernel "
            "(needs kernel_family + kernel_row_inputs + block_row_grads)"
        )
    if requested == "xla_analytic" and model.block_row_grads is None:
        raise ValueError(
            f"{type(model).__name__} defines no block_row_grads hook — "
            "the analytic variant has nothing to run"
        )
    return requested


def row_grads(model, variant: str, params, ut, it, rel_x) -> jnp.ndarray:
    """(S, d) per-row block gradients for the Hessian/grads stages.

    The Pallas variant never materializes these for *scoring* — but the
    flat program's Hessian accumulation still consumes g row-tiles, so
    it shares the analytic hook here (the bank hot path has no Hessian
    stage and skips this entirely).
    """
    if variant != "vmap_autodiff" and model.block_row_grads is not None:
        return model.block_row_grads(params, ut, it, rel_x)
    return autodiff_row_grads(model, params, ut, it, rel_x)


def fused_scores(
    model,
    variant: str,
    params,
    ut,
    it,
    t,
    rel_x,
    e,
    wv,
    ihvp,
    reg_dot,
    n_t,
    g=None,
):
    """The score stage: (S,) influence scores for flat rows.

    ``ut``/``it`` are the per-row owning-query ids, ``t`` the segment
    ids, ``e``/``wv`` the residuals and validity mask, ``ihvp`` (T, d),
    ``reg_dot``/``n_t`` (T,). ``g`` is an already-materialized (S, d)
    gradient matrix when the caller has one (the flat program computed
    it for the Hessian stage); the XLA/autodiff variants reuse it
    op-for-op — bit-identical to the historical inline einsum — while
    the Pallas variant ignores it and re-forms gradients in VMEM from
    ``rel_x`` + the resident tables (recompute-over-HBM-traffic, the
    standard fusion trade).
    """
    if variant == "pallas":
        from fia_tpu.influence.kernels import mf as _mf
        from fia_tpu.influence.kernels import ncf as _ncf

        impl = {"mf": _mf, "ncf": _ncf}[model.kernel_family]
        return impl.fused_scores(
            model, params, ut, it, t, rel_x, e, wv, ihvp, reg_dot, n_t
        )
    if g is None:
        g = row_grads(model, variant, params, ut, it, rel_x)
    return wv * (
        2.0 * e * jnp.einsum("sd,sd->s", g, ihvp[t]) + reg_dot[t]
    ) / n_t[t]
