"""Fused Pallas score kernel for the NCF block geometry.

The NCF per-row block gradient is one closed-form MLP backward
(models/ncf.py ``_own_grads`` / ``block_row_grads``): with
z1 = [pm|qm] W1 + b1, h1 = relu(z1), z2 = h1 W2 + b2, and W3 split
into its h2 rows w3h and GMF rows w3g,

    dz2 = [z2 > 0] ⊙ w3h          dh_in = ([z1 > 0] ⊙ (dz2 W2ᵀ)) W1ᵀ
    g_j = [a_j dh_in[:k] ; b_j dh_in[k:] ; a_j (qg ⊙ w3g) ; b_j (pg ⊙ w3g)]

— three tile-batched MXU matmuls per row tile, entirely in VMEM. Each
grid step streams a (TILE, 4k) tile of the four pre-gathered raw
embedding rows ``[P_mlp[u_j] | Q_mlp[i_j] | P_gmf[u_j] | Q_gmf[i_j]]``,
re-derives the forward masks, forms the gradient in registers, and
dots it against the one-hot-fetched iHVP rows; the MLP weights ride
along as whole-array VMEM operands (a few hundred KB at reference
sizes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fia_tpu.influence.kernels import common


def _kernel(rows_ref, scal_ref, t_ref, B_ref, W1_ref, b1_ref, W2_ref,
            b2_ref, W3_ref, out_ref, *, k: int, k2: int, d: int,
            t_pad: int):
    f32 = jnp.float32
    rows = rows_ref[...]
    scal = scal_ref[...]
    e, wv, a, b = scal[:, 0], scal[:, 1], scal[:, 2], scal[:, 3]

    # forward to the relu masks (biases matter only through the masks)
    hin = rows[:, : 2 * k]
    z1 = jnp.dot(hin, W1_ref[...], preferred_element_type=f32) + b1_ref[...]
    h1 = jnp.maximum(z1, 0.0)
    z2 = jnp.dot(h1, W2_ref[...], preferred_element_type=f32) + b2_ref[...]

    W3 = W3_ref[...]
    w3h = W3[:k2, 0]
    w3g = W3[k2:, 0]
    # backward: relu' = [z > 0] (matches jax.nn.relu's grad at 0)
    dz2 = jnp.where(z2 > 0.0, w3h[None, :], 0.0)
    dh1 = jax.lax.dot_general(
        dz2, W2_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
    )
    dz1 = jnp.where(z1 > 0.0, dh1, 0.0)
    dhin = jax.lax.dot_general(
        dz1, W1_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
    )  # (TILE, 2k): the (dpm | dqm) own-gradients

    pg = rows[:, 2 * k : 3 * k]
    qg = rows[:, 3 * k :]
    P = common.onehot_fetch(t_ref[...], B_ref, t_pad)  # (TILE, d + 2)
    gdot = a * (
        jnp.sum(dhin[:, :k] * P[:, :k], axis=1)
        + jnp.sum(qg * w3g[None, :] * P[:, 2 * k : 3 * k], axis=1)
    ) + b * (
        jnp.sum(dhin[:, k:] * P[:, k : 2 * k], axis=1)
        + jnp.sum(pg * w3g[None, :] * P[:, 3 * k :d], axis=1)
    )
    out_ref[...] = common.score_epilogue(gdot, e, wv, P, d)[:, None]


def fused_scores(model, params, ut, it, t, rel_x, e, wv, ihvp, reg_dot, n_t):
    """(S,) fused scores for the NCF geometry (see package doc for the
    operand contract)."""
    k = int(model.embedding_size)
    d = int(model.block_size)
    t_pad = ihvp.shape[0]
    rows = model.kernel_row_inputs(params, rel_x)  # (S, 4k)
    W1, b1, W2, b2, W3 = model.kernel_aux(params)
    k2 = W2.shape[1]
    a = (rel_x[:, 0] == ut).astype(jnp.float32)
    b = (rel_x[:, 1] == it).astype(jnp.float32)
    scal = common.pack_scalars(e, wv, a, b)
    t2 = t.astype(jnp.int32)[:, None]
    B = common.query_matrix(ihvp, reg_dot, n_t)

    S = rows.shape[0]
    s_pad = common.pad_rows(S)
    # fialint: disable=FIA202 -- static shape ints; pad choice is per-geometry
    if s_pad != S:
        pad = [(0, s_pad - S), (0, 0)]
        rows = jnp.pad(rows, pad)
        scal = jnp.pad(scal, pad)
        t2 = jnp.pad(t2, pad)

    def block_specs(pl, tile):
        whole = lambda s: (0, 0)
        return [
            pl.BlockSpec((tile, 4 * k), lambda s: (s, 0)),
            pl.BlockSpec((tile, 4), lambda s: (s, 0)),
            pl.BlockSpec((tile, 1), lambda s: (s, 0)),
            pl.BlockSpec((t_pad, d + 2), whole),
            pl.BlockSpec(W1.shape, whole),
            pl.BlockSpec(b1.shape, whole),
            pl.BlockSpec(W2.shape, whole),
            pl.BlockSpec(b2.shape, whole),
            pl.BlockSpec(W3.shape, whole),
        ]

    out = common.run_tiled(
        functools.partial(_kernel, k=k, k2=k2, d=d, t_pad=t_pad),
        s_pad,
        t_pad,
        (rows, scal, t2, B, W1, b1, W2, b2, W3),
        block_specs,
        interpret=common.interpret_mode(),
    )
    return out[:S, 0]
