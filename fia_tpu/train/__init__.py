from fia_tpu.train.trainer import Trainer, TrainConfig  # noqa: F401
from fia_tpu.train import checkpoint  # noqa: F401
