"""Checkpointing.

Reference equivalent: full-session ``tf.train.Saver`` checkpoints plus a
params-only restore (``genericNeuralNet.py:149, 407-429``). Here a
checkpoint is the (params, opt_state, step) triple saved as an npz of
flattened pytree leaves; loading restores into a template with matching
structure, leaf shapes AND dtypes (two configs with identical tree
structure but different embedding dims must never restore into each
other). An orbax-backed variant is provided for async/multi-host use.

Persistence goes through the artifact integrity layer
(``fia_tpu/reliability/artifacts.py``): every save is an fsync'd atomic
publish with a checksummed, fingerprinted sidecar manifest, and every
load verifies before deserialising. On top of that sit the crash-safety
pieces this module owns:

- :func:`save_rotated` — a last-K rotated checkpoint directory
  (``ckpt-<step>.npz`` generations, oldest pruned);
- :func:`restore_latest_valid` — walk back from the newest generation to
  the first one that passes checksum + fingerprint + template
  validation, quarantining corrupt generations (``*.corrupt``) along
  the way;
- :class:`PeriodicCheckpointer` — the trainer-side hook that publishes a
  generation every N steps, so a killed training run auto-resumes from
  the last good step instead of step 0.
"""

from __future__ import annotations

import os
import re

import jax
import numpy as np

from fia_tpu import obs
from fia_tpu.reliability import artifacts, sites

_GEN_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def _flatten(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, str(treedef)


def save(path: str, params, opt_state=None, step: int = 0,
         fingerprint=None) -> str:
    """Durably publish a checkpoint (npz + manifest); returns the path."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    leaves, treedef = _flatten(params)
    payload = {f"p{i}": np.asarray(l) for i, l in enumerate(leaves)}
    payload["__ptree__"] = np.array(treedef)
    if opt_state is not None:
        oleaves, otreedef = _flatten(opt_state)
        payload.update({f"o{i}": np.asarray(l) for i, l in enumerate(oleaves)})
        payload["__otree__"] = np.array(otreedef)
    payload["__step__"] = np.array(step)
    out = path if path.endswith(".npz") else path + ".npz"
    artifacts.publish_npz(out, payload, fingerprint=fingerprint,
                          site=sites.CHECKPOINT_PUBLISH)
    return out


def _validate_leaves(got, template, path: str, what: str) -> None:
    """Leaf-level shape/dtype validation against the template.

    The treedef string match catches structural mismatches but is blind
    to leaf shapes — two configs differing only in embedding dim restore
    silently into wrong-shaped params without this."""
    t_leaves = jax.tree_util.tree_leaves(template)
    if len(got) != len(t_leaves):
        raise ValueError(
            f"checkpoint {what} leaf count {len(got)} != template "
            f"{len(t_leaves)} in {path}"
        )
    for i, (g, t) in enumerate(zip(got, t_leaves)):
        ts = tuple(np.shape(t))
        gs = tuple(np.shape(g))
        if ts != gs:
            raise ValueError(
                f"checkpoint {what} leaf {i} shape {gs} != template "
                f"{ts} in {path}"
            )
        td = np.asarray(t).dtype if not hasattr(t, "dtype") else np.dtype(t.dtype)
        if np.dtype(g.dtype) != td:
            raise ValueError(
                f"checkpoint {what} leaf {i} dtype {g.dtype} != template "
                f"{td} in {path}"
            )


def load(path: str, params_template, opt_template=None, *,
         fingerprint=None, require_manifest: bool = False):
    """Load a checkpoint into (params, opt_state, step).

    The file is verified against its integrity manifest first (lenient
    on manifest-less legacy files unless ``require_manifest``); corrupt
    files are quarantined and raise
    :class:`~fia_tpu.reliability.artifacts.ArtifactIntegrityError`.
    Structures, leaf shapes and dtypes are then validated against the
    provided templates, mirroring the reference's Saver var-list
    matching (ValueError on mismatch).
    """
    if not path.endswith(".npz"):
        path = path + ".npz"
    z = artifacts.load_npz(path, expected_fingerprint=fingerprint,
                           require_manifest=require_manifest)
    pleaves = [z[f"p{i}"] for i in range(_count(z, "p"))]
    _, ptreedef = jax.tree_util.tree_flatten(params_template)
    if str(ptreedef) != str(z["__ptree__"]):
        raise ValueError(f"checkpoint param structure mismatch in {path}")
    _validate_leaves(pleaves, params_template, path, "param")
    params = jax.tree_util.tree_unflatten(ptreedef, pleaves)
    opt_state = None
    if opt_template is not None and "__otree__" in z:
        oleaves = [z[f"o{i}"] for i in range(_count(z, "o"))]
        _, otreedef = jax.tree_util.tree_flatten(opt_template)
        if str(otreedef) != str(z["__otree__"]):
            raise ValueError(f"checkpoint opt structure mismatch in {path}")
        _validate_leaves(oleaves, opt_template, path, "opt")
        opt_state = jax.tree_util.tree_unflatten(otreedef, oleaves)
    step = int(z["__step__"])
    return params, opt_state, step


def _count(z, prefix: str) -> int:
    n = 0
    while f"{prefix}{n}" in z:
        n += 1
    return n


def exists(path: str) -> bool:
    return os.path.isfile(path if path.endswith(".npz") else path + ".npz")


# -- rotated last-K generations + last-good-fallback restore ---------------

def generations(dir_path: str) -> list[tuple[int, str]]:
    """(step, path) of every checkpoint generation, oldest first.

    Quarantined (``*.corrupt``) files never match the generation name
    pattern, so they are invisible here — evidence stays on disk but is
    never re-read."""
    if not os.path.isdir(dir_path):
        return []
    gens = []
    for name in os.listdir(dir_path):
        m = _GEN_RE.match(name)
        if m:
            gens.append((int(m.group(1)), os.path.join(dir_path, name)))
    return sorted(gens)


def save_rotated(dir_path: str, params, opt_state=None, step: int = 0, *,
                 keep: int = 3, fingerprint=None) -> str:
    """Publish ``ckpt-<step>.npz`` into a rotated last-K directory.

    Older generations beyond ``keep`` are pruned (retention policy —
    pruning valid history is not evidence destruction; quarantined
    ``*.corrupt`` files are never touched). Stale temp files from a
    previously killed writer are swept first.
    """
    from fia_tpu.utils.io import sweep_stale_tmps

    os.makedirs(dir_path, exist_ok=True)
    sweep_stale_tmps(dir_path)
    out = save(os.path.join(dir_path, f"ckpt-{int(step):08d}.npz"),
               params, opt_state, step, fingerprint=fingerprint)
    gens = generations(dir_path)
    for _, stale_path in gens[:-keep] if keep > 0 else []:
        if os.path.abspath(stale_path) == os.path.abspath(out):
            continue
        for p in (stale_path, artifacts.manifest_path(stale_path)):
            try:
                os.unlink(p)
            except OSError:
                pass
    return out


def restore_latest_valid(dir_path: str, params_template, opt_template=None,
                         *, fingerprint=None, verbose: bool = True):
    """Restore the newest generation that passes full validation.

    Walks generations newest-first; a generation failing checksum/size/
    manifest verification is quarantined (by the integrity layer) and
    the walk continues to the next-older one. A generation with a
    mismatched *fingerprint* or template (another config's checkpoint in
    a shared dir) is skipped but left in place — it is evidence of
    nothing and may belong to someone else. Returns (params, opt_state,
    step) or None when no valid generation exists.
    """
    for step, path in reversed(generations(dir_path)):
        try:
            out = load(path, params_template, opt_template,
                       fingerprint=fingerprint, require_manifest=True)
        except artifacts.ArtifactIntegrityError as e:
            if verbose:
                obs.diag(
                    "artifacts",
                    f"checkpoint {os.path.basename(path)} rejected "
                    f"({e.reason}); falling back to an older generation",
                )
            continue
        except ValueError as e:
            if verbose:
                obs.diag(
                    "artifacts",
                    f"checkpoint {os.path.basename(path)} skipped "
                    f"(template mismatch: {e})",
                )
            continue
        if verbose:
            obs.diag(
                "artifacts",
                f"restored step {step} from {os.path.basename(path)}",
            )
        return out
    return None


class PeriodicCheckpointer:
    """Publishes rotated checkpoint generations every ``every`` steps.

    The trainer calls :meth:`maybe` at dispatch boundaries (the only
    points where params are consistent on host); saves land through
    :func:`save_rotated`, so a kill at ANY moment leaves a restorable
    last-good generation for :func:`restore_latest_valid`.
    ``every <= 0`` disables periodic saves (maybe() is a cheap no-op).
    """

    def __init__(self, dir_path: str, every: int, keep: int = 3,
                 fingerprint=None):
        self.dir_path = dir_path
        self.every = int(every)
        self.keep = int(keep)
        self.fingerprint = fingerprint
        self._last_step = 0

    def maybe(self, params, opt_state, step: int) -> str | None:
        if self.every <= 0 or step - self._last_step < self.every:
            return None
        return self.save(params, opt_state, step)

    def save(self, params, opt_state, step: int) -> str:
        self._last_step = int(step)
        return save_rotated(self.dir_path, params, opt_state, step,
                            keep=self.keep, fingerprint=self.fingerprint)
