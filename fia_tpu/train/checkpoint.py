"""Checkpointing.

Reference equivalent: full-session ``tf.train.Saver`` checkpoints plus a
params-only restore (``genericNeuralNet.py:149, 407-429``). Here a
checkpoint is the (params, opt_state, step) triple saved as an npz of
flattened pytree leaves; loading restores into a template with matching
structure. An orbax-backed variant is provided for async/multi-host use.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def _flatten(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, str(treedef)


def save(path: str, params, opt_state=None, step: int = 0) -> str:
    """Save a checkpoint; returns the file path."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    leaves, treedef = _flatten(params)
    payload = {f"p{i}": np.asarray(l) for i, l in enumerate(leaves)}
    payload["__ptree__"] = np.array(treedef)
    if opt_state is not None:
        oleaves, otreedef = _flatten(opt_state)
        payload.update({f"o{i}": np.asarray(l) for i, l in enumerate(oleaves)})
        payload["__otree__"] = np.array(otreedef)
    payload["__step__"] = np.array(step)
    out = path if path.endswith(".npz") else path + ".npz"
    # write-to-temp + atomic rename: a concurrent reader (e.g. a chip
    # experiment loading a checkpoint another backend's run is just
    # rewriting) must never see a half-written zip
    tmp = f"{out[:-4]}.tmp.{os.getpid()}.npz"  # np.savez appends .npz itself
    np.savez(tmp, **payload)
    os.replace(tmp, out)
    return out


def load(path: str, params_template, opt_template=None):
    """Load a checkpoint into (params, opt_state, step).

    Structures are validated against the provided templates, mirroring
    the reference's Saver var-list matching.
    """
    if not path.endswith(".npz"):
        path = path + ".npz"
    with np.load(path, allow_pickle=False) as z:
        pleaves = [z[f"p{i}"] for i in range(_count(z, "p"))]
        _, ptreedef = jax.tree_util.tree_flatten(params_template)
        if str(ptreedef) != str(z["__ptree__"]):
            raise ValueError(f"checkpoint param structure mismatch in {path}")
        params = jax.tree_util.tree_unflatten(ptreedef, pleaves)
        opt_state = None
        if opt_template is not None and "__otree__" in z:
            oleaves = [z[f"o{i}"] for i in range(_count(z, "o"))]
            _, otreedef = jax.tree_util.tree_flatten(opt_template)
            if str(otreedef) != str(z["__otree__"]):
                raise ValueError(f"checkpoint opt structure mismatch in {path}")
            opt_state = jax.tree_util.tree_unflatten(otreedef, oleaves)
        step = int(z["__step__"])
    return params, opt_state, step


def _count(z, prefix: str) -> int:
    n = 0
    while f"{prefix}{n}" in z:
        n += 1
    return n


def exists(path: str) -> bool:
    return os.path.isfile(path if path.endswith(".npz") else path + ".npz")
