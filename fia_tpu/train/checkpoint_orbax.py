"""Orbax-backed checkpointing (async/multi-host capable alternative to
the npz checkpoints in ``checkpoint.py``). Same (params, opt_state,
step) contract; use for sharded params that must restore with their
shardings intact."""

from __future__ import annotations

import os

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(path: str, params, opt_state=None, step: int = 0) -> str:
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    payload = {"params": params, "step": step}
    if opt_state is not None:
        payload["opt_state"] = opt_state
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    return path


def _check_like(template, got):
    """Raise if ``got`` doesn't match the template's tree/shapes/dtypes —
    the template-less raw restore must not accept a mismatched
    checkpoint."""
    tdef = jax.tree_util.tree_structure(template)
    gdef = jax.tree_util.tree_structure(got)
    if tdef != gdef:
        raise ValueError(f"checkpoint params tree {gdef} != template {tdef}")
    for t, g in zip(jax.tree_util.tree_leaves(template),
                    jax.tree_util.tree_leaves(got)):
        ts = tuple(getattr(t, "shape", ()))
        gs = tuple(getattr(g, "shape", ()))
        if ts != gs:
            raise ValueError(f"checkpoint leaf shape {gs} != template {ts}")
        td = getattr(t, "dtype", None)
        gd = getattr(g, "dtype", None)
        if td is not None and gd is not None and td != gd:
            raise ValueError(f"checkpoint leaf dtype {gd} != template {td}")


def load(path: str, params_template, opt_template=None):
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    target = {"params": params_template, "step": 0}
    if opt_template is not None:
        target["opt_state"] = opt_template
    # Orbax's strict restore rejects any structure mismatch between the
    # saved payload and the target, so a checkpoint saved with opt_state
    # must be readable without a template (and vice versa): retry with
    # the opposite opt_state arrangement before giving up.
    try:
        restored = ckptr.restore(path, target)
    except ValueError:
        if opt_template is not None:
            target.pop("opt_state")
            restored = ckptr.restore(path, target)
        else:
            restored_raw = ckptr.restore(path)
            restored_raw.pop("opt_state", None)
            _check_like(params_template, restored_raw["params"])
            params = jax.tree_util.tree_map(
                lambda t, g: jax.device_put(g, t.sharding)
                if isinstance(t, jax.Array) else g,
                params_template, restored_raw["params"],
            )
            return (params, None, int(restored_raw["step"]))
    return (
        restored["params"],
        restored.get("opt_state"),
        int(restored["step"]),
    )


def exists(path: str) -> bool:
    return os.path.isdir(os.path.abspath(path))
