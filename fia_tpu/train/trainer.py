"""Device-resident minibatch training.

Capability parity with the reference trainer
(``genericNeuralNet.py:367-449``): Adam on the total loss with
epoch-shuffled exact-divisor minibatches, optional late-phase switches to
full-batch Adam and then full-batch SGD at 10x the learning rate, Adam
state reset for retraining (``matrix_factorization.py:69-76``).

TPU-native shape: instead of one host->device feed per step, an entire
epoch is one jitted ``lax.scan`` over a device-side permutation —
per-step host traffic is zero, and leave-one-out retraining vmaps the
whole loop over removed points (see ``loo_retrain_many``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.reliability import policy as rpolicy

# Transient device failures (worker crash/restart, preemption, tunnel
# HTTP-500) during a training dispatch retry on this schedule; the
# functional step API (params in, params out) makes the retried call
# idempotent. Unclassified failures surface immediately.
_TRAIN_RETRY = rpolicy.RetryPolicy(
    max_attempts=3, base_delay=2.0, max_delay=30.0, jitter=0.25
)


@dataclass
class TrainConfig:
    batch_size: int
    num_steps: int
    learning_rate: float = 1e-3
    seed: int = 0
    iter_to_switch_to_batch: int | None = None  # full-batch Adam after this step
    iter_to_switch_to_sgd: int | None = None  # full-batch SGD (10x lr) after this
    log_every: int = 0  # 0 = silent


@dataclass
class TrainState:
    params: object
    opt_state: object
    step: int = 0


class Trainer:
    """Minibatch trainer; with ``mesh`` (a Mesh with a 'data' axis) each
    step's batch is sharded across devices — params stay replicated and
    XLA inserts the gradient psum (pure data parallelism over ICI).
    Batch sizes that don't divide the device count (the reference's
    exact-divisor sizes, 3020/3009) are padded with zero-weight
    positions, which the weighted-mean loss ignores exactly."""

    def __init__(self, model, config: TrainConfig, event_log=None, mesh=None,
                 retry_policy: "rpolicy.RetryPolicy | None" = None,
                 clock: "rpolicy.Clock | None" = None):
        self.model = model
        self.config = config
        self.retry_policy = _TRAIN_RETRY if retry_policy is None else retry_policy
        # Injectable time source for retry backoff: the chaos engine
        # trains under virtual time so an injected transient epoch fault
        # costs zero wall-clock sleep while the backoff schedule itself
        # stays the production one.
        self.clock = rpolicy.WALL if clock is None else clock
        self.optimizer = optax.adam(config.learning_rate)
        self.sgd = optax.sgd(config.learning_rate * 10.0)
        self.event_log = event_log  # utils.logging.EventLog or None
        self.mesh = mesh
        self._epoch_fns = {}  # (n_rows, n_batches) -> compiled epoch
        self._full_fns = {}

    # -- state -------------------------------------------------------------
    def init_state(self, params) -> TrainState:
        return TrainState(params, self.optimizer.init(params), 0)

    def reset_optimizer(self, state: TrainState) -> TrainState:
        """Reference ``reset_optimizer_op`` (genericNeuralNet.py:438-440)."""
        return TrainState(state.params, self.optimizer.init(state.params), state.step)

    # -- compiled kernels --------------------------------------------------
    def _make_epoch_fn(self, n_rows: int, n_batches: int, batch: int):
        model, opt, mesh = self.model, self.optimizer, self.mesh
        if mesh is None:
            batch_p = batch
            pos_w = None
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            ndev = mesh.shape["data"]
            batch_p = -(-batch // ndev) * ndev
            # static per-position weight: padded tail positions are
            # zero-weight (the masked-mean loss then ignores them)
            pos_w = jnp.concatenate(
                [jnp.ones(batch, jnp.float32),
                 jnp.zeros(batch_p - batch, jnp.float32)]
            )
            batch_spec = NamedSharding(mesh, P(None, "data"))

        def epoch(params, opt_state, x, y, w, key, start, limit):
            """One epoch: scan over a fresh device-side permutation.

            ``start``/``limit`` mask leading/trailing steps so partial
            epochs (a resume landing mid-epoch, or a final short epoch)
            reuse the same compiled function while replaying exactly the
            batches a fresh uninterrupted run would have used. ``w`` is
            an (N,) row-weight vector (1s normally; 0 on rows removed
            for retraining).
            """
            perm = jax.random.permutation(key, n_rows)[: n_batches * batch]
            sched = perm.reshape(n_batches, batch)
            if batch_p != batch:
                # pad with index 0; the position weight zeroes it out
                sched = jnp.pad(sched, ((0, 0), (0, batch_p - batch)))
            if mesh is not None:
                # shard each step's batch axis: the gather, forward and
                # per-example grads split over devices; the loss/grad
                # mean becomes an XLA-inserted psum over 'data'
                sched = jax.lax.with_sharding_constraint(sched, batch_spec)

            def step(carry, idx):
                params, opt_state, t = carry
                bx, by, bw = x[idx], y[idx], w[idx]
                if pos_w is not None:
                    bw = bw * pos_w
                loss, g = jax.value_and_grad(model.loss)(params, bx, by, bw)
                updates, new_opt = opt.update(g, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                take = jnp.logical_and(t >= start, t < limit)
                params = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(take, b, a), params, new_params
                )
                opt_state = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(take, b, a), opt_state, new_opt
                )
                return (params, opt_state, t + 1), loss

            (params, opt_state, _), losses = jax.lax.scan(
                step, (params, opt_state, jnp.int32(0)), sched
            )
            return params, opt_state, losses

        # no buffer donation: callers (retraining, tests) legitimately
        # reuse the pre-training params after fit() returns
        return jax.jit(epoch)

    def _make_full_fn(self, use_sgd: bool):
        model = self.model
        opt = self.sgd if use_sgd else self.optimizer

        def run(params, opt_state, x, y, w, n_steps):
            def step(carry, _):
                params, opt_state = carry
                loss, g = jax.value_and_grad(model.loss)(params, x, y, w)
                updates, opt_state = opt.update(g, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                step, (params, opt_state), None, length=n_steps
            )
            return params, opt_state, losses

        return jax.jit(run, static_argnums=(5,))

    # -- public API --------------------------------------------------------
    def fit(
        self,
        state: TrainState,
        x,
        y,
        weights=None,
        num_steps: int | None = None,
        checkpointer=None,
    ) -> TrainState:
        """Run ``num_steps`` training steps (cfg.num_steps by default).

        ``checkpointer`` (a ``train.checkpoint.PeriodicCheckpointer``)
        publishes a rotated checkpoint generation at dispatch boundaries
        — the only points where (params, opt_state, step) are consistent
        on host — so a killed run restarts from the last good generation
        via ``restore_latest_valid`` instead of step 0.
        """
        cfg = self.config
        num_steps = cfg.num_steps if num_steps is None else num_steps
        n = x.shape[0]
        batch = cfg.batch_size
        nb = n // batch
        if nb == 0:
            raise ValueError("batch_size larger than dataset")
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        w = jnp.ones((n,), jnp.float32) if weights is None else jnp.asarray(weights)
        if self.mesh is not None:
            from fia_tpu.parallel.distributed import put_global
            from jax.sharding import PartitionSpec as P

            x, y, w = (put_global(self.mesh, a, P()) for a in (x, y, w))
            params0 = jax.tree_util.tree_map(jnp.asarray, state.params)
            state = TrainState(
                put_global(self.mesh, params0, P()),
                put_global(self.mesh, state.opt_state, P()),
                state.step,
            )

        switch_b = cfg.iter_to_switch_to_batch
        switch_b = num_steps if switch_b is None else switch_b
        switch_s = cfg.iter_to_switch_to_sgd
        switch_s = num_steps if switch_s is None else switch_s
        mini_steps = min(num_steps, switch_b)
        # switch_s <= switch_b matches the reference's phase test order
        # (genericNeuralNet.py:388-398): minibatch wins until switch_b,
        # then SGD immediately — the full-batch Adam phase is empty.
        batch_steps = max(0, min(num_steps, switch_s) - mini_steps)
        sgd_steps = num_steps - mini_steps - batch_steps

        params, opt_state = state.params, state.opt_state
        # keyed per dataset shape: retraining on a leave-one-out subset or
        # a swapped train set must not reuse a closure compiled with the
        # old row count (stale permutation range + batch schedule)
        epoch_fn = self._epoch_fns.get((n, nb))
        if epoch_fn is None:
            epoch_fn = self._epoch_fns[(n, nb)] = self._make_epoch_fn(n, nb, batch)

        done = 0
        key = jax.random.PRNGKey(cfg.seed)
        # continue the epoch key stream from the state's absolute step —
        # a resumed run (any alignment, thanks to the leading-step mask)
        # replays the exact batch schedule a fresh run would have used
        while done < mini_steps:
            abs_step = state.step + done
            epoch_i = abs_step // nb
            r = abs_step % nb
            todo = min(nb - r, mini_steps - done)
            ekey = jax.random.fold_in(key, epoch_i)

            def dispatch_epoch(params=params, opt_state=opt_state,
                               ekey=ekey, r=r, todo=todo):
                inject.fire(sites.TRAINER_EPOCH)
                return epoch_fn(
                    params, opt_state, x, y, w, ekey,
                    jnp.int32(r), jnp.int32(r + todo),
                )

            # functional inputs are reused verbatim on retry, so a
            # transient worker death replays this epoch segment exactly
            params, opt_state, losses = self.retry_policy.run(
                dispatch_epoch, retry_on=taxonomy.TRANSIENT,
                clock=self.clock,
            )
            done += todo
            if checkpointer is not None:
                checkpointer.maybe(params, opt_state, state.step + done)
            if cfg.log_every and ((epoch_i + 1) % max(1, cfg.log_every // nb) == 0):
                # fialint: disable=FIA402 -- interactive step-progress stdout
                print(f"step {state.step + done}: "
                      f"loss = {float(losses[r + todo - 1]):.6f}")
            if self.event_log is not None:
                self.event_log.log(
                    "train_epoch", epoch=epoch_i, step=state.step + done,
                    loss=float(losses[r + todo - 1]),
                )

        if batch_steps > 0:
            fn = self._full_fns.setdefault(False, self._make_full_fn(False))
            params, opt_state, _ = fn(params, opt_state, x, y, w, batch_steps)
        if sgd_steps > 0:
            fn = self._full_fns.setdefault(True, self._make_full_fn(True))
            sgd_state = self.sgd.init(params)
            params, sgd_state, _ = fn(params, sgd_state, x, y, w, sgd_steps)

        return TrainState(params, opt_state, state.step + num_steps)

    def retrain(self, state: TrainState, x, y, weights=None,
                num_steps: int | None = None, reset_adam: bool = True) -> TrainState:
        """Reference MF.retrain: reset Adam, then minibatch steps
        (``matrix_factorization.py:69-76``; NCF skips the reset)."""
        if reset_adam:
            state = self.reset_optimizer(state)
        return self.fit(state, x, y, weights=weights, num_steps=num_steps)


def _loo_advance_fn(model, n, nb, batch_size, num_steps, learning_rate,
                    mesh=None):
    """Compiled vmapped lane-advance, cached across calls.

    ``loo_retrain_many`` is invoked once per lane chunk (eval/rq1.py) —
    defining + jitting the closure inside it would recompile an
    identical-shape program for every chunk of every test point.
    Keyed by everything the closure captures; x/y are call arguments.
    The cache lives ON the model instance: the compiled closure
    references the model, so a global (even weak-keyed) registry would
    pin every model+executable forever; as an instance attribute the
    model→cache→closure→model loop is an ordinary collectable cycle and
    sweeps constructing many models release each one's executables.
    """
    per_model = model.__dict__.setdefault("_loo_adv_cache", {})
    key = (n, nb, batch_size, num_steps, learning_rate, mesh)
    if key in per_model:
        return per_model[key]
    opt = optax.adam(learning_rate)

    def advance(params, opt_state, t, ridx, keys_seg, x, y):
        """One lane, one dispatch segment: scan over keys_seg epochs.
        Steps past num_steps are masked no-ops, so padded epochs in the
        final segment leave params untouched."""
        w = jnp.ones((n,), jnp.float32).at[
            jnp.clip(ridx, 0, n - 1)
        ].set(jnp.where(ridx >= 0, 0.0, 1.0))

        def epoch(carry, ekey):
            params, opt_state, t = carry
            perm = jax.random.permutation(ekey, n)[: nb * batch_size]
            sched = perm.reshape(nb, batch_size)

            def step(carry, idx):
                params, opt_state, t = carry
                loss, g = jax.value_and_grad(model.loss)(
                    params, x[idx], y[idx], w[idx]
                )
                updates, new_opt = opt.update(g, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                take = t < num_steps
                params = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(take, b, a), params, new_params
                )
                opt_state = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(take, b, a), opt_state, new_opt
                )
                return (params, opt_state, t + 1), loss

            (params, opt_state, t), _ = jax.lax.scan(
                step, (params, opt_state, t), sched
            )
            return (params, opt_state, t), None

        (params, opt_state, t), _ = jax.lax.scan(
            epoch, (params, opt_state, t), keys_seg
        )
        return params, opt_state, t

    vmapped = jax.vmap(advance, in_axes=(0, 0, 0, 0, 0, None, None))
    if mesh is not None:
        # lanes are embarrassingly parallel: shard the lane axis over the
        # mesh 'data' axis (no collectives at all), x/y replicated
        from jax.sharding import NamedSharding, PartitionSpec as P

        lane = NamedSharding(mesh, P("data"))
        rep = NamedSharding(mesh, P())

        def constrained(params, opt_state, t, ridx, keys_seg, x, y):
            c = lambda tree, s: jax.tree_util.tree_map(
                lambda l: jax.lax.with_sharding_constraint(l, s), tree
            )
            return vmapped(
                c(params, lane), c(opt_state, lane), c(t, lane),
                c(ridx, lane), c(keys_seg, lane), c(x, rep), c(y, rep),
            )

        body = constrained
    else:
        body = vmapped
    # donate the lane stacks: each segment's params/opt buffers alias the
    # previous one's instead of doubling peak HBM at every boundary
    adv = jax.jit(body, donate_argnums=(0, 1, 2))
    per_model[key] = adv
    return adv


def loo_retrain_many(
    model,
    params0,
    x,
    y,
    removed_indices,
    num_steps: int,
    batch_size: int,
    learning_rate: float = 1e-3,
    seeds=None,
    steps_per_dispatch: int = 2000,
    mesh=None,
    retry_policy: "rpolicy.RetryPolicy | None" = None,
    clock: "rpolicy.Clock | None" = None,
):
    """Leave-one-out retraining, vmapped over removed points.

    The RQ1 ground-truth loop retrains the model once per removed
    training row (reference ``experiments.py:109-133``, strictly
    sequential). Here all R retrains run simultaneously as one vmapped
    program: each lane masks its removed row out of the loss via a weight
    vector. A removed index of -1 removes nothing (used for the
    retraining-drift bias lane, reference ``experiments.py:94-106``).
    ``seeds`` (R,) varies the batch shuffle per lane; lanes with equal
    seeds share a schedule. Returns the (R,) pytree-stack of retrained
    params.

    With ``mesh`` (a Mesh with a 'data' axis) the lane axis is sharded
    across devices — retraining is embarrassingly parallel, so an 8-chip
    mesh runs 8 lanes for the price of one with zero collectives. Lane
    counts are padded to a device multiple with no-op (-1) lanes; results
    are identical to the single-device path lane for lane.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    n = x.shape[0]
    nb = n // batch_size
    if nb == 0:
        raise ValueError("batch_size larger than dataset")
    opt = optax.adam(learning_rate)
    removed = jnp.asarray(removed_indices, jnp.int32)
    if seeds is None:
        seeds = jnp.full(removed.shape, 17, jnp.uint32)
    else:
        seeds = jnp.asarray(seeds, jnp.uint32)
    R_real = removed.shape[0]
    if mesh is not None:
        ndev = mesh.shape["data"]
        pad = (-R_real) % ndev
        if pad:
            removed = jnp.concatenate(
                [removed, jnp.full((pad,), -1, jnp.int32)]
            )
            seeds = jnp.concatenate(
                [seeds, jnp.full((pad,), 17, jnp.uint32)]
            )

    n_epochs = -(-num_steps // nb)
    # Long vmapped training programs must be split across dispatches:
    # a single many-minute device program can exceed worker/interconnect
    # execution budgets (observed: 32-lane x 6000-step NCF retrains kill
    # the tunneled TPU worker; ~2000-step dispatches are safe).
    seg_epochs = max(1, min(n_epochs, steps_per_dispatch // nb or 1))
    # Exactly n_epochs keys per lane, independent of the dispatch split
    # (jax.random.split(key, num)[i] depends on num, so splitting into a
    # padded count would make results vary with the tuning knob — and
    # diverge from the pre-split single-program behavior).
    keys = jax.vmap(
        lambda s: jax.random.split(jax.random.PRNGKey(s), n_epochs)
    )(seeds)  # (R, n_epochs, 2)

    adv = _loo_advance_fn(model, n, nb, batch_size, num_steps, learning_rate,
                          mesh=mesh)
    R = removed.shape[0]
    params = jax.tree_util.tree_map(
        lambda l: jnp.broadcast_to(l, (R, *l.shape)), params0
    )
    opt_state = jax.tree_util.tree_map(
        lambda l: jnp.broadcast_to(l, (R, *jnp.shape(l))), opt.init(params0)
    )
    t = jnp.zeros((R,), jnp.int32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        lane = NamedSharding(mesh, P("data"))
        rep = NamedSharding(mesh, P())
        place = lambda tree, s: jax.tree_util.tree_map(
            lambda l: jax.device_put(l, s), tree
        )
        params, opt_state = place(params, lane), place(opt_state, lane)
        t, removed, keys = (place(a, lane) for a in (t, removed, keys))
        x, y = place(x, rep), place(y, rep)
    # the ragged tail scans only the remaining epochs (one extra compile)
    # rather than a padded segment of masked no-op steps
    pol = _TRAIN_RETRY if retry_policy is None else retry_policy
    for start in range(0, n_epochs, seg_epochs):
        seg = keys[:, start : start + seg_epochs]

        def dispatch_seg(params=params, opt_state=opt_state, t=t, seg=seg):
            inject.fire(sites.TRAINER_LOO_SEGMENT)
            out = adv(params, opt_state, t, removed, seg, x, y)
            jax.block_until_ready(out[2])
            return out

        # Retry caveat: adv donates its lane stacks, so a failure AFTER
        # the dispatch enters XLA may leave them deleted and the retry
        # surfaces that instead — which is correct behavior for this
        # segment-chained program (replaying from deleted inputs cannot
        # give the right answer; the caller restarts the chain). Faults
        # at the dispatch boundary (the observed tunnel/worker class,
        # and everything the injection harness schedules) retry cleanly.
        params, opt_state, t = pol.run(dispatch_seg,
                                       retry_on=taxonomy.TRANSIENT,
                                       clock=clock)
    return (
        params
        if R == R_real
        else jax.tree_util.tree_map(lambda l: l[:R_real], params)
    )
