from fia_tpu.utils.timing import Timer, fenced_time  # noqa: F401
