"""Structured logging.

The reference logs with bare ``print`` to redirected files and accepts a
``log_dir`` kwarg it never uses (``genericNeuralNet.py:89``; SURVEY.md
§5). This is the working equivalent: a tiny JSONL event logger for
training curves, influence-query timings and experiment artifacts —
machine-readable, append-only, dependency-free.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any


class EventLog:
    """Append-only JSONL event log. Falsy path = disabled (no-op)."""

    def __init__(self, path: str | None):
        self.path = path
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        else:
            self._fh = None

    def log(self, event: str, **fields: Any) -> None:
        if self._fh is None:
            return
        rec = {"t": round(time.time(), 3), "event": event, **fields}
        self._fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_events(path: str) -> list[dict]:
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]
