"""Cross-process persistence of learned device-memory envelopes.

The engine's memory-adaptive padded path (`engine.py:
_query_padded_adaptive`) learns, per engine, the largest (queries x
pad) cell count that dispatched successfully and the smallest that
exhausted device HBM. Within one process that stops repeated failing
compiles — but every fresh process re-pays one 40-66 s failing XLA
compile (through the tunnel) to rediscover the same ceiling. This
module shares the learned envelope across processes via a small JSON
file, keyed by (backend kind, device count, model name, block dim) —
the inputs the per-device per-cell temporary cost actually depends on
(see ``key``).

Best-effort by design: concurrent writers publish atomically (private
tmp + rename, the same convention as the inverse-HVP cache —
docs/design.md §9) and the worst outcome of a lost update is exactly
the status quo ante: one extra learning failure in some later process.

Integrity: every write seals the file with an ``__integrity__`` record
(magic + sha256 of the canonical entries JSON). A sealed file whose
checksum no longer matches its entries — bit rot, a torn concurrent
write — is quarantined (renamed ``*.corrupt``, evidence preserved,
never re-read) and treated as absent; a pre-seal legacy file is
accepted as-is. Wrong-*shaped* but well-formed JSON is tolerated as a
virgin cache (it is not provably ours to quarantine).
"""

from __future__ import annotations

import hashlib
import json
import os

from fia_tpu import obs

_MAGIC = "fia-memlimits-v1"
_SEAL = "__integrity__"

_ENV = "FIA_MEMLIMIT_CACHE"
_DEFAULT = os.path.join("output", ".mem_limits.json")

# Sentinel for "no failing size on record". Public as UNSET_BAD so the
# engine and the reliability layer compare against one shared constant
# instead of re-spelling the literal (the taxonomy's SIZE_EVIDENCE kinds
# are the only ones allowed to lower it — see
# fia_tpu/reliability/taxonomy.py).
UNSET_BAD = 1 << 62
_UNSET_BAD = UNSET_BAD  # backward-compatible private alias


def _path() -> str:
    return os.environ.get(_ENV, _DEFAULT)


def key(
    backend: str, num_devices: int, model_name: str, block_dim: int
) -> str:
    """Cache key for one memory-envelope regime.

    ``num_devices`` matters because the padded path shards T across the
    mesh — per-device temporaries scale with T x pad / n, so an 8-device
    envelope is ~8x a single-device one. Chip generation (HBM size) is
    NOT in the key: `jax.default_backend()` can't see it, so a cache
    carried between differently-sized chips of one backend kind relies
    on the ok>=bad clamp at seed time (engine.py:_memlimits_seed) to
    stay safe.
    """
    return f"{backend}:n{int(num_devices)}:{model_name}:d{int(block_dim)}"


def _entries_checksum(entries: dict) -> str:
    canon = json.dumps(entries, sort_keys=True)
    return "sha256:" + hashlib.sha256(canon.encode()).hexdigest()


def _quarantine(path: str) -> None:
    """Rename a damaged cache aside (``*.corrupt``, incremented on
    collision) so the evidence survives but is never re-read."""
    dst = path + ".corrupt"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{path}.corrupt.{n}"
    try:
        os.replace(path, dst)
        obs.diag(
            "memlimits",
            f"quarantined corrupt cache -> {os.path.basename(dst)}",
        )
    except OSError:
        pass


def _open_checked(path: str) -> dict:
    """Entries from the cache file, seal-verified.

    Unparseable files and sealed files whose checksum mismatches are
    quarantined and read as empty; legacy (seal-less) and wrong-shaped
    files are read as empty/as-is without quarantine.
    """
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError:
        return {}
    except ValueError:
        _quarantine(path)
        return {}
    if not isinstance(data, dict):
        return {}
    seal = data.pop(_SEAL, None)
    if seal is not None:
        ok = (
            isinstance(seal, dict)
            and seal.get("magic") == _MAGIC
            and seal.get("checksum") == _entries_checksum(data)
        )
        if not ok:
            _quarantine(path)
            return {}
    return data


def load(k: str) -> tuple[int, int]:
    """(cells_ok, cells_bad) previously learned for key ``k``.

    Returns (0, _UNSET_BAD) — the engine's virgin state — when the
    cache is absent, unreadable, corrupt (quarantined), wrong-shaped,
    or has no entry.
    """
    try:
        data = _open_checked(_path())
        entry = data.get(k)
        if not isinstance(entry, dict):
            return 0, _UNSET_BAD
        ok = max(0, int(entry.get("cells_ok", 0)))
        bad = int(entry.get("cells_bad", _UNSET_BAD))
        if bad <= 0:
            bad = _UNSET_BAD
        return ok, bad
    except (OSError, ValueError, TypeError):
        return 0, _UNSET_BAD


def update(
    k: str,
    cells_ok: int,
    cells_bad: int,
    *,
    clear_bad_at: int | None = None,
) -> None:
    """Merge one engine's learned envelope into the shared cache.

    Merging widens monotonically (max ok, min bad) so concurrent
    engines can only make the cached envelope more informed. No-ops
    when there is nothing learned, or when the cache directory does
    not exist (e.g. library use outside a repo checkout).

    ``clear_bad_at``: the caller observed a dispatch of this many cells
    SUCCEED at or above a previously recorded failing size — direct
    evidence that record was a misclassified transient fault, not a
    memory ceiling. Any stored ``cells_bad`` at or below the observed
    success is dropped; a stored bad strictly above it remains
    plausible and survives. (The observed size, not the merged
    ``cells_ok``, is the comparison point: a stale over-large ok from
    an old cache must not launder away a genuine ceiling.) Callers must
    only persist ``cells_bad`` values learned from explicit
    RESOURCE_EXHAUSTED errors; ambiguous tunnel failures stay
    in-process (r3 advisor finding — a poisoned shared ceiling
    degraded every later process until hand-deleted).
    """
    if cells_ok <= 0 and cells_bad >= _UNSET_BAD and clear_bad_at is None:
        return
    path = _path()
    d = os.path.dirname(path) or "."
    if not os.path.isdir(d):
        return
    try:
        data = _open_checked(path)
        prev = data.get(k)
        if not isinstance(prev, dict):
            prev = {}

        def _int(v, default):
            try:
                return int(v)
            except (ValueError, TypeError):
                return default

        merged_ok = max(_int(prev.get("cells_ok"), 0), int(cells_ok))
        prev_bad = _int(prev.get("cells_bad"), _UNSET_BAD)
        # The clear applies to the PREVIOUSLY stored ceiling only; the
        # caller's own cells_bad is newer evidence than its observed
        # success and must survive the merge (a run can clear a stale
        # ceiling AND re-learn a genuine one at the same size).
        if clear_bad_at is not None and prev_bad <= int(clear_bad_at):
            prev_bad = _UNSET_BAD
        merged_bad = min(prev_bad, int(cells_bad))
        merged = {"cells_ok": merged_ok, "cells_bad": merged_bad}
        if merged == prev:
            return
        data[k] = merged
        sealed = dict(data)
        sealed[_SEAL] = {
            "magic": _MAGIC, "checksum": _entries_checksum(data)
        }
        from fia_tpu.utils.io import save_json_atomic

        save_json_atomic(path, sealed)
    except OSError:
        pass  # best-effort: a lost update costs one re-learning failure
