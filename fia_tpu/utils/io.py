"""Small IO helpers shared by the engine cache and experiment drivers.

This module owns the low-level durable-write primitives; the integrity
layer on top (checksums, manifests, quarantine) is
``fia_tpu/reliability/artifacts.py``. New artifact writers should go
through that layer — ``scripts/check_raw_writes.sh`` flags raw
``np.savez`` / ``open(.., "wb")`` writes anywhere else.
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile

import numpy as np

# Temp-file naming embeds the writer's pid so a kill between write and
# rename leaves something sweep_stale_tmps can prove is dead:
#   .npztmp.<pid>.XXXXXX.npz      (this module's mkstemp pattern)
#   <stem>.tmp.<pid>.npz          (the legacy checkpoint.save pattern)
_TMP_PATTERNS = (
    re.compile(r"^\.npztmp\.(\d+)\..*\.npz$"),
    re.compile(r"\.tmp\.(\d+)\.npz$"),
    re.compile(r"^\.manifest-tmp\.(\d*).*\.json$"),  # pid-less: see sweep
)


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss.

    ``os.replace`` makes the rename atomic against concurrent readers,
    but the new directory entry itself is not durable until the
    directory inode is synced — a kill after replace could resurface
    the old file (or nothing). Best-effort: some platforms/filesystems
    refuse directory fsync; that degrades to the pre-PR durability, not
    an error.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_npz_atomic(path: str, **arrays) -> tuple[str, str, int]:
    """np.savez published by fsync'd write + atomic rename.

    A kill mid-write must never leave a truncated npz at ``path`` (the
    engine's inverse-HVP cache is read back; RQ sweeps accumulate hours
    of results in one file). A private mkstemp tmp also keeps concurrent
    writers from interleaving into each other's files. The temp file is
    fsync'd before the rename and the directory after it, so the
    published bytes are durable — not just atomic — at return.

    Returns ``(path, sha256_hex, size)`` of the published bytes, so the
    integrity layer (reliability/artifacts.py) can stamp its manifest
    without re-reading the file it just wrote.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=f".npztmp.{os.getpid()}.", suffix=".npz"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        sha = _file_sha256(tmp)
        size = os.path.getsize(tmp)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    fsync_dir(d)
    return path, sha, size


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours — leave its temp files alone
    return True


def sweep_stale_tmps(dirpath: str) -> list[str]:
    """Remove temp files abandoned by a killed writer; return them.

    A kill between write and rename leaves ``.npztmp.<pid>.*.npz`` /
    ``*.tmp.<pid>.npz`` droppings that would otherwise accumulate
    forever. A temp file is provably stale when its embedded pid is no
    longer a live process; files whose writer is still alive (including
    this process) are untouched. pid-less manifest temps are swept only
    when their mtime is over an hour old.
    """
    removed: list[str] = []
    if not os.path.isdir(dirpath):
        return removed
    import time

    for name in os.listdir(dirpath):
        for pat in _TMP_PATTERNS:
            m = pat.search(name)
            if not m:
                continue
            full = os.path.join(dirpath, name)
            pid = int(m.group(1)) if m.group(1) else None
            stale = (
                not _pid_alive(pid) if pid is not None
                else _older_than(full, 3600.0, time.time())
            )
            if stale:
                try:
                    os.unlink(full)
                    removed.append(full)
                except OSError:
                    pass
            break
    return removed


def _older_than(path: str, age_s: float, now: float) -> bool:
    try:
        return now - os.path.getmtime(path) > age_s
    except OSError:
        return False
