"""Small IO helpers shared by the engine cache and experiment drivers.

This module owns the low-level durable-write primitives; the integrity
layer on top (checksums, manifests, quarantine) is
``fia_tpu/reliability/artifacts.py``. New artifact writers should go
through that layer — lint rule ``FIA101``
(``python -m fia_tpu.analysis.lint``, wired into ``make lint-io`` and
tier-1) flags raw ``open(.., "w")`` / ``np.save*`` / ``json.dump`` /
``Path.write_*`` calls anywhere else.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile

import numpy as np

# Temp-file naming embeds the writer's pid so a kill between write and
# rename leaves something sweep_stale_tmps can prove is dead:
#   .npztmp.<pid>.XXXXXX.npz      (this module's mkstemp pattern)
#   <stem>.tmp.<pid>.npz          (the legacy checkpoint.save pattern)
_TMP_PATTERNS = (
    re.compile(r"^\.npztmp\.(\d+)\..*\.npz$"),
    re.compile(r"\.tmp\.(\d+)\.npz$"),
    re.compile(r"^\.jsontmp\.(\d+)\..*\.json$"),
    re.compile(r"^\.txttmp\.(\d+)\..*\.txt$"),
    re.compile(r"^\.manifest-tmp\.(\d*).*\.json$"),  # pid-less: see sweep
)


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss.

    ``os.replace`` makes the rename atomic against concurrent readers,
    but the new directory entry itself is not durable until the
    directory inode is synced — a kill after replace could resurface
    the old file (or nothing). Best-effort: some platforms/filesystems
    refuse directory fsync; that degrades to the pre-PR durability, not
    an error.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_npz_atomic(path: str, **arrays) -> tuple[str, str, int]:
    """np.savez published by fsync'd write + atomic rename.

    A kill mid-write must never leave a truncated npz at ``path`` (the
    engine's inverse-HVP cache is read back; RQ sweeps accumulate hours
    of results in one file). A private mkstemp tmp also keeps concurrent
    writers from interleaving into each other's files. The temp file is
    fsync'd before the rename and the directory after it, so the
    published bytes are durable — not just atomic — at return.

    Returns ``(path, sha256_hex, size)`` of the published bytes, so the
    integrity layer (reliability/artifacts.py) can stamp its manifest
    without re-reading the file it just wrote.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=f".npztmp.{os.getpid()}.", suffix=".npz"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        sha = _file_sha256(tmp)
        size = os.path.getsize(tmp)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    fsync_dir(d)
    return path, sha, size


def _write_atomic(path: str, prefix: str, suffix: str, write_fn) -> str:
    """Shared fsync'd temp-write + atomic-rename dance.

    ``write_fn(file_object)`` produces the bytes; the temp name embeds
    the writer's pid so :func:`sweep_stale_tmps` can reap droppings
    from a killed writer.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=f"{prefix}{os.getpid()}.", suffix=suffix
    )
    try:
        with os.fdopen(fd, "w") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    fsync_dir(d)
    return path


def save_json_atomic(path: str, obj, *, indent: int | None = None) -> str:
    """json.dump published by fsync'd write + atomic rename.

    The JSON counterpart of :func:`save_npz_atomic` for experiment
    reports and sealed envelopes: a kill mid-write never leaves a
    truncated document at ``path``. This (or the artifacts layer) is
    the sanctioned route for persisted JSON — raw ``json.dump`` /
    ``open(.., "w")`` writes are flagged by lint rule FIA101.
    """
    return _write_atomic(
        path, ".jsontmp.", ".json",
        # sort_keys pins the byte stream to the content, not to dict
        # construction order (FIA504: fingerprints hash these bytes)
        lambda f: json.dump(obj, f, indent=indent, sort_keys=True),
    )


def save_text_atomic(path: str, text: str) -> str:
    """A text document published by fsync'd write + atomic rename."""
    return _write_atomic(
        path, ".txttmp.", ".txt", lambda f: f.write(text)
    )


def savetxt_atomic(path: str, array, **kwargs) -> str:
    """np.savetxt published by fsync'd write + atomic rename (the TSV
    dataset-fixture writer's durable form)."""
    return _write_atomic(
        path, ".txttmp.", ".txt",
        lambda f: np.savetxt(f, array, **kwargs),
    )


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours — leave its temp files alone
    return True


def sweep_stale_tmps(
    dirpath: str, age_horizon_s: float = 6 * 3600.0
) -> list[str]:
    """Remove temp files abandoned by a killed writer; return them.

    A kill between write and rename leaves ``.npztmp.<pid>.*.npz`` /
    ``*.tmp.<pid>.npz`` droppings that would otherwise accumulate
    forever. A temp file is provably stale when its embedded pid is no
    longer a live process. A *live* pid is not proof of ownership —
    pids are recycled, so a kill-loop (the chaos engine's
    train→kill→resume scenario, or any supervisor that restarts
    writers) can leave a dropping whose pid now names an unrelated
    process, which the pid probe would protect forever. The age
    fallback breaks that tie: a temp file older than ``age_horizon_s``
    (default 6 h — no atomic publish holds its temp open that long) is
    sweepable regardless of what its embedded pid looks like today.
    pid-less manifest temps are swept only when their mtime is over an
    hour old.
    """
    removed: list[str] = []
    if not os.path.isdir(dirpath):
        return removed
    import time

    for name in os.listdir(dirpath):
        for pat in _TMP_PATTERNS:
            m = pat.search(name)
            if not m:
                continue
            full = os.path.join(dirpath, name)
            pid = int(m.group(1)) if m.group(1) else None
            stale = (
                (not _pid_alive(pid)
                 or _older_than(full, age_horizon_s, time.time()))
                if pid is not None
                else _older_than(full, 3600.0, time.time())
            )
            if stale:
                try:
                    os.unlink(full)
                    removed.append(full)
                except OSError:
                    pass
            break
    return removed


def _older_than(path: str, age_s: float, now: float) -> bool:
    try:
        return now - os.path.getmtime(path) > age_s
    except OSError:
        return False
