"""Small IO helpers shared by the engine cache and experiment drivers."""

from __future__ import annotations

import os
import tempfile

import numpy as np


def save_npz_atomic(path: str, **arrays) -> None:
    """np.savez published by atomic rename.

    A kill mid-write must never leave a truncated npz at ``path`` (the
    engine's inverse-HVP cache is read back; RQ sweeps accumulate hours
    of results in one file). A private mkstemp tmp also keeps concurrent
    writers from interleaving into each other's files.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
