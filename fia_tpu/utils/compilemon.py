"""Process-wide backend-compile counter over ``jax.monitoring``.

The no-recompile steady-state contract (docs/design.md §14) needs an
observable that cannot lie: engine-side caches (``_jitted``/``_aot``)
say what *we* stored, not what XLA actually compiled. ``jax.monitoring``
emits a ``/jax/core/compile/backend_compile_duration`` duration event
for every real backend compilation, so counting those events proves a
hot path compiled nothing — tracing-cache hits and AOT executable calls
emit none.

Usage::

    from fia_tpu.utils import compilemon
    before = compilemon.count()
    ... hot path ...
    assert compilemon.count() == before

The listener registers once per process on first use and is never
removed: ``jax.monitoring`` only offers a global
``clear_event_listeners`` (which would drop listeners we don't own),
and an idle counter callback costs nothing.
"""

from __future__ import annotations

from jax import monitoring as _monitoring

from fia_tpu.obs.registry import REGISTRY
from fia_tpu.obs.trace import TRACER

# The per-backend-compile duration event (jax 0.4.x); one firing ==
# one XLA compilation, whether reached through jit or AOT .compile().
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_counts = {"backend_compile": 0}
_installed = False


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event == BACKEND_COMPILE_EVENT:
        _counts["backend_compile"] += 1
        # Mirror into the obs spine: the counter feeds compile-storm
        # dashboards; the span event lands inside whatever stage was
        # active (e.g. engine.precompile carries the AOT key), which
        # is how a compile gets attributed to a request/key.
        REGISTRY.counter("compile.backend_total").inc()
        REGISTRY.histogram("compile.backend_us").observe(duration * 1e6)
        TRACER.current_span().event(
            "compile.backend", dur_us=round(duration * 1e6, 1))


def install() -> None:
    """Idempotently register the counting listener."""
    global _installed
    if _installed:
        return
    _monitoring.register_event_duration_secs_listener(_on_duration)
    _installed = True


def count() -> int:
    """Backend compilations observed so far in this process (installs
    the listener on first call — compiles before that are unseen)."""
    install()
    return _counts["backend_compile"]
