"""Timing helpers.

The reference benchmarks with bare ``time.time()`` prints around
``sess.run`` calls (``matrix_factorization.py:216-250``). On an async
dispatch runtime that under-measures; these helpers fence with
``block_until_ready`` and can emit a ``jax.profiler`` trace.
"""

from __future__ import annotations

import contextlib
import time

import jax


def fenced_time(fn, *args, **kwargs):
    """(result, seconds) with a device fence after fn."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, time.perf_counter() - t0


class Timer:
    """Named section timer: ``with timer('solve'): ...``; .report() dict."""

    def __init__(self):
        self.sections: dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str, fence: bool = False):
        t0 = time.perf_counter()
        yield
        if fence:
            # fence everything outstanding on the default backend
            jax.effects_barrier()
        self.sections[name] = self.sections.get(name, 0.0) + time.perf_counter() - t0

    def report(self) -> dict[str, float]:
        return dict(self.sections)


@contextlib.contextmanager
def profile_trace(log_dir: str | None):
    """Optionally wrap a block in a jax.profiler trace."""
    if log_dir:
        with jax.profiler.trace(log_dir):
            yield
    else:
        yield
