"""Timing helpers.

The reference benchmarks with bare ``time.time()`` prints around
``sess.run`` calls (``matrix_factorization.py:216-250``). On an async
dispatch runtime that under-measures; these helpers fence with
``block_until_ready`` and can emit a ``jax.profiler`` trace.
"""

from __future__ import annotations

import contextlib
import time

import jax

from fia_tpu.obs import trace as _obs_trace


def fenced_time(fn, *args, **kwargs):
    """(result, seconds) with a device fence after fn."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def _flush_device_queue():
    """Block until previously dispatched device programs finish.

    ``jax.effects_barrier()`` only waits for *side-effecting*
    computations, so it does not fence ordinary async dispatch. Compiled
    programs execute in dispatch order per device, so blocking on a
    freshly dispatched trivial computation drains the queue.
    """
    import jax.numpy as jnp

    jax.block_until_ready(jax.jit(lambda: jnp.zeros(()))())


class Timer:
    """Named section timer: ``with timer('solve'): ...``; .report() dict.

    ``fence=True`` drains the device queue before stopping the clock
    (async dispatch otherwise records only dispatch time). For exact
    fencing on a specific result, call ``.fence(out)`` on the yielded
    handle instead: ``with timer('x') as t: t.fence(f())``.
    """

    class _Section:
        def fence(self, value):
            return jax.block_until_ready(value)

    def __init__(self, span_prefix: str = "timer"):
        self.sections: dict[str, float] = {}
        # span name prefix when tracing is on: each timed section also
        # becomes an obs span, so bench stage timers and serve spans
        # report from one instrument set (docs/observability.md)
        self.span_prefix = span_prefix

    @contextlib.contextmanager
    def __call__(self, name: str, fence: bool = False):
        with _obs_trace.span(f"{self.span_prefix}.{name}"):
            t0 = time.perf_counter()
            yield Timer._Section()
            if fence:
                _flush_device_queue()
            self.sections[name] = self.sections.get(name, 0.0) + time.perf_counter() - t0

    def report(self) -> dict[str, float]:
        return dict(self.sections)


@contextlib.contextmanager
def profile_trace(log_dir: str | None):
    """Optionally wrap a block in a jax.profiler trace."""
    if log_dir:
        with jax.profiler.trace(log_dir):
            yield
    else:
        yield
