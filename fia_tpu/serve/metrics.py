"""Serving metrics: per-request JSONL events and latency rollups.

Schema (one JSON object per line, via utils/logging.EventLog — every
record carries ``t``, a wall-clock epoch-seconds stamp):

``serve.request`` — one line per finished request::

    {"t": ..., "event": "serve.request", "id": ..., "user": u,
     "item": i, "status": "ok"|"rejected", "reason": null|"deadline"|
     "overload"|"invalid"|<taxonomy kind>, "tier": null|"hot"|"disk"|
     "compute", "queue_wait_ms": f, "solve_ms": f,
     "batch_id": n|null, "batch_size": n|null,
     "approx": bool, "err_bound": f|null,
     "class": "interactive"|"batch"|"scavenger", "tenant": s|null}

``approx``/``err_bound`` are the certified-approximate stamp
(docs/design.md §22): True marks an answer served from the subsampled
``sampled`` rung (a brownout miss, or any dispatch on a
solver='sampled' engine) and ``err_bound`` carries its concentration
bound on the per-row score error. Exact answers log ``false``/null.

``serve.batch`` — one line per micro-batch dispatch::

    {"event": "serve.batch", "batch_id": n, "size": n,
     "total_rows": n, "solve_ms": f, "status": "ok"|<reason>}

``serve.rollup`` — the aggregate summary (also returned by
:meth:`ServeMetrics.rollup`)::

    {"event": "serve.rollup", "requests": n, "ok": n,
     "rejected": {reason: n}, "tiers": {tier: n}, "hot_hit_rate": f,
     "queue_wait_ms": {"p50": f, "p95": f, "max": f},
     "solve_ms": {"p50": f, "p95": f, "max": f},
     "batches": n, "mean_batch_size": f, "cache": {...},
     "modes": {mode: n}, "mode_transitions": n,
     "device_loss_recoveries": n, "host_loss_recoveries": n,
     "answered_approx": n,
     "classes": {cls: {"requests": n, "ok": n, "rejected": {reason: n},
                       "answered_approx": n, "queue_wait_ms": {...}}}}

``serve.mode`` — one line per brownout-ladder transition
(docs/reliability.md "Degraded modes")::

    {"event": "serve.mode", "from": mode, "to": mode, "tick": n,
     "error_rate": f, "queue_frac": f}

``scripts/latency_report.py`` renders a human report from these lines;
the schema is the stable surface operators build dashboards on.
"""

from __future__ import annotations

import numpy as np

from fia_tpu.obs.export import span_fields
from fia_tpu.obs.registry import REGISTRY
from fia_tpu.obs.trace import TRACER
from fia_tpu.serve.request import Response
from fia_tpu.utils.logging import EventLog

# The declared event schema — THE stable surface operators build
# dashboards on. Every EventLog emit under fia_tpu/serve/ and every
# field scripts/latency_report.py consumes (its CONSUMES declaration)
# is cross-checked against this dict by lint rule FIA401, so a renamed
# field is a lint error instead of a silently empty report column.
# (`t` and `event` are implicit on every record; keep this a literal
# dict — the linter reads it with ast.literal_eval.)
SCHEMA = {
    "serve.request": (
        "id", "user", "item", "status", "reason", "tier",
        "queue_wait_ms", "solve_ms", "batch_id", "batch_size", "mode",
        "approx", "err_bound", "class", "tenant",
    ),
    "serve.batch": (
        "batch_id", "size", "total_rows", "solve_ms", "status",
    ),
    "serve.rollup": (
        "requests", "ok", "rejected", "tiers", "hot_hit_rate",
        "queue_wait_ms", "solve_ms", "batches", "mean_batch_size",
        "cache", "modes", "mode_transitions", "device_loss_recoveries",
        "host_loss_recoveries", "answered_approx", "classes",
    ),
    # one line per brownout-ladder transition (serve/health.py): the
    # windowed signal values that drove the step, for post-mortems
    "serve.mode": ("from", "to", "tick", "error_rate", "queue_frac"),
    # streaming updates (docs/design.md §17): one line per
    # apply_updates attempt, and one per epoch-fenced serving swap with
    # its surgical-invalidation accounting
    "stream.update": (
        "update_id", "status", "reason", "steps", "new_rows",
        "base_step", "resumed_step", "touched_users", "touched_items",
        "staleness_ms", "seconds",
    ),
    "stream.swap": (
        "epoch", "wholesale", "hot_rekeyed", "hot_dropped",
        "disk_rekeyed", "disk_dropped",
    ),
    # surgical factor-bank refresh on a params/train change
    "factor.refresh": ("kept", "dropped", "model_key"),
    # audit subsystem (docs/design.md §23): one line per reverse
    # top-k sweep over the training stream ...
    "audit.sweep": (
        "sweep_id", "test_points", "train_rows", "rows_scored",
        "chunks", "k", "seconds", "rows_per_s",
    ),
    # ... and one per live unlearning apply (removal/reweight flowed
    # through the epoch-fenced stream loop)
    "audit.apply": (
        "plan_id", "action", "status", "reason", "rows_removed",
        "rows_reweighted", "predicted_delta", "steps",
        "touched_users", "touched_items", "seconds",
    ),
}


def _pcts(values: list[float]) -> dict:
    if not values:
        return {"p50": 0.0, "p95": 0.0, "max": 0.0}
    a = np.asarray(values, np.float64)
    return {
        "p50": round(float(np.percentile(a, 50)), 3),
        "p95": round(float(np.percentile(a, 95)), 3),
        "max": round(float(a.max()), 3),
    }


class ServeMetrics:
    """Accumulates per-request records and mirrors them to JSONL.

    ``path``: JSONL file (falsy disables the file, rollups still work).
    """

    def __init__(self, path: str | None = None):
        self.log = EventLog(path)
        self.queue_wait_ms: list[float] = []
        self.solve_ms: list[float] = []
        self.by_status: dict[str, int] = {}
        self.by_reason: dict[str, int] = {}
        self.by_tier: dict[str, int] = {}
        self.by_mode: dict[str, int] = {}
        self.batch_sizes: list[int] = []
        self.mode_transitions = 0
        self.device_loss_recoveries = 0
        self.host_loss_recoveries = 0
        self.answered_approx = 0
        self.err_bounds: list[float] = []  # stamped bounds, ok+approx
        # per-class accounting (multi-tenant rollup "classes" block):
        # class -> {"requests", "ok", "rejected": {reason: n},
        #           "approx", queue-wait samples}
        self.by_class: dict[str, dict] = {}

    def record_request(self, resp: Response) -> None:
        self.by_status[resp.status] = self.by_status.get(resp.status, 0) + 1
        if resp.reason:
            self.by_reason[resp.reason] = (
                self.by_reason.get(resp.reason, 0) + 1
            )
        if resp.cache_tier:
            self.by_tier[resp.cache_tier] = (
                self.by_tier.get(resp.cache_tier, 0) + 1
            )
        if resp.mode:
            self.by_mode[resp.mode] = self.by_mode.get(resp.mode, 0) + 1
        if resp.ok:
            self.queue_wait_ms.append(resp.queue_wait_s * 1e3)
            self.solve_ms.append(resp.solve_s * 1e3)
        # per-class lane accounting (the multi-tenant fairness surface)
        cls = resp.cls or "none"
        lane = self.by_class.setdefault(cls, {
            "requests": 0, "ok": 0, "rejected": {}, "approx": 0,
            "queue_wait_ms": [],
        })
        lane["requests"] += 1
        if resp.ok:
            lane["ok"] += 1
            lane["queue_wait_ms"].append(resp.queue_wait_s * 1e3)
            if resp.approx:
                lane["approx"] += 1
        elif resp.reason:
            lane["rejected"][resp.reason] = (
                lane["rejected"].get(resp.reason, 0) + 1
            )
        # mirror into the process-wide obs registry: the per-rung /
        # per-mode µs histograms scripts/latency_report.py renders
        # p50/p99 from (via the obs.metrics snapshot line)
        mode = resp.mode or "none"
        REGISTRY.counter(
            "serve.requests_total", status=resp.status, mode=mode
        ).inc()
        if resp.reason:
            REGISTRY.counter(
                "serve.rejects_total", reason=resp.reason).inc()
            REGISTRY.counter(
                "serve.rejects_by_class_total",
                **{"reason": resp.reason, "class": cls}).inc()
        if resp.ok and resp.approx:
            # certified-approximate answers (the sampled rung): counted
            # per mode so brownout salvage is visible next to the
            # degraded-shed counter it replaces
            self.answered_approx += 1
            if resp.err_bound is not None:
                self.err_bounds.append(float(resp.err_bound))
            REGISTRY.counter("serve.approx_total", mode=mode).inc()
        if resp.ok:
            solver = resp.extra.get("solver") or "none"
            REGISTRY.histogram(
                "serve.queue_wait_us", mode=mode
            ).observe(resp.queue_wait_s * 1e6)
            REGISTRY.histogram(
                "serve.solve_by_mode_us", mode=mode
            ).observe(resp.solve_s * 1e6)
            REGISTRY.histogram(
                "serve.solve_by_solver_us", solver=solver
            ).observe(resp.solve_s * 1e6)
            # class-labelled twins of the latency histograms: NEW
            # series (the mode/solver-labelled ones above are a pinned
            # surface), rendered per class by scripts/latency_report.py
            REGISTRY.histogram(
                "serve.queue_wait_by_class_us", **{"class": cls}
            ).observe(resp.queue_wait_s * 1e6)
            REGISTRY.histogram(
                "serve.solve_by_class_us", **{"class": cls}
            ).observe(resp.solve_s * 1e6)
            if resp.cache_tier:
                REGISTRY.counter(
                    "serve.tier_total", tier=resp.cache_tier).inc()
        self.log.log("serve.request", **resp.json(include_payload=False))

    def record_batch(self, batch_id: int, size: int, total_rows: int,
                     solve_s: float, status: str = "ok") -> None:
        self.batch_sizes.append(int(size))
        self.log.log(
            "serve.batch", batch_id=batch_id, size=int(size),
            total_rows=int(total_rows),
            solve_ms=round(solve_s * 1e3, 3), status=status,
        )

    def record_mode(self, **fields) -> None:
        """One ``serve.mode`` line (a brownout-ladder transition)."""
        self.mode_transitions += 1
        self.log.log("serve.mode", **fields)

    def record_device_loss_recovery(self) -> None:
        """Count one completed mesh-shrink recovery (no event line of
        its own — the ``mesh.rebuild`` site and the rollup carry it)."""
        self.device_loss_recoveries += 1

    def record_host_loss_recovery(self) -> None:
        """Count one completed host-drop mesh-shrink recovery (no event
        line of its own — the ``host.lost`` / ``mesh.rebuild_multihost``
        sites and the rollup carry it)."""
        self.host_loss_recoveries += 1

    def record_update(self, **fields) -> None:
        """One ``stream.update`` line (an apply_updates attempt)."""
        self.log.log("stream.update", **fields)

    def record_swap(self, **fields) -> None:
        """One ``stream.swap`` line (an epoch-fenced serving swap)."""
        self.log.log("stream.swap", **fields)

    def record_factor_refresh(self, **fields) -> None:
        """One ``factor.refresh`` line (surgical bank revalidation)."""
        self.log.log("factor.refresh", **fields)

    def record_audit_sweep(self, **fields) -> None:
        """One ``audit.sweep`` line (a reverse top-k sweep)."""
        self.log.log("audit.sweep", **fields)

    def record_audit_apply(self, **fields) -> None:
        """One ``audit.apply`` line (a live unlearning apply)."""
        self.log.log("audit.apply", **fields)

    def rollup(self, cache_stats: dict | None = None) -> dict:
        n = sum(self.by_status.values())
        hot = self.by_tier.get("hot", 0)
        served = sum(self.by_tier.values())
        out = {
            "requests": n,
            "ok": self.by_status.get("ok", 0),
            "rejected": dict(self.by_reason),
            "tiers": dict(self.by_tier),
            "hot_hit_rate": round(hot / served, 4) if served else 0.0,
            "queue_wait_ms": _pcts(self.queue_wait_ms),
            "solve_ms": _pcts(self.solve_ms),
            "batches": len(self.batch_sizes),
            "mean_batch_size": round(
                float(np.mean(self.batch_sizes)), 2
            ) if self.batch_sizes else 0.0,
            "modes": dict(self.by_mode),
            "mode_transitions": self.mode_transitions,
            "device_loss_recoveries": self.device_loss_recoveries,
            "host_loss_recoveries": self.host_loss_recoveries,
            "answered_approx": self.answered_approx,
            # per-class lanes: the same accounting identity holds per
            # class (requests == ok + Σ rejected within each lane)
            "classes": {
                cls: {
                    "requests": lane["requests"],
                    "ok": lane["ok"],
                    "rejected": dict(lane["rejected"]),
                    "answered_approx": lane["approx"],
                    "queue_wait_ms": _pcts(lane["queue_wait_ms"]),
                }
                for cls, lane in sorted(self.by_class.items())
            },
        }
        if cache_stats is not None:
            out["cache"] = dict(cache_stats)
        return out

    def log_rollup(self, cache_stats: dict | None = None) -> dict:
        r = self.rollup(cache_stats)
        self.log.log("serve.rollup", **r)
        return r

    def flush_obs(self) -> None:
        """Drain the tracer's finished spans into the JSONL stream
        (one ``obs.span`` line each — obs/events.py SCHEMA). The
        service calls this once per drain; a falsy metrics path makes
        it a queue drain with no file writes."""
        for sp in TRACER.flush():
            self.log.log("obs.span", **span_fields(sp))

    def close(self) -> None:
        self.flush_obs()
        # final registry snapshot: the ``obs.metrics`` line the CLI's
        # ``prom`` renderer and the latency report's histogram
        # sections read (deterministic series order)
        self.log.log("obs.metrics", snapshot=REGISTRY.snapshot())
        self.log.close()
