"""The brownout ladder: health-driven serving modes.

Under sustained overload or a sick backend, a binary admit/shed door
(serve/admission.py) wastes the one asset the service still has: tiers
that answer without device miss-work. The factor bank serves O(1) hits
(docs/design.md §14) and the hot/disk caches serve for free — so
instead of shedding uniformly, the service *browns out*: it steps down
a ladder of modes that keep the cheap tiers answering and shed only
the expensive miss path.

Modes (severity order)::

    full            everything serves (the healthy steady state)
    bank_preferred  cache hits + precomputed-bank hits serve; misses
                    that would need a ladder solve serve from the
                    certified ``sampled`` rung instead — answered
                    ``approx=True`` with a stamped error bound — when
                    ``approx_ok`` allows it, and are shed "degraded"
                    otherwise
    cache_only      only hot/disk cache hits serve; every miss is shed
                    (the one mode where "degraded" rejections remain)

The :class:`HealthController` drives the mode from two windowed
signals observed once per drain:

- **error rate** — classified dispatch failures / dispatches, over the
  last ``window`` drains that dispatched anything;
- **queue fraction** — queue depth / queue capacity at drain start.

Transitions are hysteretic in both directions. Stepping DOWN needs
*sustained* evidence — the error signal only counts once the window
holds ``min_evidence`` dispatches (two shed micro-batches are a blip,
not a trend), and the queue signal only counts after ``queue_hold``
consecutive saturated samples (a full queue at drain start is the
NORMAL maximal-coalescing pattern; only a queue that stays pinned is
pressure). Once the evidence is in, the step down is immediate and
jumps as far as the signals demand. Stepping UP requires ``hold``
consecutive calm samples (both signals at or below their ``*_recover``
thresholds) and moves one rung at a time. The dead band between
recover and degrade thresholds means a signal hovering at the degrade
line cannot flap: crossing down requires strictly hotter evidence
than crossing up tolerates.

Determinism: the controller consumes only the numbers passed to
:meth:`HealthController.observe` — no wall clock, no randomness — so a
replayed signal stream yields the identical transition log
(tests/test_degraded.py pins this).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from fia_tpu.serve.request import (
    CLASS_INTERACTIVE,
    CLASS_SCAVENGER,
    DEFAULT_CLASS,
)

MODE_FULL = "full"
MODE_BANK_PREFERRED = "bank_preferred"
MODE_CACHE_ONLY = "cache_only"

# severity order: index = rungs below full serving
LADDER = (MODE_FULL, MODE_BANK_PREFERRED, MODE_CACHE_ONLY)


@dataclass
class HealthConfig:
    """Thresholds for the brownout ladder.

    Each signal has a degrade threshold (at or above ⇒ step down) and a
    recover threshold (at or below ⇒ counts toward stepping up); the
    gap between them is the anti-flap dead band, validated > 0. Error
    rate escalates two rungs at ``err_cache_only`` (a backend failing
    most dispatches should not waste bank solves either); queue
    pressure alone never forces ``cache_only`` — a deep queue with a
    healthy backend is what the bank tier is FOR.
    """

    window: int = 8            # drains remembered per signal
    err_degrade: float = 0.5   # windowed error rate ⇒ bank_preferred
    err_cache_only: float = 0.9  # windowed error rate ⇒ cache_only
    err_recover: float = 0.25  # calm when at or below
    # dispatches the error window must hold before the error signal is
    # trusted: a single two-batch drain shedding both is 100% "error
    # rate" on no evidence
    min_evidence: int = 4
    queue_degrade: float = 0.9  # queue_depth/queue_cap ⇒ bank_preferred
    queue_recover: float = 0.5  # calm when at or below
    # consecutive saturated queue samples before queue pressure counts:
    # a full queue at one drain is maximal coalescing working as
    # intended, a queue pinned full across drains is overload
    queue_hold: int = 3
    hold: int = 2              # consecutive calm samples per rung up
    # degraded modes may answer misses from the certified sampled rung
    # (approx=True + err_bound) instead of shedding them "degraded";
    # False restores the PR-10 shed-everything brownout
    approx_ok: bool = True

    def validate(self) -> "HealthConfig":
        if self.window < 1 or self.hold < 1:
            raise ValueError("health window and hold must be >= 1")
        if self.min_evidence < 1 or self.queue_hold < 1:
            raise ValueError("min_evidence and queue_hold must be >= 1")
        if not (0.0 <= self.err_recover < self.err_degrade
                <= self.err_cache_only):
            raise ValueError(
                "need 0 <= err_recover < err_degrade <= err_cache_only "
                "(the gap is the anti-flap dead band)"
            )
        if not 0.0 <= self.queue_recover < self.queue_degrade:
            raise ValueError("need 0 <= queue_recover < queue_degrade")
        return self


class HealthController:
    """Windowed-signal mode ladder with hysteresis.

    Feed :meth:`observe` once per drain; read :attr:`mode` (or the
    return value) for the regime the NEXT drain serves under — the mode
    is fixed for the whole of a drain, so within-drain decisions stay
    deterministic. :attr:`transitions` is the append-only log of every
    mode change with the signal values that drove it.
    """

    def __init__(self, config: HealthConfig | None = None):
        self.config = (config or HealthConfig()).validate()
        self.mode = MODE_FULL
        self.transitions: list[dict] = []
        self._errors: deque = deque(maxlen=self.config.window)
        self._queue: deque = deque(maxlen=self.config.window)
        self._calm = 0
        self._queue_hot = 0  # consecutive saturated queue samples
        self._tick = 0

    # -- signals ----------------------------------------------------------
    def error_rate(self) -> float:
        """Classified-failure fraction over the remembered dispatching
        drains (0.0 while nothing has dispatched)."""
        disp = sum(d for _, d in self._errors)
        if disp == 0:
            return 0.0
        return sum(e for e, _ in self._errors) / disp

    def queue_frac(self) -> float:
        """Most recent queue_depth/queue_cap sample (the queue signal
        is about NOW, not history — old depth says nothing once the
        queue drains)."""
        return self._queue[-1] if self._queue else 0.0

    # -- the ladder -------------------------------------------------------
    def observe(self, *, errors: int = 0, dispatches: int = 0,
                queue_depth: int = 0, queue_cap: int = 1) -> str:
        """Fold one drain's signals in; returns the (possibly new) mode.

        ``errors``/``dispatches``: classified dispatch failures out of
        device dispatches this drain (drains that dispatched nothing
        leave the error window untouched — no evidence either way).
        ``queue_depth``/``queue_cap``: admission queue occupancy at
        drain start.
        """
        self._tick += 1
        if dispatches > 0:
            self._errors.append((min(int(errors), int(dispatches)),
                                 int(dispatches)))
        self._queue.append(min(int(queue_depth) / max(int(queue_cap), 1),
                               1.0))
        err = self.error_rate()
        q = self.queue_frac()
        cfg = self.config
        self._queue_hot = (self._queue_hot + 1
                           if q >= cfg.queue_degrade else 0)
        err_trusted = (
            sum(d for _, d in self._errors) >= cfg.min_evidence
        )

        # target severity demanded by the current windows
        want = 0
        if err_trusted and err >= cfg.err_degrade:
            want = 1
        if self._queue_hot >= cfg.queue_hold:
            want = max(want, 1)
        if err_trusted and err >= cfg.err_cache_only:
            want = 2
        cur = LADDER.index(self.mode)

        if want > cur:
            # degrade immediately, as far as the signals demand
            self._calm = 0
            self._step(LADDER[want], err, q)
        elif cur > 0 and err <= cfg.err_recover and q <= cfg.queue_recover:
            # calm sample: one rung up after `hold` of them in a row
            self._calm += 1
            if self._calm >= cfg.hold:
                self._calm = 0
                self._step(LADDER[cur - 1], err, q)
        else:
            # in the dead band (or still failing): recovery restarts
            self._calm = 0
        return self.mode

    def _step(self, to: str, err: float, q: float) -> None:
        self.transitions.append({
            "from": self.mode, "to": to, "tick": self._tick,
            "error_rate": round(err, 4), "queue_frac": round(q, 4),
        })
        self.mode = to

    # -- mode predicates the service consults -----------------------------
    # Class-aware brownout (docs/reliability.md "Multi-tenant serving &
    # fairness"): the ladder position is GLOBAL (one signal history, one
    # transition log — replay determinism is unchanged) but each rung
    # degrades the classes in reverse priority order. At severity 1
    # (bank_preferred) interactive traffic still takes full ladder
    # solves — it sheds only at severity 2 — while batch browns out to
    # bank/approx and scavenger loses the bank tier too (approx or
    # shed: the cheap-tier capacity the bank preserves is exactly the
    # headroom the brownout protects for higher classes). Severity 2
    # (cache_only) is the exhaustion floor for everyone. The default
    # ``cls`` is the legacy/batch class, so every pre-multi-tenant
    # call site keeps its PR-10 semantics bit-for-bit.
    def class_mode(self, cls: str = DEFAULT_CLASS) -> str:
        """The effective serving mode ``cls`` experiences under the
        current global ladder position."""
        if self.mode == MODE_FULL:
            return MODE_FULL
        if self.mode == MODE_CACHE_ONLY:
            return MODE_CACHE_ONLY
        # global bank_preferred: interactive rides above the brownout
        if cls == CLASS_INTERACTIVE:
            return MODE_FULL
        return MODE_BANK_PREFERRED

    def allows_solve(self, cls: str = DEFAULT_CLASS) -> bool:
        """May a miss of ``cls`` take a from-scratch ladder solve?"""
        return self.class_mode(cls) == MODE_FULL

    def allows_bank(self, cls: str = DEFAULT_CLASS) -> bool:
        """May a miss of ``cls`` take the O(1) precomputed-bank path?
        Scavenger loses it one rung early: under brownout the bank's
        O(1) capacity is reserved for the classes above."""
        if self.class_mode(cls) == MODE_CACHE_ONLY:
            return False
        return not (self.mode != MODE_FULL and cls == CLASS_SCAVENGER)

    def allows_approx(self, cls: str = DEFAULT_CLASS) -> bool:
        """May a brownout miss of ``cls`` serve a certified approximate
        answer (the ``sampled`` rung) instead of shedding?
        ``cache_only`` is the exhaustion floor — by then the backend is
        failing most dispatches and even a subsampled solve is work it
        cannot do. Interactive never answers approx: its contract is
        exact-or-shed."""
        return (self.config.approx_ok
                and self.class_mode(cls) == MODE_BANK_PREFERRED)
