"""Micro-batching: coalesce compatible queries into one dispatch.

A single influence query is a tiny device program drowning in fixed
costs (host→device transfer of the test point, dispatch RPC, result
fetch); the engine's whole design is batch amortization
(docs/design.md §2). The scheduler recovers that amortization for a
*stream*: queued queries sharing one engine configuration are packed
into batches of at most ``max_batch``.

Two coalescing orders:

- ``"bucket"`` (default): a *stable* sort by the query's padded-size
  bucket (``data/index.py:bucketed_pad`` over its related count)
  before chunking — queries landing in the same bucket share compiled
  programs on the padded path, and on the flat path similar-degree
  neighbours tighten the total-row buckets. The sort is stable, so
  arrival order is preserved within a bucket and the plan is
  deterministic for a given queue.
- ``"fifo"``: strict arrival order (lowest queue-position jitter).

The plan is pure (no engine calls): a list of batches over the caller's
items, so the service can apply it to tickets and the warmup path can
apply the SAME planner to a sample stream — the shapes warmup compiles
are exactly the shapes serving will dispatch.
"""

from __future__ import annotations

import numpy as np

from fia_tpu.data.index import bucketed_pad
from fia_tpu.serve.request import CLASSES


class MicroBatcher:
    def __init__(self, max_batch: int = 32, coalesce: str = "bucket",
                 pad_bucket: int = 128):
        if coalesce not in ("bucket", "fifo"):
            raise ValueError(f"unknown coalesce policy {coalesce!r}")
        self.max_batch = max(int(max_batch), 1)
        self.coalesce = coalesce
        self.pad_bucket = int(pad_bucket)

    def order(self, counts: np.ndarray) -> np.ndarray:
        """Dispatch order over queue positions (stable)."""
        n = len(counts)
        if self.coalesce == "fifo" or n <= 1:
            return np.arange(n)
        buckets = np.array(
            [bucketed_pad(int(c), self.pad_bucket) for c in counts]
        )
        return np.argsort(buckets, kind="stable")

    def planned_shapes(self, counts: np.ndarray) -> list[tuple[int, int]]:
        """``(n_queries, total_related_rows)`` per planned batch — the
        pure packing preview warmup/bench reports use to show what the
        mega-batch coalescing produced without touching the engine
        (the engine's ``flat_geometry`` turns these into compile
        geometries by applying its query/row buckets)."""
        counts = np.asarray(counts)
        return [
            (len(b), int(counts[b].sum())) for b in self.plan(counts)
        ]

    def plan(self, counts: np.ndarray) -> list[np.ndarray]:
        """Batches of queue positions: the coalesced order chunked into
        consecutive ``max_batch`` slices.

        Chunking the *ordered stream* (rather than emitting one batch
        per bucket) keeps batches full: a bucket with 3 queries rides
        with its neighbour bucket instead of paying a 3-query dispatch.
        It also makes the dispatch stream reproducible by
        ``engine.query_many(points[order], batch_queries=max_batch)`` —
        the byte-identity contract the serving tests pin.
        """
        order = self.order(np.asarray(counts))
        return [
            order[s: s + self.max_batch]
            for s in range(0, len(order), self.max_batch)
        ]


# Deficit-round-robin quanta per class, in units of max_batch query
# slots per visit. Interactive drains ~8 batches for every 1 scavenger
# batch under sustained contention; the deficit counters make the
# ratio exact over time instead of per-plan (a class skipped this plan
# accumulates credit for the next).
CLASS_WEIGHTS = {
    "interactive": 8,
    "batch": 3,
    "scavenger": 1,
}


class FairScheduler:
    """Deficit-weighted fair queueing over per-class lanes.

    Wraps a :class:`MicroBatcher`: each class's queue positions are
    coalesced by the SAME bucket/fifo policy into class-pure batches
    (never coalesce an interactive request behind a bulk chunk), then
    the batches interleave by deficit round-robin — per round each
    class earns ``weight × max_batch`` query slots of credit and emits
    its next batch while the credit covers it, visiting classes in
    priority order so ties break toward interactive.

    Single-class streams (including every unclassed/legacy stream:
    ``classes=None`` or all-equal) bypass the DRR machinery entirely
    and return ``MicroBatcher.plan`` verbatim — the pre-multi-tenant
    byte-identity contract is untouched (tests/test_serve.py pins it).

    Deadline-aware packing: ``urgent`` marks queue positions whose
    deadline is near; batches containing any urgent position are
    stably promoted to the front of the interleaved plan (multi-class
    plans only — a single-class plan is already the pinned contract).

    Deficits persist across :meth:`plan` calls (deterministic for a
    replayed drain sequence; :meth:`reset` forgets them).
    """

    def __init__(self, batcher: MicroBatcher,
                 class_weights: dict[str, int] | None = None):
        self.batcher = batcher
        weights = dict(CLASS_WEIGHTS)
        weights.update(class_weights or {})
        for cls, w in weights.items():
            if cls not in CLASSES:
                raise ValueError(f"class_weights names unknown class "
                                 f"{cls!r} (know {CLASSES})")
            if int(w) < 1:
                raise ValueError(f"class weight for {cls!r} must be "
                                 f">= 1, got {w}")
        self.weights = {cls: int(weights[cls]) for cls in CLASSES}
        self._deficit = {cls: 0 for cls in CLASSES}

    def reset(self) -> None:
        self._deficit = {cls: 0 for cls in CLASSES}

    def _class_plan(self, counts: np.ndarray,
                    positions: np.ndarray) -> list[np.ndarray]:
        """One class's batches: the wrapped batcher's coalescing over
        the class's own positions, mapped back to global queue
        positions — class-pure by construction, and per batch the
        dispatch order is exactly what a single-class stream of these
        requests would have produced."""
        order = self.batcher.order(counts[positions])
        ordered = positions[order]
        mb = self.batcher.max_batch
        return [ordered[s: s + mb] for s in range(0, len(ordered), mb)]

    def plan(self, counts: np.ndarray, classes=None,
             urgent=None) -> list[np.ndarray]:
        """Batches of queue positions (same contract as
        :meth:`MicroBatcher.plan`), fair-interleaved across classes.

        ``classes``: per-position class labels (None = single lane).
        ``urgent``: optional per-position bools — deadline pressure.
        """
        counts = np.asarray(counts)
        if classes is None:
            return self.batcher.plan(counts)
        classes = list(classes)
        if len(classes) != len(counts):
            raise ValueError("classes must label every queue position")
        present = [c for c in CLASSES if c in classes]
        unknown = set(classes) - set(CLASSES)
        if unknown:
            raise ValueError(f"unknown class label(s) {sorted(unknown)}")
        if len(present) <= 1:
            return self.batcher.plan(counts)

        lanes = {
            cls: self._class_plan(
                counts,
                np.array([p for p, c in enumerate(classes) if c == cls],
                         dtype=np.int64),
            )
            for cls in present
        }
        quantum = self.batcher.max_batch
        plan: list[np.ndarray] = []
        remaining = sum(len(lane) for lane in lanes.values())
        while remaining:
            for cls in present:
                if not lanes[cls]:
                    continue
                self._deficit[cls] += self.weights[cls] * quantum
                while lanes[cls] and \
                        self._deficit[cls] >= len(lanes[cls][0]):
                    batch = lanes[cls].pop(0)
                    self._deficit[cls] -= len(batch)
                    plan.append(batch)
                    remaining -= 1
                if not lanes[cls]:
                    # an idle lane banks no credit (classic DRR: the
                    # deficit exists to honour backlog, not absence)
                    self._deficit[cls] = 0
        for cls in present:
            if not lanes[cls]:
                self._deficit[cls] = 0
        if urgent is not None:
            hot = {int(p) for p, u in zip(range(len(counts)), urgent)
                   if u}
            if hot:
                front = [b for b in plan
                         if any(int(p) in hot for p in b)]
                back = [b for b in plan
                        if not any(int(p) in hot for p in b)]
                plan = front + back
        return plan
