"""Micro-batching: coalesce compatible queries into one dispatch.

A single influence query is a tiny device program drowning in fixed
costs (host→device transfer of the test point, dispatch RPC, result
fetch); the engine's whole design is batch amortization
(docs/design.md §2). The scheduler recovers that amortization for a
*stream*: queued queries sharing one engine configuration are packed
into batches of at most ``max_batch``.

Two coalescing orders:

- ``"bucket"`` (default): a *stable* sort by the query's padded-size
  bucket (``data/index.py:bucketed_pad`` over its related count)
  before chunking — queries landing in the same bucket share compiled
  programs on the padded path, and on the flat path similar-degree
  neighbours tighten the total-row buckets. The sort is stable, so
  arrival order is preserved within a bucket and the plan is
  deterministic for a given queue.
- ``"fifo"``: strict arrival order (lowest queue-position jitter).

The plan is pure (no engine calls): a list of batches over the caller's
items, so the service can apply it to tickets and the warmup path can
apply the SAME planner to a sample stream — the shapes warmup compiles
are exactly the shapes serving will dispatch.
"""

from __future__ import annotations

import numpy as np

from fia_tpu.data.index import bucketed_pad


class MicroBatcher:
    def __init__(self, max_batch: int = 32, coalesce: str = "bucket",
                 pad_bucket: int = 128):
        if coalesce not in ("bucket", "fifo"):
            raise ValueError(f"unknown coalesce policy {coalesce!r}")
        self.max_batch = max(int(max_batch), 1)
        self.coalesce = coalesce
        self.pad_bucket = int(pad_bucket)

    def order(self, counts: np.ndarray) -> np.ndarray:
        """Dispatch order over queue positions (stable)."""
        n = len(counts)
        if self.coalesce == "fifo" or n <= 1:
            return np.arange(n)
        buckets = np.array(
            [bucketed_pad(int(c), self.pad_bucket) for c in counts]
        )
        return np.argsort(buckets, kind="stable")

    def planned_shapes(self, counts: np.ndarray) -> list[tuple[int, int]]:
        """``(n_queries, total_related_rows)`` per planned batch — the
        pure packing preview warmup/bench reports use to show what the
        mega-batch coalescing produced without touching the engine
        (the engine's ``flat_geometry`` turns these into compile
        geometries by applying its query/row buckets)."""
        counts = np.asarray(counts)
        return [
            (len(b), int(counts[b].sum())) for b in self.plan(counts)
        ]

    def plan(self, counts: np.ndarray) -> list[np.ndarray]:
        """Batches of queue positions: the coalesced order chunked into
        consecutive ``max_batch`` slices.

        Chunking the *ordered stream* (rather than emitting one batch
        per bucket) keeps batches full: a bucket with 3 queries rides
        with its neighbour bucket instead of paying a 3-query dispatch.
        It also makes the dispatch stream reproducible by
        ``engine.query_many(points[order], batch_queries=max_batch)`` —
        the byte-identity contract the serving tests pin.
        """
        order = self.order(np.asarray(counts))
        return [
            order[s: s + self.max_batch]
            for s in range(0, len(order), self.max_batch)
        ]
