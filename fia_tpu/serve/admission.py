"""Admission control: overload sheds load deterministically.

The alternative to admission control on an accelerator-backed service
is not slowness, it is death: an unbounded queue turns a traffic burst
into unbounded host memory plus ever-larger coalesced batches, and the
engine's own memory envelope (docs/design.md §9b) then learns failure
ceilings from load spikes rather than real capacity. The controller
bounds the queue and stamps every rejection with a classified reason,
reusing the reliability failure taxonomy where one applies
(``deadline``) and serve-specific reasons otherwise (``overload``,
``invalid``) — "dropped without reason" is a bug class the smoke test
asserts against.

Decisions are a pure function of (request, queue depth, clock), so a
replayed request stream sheds exactly the same requests.
"""

from __future__ import annotations

from fia_tpu.reliability import taxonomy
from fia_tpu.serve.request import CLASSES, Request, Ticket

# Rejection reasons. DEADLINE is the taxonomy kind (a request whose
# budget expired is the same failure class as a Deadline-guarded
# workload stopping); the others are admission-specific. DEGRADED is
# stamped by the service, not this controller: a brownout mode
# (serve/health.py) shedding miss-path work — the request was valid and
# the queue had room, but the active mode serves only bank/cache hits.
REASON_DEADLINE = taxonomy.DEADLINE
REASON_OVERLOAD = "overload"
REASON_INVALID = "invalid"
REASON_DEGRADED = "degraded"

# Per-class queue quotas as fractions of max_queue. The defaults keep
# the pre-multi-tenant behaviour for interactive/batch (full queue)
# and cap only the new scavenger class, so a scavenger flood can never
# evict interactive/batch headroom; stricter isolation is opt-in via
# ServeConfig.class_quotas. A class's quota bounds how many of ITS
# tickets may wait — the total queue bound still applies on top.
DEFAULT_CLASS_QUOTAS = {
    "interactive": 1.0,
    "batch": 1.0,
    "scavenger": 0.5,
}


class AdmissionController:
    """Bounded-queue, deadline-aware admission.

    ``max_queue``: tickets allowed to wait; a submit finding the queue
    full is rejected (newest-sheds — deterministic, and the queued work
    keeps its arrival-order latency bound).
    ``default_deadline_s``: budget stamped on requests that carry none
    (None = unbounded).
    ``num_users``/``num_items``: id-range validation — an out-of-range
    id must be refused at the door, not discovered as a host-side
    IndexError inside a coalesced batch dispatch.
    ``class_quotas``: per-class queue quota fractions merged over
    ``DEFAULT_CLASS_QUOTAS`` — each class's waiting tickets are bounded
    by ``max(1, round(frac * max_queue))`` so a lower-priority flood
    fills only its own lane.
    ``tenant_quotas``: the same bound one level down — fractions keyed
    by tenant label, applied UNDER the class quotas (both must pass).
    Only listed tenants are capped; unlisted tenants (and unlabelled
    requests) see no per-tenant bound, so the knob is opt-in per
    tenant exactly like ``class_quotas`` is per class. One noisy
    tenant inside a class can otherwise starve its own class's lane —
    the class quota is blind to who filled it.
    ``class_deadlines``: per-class deadline defaults in seconds
    (typically the class SLOs, ``request.CLASS_SLOS``) consulted for
    requests that carry no deadline of their own, BEFORE the global
    ``default_deadline_s``. A request's explicit ``deadline_s`` always
    wins — the SLO is the promise made to a class, not a cap on what
    one caller may ask for.
    """

    def __init__(self, max_queue: int = 256,
                 default_deadline_s: float | None = None,
                 num_users: int | None = None,
                 num_items: int | None = None,
                 class_quotas: dict[str, float] | None = None,
                 tenant_quotas: dict[str, float] | None = None,
                 class_deadlines: dict[str, float] | None = None):
        self.max_queue = max(int(max_queue), 1)
        self.default_deadline_s = default_deadline_s
        for cls in (class_deadlines or {}):
            if cls not in CLASSES:
                raise ValueError(f"class_deadlines names unknown class "
                                 f"{cls!r} (know {CLASSES})")
        self.class_deadlines = dict(class_deadlines or {})
        self.num_users = num_users
        self.num_items = num_items
        quotas = dict(DEFAULT_CLASS_QUOTAS)
        quotas.update(class_quotas or {})
        for cls, frac in quotas.items():
            if cls not in CLASSES:
                raise ValueError(f"class_quotas names unknown class "
                                 f"{cls!r} (know {CLASSES})")
            if not 0.0 < float(frac) <= 1.0:
                raise ValueError(
                    f"class quota for {cls!r} must be in (0, 1], "
                    f"got {frac}")
        self.class_caps = {
            cls: max(1, int(round(float(frac) * self.max_queue)))
            for cls, frac in quotas.items()
        }
        for tenant, frac in (tenant_quotas or {}).items():
            if not 0.0 < float(frac) <= 1.0:
                raise ValueError(
                    f"tenant quota for {tenant!r} must be in (0, 1], "
                    f"got {frac}")
        self.tenant_caps = {
            tenant: max(1, int(round(float(frac) * self.max_queue)))
            for tenant, frac in (tenant_quotas or {}).items()
        }

    def reject_reason(self, req: Request, queue_depth: int,
                      class_depth: int = 0,
                      tenant_depth: int = 0) -> str | None:
        """The rejection reason for ``req`` at ``queue_depth``, or None
        when it is admitted. ``class_depth`` is the count of queued
        tickets already in ``req``'s class, ``tenant_depth`` the count
        already carrying ``req``'s tenant label (0 keeps the
        single-tenant behaviour: only the total bound applies)."""
        u, i = int(req.user), int(req.item)
        if u < 0 or i < 0:
            return REASON_INVALID
        if self.num_users is not None and u >= self.num_users:
            return REASON_INVALID
        if self.num_items is not None and i >= self.num_items:
            return REASON_INVALID
        if req.cls not in CLASSES:
            return REASON_INVALID
        if queue_depth >= self.max_queue:
            return REASON_OVERLOAD
        if class_depth >= self.class_caps[req.cls]:
            return REASON_OVERLOAD
        cap = (self.tenant_caps.get(req.tenant)
               if req.tenant is not None else None)
        if cap is not None and tenant_depth >= cap:
            return REASON_OVERLOAD
        return None

    def ticket(self, req: Request, now: float) -> Ticket:
        """An admitted request's queue ticket (absolute deadline on the
        service clock)."""
        budget = req.deadline_s
        if budget is None:
            budget = self.class_deadlines.get(req.cls)
        if budget is None:
            budget = self.default_deadline_s
        t_deadline = None if budget is None or budget <= 0 else now + budget
        return Ticket(req=req, t_arrival=now, t_deadline=t_deadline)
