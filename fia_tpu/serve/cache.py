"""Hot-block caching for the serving layer.

Three tiers above the engine's from-scratch device compute:

- :class:`HotBlockCache` — a bounded in-memory LRU over per-(user,
  item) solved blocks (iHVP, test-side vector, unpadded scores). Keys
  fold in the engine's params fingerprint digest and solver name, so a
  retrained/mutated model can never serve a stale entry even if a
  caller forgets to invalidate (api.FIAModel._invalidate also clears
  derived services explicitly — belt and braces).
- the on-disk tier — verified npz entries under
  ``<cache_dir>/serve/``, published and read through the artifact
  integrity layer (:mod:`fia_tpu.reliability.artifacts`): fsync'd
  atomic publish with a checksummed manifest carrying the same
  fingerprint, verify-on-read with quarantine-to-``*.corrupt`` on
  damage — a torn or bit-rotted entry is a clean miss, never poison.
- the factor-bank tier — below both: a miss that reaches the device on
  a ``solver='precomputed'`` engine is answered from the preloaded
  factorized block-inverse bank (one triangular-solve/matvec) when the
  (user, item) pair is banked, falling through the solver ladder
  otherwise. The bank itself is engine state
  (:meth:`~fia_tpu.influence.engine.InfluenceEngine.load_factor_bank`);
  this layer only labels the tier and counts the hits
  (``CacheStats.hits_bank``).

Entry payloads are plain numpy arrays, write-protected before they
enter the hot tier so a consumer mutating a response cannot corrupt
later hits.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from fia_tpu.reliability import sites


@dataclass
class CacheStats:
    hits_hot: int = 0
    hits_disk: int = 0
    hits_bank: int = 0  # factor-bank (precomputed-tier) dispatch hits
    misses: int = 0
    evictions: int = 0
    invalidations: int = 0
    disk_rejects: int = 0  # corrupt/foreign disk entries refused
    # surgical-invalidation accounting (streaming updates): entries
    # re-keyed to a new params fingerprint without recompute vs dropped
    # because the update's footprint touched them
    rekeyed: int = 0
    rekey_dropped: int = 0
    disk_rekeyed: int = 0
    disk_rekey_dropped: int = 0

    def json(self) -> dict:
        return dict(self.__dict__)


@dataclass
class BlockEntry:
    """One solved (user, item) block: everything a Response needs."""

    scores: np.ndarray  # (count,) unpadded related scores
    ihvp: np.ndarray  # (d,)
    test_grad: np.ndarray  # (d,)
    count: int
    extra: dict = field(default_factory=dict)

    def freeze(self) -> "BlockEntry":
        for a in (self.scores, self.ihvp, self.test_grad):
            a.setflags(write=False)
        return self

    @property
    def nbytes(self) -> int:
        return self.scores.nbytes + self.ihvp.nbytes + self.test_grad.nbytes


class HotBlockCache:
    """Bounded LRU over solved blocks, keyed on
    ``(params_fp_digest, solver, user, item)``.

    ``capacity_entries`` bounds the entry count; ``capacity_bytes``
    (optional) additionally bounds the payload footprint — eviction is
    strictly LRU under whichever bound binds first, so the shed set for
    a given access sequence is deterministic.
    """

    def __init__(self, capacity_entries: int = 1024,
                 capacity_bytes: int | None = None):
        self.capacity_entries = max(int(capacity_entries), 0)
        self.capacity_bytes = capacity_bytes
        self.stats = CacheStats()
        self._entries: OrderedDict[tuple, BlockEntry] = OrderedDict()
        self._nbytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def get(self, key: tuple) -> BlockEntry | None:
        e = self._entries.get(key)
        if e is None:
            self.stats.misses += 1
            return None
        self._entries.move_to_end(key)
        self.stats.hits_hot += 1
        return e

    def peek(self, key: tuple) -> BlockEntry | None:
        """Lookup without touching recency or the hit/miss counters."""
        return self._entries.get(key)

    def put(self, key: tuple, entry: BlockEntry) -> None:
        if self.capacity_entries == 0:
            return
        entry.freeze()
        old = self._entries.pop(key, None)
        if old is not None:
            self._nbytes -= old.nbytes
        self._entries[key] = entry
        self._nbytes += entry.nbytes
        while len(self._entries) > self.capacity_entries or (
            self.capacity_bytes is not None
            and self._nbytes > self.capacity_bytes
            and len(self._entries) > 1
        ):
            _, ev = self._entries.popitem(last=False)
            self._nbytes -= ev.nbytes
            self.stats.evictions += 1

    def invalidate(self) -> None:
        self.stats.invalidations += 1
        self._entries.clear()
        self._nbytes = 0

    def rekey(self, old_fp: str, new_fp: str, touched) -> dict:
        """Surgical re-key after a footprinted params update.

        Entries under ``old_fp`` whose (user, item) block the update's
        footprint did NOT touch adopt ``new_fp`` in place — the update
        provably left their solved block bit-identical, so the cached
        payload is still the answer the new engine would compute.
        Touched entries (and entries under any other fingerprint) are
        dropped. LRU order is preserved. ``touched`` is a
        ``(user, item) -> bool`` predicate
        (:meth:`fia_tpu.stream.footprint.Footprint.touched`).
        """
        out: OrderedDict[tuple, BlockEntry] = OrderedDict()
        nbytes = 0
        rekeyed = dropped = 0
        for key, e in self._entries.items():
            if key[0] == old_fp and not touched(key[2], key[3]):
                out[(new_fp,) + key[1:]] = e
                nbytes += e.nbytes
                rekeyed += 1
            else:
                dropped += 1
        self._entries = out
        self._nbytes = nbytes
        self.stats.rekeyed += rekeyed
        self.stats.rekey_dropped += dropped
        return {"rekeyed": rekeyed, "dropped": dropped}


# -- on-disk tier ----------------------------------------------------------

def disk_entry_path(cache_dir: str, model_name: str, solver: str,
                    user: int, item: int) -> str:
    """Path of one serving-tier disk entry under ``cache_dir``.

    Keyed like the engine's reference-shaped iHVP cache (model name +
    solver in the filename) plus the query pair; the params fingerprint
    lives in the manifest, not the name — a retrain overwrites the
    entry in place rather than accumulating dead generations.
    """
    return os.path.join(
        cache_dir, "serve",
        f"{model_name}-{solver}-u{int(user)}-i{int(item)}.npz",
    )


def disk_fingerprint(model_name: str, solver: str, fp_digest: str) -> dict:
    return {
        "kind": "serve-block",
        "model_key": model_name,
        "solver": solver,
        "params_fp": fp_digest,
    }


def disk_get(path: str, fingerprint: dict,
             stats: CacheStats | None = None) -> BlockEntry | None:
    """Verified read of a disk-tier entry; any integrity or fingerprint
    failure is a miss (corrupt classes are quarantined by load_npz)."""
    from fia_tpu.reliability import artifacts

    if not os.path.exists(path):
        return None
    try:
        d = artifacts.load_npz(
            path, expected_fingerprint=fingerprint, require_manifest=True
        )
    except artifacts.ArtifactIntegrityError:
        if stats is not None:
            stats.disk_rejects += 1
        return None
    try:
        # certificate provenance (certified-approximate entries — an
        # engine on the 'sampled' rung): round-trip the stamped bound
        # so a disk hit cannot launder an approximate block into an
        # exact-looking response
        extra = {}
        if "err_bound" in d and bool(np.asarray(d.get("approx", 0))):
            extra = {"approx": True, "err_bound": float(d["err_bound"])}
        return BlockEntry(
            scores=np.asarray(d["scores"]),
            ihvp=np.asarray(d["ihvp"]),
            test_grad=np.asarray(d["test_grad"]),
            count=int(d["count"]),
            extra=extra,
        ).freeze()
    except KeyError:
        if stats is not None:
            stats.disk_rejects += 1
        return None


def disk_rekey(cache_dir: str, model_name: str, solver: str,
               old_fp: str, new_fp: str, touched,
               stats: CacheStats | None = None) -> dict:
    """Surgical re-key of the on-disk serve tier (streaming updates).

    Walks ``<cache_dir>/serve/`` entries of this (model, solver):
    touched blocks are unlinked (their payload is stale under the new
    params); untouched blocks — whose manifest fingerprint matches the
    OLD params digest and whose bytes verify — adopt the new fingerprint
    via a manifest-only rewrite
    (:func:`fia_tpu.reliability.artifacts.rewrite_fingerprint`): no
    recompute, no data rewrite, and a torn/foreign entry is skipped, so
    nothing stale is ever laundered into the new generation.
    """
    import re

    from fia_tpu.reliability import artifacts

    d = os.path.join(cache_dir, "serve")
    out = {"rekeyed": 0, "dropped": 0}
    if not os.path.isdir(d):
        return out
    pat = re.compile(
        re.escape(f"{model_name}-{solver}-") + r"u(\d+)-i(\d+)\.npz"
    )
    old_want = artifacts.canonical_fingerprint(
        disk_fingerprint(model_name, solver, old_fp)
    )
    new_fingerprint = disk_fingerprint(model_name, solver, new_fp)
    for fn in sorted(os.listdir(d)):
        m = pat.fullmatch(fn)
        if m is None:
            continue
        path = os.path.join(d, fn)
        if touched(int(m.group(1)), int(m.group(2))):
            for p in (path, artifacts.manifest_path(path)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            out["dropped"] += 1
            continue
        try:
            man = artifacts.read_manifest(path)
        except artifacts.ArtifactIntegrityError:
            continue  # damaged manifest: leave for the read path's miss
        if man is None or man.get("fingerprint") != old_want:
            continue  # foreign/older generation: unservable either way
        if artifacts.rewrite_fingerprint(path, new_fingerprint):
            out["rekeyed"] += 1
    if stats is not None:
        stats.disk_rekeyed += out["rekeyed"]
        stats.disk_rekey_dropped += out["dropped"]
    return out


def disk_put(path: str, entry: BlockEntry, fingerprint: dict) -> None:
    """Publish a disk-tier entry through the integrity layer.

    ``serve.cache_publish`` is the fault-injection site: the damage
    channel corrupts exactly this generation after the (honest) atomic
    publish, so tests exercise the read-side verification above.
    """
    from fia_tpu.reliability import artifacts

    payload = dict(
        scores=np.asarray(entry.scores),
        ihvp=np.asarray(entry.ihvp),
        test_grad=np.asarray(entry.test_grad),
        count=np.asarray(entry.count, np.int64),
    )
    if entry.extra.get("approx"):
        payload["approx"] = np.asarray(1, np.int64)
        payload["err_bound"] = np.asarray(
            entry.extra["err_bound"], np.float64
        )
    artifacts.publish_npz(
        path,
        payload,
        fingerprint=fingerprint,
        site=sites.SERVE_CACHE_PUBLISH,
    )
