"""Host-sharded miss dispatch (docs/design.md §25).

On a pod, one drain's coalesced dispatch order is embarrassingly
parallel across hosts: the query axis has no cross-query coupling
(docs/design.md §14), so each host can compute a contiguous row-slice
of the bucketed query scratch with ZERO hot-path collectives — no
all-gather of results, no barrier per batch, nothing for a dead peer
to stall. Cross-host coordination happens entirely through durable
journals instead: each host publishes its shard through the artifact
integrity layer (:func:`fia_tpu.reliability.artifacts.publish_npz` —
fsync'd atomic rename, checksummed manifest, fingerprint), and the
coordinator merges the journals in host order. Three properties fall
out:

- **Byte identity.** Shards are contiguous slices of the single-process
  dispatch order, each computed by the same engine program bytes, so
  the host-order concatenation is bitwise identical to one process
  running the whole order (``scripts/multihost_smoke.sh`` asserts
  ``np.array_equal``).
- **Restart resumption.** A shard journal that already exists and
  verifies (checksum + fingerprint over the engine state, the drain
  tag and the exact query bytes) is NOT recomputed — a restarted host
  or coordinator picks up where the journals left off.
- **Classified host loss.** A peer whose journal never appears inside
  the merge budget is a ``host_lost`` failure
  (:class:`~fia_tpu.reliability.taxonomy.HostLost`), not a hang: the
  coordinator's wait loop runs on the injectable reliability clock
  (:data:`fia_tpu.reliability.policy.WALL`), times out, and the
  service sheds exactly the missing hosts' rows with the classified
  reason.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from fia_tpu import obs
from fia_tpu.reliability import artifacts, policy as rpolicy, taxonomy


def shard_rows(n: int, nhosts: int, align: int = 1) -> list[tuple[int, int]]:
    """Contiguous ``[start, stop)`` row ranges per host.

    An even split with the remainder spread over the first hosts —
    pure arithmetic on (n, nhosts, align), so every host computes the
    same partition without talking to anyone. Hosts past the work get
    empty ranges (they still journal, so the merge never waits on a
    host with no work).

    ``align`` (the dispatcher's ``max_batch``) keeps every shard
    boundary on a batch boundary of the single-process dispatch order:
    each batch's compile pad derives from the max related-count IN that
    batch, so splitting a batch across hosts would change batch
    composition — and with it the pad geometry — versus the
    single-process run the byte-identity contract is pinned against.
    Whole batches are the unit of distribution; rows only denominate
    the ranges.
    """
    n, nhosts, align = int(n), int(nhosts), max(int(align), 1)
    if nhosts < 1:
        raise ValueError(f"nhosts must be >= 1, got {nhosts}")
    units = (n + align - 1) // align
    base, rem = divmod(units, nhosts)
    out = []
    start_u = 0
    for h in range(nhosts):
        size_u = base + (1 if h < rem else 0)
        stop_u = start_u + size_u
        out.append((min(start_u * align, n), min(stop_u * align, n)))
        start_u = stop_u
    return out


def shard_path(journal_dir: str, tag: str, host: int, nhosts: int) -> str:
    """The journal file one host's shard publishes to."""
    return os.path.join(
        str(journal_dir), f"shard-{tag}-{int(host)}of{int(nhosts)}.npz"
    )


def shard_fingerprint(engine_fp: str, tag: str, host: int, nhosts: int,
                      points: np.ndarray):
    """The manifest fingerprint a shard journal is keyed under.

    Binds the journal to the engine state (params fingerprint), the
    drain tag, the shard geometry AND the exact query bytes — a journal
    from another drain, another model generation, or a reordered query
    stream is a verified miss, never silently merged.
    """
    pts = np.ascontiguousarray(np.asarray(points, np.int64))
    return {
        "engine_fp": str(engine_fp),
        "tag": str(tag),
        "host": int(host),
        "nhosts": int(nhosts),
        "points_sha1": hashlib.sha1(pts.tobytes()).hexdigest(),
    }


def _pack_result(results) -> dict:
    """Per-batch InfluenceResults as flat journal arrays.

    ``results`` is ``query_many``'s return — one InfluenceResult per
    consecutive batch of the shard's rows. The packed form is
    ragged-safe and byte-exact: per-row score slices concatenate into
    one flat array with explicit counts (offsets are re-derived as the
    cumulative sum on load), and the uniform-shape ihvp/test_grad
    blocks ride as-is.
    """
    if not results:
        return {
            "scores": np.zeros((0,), np.float64),
            "counts": np.zeros((0,), np.int64),
            "ihvp": np.zeros((0, 0), np.float64),
            "test_grad": np.zeros((0, 0), np.float64),
        }
    counts, scores = [], []
    for res in results:
        n = len(res.counts)
        counts.extend(int(res.counts[r]) for r in range(n))
        scores.extend(np.asarray(res.scores_of(r)).reshape(-1)
                      for r in range(n))
    counts = np.asarray(counts, np.int64)
    return {
        "scores": (np.concatenate(scores) if counts.sum()
                   else np.zeros((0,), np.float64)),
        "counts": counts,
        "ihvp": np.concatenate(
            [np.asarray(res.ihvp) for res in results]),
        "test_grad": np.concatenate(
            [np.asarray(res.test_grad) for res in results]),
    }


def dispatch_local_shard(
    eng,
    points: np.ndarray,
    *,
    host: int,
    nhosts: int,
    journal_dir: str,
    tag: str,
    engine_fp: str,
    max_batch: int | None = None,
) -> str:
    """Compute and journal THIS host's shard of one dispatch order.

    ``points`` is the FULL coalesced (T, 2) dispatch order — every host
    receives the same array and derives its own contiguous slice from
    :func:`shard_rows`, so there is no work-assignment round trip. The
    slice runs through the engine's own windowed/flat dispatch
    (``query_many``), then publishes through the artifact layer under
    :func:`shard_fingerprint`. If a verified journal for exactly this
    (engine state, tag, geometry, query bytes) already exists, the
    compute is skipped entirely — the resume path after a host or
    coordinator restart.

    Returns the journal path.
    """
    points = np.asarray(points, np.int64)
    start, stop = shard_rows(
        len(points), nhosts, align=max_batch or len(points) or 1
    )[int(host)]
    path = shard_path(journal_dir, tag, host, nhosts)
    fp = shard_fingerprint(engine_fp, tag, host, nhosts, points)
    try:
        artifacts.verify(path, expected_fingerprint=fp)
        obs.diag(
            "hostshard",
            f"host {host}/{nhosts}: shard journal {os.path.basename(path)}"
            " verified, resuming without recompute",
        )
        return path
    except artifacts.ArtifactIntegrityError:
        pass
    with obs.span("serve.hostshard_dispatch", host=int(host),
                  nhosts=int(nhosts), rows=int(stop - start)):
        results = []
        if stop > start:
            results = eng.query_many(
                points[start:stop],
                batch_queries=max_batch or len(points),
            )
        arrays = _pack_result(results)
    os.makedirs(str(journal_dir), exist_ok=True)
    return artifacts.publish_npz(path, arrays, fingerprint=fp)


def merge_host_shards(
    journal_dir: str,
    tag: str,
    nhosts: int,
    points: np.ndarray,
    *,
    engine_fp: str,
    max_batch: int | None = None,
    timeout_s: float = 60.0,
    poll_s: float = 0.05,
    clock: rpolicy.Clock = rpolicy.WALL,
) -> dict:
    """Merge every host's shard journal back into dispatch order.

    Pure journal reads — the coordinator needs no live connection to
    any peer, which is exactly why a coordinator restart resumes from
    here. Each shard is a verified load under the same fingerprint the
    publisher used; shards not yet on disk are polled for on the
    injectable reliability clock until ``timeout_s``, after which the
    missing hosts are a *proved* loss and :class:`taxonomy.HostLost`
    raises with their indices (the service sheds those rows classified,
    ``host_lost``).

    Returns ``{"scores", "counts", "offsets", "ihvp", "test_grad"}``
    over the full ``points`` order — shards are contiguous slices, so
    host-order concatenation IS the single-process order, byte for
    byte.
    """
    points = np.asarray(points, np.int64)
    ranges = shard_rows(
        len(points), nhosts, align=max_batch or len(points) or 1
    )
    shards: dict[int, dict] = {}
    deadline = clock.monotonic() + float(timeout_s)
    pending = list(range(int(nhosts)))
    while pending:
        still = []
        for h in pending:
            path = shard_path(journal_dir, tag, h, nhosts)
            fp = shard_fingerprint(engine_fp, tag, h, nhosts, points)
            try:
                shards[h] = artifacts.load_npz(
                    path, expected_fingerprint=fp, require_manifest=True
                )
            except artifacts.ArtifactIntegrityError:
                still.append(h)
        pending = still
        if not pending:
            break
        if clock.monotonic() >= deadline:
            raise taxonomy.HostLost(
                f"shard journal(s) from host(s) {pending} never "
                f"appeared within {timeout_s}s (tag {tag!r}, "
                f"{nhosts} hosts); those hosts are presumed lost"
            )
        clock.sleep(float(poll_s))
    counts = np.concatenate([
        np.asarray(shards[h]["counts"], np.int64) for h in range(nhosts)
    ]) if nhosts else np.zeros((0,), np.int64)
    scores = np.concatenate([
        np.asarray(shards[h]["scores"]).reshape(-1) for h in range(nhosts)
    ]) if nhosts else np.zeros((0,))
    blocks = [h for h in range(nhosts)
              if ranges[h][1] > ranges[h][0]]
    ihvp = (np.concatenate([shards[h]["ihvp"] for h in blocks])
            if blocks else np.zeros((0, 0)))
    test_grad = (np.concatenate([shards[h]["test_grad"] for h in blocks])
                 if blocks else np.zeros((0, 0)))
    offsets = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    return {
        "scores": scores,
        "counts": counts,
        "offsets": offsets,
        "ihvp": ihvp,
        "test_grad": test_grad,
    }
