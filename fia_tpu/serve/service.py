"""``InfluenceService`` — the online influence-query event loop.

One synchronous, deterministic loop (no threads: determinism is a
feature the reliability tests pin, and the engine's device dispatch is
already async under the hood):

1. :meth:`submit` runs admission (queue bound, id validation, deadline
   stamping) and enqueues a ticket or returns an immediate rejection.
2. :meth:`drain` resolves every queued ticket: expired deadlines are
   rejected; hot-cache and verified disk-tier hits answer without
   device work; the misses are de-duplicated, micro-batched by the
   scheduler, and dispatched — one compiled mega-batch program per
   batch instead of one per query. On the single-device flat path up
   to ``dispatch_window`` programs stay in flight (dispatch of batch
   N+1 overlaps result assembly of batch N — docs/design.md §14);
   everywhere else batches go through ``engine.query_batch``
   sequentially. Results fill both cache tiers, then every ticket
   resolves from the hot tier (a key repeated within one drain
   computes once and hits for the rest).
3. A classified device/deadline failure during a batch dispatch rejects
   exactly that batch's requests with the taxonomy kind as the reason
   and the loop continues — overload and faults shed load
   deterministically; unclassified failures surface.

Byte-identity contract: for a given drain, the dispatch stream is the
scheduler's coalesced order and batches are consecutive ``max_batch``
chunks of it, so the admitted results are bit-identical to
``engine.query_many(points[order], batch_queries=max_batch)`` —
serving must not change answers (tests/test_serve.py pins this).

Brownout (serve/health.py): in ``bank_preferred`` mode, misses the
factor bank cannot answer serve a *certified approximate* answer from
the engine's cache-less ``sampled`` sibling — ``approx=True`` plus a
stamped error bound on the response (docs/design.md §22) — instead of
shedding ``degraded``; ``cache_only`` remains the shed-everything
floor. See :meth:`InfluenceService._dispatch_approx` for the isolation
rules that keep the exact path byte-identical to an approx-off run.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

import numpy as np

from fia_tpu import obs
from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.serve.admission import (
    REASON_DEADLINE,
    REASON_DEGRADED,
    AdmissionController,
)
from fia_tpu.serve import cache as scache
from fia_tpu.serve.cache import BlockEntry, HotBlockCache
from fia_tpu.serve.health import (
    MODE_FULL,
    HealthConfig,
    HealthController,
)
from fia_tpu.serve.metrics import ServeMetrics
from fia_tpu.serve.request import (
    CLASSES,
    STATUS_REJECTED,
    TIER_COMPUTE,
    TIER_DISK,
    TIER_HOT,
    TIER_PRECOMPUTED,
    Request,
    Response,
    Ticket,
)
from fia_tpu.serve.scheduler import FairScheduler, MicroBatcher

# Failure kinds whose recovery is a topology shrink (rebuild the mesh
# over survivors) rather than a same-topology retry ladder: device loss
# drops one device, host loss drops every device behind a dead process.
# The dispatch paths treat them identically up to which shrink runs —
# see _recover_topology.
_TOPOLOGY_KINDS = (taxonomy.DEVICE_LOST, taxonomy.HOST_LOST)


@dataclass
class ServeConfig:
    """Service knobs (see module docstrings for the semantics)."""

    # Mega-batch coalescing cap: BENCH_r05 device_split measured the
    # dispatch wall (~95 of 133 ms per program is host overhead, and
    # 1024-query dispatches score ~2x the 256-query row), so the
    # default packs as many queued queries as fit into one fused
    # dispatch; latency-sensitive deployments dial it back down.
    max_batch: int = 1024
    max_queue: int = 4096  # admission: tickets allowed to wait
    coalesce: str = "bucket"  # "bucket" | "fifo" dispatch order
    default_deadline_s: float | None = None  # per-request budget
    cache_entries: int = 1024  # hot-block LRU capacity
    cache_bytes: int | None = None  # optional hot-tier byte bound
    disk_cache: bool = True  # use cache_dir tier when engine has one
    include_related: bool = True  # attach related train-row ids
    metrics_path: str | None = None  # JSONL events (None = in-memory)
    # Overlapped dispatch: up to this many flat programs in flight per
    # drain, so host-side result assembly of batch N overlaps device
    # execution of batch N+1 (engine dispatch is async). 1 = the
    # sequential guarded path; >1 applies wherever the engine's flat
    # path is eligible in this process (single device or a local mesh).
    dispatch_window: int = 2
    # Serve over a device mesh: an int (shard the flat dispatch over
    # the first N devices) or a jax Mesh with a 'data' axis. In
    # fixed-engine mode the engine must already be built over the SAME
    # mesh (validated at construction); from_model builds its engines
    # over it. None (default) = whatever the engine was built with.
    mesh: object | None = None
    # Factor-bank tier: warmup() preloads the engine's published bank
    # device-resident (solver='precomputed' engines only; a no-op
    # elsewhere) so the first hot-set request never pays the load.
    # False skips the preload — the engine still loads lazily on its
    # first precomputed dispatch.
    factor_bank: bool = True
    # Brownout-ladder thresholds (serve/health.py); None = defaults.
    health: HealthConfig | None = None
    # Multi-tenant knobs (docs/reliability.md "Multi-tenant serving &
    # fairness"). class_quotas: per-class queue quota fractions merged
    # over admission.DEFAULT_CLASS_QUOTAS; class_weights: DRR weights
    # merged over scheduler.CLASS_WEIGHTS. None = defaults (unclassed
    # streams behave exactly as before the multi-tenant layer).
    class_quotas: dict | None = None
    class_weights: dict | None = None
    # Per-tenant admission quotas (fractions of max_queue), applied
    # UNDER the class quotas: {"acme": 0.25} bounds tenant "acme" to a
    # quarter of the queue regardless of class mix. Unlisted tenants
    # and unlabelled requests are uncapped (opt-in per tenant).
    tenant_quotas: dict | None = None
    # Deadline-aware packing: a queued request whose remaining budget
    # is at or under this slack promotes its batch to the front of a
    # multi-class plan. None disables the promotion (single-class
    # plans are never reordered — that order is the pinned contract).
    # When class_deadlines is active and this is None, the slack is
    # derived from the tightest class SLO (see class_deadlines).
    deadline_slack_s: float | None = None
    # SLO-derived per-class deadline defaults. True adopts
    # request.CLASS_SLOS verbatim; a dict merges over it (values in
    # seconds); None/False disables (requests without deadlines keep
    # default_deadline_s, the pre-SLO behaviour). When active, a
    # request carrying no deadline of its own is stamped its class's
    # SLO at admission, and deadline_slack_s (if unset) defaults to a
    # quarter of the tightest configured SLO — the dispatcher's
    # "about to miss" horizon tracks the strictest promise made.
    class_deadlines: dict | bool | None = None
    # Host-sharded dispatch (docs/design.md §25): a (host, n_hosts,
    # journal_dir) triple naming this process's shard of the pod's
    # miss-dispatch work. Each host computes a contiguous row-slice of
    # every drain's coalesced dispatch order, journals it durably
    # (reliability/artifacts.py), and the coordinator (host 0) merges
    # the shard journals — zero hot-path collectives, and a coordinator
    # restart resumes from the journals instead of recomputing. None =
    # single-host dispatch (every prior behaviour unchanged).
    host_role: tuple | None = None
    # Merge budget for peer shard journals (seconds): a peer whose
    # journal never appears within this window is a *proved* host loss
    # (classified ``host_lost``), and the survivors adopt its shard.
    host_merge_timeout_s: float = 60.0


def _approx_extra(res, row: int) -> dict:
    """BlockEntry.extra for one result row: the certificate provenance
    ({'approx': True, 'err_bound': f} from a sampled-rung result, {}
    from an exact one) — cached alongside the payload so later hot/disk
    hits re-stamp the same bound instead of laundering the answer into
    an exact-looking response."""
    if not getattr(res, "approx", False) or res.err_bound is None:
        return {}
    return {"approx": True, "err_bound": float(res.err_bound[row])}


class _MergedRows:
    """Rows ``[base, base + n)`` of a merged host-shard result,
    presented through the InfluenceResult row accessors
    ``_bank_batch`` consumes (``scores_of`` / ``counts`` / ``ihvp`` /
    ``test_grad``)."""

    def __init__(self, merged: dict, base: int, n: int):
        self._scores = merged["scores"]
        self._offsets = merged["offsets"]
        self._base = int(base)
        self.counts = merged["counts"][base:base + n]
        self.ihvp = merged["ihvp"][base:base + n]
        self.test_grad = merged["test_grad"][base:base + n]

    def scores_of(self, row: int):
        r = self._base + int(row)
        return self._scores[self._offsets[r]:self._offsets[r + 1]]


def _resolve_mesh(mesh):
    """ServeConfig.mesh → a jax Mesh (int = first-N-devices 'data'
    mesh, <=1 or None = no mesh)."""
    if mesh is None:
        return None
    if isinstance(mesh, int):
        if mesh <= 1:
            return None
        from fia_tpu.parallel.mesh import make_mesh

        return make_mesh(mesh)
    return mesh


class InfluenceService:
    """Serve a stream of (user, item) influence queries over one engine.

    Args:
      engine: an :class:`~fia_tpu.influence.engine.InfluenceEngine`
        (fixed-engine mode), or
      engine_provider: a zero-arg callable returning the current engine
        — the :meth:`from_model` path, so a retrained
        :class:`~fia_tpu.api.FIAModel` transparently swaps a fresh
        engine in and the fingerprinted cache keys retire stale entries.
      config: a :class:`ServeConfig`.
      clock: monotonic-seconds callable, or a
        :class:`fia_tpu.reliability.policy.Clock` object (its
        ``monotonic`` method is used) — injectable for deterministic
        tests, simulated open-loop load, and virtual-time chaos runs.
    """

    def __init__(self, engine=None, engine_provider=None,
                 config: ServeConfig | None = None,
                 clock=time.monotonic):
        if (engine is None) == (engine_provider is None):
            raise ValueError("pass exactly one of engine/engine_provider")
        self._engine_static = engine
        self._engine_provider = engine_provider
        self.config = config or ServeConfig()
        # a policy.Clock (e.g. VirtualClock) normalises to its reader
        self.clock = getattr(clock, "monotonic", clock)
        # keep the full Clock object (monotonic + sleep) when one was
        # passed: the host-shard merge SPENDS time waiting on peers'
        # journals, and virtual-time tests need that wait to be virtual
        self._clock_obj = clock if hasattr(clock, "monotonic") else None
        self.cache = HotBlockCache(self.config.cache_entries,
                                   self.config.cache_bytes)
        self.metrics = ServeMetrics(self.config.metrics_path)
        self.batcher = MicroBatcher(
            self.config.max_batch, self.config.coalesce,
            pad_bucket=int(getattr(self._peek_engine(), "pad_bucket", 128)),
        )
        # fair-queueing over per-class lanes; single-class streams pass
        # through to the wrapped batcher verbatim (byte identity)
        self.scheduler = FairScheduler(self.batcher,
                                       self.config.class_weights)
        eng = self._peek_engine()
        self.mesh = _resolve_mesh(self.config.mesh)
        if self.mesh is not None:
            from fia_tpu.parallel.mesh import (
                lost_device_ids,
                lost_host_ids,
                mesh_fingerprint,
            )

            # liveness first: a configured mesh referencing dead device
            # ids must fail construction with a CLASSIFIED error (the
            # operator restarted onto a shrunk slice), not surface as a
            # backend RuntimeError at the first dispatch. The error
            # names exactly which probe members failed — device ids
            # always, whole hosts when every device behind a process is
            # dark — and carries them as attributes so the CLI's
            # serve.construct_failed line is actionable, not a bare
            # "construction failed".
            dead = lost_device_ids(self.mesh)
            if dead:
                # host granularity only means something on a mesh that
                # actually spans hosts — a single-host mesh with dead
                # devices is device loss, same as always
                from fia_tpu.parallel.mesh import mesh_hosts

                dead_hosts = (lost_host_ids(self.mesh)
                              if len(mesh_hosts(self.mesh)) > 1 else ())
                host_note = (
                    f" (host(s) {list(dead_hosts)} lost entirely)"
                    if dead_hosts else ""
                )
                err = taxonomy.HostLost if dead_hosts else \
                    taxonomy.DeviceLost
                e = err(
                    f"ServeConfig.mesh references device id(s) "
                    f"{list(dead)} the backend cannot see{host_note}; "
                    "rebuild the mesh over live devices "
                    "(parallel.mesh.make_mesh / surviving_mesh) before "
                    "constructing the service"
                )
                e.devices = list(dead)
                e.hosts = list(dead_hosts)
                raise e
            if mesh_fingerprint(getattr(eng, "mesh", None)) != \
                    mesh_fingerprint(self.mesh):
                raise ValueError(
                    "ServeConfig.mesh does not match the engine's mesh; "
                    "build the engine over the same mesh "
                    "(InfluenceEngine(mesh=...) / cli mesh_for) or use "
                    "from_model, which builds its engines over it"
                )
        self.health = HealthController(self.config.health)
        # SLO-derived deadline defaults: resolve the class_deadlines
        # knob (True = the published CLASS_SLOS; dict = overrides
        # merged over them), and derive the urgent-lane slack from the
        # tightest SLO when the operator did not pin one explicitly.
        cds = self.config.class_deadlines
        if cds:
            from fia_tpu.serve.request import CLASS_SLOS

            resolved = dict(CLASS_SLOS)
            if isinstance(cds, dict):
                resolved.update({k: float(v) for k, v in cds.items()})
            self.class_deadlines = resolved
        else:
            self.class_deadlines = None
        self.deadline_slack_s = self.config.deadline_slack_s
        if self.deadline_slack_s is None and self.class_deadlines:
            self.deadline_slack_s = 0.25 * min(
                self.class_deadlines.values()
            )
        # Host-sharded dispatch role: (host, n_hosts, journal_dir)
        self.host_role = None
        if self.config.host_role is not None:
            h, n, jdir = self.config.host_role
            h, n = int(h), int(n)
            if not 0 <= h < n:
                raise ValueError(
                    f"host_role host index {h} out of range for "
                    f"{n} host(s)")
            self.host_role = (h, n, str(jdir))
        self.admission = AdmissionController(
            max_queue=self.config.max_queue,
            default_deadline_s=self.config.default_deadline_s,
            num_users=eng.model.num_users,
            num_items=eng.model.num_items,
            class_quotas=self.config.class_quotas,
            tenant_quotas=self.config.tenant_quotas,
            class_deadlines=self.class_deadlines,
        )
        self._queue: list[Ticket] = []
        # queued tickets per class / per tenant (admission quota
        # signals) — rebuilt to empty when a drain swaps the queue out
        self._class_depth: dict[str, int] = {}
        self._tenant_depth: dict[str, int] = {}
        self._next_id = 0
        self._batch_id = 0
        self._fp_cache: tuple | None = None  # (engine identity, digest)
        # Epoch fence (docs/design.md §17): tickets are stamped with the
        # serving epoch at admission; a streaming update pins the old
        # (engine, fp) here before swapping, so a drain resolves each
        # ticket against the state it was admitted under. Entries are
        # cleared once the queue that referenced them is consumed.
        self._epoch = 0
        self._fenced: dict[int, tuple] = {}  # epoch -> (engine, fp)
        # dispatch log: (batch_id, (T, 2) points) per device dispatch —
        # the byte-identity tests and capacity post-mortems read this
        self.dispatch_log: list[tuple[int, np.ndarray]] = []
        # per-drain health signals (classified failures / dispatches)
        self._drain_errors = 0
        self._drain_dispatches = 0
        # drain counter: seeds the per-drain trace id (obs/trace.py) —
        # deterministic across runs of the same request stream
        self._drain_seq = 0

    # -- wiring ------------------------------------------------------------
    @classmethod
    def from_model(cls, model, config: ServeConfig | None = None,
                   solver: str | None = None, clock=time.monotonic,
                   **engine_extra) -> "InfluenceService":
        """A service over an :class:`~fia_tpu.api.FIAModel`.

        The engine is resolved lazily through ``model.engine()`` (the
        one solver-resolution path), so ``model.retrain`` /
        ``update_train_x_y`` — which clear the model's engines and
        notify derived services — leave the service answering from
        fresh state, never a stale hot block. A ``config.mesh`` is
        forwarded into the engine build, so every refreshed engine
        lands on the same device layout.
        """
        m = _resolve_mesh((config or ServeConfig()).mesh)
        if m is not None:
            engine_extra.setdefault("mesh", m)
        svc = cls(
            engine_provider=lambda: model.engine(solver, **engine_extra),
            config=config, clock=clock,
        )
        model._register_serving(svc)
        return svc

    def _peek_engine(self):
        return (self._engine_static if self._engine_static is not None
                else self._engine_provider())

    def _engine_and_fp(self):
        eng = self._peek_engine()
        if self._fp_cache is not None and self._fp_cache[0] is eng:
            return eng, self._fp_cache[1]
        fp = hashlib.sha1(
            np.ascontiguousarray(eng._params_fingerprint()).tobytes()
        ).hexdigest()
        self._fp_cache = (eng, fp)
        return eng, fp

    def invalidate(self) -> None:
        """Drop every serving-layer cache derived from model state.

        Called by ``FIAModel._invalidate()`` (retrain, checkpoint load,
        train-set mutation). The fingerprinted keys already make stale
        hits impossible; this additionally frees the dead entries and
        forgets the memoized engine fingerprint. Fenced epochs are
        dropped too — wholesale invalidation means queued tickets
        resolve against the fresh state, exactly as before streaming
        updates existed.
        """
        self.cache.invalidate()
        self._fp_cache = None
        self._fenced.clear()

    # -- epoch-fenced streaming swap (docs/design.md §17) ------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    def pin_epoch(self) -> None:
        """Fence the current (engine, fingerprint) under the serving
        epoch — called by the streaming update loop *before* the model
        mutates, so tickets admitted under this epoch keep resolving
        against exactly this state. Harmless if the update later rolls
        back (the fence is cleared at the next drain)."""
        self._fenced[self._epoch] = self._engine_and_fp()

    def advance_epoch(self, footprint=None) -> dict:
        """Swap serving onto the model's new state, surgically.

        Bumps the serving epoch (new admissions stamp the new one),
        resolves the NEW engine and fingerprint — making the new state
        resident *before* any old entry is dropped — then, given a
        ``footprint`` (:class:`fia_tpu.stream.footprint.Footprint` or a
        ``(user, item) -> bool`` predicate), re-keys every untouched
        hot/disk entry to the new fingerprint in place and drops exactly
        the touched blocks. Without a footprint the hot tier is
        wholesale-invalidated (the epoch fence still holds for queued
        tickets). Returns the swap accounting, also logged as a
        ``stream.swap`` metrics event.
        """
        old = self._fenced.get(self._epoch) or self._fp_cache
        if old is not None:
            self._fenced[self._epoch] = old
        self._epoch += 1
        self._fp_cache = None
        eng, new_fp = self._engine_and_fp()  # new state resident now
        out = {"epoch": self._epoch, "wholesale": footprint is None,
               "hot_rekeyed": 0, "hot_dropped": 0,
               "disk_rekeyed": 0, "disk_dropped": 0}
        touched = getattr(footprint, "touched", footprint)
        with obs.span("stream.rekey",
                      trace_seed=f"epoch-{self._epoch}") as sp:
            if touched is None:
                if old is not None:
                    self.cache.invalidate()
            elif old is not None and old[1] != new_fp:
                hot = self.cache.rekey(old[1], new_fp, touched)
                out["hot_rekeyed"] = hot["rekeyed"]
                out["hot_dropped"] = hot["dropped"]
                d = self._disk_dir(eng)
                if d is not None:
                    disk = scache.disk_rekey(
                        d, eng.model_name, eng.solver, old[1], new_fp,
                        touched, stats=self.cache.stats,
                    )
                    out["disk_rekeyed"] = disk["rekeyed"]
                    out["disk_dropped"] = disk["dropped"]
            sp.set(**out)
        self.metrics.record_swap(**out)
        self.metrics.flush_obs()
        return out

    # -- request intake ----------------------------------------------------
    def submit(self, req: Request) -> Response | None:
        """Admit ``req`` into the queue, or reject it immediately.

        Returns None when admitted (the answer arrives from a later
        :meth:`drain`), or a rejected :class:`Response`.
        """
        if req.id is None:
            req.id = f"r{self._next_id}"
        self._next_id += 1
        reason = self.admission.reject_reason(
            req, len(self._queue),
            class_depth=self._class_depth.get(req.cls, 0),
            tenant_depth=(self._tenant_depth.get(req.tenant, 0)
                          if req.tenant is not None else 0),
        )
        if reason is not None:
            resp = Response(
                id=req.id, user=req.user, item=req.item,
                status=STATUS_REJECTED, reason=reason,
                mode=self.health.mode,
                cls=req.cls, tenant=req.tenant,
            )
            self.metrics.record_request(resp)
            self._trace_request(resp, self.clock())
            self.metrics.flush_obs()
            return resp
        t = self.admission.ticket(req, self.clock())
        t.epoch = self._epoch
        self._queue.append(t)
        self._class_depth[req.cls] = self._class_depth.get(req.cls, 0) + 1
        if req.tenant is not None:
            self._tenant_depth[req.tenant] = (
                self._tenant_depth.get(req.tenant, 0) + 1)
        return None

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # -- the drain loop ----------------------------------------------------
    def drain(self) -> list[Response]:
        """Resolve every queued ticket (see module docstring).

        Tickets are grouped by admission epoch and each group resolves
        against that epoch's fenced (engine, fingerprint) — a streaming
        update between submit and drain never changes what an in-flight
        ticket answers from. The current epoch (and any epoch whose
        fence was dropped by a wholesale invalidation) resolves against
        the live engine. The fence table is cleared afterwards: the
        service is synchronous, so the queue that referenced the old
        epochs is fully consumed here.

        Span-only wrapper since the obs spine landed: the loop body
        lives in ``_drain_impl`` (registered on the FIA204/205 dispatch
        path in analysis/config.py); this level opens the drain trace,
        rebuilds each resolved request's span chain, and flushes the
        queued spans to the metrics JSONL. Tracing never touches the
        responses themselves (byte identity vs tracing-off is pinned by
        tests/test_obs.py).
        """
        if not self._queue:
            return []
        self._drain_seq += 1
        obs.REGISTRY.gauge("serve.queue_depth").set(len(self._queue))
        with obs.trace(f"drain-{self._drain_seq}"):
            with obs.span("serve.drain", n=len(self._queue)) as sp:
                out = self._drain_impl()
                sp.set(responses=len(out))
        now = self.clock()
        for r in out:
            self._trace_request(r, now)
        self.metrics.flush_obs()
        return out

    def _drain_impl(self) -> list[Response]:
        depth = len(self._queue)  # health signal: occupancy at drain start
        work, self._queue = self._queue, []
        self._class_depth = {}
        self._tenant_depth = {}
        now = self.clock()
        # the mode is FIXED for the whole drain (self.health only moves
        # in the observe() below) — within-drain decisions stay a pure
        # function of the signal history, never of this drain's own luck
        self._drain_errors = 0
        self._drain_dispatches = 0

        responses: dict[int, Response] = {}  # queue position -> response
        by_epoch: dict[int, list[tuple[int, Ticket]]] = {}
        for pos, t in enumerate(work):
            if t.expired(now):
                responses[pos] = self._reject(t, REASON_DEADLINE, now)
            else:
                by_epoch.setdefault(t.epoch, []).append((pos, t))

        for epoch in sorted(by_epoch):
            fenced = (self._fenced.get(epoch)
                      if epoch != self._epoch else None)
            eng, fp = (fenced if fenced is not None
                       else self._engine_and_fp())
            self._resolve_group(eng, fp, by_epoch[epoch], responses)
        self._fenced.clear()

        out = [responses[pos] for pos in sorted(responses)]
        for r in out:
            self.metrics.record_request(r)
        n0 = len(self.health.transitions)
        self.health.observe(
            errors=self._drain_errors, dispatches=self._drain_dispatches,
            queue_depth=depth, queue_cap=self.admission.max_queue,
        )
        for tr in self.health.transitions[n0:]:
            self.metrics.record_mode(**tr)
            obs.REGISTRY.counter(
                "serve.mode_transitions",
                **{"from": tr["from"], "to": tr["to"]}
            ).inc()
            obs.event("serve.mode_transition",
                      **{"from": tr["from"], "to": tr["to"]})
        return out

    def _trace_request(self, resp: Response, now: float) -> None:
        """Rebuild one resolved request's span chain retroactively.

        The drain loop already tracks every per-request latency
        (queue_wait_s spans arrival→resolve, solve_s the batch
        dispatch), so the chain is reconstructed at flush time instead
        of threading span handles through the dispatch machinery. Ids
        are derived from the request id (``trace_id_for(f"req-{id}")``)
        — deterministic, and zero bytes change on the response. Chain
        (seq): 0 serve.request (root) > 1 serve.admit, 2 serve.queue,
        3 serve.batch > 4 serve.dispatch > 5 serve.solver.
        """
        if not obs.tracing_enabled():
            return
        tr = obs.TRACER
        tid = obs.trace_id_for(f"req-{resp.id}")
        t_res = now
        t_arr = t_res - max(resp.queue_wait_s, 0.0)
        t_disp = t_res - max(resp.solve_s, 0.0)
        tr.record(
            tid, "serve.request", t_arr, t_res, seq=0,
            id=resp.id, user=int(resp.user), item=int(resp.item),
            status=resp.status, reason=resp.reason, mode=resp.mode,
            approx=bool(resp.approx), err_bound=resp.err_bound,
        )
        tr.record(tid, "serve.admit", t_arr, t_arr, seq=1, parent_seq=0)
        tr.record(tid, "serve.queue", t_arr, t_disp, seq=2, parent_seq=0)
        if not resp.ok:
            return
        tr.record(tid, "serve.batch", t_disp, t_res, seq=3, parent_seq=0,
                  batch_id=resp.batch_id, batch_size=resp.batch_size)
        tr.record(tid, "serve.dispatch", t_disp, t_res, seq=4,
                  parent_seq=3, tier=resp.cache_tier)
        tr.record(tid, "serve.solver", t_disp, t_res, seq=5,
                  parent_seq=4, tier=resp.cache_tier,
                  solver=resp.extra.get("solver"),
                  approx=bool(resp.approx), err_bound=resp.err_bound)

    def _resolve_group(self, eng, fp, live, responses) -> None:
        """Resolve one epoch group of live tickets against (eng, fp)."""
        now = self.clock()
        # cache tiers first; misses keep first-arrival order per key
        misses: dict[tuple, list[tuple[int, Ticket]]] = {}
        exact_solver = eng.solver != "sampled"
        for pos, t in live:
            key = (fp, eng.solver) + t.req.key()
            entry = self.cache.get(key)
            if entry is not None:
                responses[pos] = self._respond(t, entry, TIER_HOT, now, eng)
                continue
            entry = self._disk_get(eng, fp, t.req)
            if entry is not None:
                self.cache.put(key, entry)
                responses[pos] = self._respond(t, entry, TIER_DISK, now, eng)
                continue
            if exact_solver and self.health.allows_approx(t.req.cls):
                # a certified answer banked by an earlier brownout drain
                # (hot tier only, under the sampled sibling's solver key
                # — the exact key space above stays byte-untouched)
                entry = self.cache.peek((fp, "sampled") + t.req.key())
                if entry is not None:
                    self.cache.stats.hits_hot += 1
                    responses[pos] = self._respond(
                        t, entry, TIER_HOT, now, eng.approx_sibling()
                    )
                    continue
            misses.setdefault(key, []).append((pos, t))

        approx: dict[tuple, list] = {}
        if misses and self.health.mode != MODE_FULL:
            misses, approx = self._shed_degraded(eng, misses, responses)
        # exact-path batches dispatch FIRST: their batch ids (and bytes)
        # match a run with approx serving disabled, where the approx
        # misses below would have been shed before any dispatch
        if misses:
            self._dispatch_misses(eng, fp, misses, responses)
        if approx:
            self._dispatch_approx(eng, fp, approx, responses)

    @staticmethod
    def _key_class(waiting) -> str:
        """The class a miss key is served under: the highest-priority
        class among its coalesced waiters (a duplicate key shared by an
        interactive and a scavenger waiter dispatches as interactive —
        de-duplication must never demote the urgent one)."""
        return min((t.req.cls for _, t in waiting),
                   key=lambda c: CLASSES.index(c))

    def _shed_degraded(self, eng, misses, responses) -> tuple[dict, dict]:
        """Brownout: route each miss where the active mode may serve
        its class (serve/health.py class_mode — the ladder degrades
        scavenger → batch → interactive in order).

        Per miss key, under the highest-priority waiter's class:
        classes the global rung leaves at ``full`` (interactive at
        ``bank_preferred``) keep their exact ladder solve; degraded
        classes keep misses the precomputed factor bank answers in
        O(1) where the class may still use it (docs/design.md §14,
        unchanged bytes vs full mode — scavenger loses the bank one
        rung early); the rest serve a certified approximate answer
        from the engine's ``sampled`` sibling when
        ``health.allows_approx(cls)`` says so, and are rejected
        ``degraded`` otherwise. In ``cache_only`` — or with
        ``approx_ok`` off — every unbanked miss is shed ``degraded``:
        that rung is the exhaustion floor for every class. Hits never
        reach here: degraded modes shed only miss-path work. Returns
        ``(exact_misses, approx_misses)``.
        """
        bank_loaded = (
            eng.solver == "precomputed"
            and eng.ensure_factor_bank() > 0
        )
        keep: dict[tuple, list] = {}
        approx: dict[tuple, list] = {}
        now = self.clock()
        for key, waiting in misses.items():
            cls = self._key_class(waiting)
            if self.health.allows_solve(cls):
                keep[key] = waiting
            elif (bank_loaded and self.health.allows_bank(cls)
                  and eng.bank_contains(key[2], key[3])):
                keep[key] = waiting
            elif self.health.allows_approx(cls):
                approx[key] = waiting
            else:
                for pos, t in waiting:
                    responses[pos] = self._reject(t, REASON_DEGRADED, now)
        return keep, approx

    def _overlap_eligible(self, eng) -> bool:
        """Windowed dispatch applies only where query_batch would run
        one flat dispatch per batch anyway — so the overlapped stream
        is dispatch-for-dispatch the program sequence the byte-identity
        contract pins. Local meshes qualify since r7 (the flat path
        shards the query axis in-process); cross-process engines keep
        the sequential guarded path."""
        return (
            int(self.config.dispatch_window) > 1
            and eng.impl in ("auto", "flat")
            and eng._flat_eligible()
            and not eng._wide_block_cap()
            and not eng._multihost
        )

    def _miss_lanes(self, misses, keys) -> tuple[list, list | None]:
        """(classes, urgent) scheduler inputs for a miss-key list:
        per key, the highest-priority waiter's class, and whether any
        waiter's remaining deadline budget is inside the configured
        slack (None when deadline promotion is disabled)."""
        classes = [self._key_class(misses[k]) for k in keys]
        slack = self.deadline_slack_s
        if slack is None:
            return classes, None
        now = self.clock()
        urgent = [
            any(t.t_deadline is not None
                and (t.t_deadline - now) <= float(slack)
                for _, t in misses[k])
            for k in keys
        ]
        return classes, urgent

    def _dispatch_misses(self, eng, fp, misses, responses) -> None:
        keys = list(misses.keys())  # first-arrival order (dict insertion)
        points = np.asarray([[k[2], k[3]] for k in keys], np.int64)
        counts = eng.index.counts_batch(points)
        classes, urgent = self._miss_lanes(misses, keys)
        plan = self.scheduler.plan(counts, classes, urgent)
        if self.host_role is not None:
            self._dispatch_hostshard(eng, fp, misses, responses, keys,
                                     counts, points, plan)
            return
        if not self._overlap_eligible(eng):
            for batch in plan:
                self._dispatch_one(eng, fp, misses, responses, keys,
                                   counts, points, batch)
            return
        # Overlapped mega-batch dispatch: keep up to dispatch_window
        # flat programs in flight; finalize strictly in dispatch order.
        # The SERVE_DISPATCH fire stays host-side immediately before
        # each batch's dispatch, so a classified fault there (injected
        # or real) sheds exactly that batch and the stream continues —
        # the same shed contract as the sequential path.
        window = int(self.config.dispatch_window)
        inflight: list = []  # (batch, bid, t0, handle) in dispatch order
        bi = 0
        while bi < len(plan) or inflight:
            while bi < len(plan) and len(inflight) < window:
                batch = plan[bi]
                bi += 1
                bid = self._batch_id
                self._batch_id += 1
                bpts = points[batch]
                self.dispatch_log.append((bid, np.array(bpts)))
                t0 = self.clock()
                try:
                    inject.fire(sites.SERVE_DISPATCH)
                except Exception as e:
                    kind = taxonomy.classify(e)
                    if kind is None:
                        raise
                    if kind in _TOPOLOGY_KINDS:
                        # a lost device/host poisons the in-flight
                        # handles too: shrink the mesh, then re-dispatch
                        # this batch, the in-flight ones, and the
                        # remainder through the guarded path on the
                        # survivors — nothing sheds, the stream
                        # completes bit-identically (docs/design.md
                        # §18). Only if no shrink is possible does this
                        # batch shed.
                        if self._recover_topology(kind, eng, [
                            points[b] for (b, _, _, _) in inflight
                        ] + [bpts] + [points[b] for b in plan[bi:]]):
                            retry = [(b, b_bid)
                                     for (b, b_bid, _, _) in inflight]
                            retry += [(batch, bid)]
                            retry += [(b, None) for b in plan[bi:]]
                            inflight.clear()
                            for b, b_bid in retry:
                                self._dispatch_one(eng, fp, misses,
                                                   responses, keys,
                                                   counts, points, b,
                                                   bid=b_bid)
                            return
                    self._shed_batch(misses, responses, keys, counts,
                                     batch, bid, kind, t0)
                    continue
                try:
                    with obs.span("serve.batch_dispatch", batch_id=bid,
                                  size=len(batch)):
                        h = eng._dispatch_flat(bpts, None)
                except Exception as e:
                    kind = taxonomy.classify(e)
                    if kind is None:
                        raise
                    if kind in _TOPOLOGY_KINDS:
                        # best-effort shrink before rerouting: on
                        # success the guarded path below re-dispatches
                        # everything on the surviving mesh; on failure
                        # it sheds classified, batch by batch
                        self._recover_topology(kind, eng, [
                            points[b] for (b, _, _, _) in inflight
                        ] + [bpts] + [points[b] for b in plan[bi:]])
                    # A real dispatch-time device fault poisons the
                    # in-flight handles too. Nothing sheds here: reroute
                    # this batch, the in-flight ones, and the remainder
                    # through the guarded sequential path — the
                    # engine-side ladder (reset → retry → halve → CPU
                    # rung) absorbs what it can, exactly as the
                    # non-overlapped path would have.
                    retry = [(b, b_bid) for (b, b_bid, _, _) in inflight]
                    retry += [(batch, bid)]
                    retry += [(b, None) for b in plan[bi:]]
                    inflight.clear()
                    for b, b_bid in retry:
                        self._dispatch_one(eng, fp, misses, responses,
                                           keys, counts, points, b,
                                           bid=b_bid)
                    return
                inflight.append((batch, bid, t0, h))
            if not inflight:
                continue
            batch, bid, t0, h = inflight.pop(0)
            try:
                with obs.span("serve.batch_finalize", batch_id=bid):
                    res = eng._finalize_flat(h)
                    # same NaN screen query_batch applies: a non-finite
                    # payload walks the solver degradation ladder
                    res = eng._nan_ladder(
                        res,
                        lambda b=points[batch]: eng._query_batch_impl(b)
                    )
            except Exception as e:
                kind = taxonomy.classify(e)
                if kind is None:
                    raise
                # A classified finalize fault (worker crash, preemption)
                # killed every in-flight buffer with it. Shed ONLY the
                # faulted batch; drop the dead handles and re-dispatch
                # their batches — plus the unplanned remainder — through
                # the guarded sequential path, whose engine-side ladder
                # (reset → retry → halve → CPU rung) owns the recovery.
                # DEVICE_LOST differs: the ladder cannot fix a dead
                # device, so shrink the mesh first — and on a
                # successful shrink the faulted batch re-dispatches too
                # instead of shedding (its inputs are host-side; only
                # the dead device's output buffers were lost).
                recovered = (
                    kind in _TOPOLOGY_KINDS
                    and self._recover_topology(kind, eng, [
                        points[batch]
                    ] + [points[b] for (b, _, _, _) in inflight]
                        + [points[b] for b in plan[bi:]])
                )
                retry = []
                if recovered:
                    retry += [(batch, bid)]
                else:
                    self._shed_batch(misses, responses, keys, counts,
                                     batch, bid, kind, t0)
                retry += [(b, b_bid) for (b, b_bid, _, _) in inflight]
                retry += [(b, None) for b in plan[bi:]]
                inflight.clear()
                for b, b_bid in retry:
                    self._dispatch_one(eng, fp, misses, responses, keys,
                                       counts, points, b, bid=b_bid)
                return
            self._bank_batch(eng, fp, misses, responses, keys, counts,
                             batch, bid, res, t0)

    def _dispatch_one(self, eng, fp, misses, responses, keys, counts,
                      points, batch, bid=None) -> None:
        """One guarded sequential dispatch (the non-overlapped serve
        path, and the degradation rung after a classified fault in the
        overlapped loop). ``bid`` reuses a batch id the windowed loop
        already allocated and logged for this batch."""
        if bid is None:
            bid = self._batch_id
            self._batch_id += 1
            self.dispatch_log.append((bid, np.array(points[batch])))
        t0 = self.clock()
        try:
            inject.fire(sites.SERVE_DISPATCH)
            with obs.span("serve.batch_dispatch", batch_id=bid,
                          size=len(batch)):
                res = eng.query_batch(points[batch])
        except Exception as e:
            kind = taxonomy.classify(e)
            if kind is None:
                raise
            if kind in _TOPOLOGY_KINDS and self._recover_topology(
                kind, eng, [points[batch]]
            ):
                # shrink succeeded: this very batch re-dispatches on the
                # surviving mesh (recursion is bounded — every recovery
                # drops a device/host, and with none left to drop the
                # shrink fails and the batch sheds classified below)
                self._dispatch_one(eng, fp, misses, responses, keys,
                                   counts, points, batch, bid=bid)
                return
            self._shed_batch(misses, responses, keys, counts, batch, bid,
                             kind, t0)
            return
        self._bank_batch(eng, fp, misses, responses, keys, counts, batch,
                         bid, res, t0)

    def _shed_batch(self, misses, responses, keys, counts, batch, bid,
                    kind, t0) -> None:
        self._drain_errors += 1
        self._drain_dispatches += 1
        dt = self.clock() - t0
        self.metrics.record_batch(
            bid, len(batch), int(counts[batch].sum()), dt, status=kind
        )
        for j in batch:
            for pos, t in misses[keys[int(j)]]:
                responses[pos] = self._reject(
                    t, kind, self.clock(), batch_id=bid,
                    batch_size=len(batch),
                )

    def _bank_batch(self, eng, fp, misses, responses, keys, counts, batch,
                    bid, res, t0) -> None:
        self._drain_dispatches += 1
        dt = self.clock() - t0
        self.metrics.record_batch(
            bid, len(batch), int(counts[batch].sum()), dt
        )
        now = self.clock()
        for row, j in enumerate(batch):
            key = keys[int(j)]
            entry = BlockEntry(
                scores=np.array(res.scores_of(row)),
                ihvp=np.array(res.ihvp[row]),
                test_grad=np.array(res.test_grad[row]),
                count=int(res.counts[row]),
                extra=_approx_extra(res, row),
            )
            self.cache.put(key, entry)
            self._disk_put(eng, fp, key, entry)
            waiting = misses[key]
            # dispatch answered from the factor bank (an O(1)
            # triangular-solve/matvec, not a ladder solve): label the
            # paying waiter with the bank tier and count the hit
            banked = (
                eng.solver == "precomputed"
                and eng.bank_contains(key[2], key[3])
            )
            for rank, (pos, t) in enumerate(waiting):
                # first waiter per key pays the compute; duplicates
                # coalesced into the same drain are hot-tier hits
                if rank == 0:
                    tier = TIER_PRECOMPUTED if banked else TIER_COMPUTE
                    if banked:
                        self.cache.stats.hits_bank += 1
                else:
                    tier = TIER_HOT
                    self.cache.stats.hits_hot += 1
                responses[pos] = self._respond(
                    t, entry, tier, now, eng, solve_s=dt,
                    batch_id=bid, batch_size=len(batch),
                )

    # -- host-sharded dispatch (docs/design.md §25) ------------------------
    def _dispatch_hostshard(self, eng, fp, misses, responses, keys,
                            counts, points, plan) -> None:
        """One drain's miss dispatch split across pod hosts by journal.

        Every host runs this same code over the same coalesced plan:
        compute OWN contiguous batch-aligned shard of the dispatch
        order through the engine (``hostshard.dispatch_local_shard`` —
        skipped entirely when a verified journal for it already exists,
        the restart-resume path), then merge every host's journal back
        into dispatch order (``hostshard.merge_host_shards`` — pure
        journal reads, zero hot-path collectives). Shards are
        batch-boundary-aligned slices of the single-process order, so
        the merged results are bitwise the single-host stream
        (``scripts/multihost_smoke.sh`` pins this).

        A peer whose journal never lands inside
        ``host_merge_timeout_s`` is a proved ``host_lost``: the
        survivors adopt the dead hosts' shards (recompute them locally
        from the same plan — the journals make the adoption idempotent)
        and the drain still answers every request. Only when adoption
        itself fails classified does the drain shed, batch by batch,
        with the taxonomy kind.
        """
        from fia_tpu.serve import hostshard

        host, nhosts, jdir = self.host_role
        order = [int(j) for batch in plan for j in batch]
        opts = points[order]
        tag = f"drain{self._drain_seq}"
        mb = int(self.config.max_batch)
        t0 = self.clock()
        # batch ids allocated up front in plan order, so ids and the
        # dispatch log match the single-host stream
        bids = []
        for batch in plan:
            bid = self._batch_id
            self._batch_id += 1
            self.dispatch_log.append((bid, np.array(points[batch])))
            bids.append(bid)
        try:
            inject.fire(sites.SERVE_DISPATCH)
            with obs.span("serve.hostshard_drain", host=int(host),
                          nhosts=int(nhosts), rows=len(order)):
                hostshard.dispatch_local_shard(
                    eng, opts, host=host, nhosts=nhosts,
                    journal_dir=jdir, tag=tag, engine_fp=fp,
                    max_batch=mb,
                )
                merged = hostshard.merge_host_shards(
                    jdir, tag, nhosts, opts, engine_fp=fp, max_batch=mb,
                    timeout_s=float(self.config.host_merge_timeout_s),
                    clock=self._merge_clock(),
                )
        except Exception as e:
            kind = taxonomy.classify(e)
            if kind is None:
                raise
            merged = None
            if kind == taxonomy.HOST_LOST:
                merged = self._adopt_missing_shards(eng, fp, opts, tag)
            if merged is None:
                for bi, batch in enumerate(plan):
                    self._shed_batch(misses, responses, keys, counts,
                                     batch, bids[bi], kind, t0)
                return
        base = 0
        for bi, batch in enumerate(plan):
            view = _MergedRows(merged, base, len(batch))
            self._bank_batch(eng, fp, misses, responses, keys, counts,
                             batch, bids[bi], view, t0)
            base += len(batch)

    def _merge_clock(self):
        from fia_tpu.reliability import policy as rpolicy

        return self._clock_obj if self._clock_obj is not None \
            else rpolicy.WALL

    def _adopt_missing_shards(self, eng, fp, opts, tag):
        """Survivor-side recovery for the journal transport: recompute
        every shard whose journal is missing (``dispatch_local_shard``
        verifies and skips the ones already on disk — including our
        own) and re-merge with a zero wait. Returns the merged arrays,
        or None when the adoption itself failed classified (the caller
        sheds)."""
        from fia_tpu.serve import hostshard

        host, nhosts, jdir = self.host_role
        mb = int(self.config.max_batch)
        try:
            inject.fire(sites.HOST_LOST)
            seed = (f"host-loss-"
                    f"{self.metrics.host_loss_recoveries}")
            with obs.span("serve.host_loss_recovery", trace_seed=seed,
                          host=int(host), nhosts=int(nhosts),
                          transport="journal"):
                for h in range(nhosts):
                    hostshard.dispatch_local_shard(
                        eng, opts, host=h, nhosts=nhosts,
                        journal_dir=jdir, tag=tag, engine_fp=fp,
                        max_batch=mb,
                    )
                merged = hostshard.merge_host_shards(
                    jdir, tag, nhosts, opts, engine_fp=fp, max_batch=mb,
                    timeout_s=0.0, clock=self._merge_clock(),
                )
        except Exception as e:
            if taxonomy.classify(e) is None:
                raise
            return None
        self.metrics.record_host_loss_recovery()
        obs.REGISTRY.counter("serve.host_loss_recoveries").inc()
        return merged

    def _dispatch_approx(self, eng, fp, misses, responses) -> None:
        """Serve brownout misses from the certified ``sampled`` rung.

        A guarded sequential dispatch stream over the engine's
        cache-less :meth:`~fia_tpu.influence.engine.InfluenceEngine.
        approx_sibling` (solver='sampled'): every answer is stamped
        ``approx=True`` with its concentration error bound
        (docs/design.md §22), and results bank only in the HOT tier
        under the sibling's solver key — never under the exact
        solver's hot/disk keys, so the exact path's bytes are
        identical to a run with approx serving disabled. A classified
        fault sheds exactly that batch with the taxonomy kind (the
        rung is salvage — it gets no retry ladder of its own).

        These dispatches also run AFTER the drain's exact-path batches
        (stable batch ids on the exact path) and stay OUT of the
        drain's health signals: the brownout controller listens to the
        primary dispatch path only, so the salvage rung can neither
        mask a sick backend with its successes nor deepen the brownout
        with its failures.
        """
        sib = eng.approx_sibling()
        keys = list(misses.keys())
        points = np.asarray([[k[2], k[3]] for k in keys], np.int64)
        counts = eng.index.counts_batch(points)
        classes, urgent = self._miss_lanes(misses, keys)
        for batch in self.scheduler.plan(counts, classes, urgent):
            bid = self._batch_id
            self._batch_id += 1
            self.dispatch_log.append((bid, np.array(points[batch])))
            t0 = self.clock()
            try:
                inject.fire(sites.SERVE_DISPATCH)
                with obs.span("serve.batch_dispatch", batch_id=bid,
                              size=len(batch), approx=True):
                    res = sib.query_batch(points[batch])
            except Exception as e:
                kind = taxonomy.classify(e)
                if kind is None:
                    raise
                dt = self.clock() - t0
                self.metrics.record_batch(
                    bid, len(batch), int(counts[batch].sum()), dt,
                    status=kind,
                )
                for j in batch:
                    for pos, t in misses[keys[int(j)]]:
                        responses[pos] = self._reject(
                            t, kind, self.clock(), batch_id=bid,
                            batch_size=len(batch),
                        )
                continue
            dt = self.clock() - t0
            self.metrics.record_batch(
                bid, len(batch), int(counts[batch].sum()), dt
            )
            now = self.clock()
            for row, j in enumerate(batch):
                key = keys[int(j)]
                entry = BlockEntry(
                    scores=np.array(res.scores_of(row)),
                    ihvp=np.array(res.ihvp[row]),
                    test_grad=np.array(res.test_grad[row]),
                    count=int(res.counts[row]),
                    extra=_approx_extra(res, row),
                )
                self.cache.put((fp, sib.solver) + key[2:], entry)
                for rank, (pos, t) in enumerate(misses[key]):
                    # first waiter per key pays the compute; duplicates
                    # coalesced into the same drain are hot-tier hits
                    if rank == 0:
                        tier = TIER_COMPUTE
                    else:
                        tier = TIER_HOT
                        self.cache.stats.hits_hot += 1
                    responses[pos] = self._respond(
                        t, entry, tier, now, sib, solve_s=dt,
                        batch_id=bid, batch_size=len(batch),
                    )

    # -- response/tier helpers --------------------------------------------
    def _respond(self, t: Ticket, entry: BlockEntry, tier: str, now: float,
                 eng, solve_s: float = 0.0, batch_id=None,
                 batch_size=None) -> Response:
        related = None
        if self.config.include_related:
            related = eng.index.related(int(t.req.user), int(t.req.item))
        return Response(
            id=t.req.id, user=t.req.user, item=t.req.item,
            scores=entry.scores, related=related, ihvp=entry.ihvp,
            test_grad=entry.test_grad, cache_tier=tier,
            queue_wait_s=max(now - t.t_arrival, 0.0), solve_s=solve_s,
            batch_id=batch_id, batch_size=batch_size,
            mode=self.health.mode,
            cls=t.req.cls, tenant=t.req.tenant,
            # certificate provenance rides the cached entry, so hot/disk
            # hits of an approximate block keep their stamped bound
            approx=bool(entry.extra.get("approx", False)),
            err_bound=entry.extra.get("err_bound"),
            # solver provenance for the serve.solver span + per-rung
            # histograms; extra never reaches Response.json(), so the
            # wire bytes are unchanged (and identical trace-on/off)
            extra={"solver": eng.solver},
        )

    def _reject(self, t: Ticket, reason: str, now: float, batch_id=None,
                batch_size=None) -> Response:
        return Response(
            id=t.req.id, user=t.req.user, item=t.req.item,
            status=STATUS_REJECTED, reason=reason,
            queue_wait_s=max(now - t.t_arrival, 0.0),
            batch_id=batch_id, batch_size=batch_size,
            mode=self.health.mode,
            cls=t.req.cls, tenant=t.req.tenant,
        )

    # -- device-loss recovery (docs/design.md §18) -------------------------
    def _recover_device_loss(self, eng, pending_points) -> bool:
        """Shrink the serving mesh over the surviving devices.

        Called when a dispatch failure classified ``device_lost``: asks
        the backend which mesh devices are still visible
        (:func:`~fia_tpu.parallel.mesh.lost_device_ids`; an injected
        loss names none, so the deterministic last-device drop
        applies), re-homes the engine on the survivors
        (:meth:`~fia_tpu.influence.engine.InfluenceEngine.rebuild_mesh`)
        and AOT re-arms the still-pending dispatch geometries so
        steady state stays zero-compile on the new topology. Results
        are unchanged by construction — every mesh size runs the exact
        single-device program per shard (docs/design.md §15).

        Returns False — caller sheds classified — when there is no mesh
        to shrink (single-device engine), no survivor to shrink to, or
        the rebuild itself failed with a classified fault.
        """
        from fia_tpu.parallel import mesh as pmesh

        cur = getattr(eng, "mesh", None)
        if cur is None:
            return False
        new = pmesh.surviving_mesh(cur, pmesh.lost_device_ids(cur))
        if new is None:
            return False
        try:
            seed = (f"device-loss-"
                    f"{self.metrics.device_loss_recoveries}")
            with obs.span("serve.device_loss_recovery",
                          trace_seed=seed,
                          ndev=int(new.devices.size)) as sp:
                eng.rebuild_mesh(new)
                if (eng.impl in ("auto", "flat") and eng._flat_eligible()
                        and not eng._wide_block_cap()
                        and not eng._multihost):
                    geoms = {tuple(eng.flat_geometry(np.asarray(p)))
                             for p in pending_points if len(p)}
                    eng.precompile_flat(sorted(geoms))
                    sp.set(rearmed=len(geoms))
        except Exception as e:
            if taxonomy.classify(e) is None:
                raise
            return False
        self.mesh = new
        self.metrics.record_device_loss_recovery()
        obs.REGISTRY.counter("serve.device_loss_recoveries").inc()
        return True

    # -- host-loss recovery (docs/design.md §25) ---------------------------
    def _recover_host_loss(self, eng, pending_points) -> bool:
        """Shrink the serving mesh over the surviving *hosts*.

        The ``host_lost`` analogue of :meth:`_recover_device_loss`, one
        granularity up: a collective timing out (or a coordination-
        service heartbeat error) says some peer process is gone, so the
        liveness probe asks which mesh hosts lost every device
        (:func:`~fia_tpu.parallel.mesh.lost_host_ids`; an injected loss
        names none, so the deterministic last-host drop applies), drops
        those hosts wholesale, re-homes the engine on the survivors —
        which re-shards row-sharded tables onto them and re-fires the
        ``mesh.rebuild_multihost`` site when the result still spans
        hosts — and AOT re-arms the pending dispatch geometries.
        Results are unchanged by construction: every mesh size runs the
        exact single-device program per shard (docs/design.md §15), so
        the survivors' answers byte-match a fault-free smaller-pod run.

        Returns False — caller sheds classified — when there is no mesh
        to shrink, no host would survive the drop, or the rebuild
        itself failed with a classified fault.
        """
        from fia_tpu.parallel import mesh as pmesh

        cur = getattr(eng, "mesh", None)
        if cur is None:
            return False
        new = pmesh.surviving_mesh(
            cur,
            lost_ids=pmesh.lost_device_ids(cur),
            lost_hosts=pmesh.lost_host_ids(cur),
            unnamed="host",
        )
        if new is None:
            return False
        try:
            inject.fire(sites.HOST_LOST)
            seed = (f"host-loss-"
                    f"{self.metrics.host_loss_recoveries}")
            with obs.span("serve.host_loss_recovery",
                          trace_seed=seed,
                          ndev=int(new.devices.size),
                          nhosts=len(pmesh.mesh_hosts(new))) as sp:
                eng.rebuild_mesh(new)
                if (eng.impl in ("auto", "flat") and eng._flat_eligible()
                        and not eng._wide_block_cap()
                        and not eng._multihost):
                    geoms = {tuple(eng.flat_geometry(np.asarray(p)))
                             for p in pending_points if len(p)}
                    eng.precompile_flat(sorted(geoms))
                    sp.set(rearmed=len(geoms))
        except Exception as e:
            if taxonomy.classify(e) is None:
                raise
            return False
        self.mesh = new
        self.metrics.record_host_loss_recovery()
        obs.REGISTRY.counter("serve.host_loss_recoveries").inc()
        return True

    def _recover_topology(self, kind, eng, pending_points) -> bool:
        """Route a topology-loss kind to its shrink: ``host_lost``
        drops whole hosts, ``device_lost`` drops one device."""
        if kind == taxonomy.HOST_LOST:
            return self._recover_host_loss(eng, pending_points)
        return self._recover_device_loss(eng, pending_points)

    def _disk_dir(self, eng) -> str | None:
        if not self.config.disk_cache or not eng.cache_dir:
            return None
        return eng.cache_dir

    def _disk_get(self, eng, fp: str, req: Request) -> BlockEntry | None:
        d = self._disk_dir(eng)
        if d is None:
            return None
        path = scache.disk_entry_path(
            d, eng.model_name, eng.solver, req.user, req.item
        )
        e = scache.disk_get(
            path, scache.disk_fingerprint(eng.model_name, eng.solver, fp),
            stats=self.cache.stats,
        )
        if e is not None:
            self.cache.stats.hits_disk += 1
        return e

    def _disk_put(self, eng, fp: str, key: tuple, entry: BlockEntry) -> None:
        d = self._disk_dir(eng)
        if d is None:
            return
        scache.disk_put(
            scache.disk_entry_path(d, eng.model_name, eng.solver,
                                   key[2], key[3]),
            entry,
            scache.disk_fingerprint(eng.model_name, eng.solver, fp),
        )

    # -- convenience -------------------------------------------------------
    def run(self, requests, drain_every: int | None = None
            ) -> list[Response]:
        """Submit a request iterable and drain to completion.

        ``drain_every``: drain after every N submits (None = one drain
        at the end — maximal coalescing). Responses return in
        submission order.
        """
        by_id: dict[str, Response] = {}
        order: list[str] = []
        n = 0
        for req in requests:
            if not isinstance(req, Request):
                req = Request(*req)
            r = self.submit(req)
            order.append(req.id)
            if r is not None:
                by_id[req.id] = r
            n += 1
            if drain_every and n % drain_every == 0:
                for resp in self.drain():
                    by_id[resp.id] = resp
        for resp in self.drain():
            by_id[resp.id] = resp
        return [by_id[i] for i in order]

    def rollup(self) -> dict:
        return self.metrics.rollup(self.cache.stats.json())

    def close(self) -> dict:
        """Final rollup (logged to the metrics JSONL) + release files."""
        r = self.metrics.log_rollup(self.cache.stats.json())
        self.metrics.close()
        return r

    # -- warmup ------------------------------------------------------------
    def warmup(self, points: np.ndarray, fill_cache: bool = False) -> dict:
        """Arm the serving dispatch path for ``points``' planned batches.

        Two stages. First, every planned batch's flat dispatch geometry
        is AOT pre-lowered and compiled (``engine.precompile_flat`` —
        ``jax.jit(...).lower(...).compile()``), so steady-state serving
        never traces or compiles on the hot path. Second, the planned
        batches are actually dispatched: that warms the backend's
        autotuning state, exercises the exact program the stream will
        hit, and covers the non-flat engines AOT skips (their jit
        caches fill per dispatched shape). ``fill_cache=True``
        additionally banks the warmup results in the hot/disk tiers
        (useful when ``points`` are the expected hot set, not
        synthetic).

        Returns {"batches", "compiled_keys", "seconds",
        "planned_geometries", "aot", "all_planned_compiled"} — smoke
        runs assert ``all_planned_compiled`` so a warmup that missed a
        planned bucket fails loudly instead of paying a first-request
        compile in production.
        """
        eng, fp = self._engine_and_fp()
        points = np.asarray(points)
        if points.ndim == 1:
            points = points[None, :]
        before = set(eng._jitted)
        t0 = time.perf_counter()
        bank_entries = 0
        if self.config.factor_bank and eng.solver == "precomputed":
            # preload the published factor bank device-resident (a
            # verified load: checksum + fingerprint + per-entry params
            # digests) so the first hot-set request never pays it
            bank_entries = eng.ensure_factor_bank()
        counts = eng.index.counts_batch(points)
        plan = self.batcher.plan(counts)
        flat_ok = (
            eng.impl in ("auto", "flat") and eng._flat_eligible()
            and not eng._wide_block_cap() and not eng._multihost
        )
        planned = []
        aot = {"compiled": [], "cached": [], "seconds": 0.0}
        if flat_ok:
            planned = [list(eng.flat_geometry(points[b])) for b in plan]
            aot = eng.precompile_flat(planned)
        nb = 0
        for batch in plan:
            bpts = points[batch]
            res = eng.query_batch(bpts)
            nb += 1
            if fill_cache:
                for row, j in enumerate(batch):
                    key = (fp, eng.solver, int(bpts[row, 0]),
                           int(bpts[row, 1]))
                    entry = BlockEntry(
                        scores=np.array(res.scores_of(row)),
                        ihvp=np.array(res.ihvp[row]),
                        test_grad=np.array(res.test_grad[row]),
                        count=int(res.counts[row]),
                        extra=_approx_extra(res, row),
                    )
                    self.cache.put(key, entry)
                    self._disk_put(eng, fp, key, entry)
        armed = {(k[1], k[2]) for k in getattr(eng, "_aot", {})}
        return {
            "batches": nb,
            "compiled_keys": sorted(
                str(k) for k in set(eng._jitted) - before
            ),
            "seconds": round(time.perf_counter() - t0, 3),
            "planned_geometries": planned,
            "aot": aot,
            # which score-kernel variant the armed programs embed
            # (influence/kernels/) — smoke/ops checks pin it so a
            # production TPU pod never silently serves the autodiff
            # reference after a model/config drift
            "kernel_variant": eng.active_kernel_variant(),
            "factor_bank_entries": bank_entries,
            "all_planned_compiled": (
                all(tuple(g) in armed for g in planned) if flat_ok
                else True  # jit caches warmed by the real dispatches
            ),
        }
