"""Request/response records for the serving layer.

A :class:`Request` is one ``(user, item)`` influence query plus its
serving metadata (id, arrival time, optional per-request deadline). A
:class:`Response` carries the answer — the unpadded related-row scores
and the iHVP/test-grad block vectors — or a taxonomy-classified
rejection, plus the per-request latency breakdown the metrics layer
logs (queue wait, solve time, cache tier, batch placement).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# Cache tiers a response can be served from. ``compute`` = this request
# triggered (or rode) a device dispatch this drain; ``hot`` = in-memory
# LRU hit (including duplicates coalesced within one drain); ``disk`` =
# verified on-disk entry promoted into the hot tier; ``precomputed`` =
# the dispatch was an O(1) factor-bank hit (solver='precomputed'):
# device work happened, but it was one triangular-solve/matvec against
# the preloaded bank rather than a from-scratch ladder solve.
TIER_COMPUTE = "compute"
TIER_HOT = "hot"
TIER_DISK = "disk"
TIER_PRECOMPUTED = "precomputed"

STATUS_OK = "ok"
STATUS_REJECTED = "rejected"

# Priority classes (multi-tenant serving, docs/reliability.md
# "Multi-tenant serving & fairness"). Order = priority: interactive
# dispatches ahead of batch ahead of scavenger under the fair-queueing
# scheduler, and the brownout ladder degrades the tail first.
# Unclassed requests are `batch` — the pre-multi-tenant behaviour
# (full brownout/approx semantics) unchanged.
CLASS_INTERACTIVE = "interactive"
CLASS_BATCH = "batch"
CLASS_SCAVENGER = "scavenger"
CLASSES = (CLASS_INTERACTIVE, CLASS_BATCH, CLASS_SCAVENGER)
DEFAULT_CLASS = CLASS_BATCH

# Per-class latency SLOs in seconds — the published service objectives
# each priority class is sold under. `ServeConfig.class_deadlines=True`
# adopts these as per-class deadline defaults for requests that carry
# none of their own, and derives `deadline_slack_s` (the urgent-lane
# promotion threshold) from the tightest class SLO so the dispatcher's
# notion of "about to miss" tracks the strictest promise actually made.
CLASS_SLOS = {
    CLASS_INTERACTIVE: 0.5,
    CLASS_BATCH: 10.0,
    CLASS_SCAVENGER: 60.0,
}


@dataclass
class Request:
    """One influence query entering the service."""

    user: int
    item: int
    id: str | None = None
    # wall-clock budget in seconds, measured from arrival; None adopts
    # the service default (ServeConfig.default_deadline_s)
    deadline_s: float | None = None
    # priority class ("interactive" | "batch" | "scavenger") — drives
    # admission quotas, fair-queueing weight, and the class-aware
    # brownout ladder; an unknown class is rejected "invalid" at the
    # door. JSON wire key: "class".
    cls: str = DEFAULT_CLASS
    # opaque tenant label for per-tenant accounting; never interpreted
    tenant: str | None = None

    def key(self) -> tuple[int, int]:
        return (int(self.user), int(self.item))


@dataclass
class Ticket:
    """A queued admitted request (service-internal)."""

    req: Request
    t_arrival: float
    t_deadline: float | None  # absolute, on the service clock
    # serving epoch this ticket was admitted under: a drain resolves it
    # against that epoch's fenced (engine, fingerprint) even if a
    # streaming update swapped the model in between (docs/design.md §17)
    epoch: int = 0

    def expired(self, now: float) -> bool:
        return self.t_deadline is not None and now > self.t_deadline


@dataclass
class Response:
    """The service's answer to one request."""

    id: str | None
    user: int
    item: int
    status: str = STATUS_OK
    # taxonomy kind ("deadline", "oom", ...) or an admission reason
    # ("overload", "invalid") when status == "rejected"
    reason: str | None = None
    scores: np.ndarray | None = None  # (count,) unpadded related scores
    related: np.ndarray | None = None  # (count,) train-row ids
    ihvp: np.ndarray | None = None  # (d,) block inverse-HVP
    test_grad: np.ndarray | None = None  # (d,) test-side block vector
    cache_tier: str | None = None
    queue_wait_s: float = 0.0
    solve_s: float = 0.0
    batch_id: int | None = None
    batch_size: int | None = None
    # serving mode active when this response was produced ("full" /
    # "bank_preferred" / "cache_only", serve/health.py) — every answer
    # AND every rejection says what regime produced it
    mode: str | None = None
    # certified-approximate answers (the 'sampled' rung, docs/design.md
    # §22): approx marks a subsampled payload and err_bound carries its
    # concentration bound on the max per-row score error (0.0 when the
    # sample covered every related row). Exact answers keep the
    # defaults, so absence reads as exactness.
    approx: bool = False
    err_bound: float | None = None
    # priority class and tenant echoed from the request (wire keys
    # "class"/"tenant") — every answer AND every rejection says which
    # tenant lane produced it
    cls: str = DEFAULT_CLASS
    tenant: str | None = None
    extra: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK

    def json(self, include_payload: bool = True) -> dict:
        """JSON-encodable form (the CLI's stdout line)."""
        out = {
            "id": self.id,
            "user": int(self.user),
            "item": int(self.item),
            "status": self.status,
            "reason": self.reason,
            "tier": self.cache_tier,
            "queue_wait_ms": round(self.queue_wait_s * 1e3, 3),
            "solve_ms": round(self.solve_s * 1e3, 3),
            "batch_id": self.batch_id,
            "batch_size": self.batch_size,
            "mode": self.mode,
            "approx": bool(self.approx),
            "err_bound": (None if self.err_bound is None
                          else float(self.err_bound)),
            "class": self.cls,
            "tenant": self.tenant,
        }
        if include_payload and self.scores is not None:
            out["scores"] = np.asarray(self.scores).tolist()
            if self.related is not None:
                out["related"] = np.asarray(self.related).tolist()
        return out
