"""Online influence-query serving.

The offline drivers (``cli/rq1.py``, ``cli/rq2.py``) answer influence
queries in one-shot experiment sweeps; this package turns the engine
into a *service*: a stream of ``(user, item)`` requests answered under
a latency budget, with micro-batching to amortize compilation and
device transfers, a hot-block cache over per-query iHVP results, and
admission control so overload sheds load deterministically instead of
OOMing (docs/design.md §12).

Layers (each its own module, composable without the service):

- :mod:`fia_tpu.serve.request`   — request/response records.
- :mod:`fia_tpu.serve.cache`     — bounded in-memory hot-block LRU and
  the verified on-disk tier beneath it (reliability/artifacts.py).
- :mod:`fia_tpu.serve.scheduler` — the micro-batching planner.
- :mod:`fia_tpu.serve.admission` — queue-depth/deadline admission.
- :mod:`fia_tpu.serve.health`    — the brownout ladder
  (``full → bank_preferred → cache_only``) and its hysteresis.
- :mod:`fia_tpu.serve.metrics`   — per-request JSONL events + rollups.
- :mod:`fia_tpu.serve.service`   — :class:`InfluenceService`, the event
  loop tying the above to an :class:`InfluenceEngine`, including
  device-loss mesh-shrink recovery (docs/design.md §18).
"""

from fia_tpu.serve.admission import (  # noqa: F401
    DEFAULT_CLASS_QUOTAS,
    REASON_DEADLINE,
    REASON_DEGRADED,
    REASON_INVALID,
    REASON_OVERLOAD,
    AdmissionController,
)
from fia_tpu.serve.cache import CacheStats, HotBlockCache  # noqa: F401
from fia_tpu.serve.health import (  # noqa: F401
    MODE_BANK_PREFERRED,
    MODE_CACHE_ONLY,
    MODE_FULL,
    HealthConfig,
    HealthController,
)
from fia_tpu.serve.metrics import ServeMetrics  # noqa: F401
from fia_tpu.serve.request import (  # noqa: F401
    CLASS_BATCH,
    CLASS_INTERACTIVE,
    CLASS_SCAVENGER,
    CLASSES,
    DEFAULT_CLASS,
    Request,
    Response,
)
from fia_tpu.serve.scheduler import (  # noqa: F401
    CLASS_WEIGHTS,
    FairScheduler,
    MicroBatcher,
)
from fia_tpu.serve.service import InfluenceService, ServeConfig  # noqa: F401
