"""FIA301/302/303 — fault-site integrity.

The reliability layer's value rests on two registries staying honest:
the injection-site names (a typo'd site is a fault plan that silently
never fires — the recovery path the test believes it covers never
runs) and the failure taxonomy (an unclassifiable raise in a
reliability-threaded path is retried blindly or surfaces as an
unhandled crash instead of a recovery decision).

- **FIA301 unregistered-site** — every *string literal* passed as a
  site to ``inject.fire`` / ``inject.corrupt`` / ``inject.damage``, as
  a ``site=`` keyword (``artifacts.publish_npz``), or as the first
  argument of ``inject.Fault(...)`` must be a member of
  ``fia_tpu/reliability/sites.py``'s ``ALL_SITES``. References through
  the ``sites.*`` constants are checked against the same registry.
- **FIA302 untyped-reliability-raise** — ``raise`` statements in
  ``fia_tpu/reliability/`` must use a taxonomy-classifiable or
  reliability-owned exception type (``config.RELIABILITY_RAISABLE``).
- **FIA303 site-docs-drift** — the "Injection-site registry" table in
  ``docs/reliability.md`` must list every registered site, and must
  not list sites that no longer exist.
"""

from __future__ import annotations

import ast
import os
import re

from fia_tpu.analysis import config, core
from fia_tpu.analysis.core import (
    FileRule,
    Finding,
    ProjectRule,
    SourceFile,
    register,
)
from fia_tpu.analysis.visitor import call_name, const_str


def load_site_registry(root: str) -> tuple[set[str], set[str]] | None:
    """Read sites.py's registry from the invocation parse cache.

    Returns ``(site_names, constant_names)`` — the string values in
    ``ALL_SITES``-style constants and the constant identifiers — or
    None when the module is missing/unparseable. Both FIA301 and
    FIA303 need this, so the parsed module comes from
    :func:`core.current_context` (one parse per ``make lint``, shared
    across rules) and the *registry extraction* itself is memoized.
    """
    ctx = core.current_context()
    if ctx is not None and ctx.root == root:
        return ctx.memo("sites-registry", lambda: _extract_registry(root))
    return _extract_registry(root)


def _extract_registry(root: str) -> tuple[set[str], set[str]] | None:
    tree = core.parsed_module(root, config.SITES_MODULE)
    if tree is None:
        return None
    names: set[str] = set()
    constants: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            value = const_str(node.value)
            if value is not None:
                names.add(value)
                constants.add(node.targets[0].id)
    return names, constants


_SITE_CALLEES = {
    "inject.fire": 0,
    "inject.corrupt": 0,
    "inject.damage": 0,
    "inject.Fault": 0,
    "Fault": 0,
    "inject.call_count": 0,
    "sites.check": 0,
}


def _site_literals(node: ast.Call) -> list[tuple[ast.AST, str]]:
    """(node, literal) pairs for site-position string literals."""
    out: list[tuple[ast.AST, str]] = []
    cn = call_name(node)
    if cn in _SITE_CALLEES and node.args:
        s = const_str(node.args[_SITE_CALLEES[cn]])
        if s is not None:
            out.append((node.args[0], s))
    for kw in node.keywords:
        if kw.arg == "site":
            s = const_str(kw.value)
            if s is not None:
                out.append((kw.value, s))
    return out


@register
class UnregisteredSiteRule(ProjectRule):
    """Injection-site literals must resolve to the checked-in registry."""

    id = "FIA301"
    name = "unregistered-site"

    def check_project(self, files: list[SourceFile], root: str):
        reg = load_site_registry(root)
        findings: list[Finding] = []
        if reg is None:
            # only demand a registry when the linted files actually
            # name injection sites — a tree without fault injection
            # has nothing to register
            if any(
                sf.tree is not None and self._uses_sites(sf)
                for sf in files
            ):
                findings.append(Finding(
                    self.id, config.SITES_MODULE, 1, 0,
                    "site registry missing or unparseable "
                    f"(expected at {config.SITES_MODULE})",
                ))
            return findings
        site_names, constant_names = reg
        for sf in files:
            if sf.tree is None or sf.rel.endswith("reliability/sites.py"):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                for anchor, lit in _site_literals(node):
                    if lit not in site_names:
                        findings.append(Finding(
                            self.id, sf.rel, anchor.lineno,
                            anchor.col_offset,
                            f"injection site {lit!r} is not registered "
                            "in fia_tpu/reliability/sites.py",
                        ))
                # sites.FOO attribute references: constant must exist
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if (isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "sites"
                            and call_name(node) in _SITE_CALLEES
                            and arg.attr not in constant_names
                            and arg.attr != "check"):
                        findings.append(Finding(
                            self.id, sf.rel, arg.lineno, arg.col_offset,
                            f"sites.{arg.attr} is not defined in the "
                            "site registry",
                        ))
        return findings

    @staticmethod
    def _uses_sites(sf: SourceFile) -> bool:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and (
                call_name(node) in _SITE_CALLEES
            ):
                return True
        return False


@register
class ReliabilityRaiseRule(FileRule):
    """Raises in reliability/ must be taxonomy-classifiable types."""

    id = "FIA302"
    name = "untyped-reliability-raise"

    def check(self, sf: SourceFile):
        if config.RELIABILITY_PREFIX not in sf.rel:
            return []
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue  # bare re-raise is fine
            exc = node.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            name = None
            if isinstance(exc, ast.Name):
                name = exc.id
            elif isinstance(exc, ast.Attribute):
                name = exc.attr
            if name is not None and name not in config.RELIABILITY_RAISABLE:
                findings.append(Finding(
                    self.id, sf.rel, node.lineno, node.col_offset,
                    f"raise of {name} in a reliability-threaded path — "
                    "use a taxonomy-classifiable type "
                    "(DeadlineExpired/NanPayload/ArtifactIntegrityError/"
                    "JournalMismatch) or a contract error "
                    "(ValueError/TypeError)",
                ))
        return findings


_DOC_SITE_RE = re.compile(r"`([a-z_]+\.[a-z_]+)`")


@register
class SiteDocsDriftRule(ProjectRule):
    """docs/reliability.md's site table must match the registry."""

    id = "FIA303"
    name = "site-docs-drift"

    def check_project(self, files: list[SourceFile], root: str):
        reg = load_site_registry(root)
        if reg is None:
            return []  # FIA301 already reports the missing registry
        site_names, _ = reg
        doc_path = os.path.join(root, config.SITES_DOC)
        findings: list[Finding] = []
        try:
            with open(doc_path, encoding="utf-8") as fh:
                doc = fh.read()
        except OSError:
            findings.append(Finding(
                self.id, config.SITES_DOC, 1, 0,
                f"site documentation missing (expected {config.SITES_DOC})",
            ))
            return findings
        documented: dict[str, int] = {}
        in_table = False
        for lineno, line in enumerate(doc.splitlines(), start=1):
            if line.startswith("## "):
                in_table = "Injection-site registry" in line
            if in_table and line.lstrip().startswith("|"):
                for m in _DOC_SITE_RE.finditer(line):
                    documented.setdefault(m.group(1), lineno)
        for site in sorted(site_names - set(documented)):
            findings.append(Finding(
                self.id, config.SITES_DOC, 1, 0,
                f"registered site {site!r} is missing from the "
                "'Injection-site registry' table",
            ))
        for site, lineno in sorted(documented.items()):
            if site not in site_names:
                findings.append(Finding(
                    self.id, config.SITES_DOC, lineno, 0,
                    f"documented site {site!r} is not in the registry "
                    "(stale table row?)",
                ))
        return findings
