"""CLI entry point: ``python -m fia_tpu.analysis.lint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage error.

Common invocations::

    python -m fia_tpu.analysis.lint fia_tpu/            # lint the package
    python -m fia_tpu.analysis.lint --self-check        # the tier-1 gate
    python -m fia_tpu.analysis.lint --select FIA101 ... # one rule
    python -m fia_tpu.analysis.lint --select FIA5 ...   # a whole family
    python -m fia_tpu.analysis.lint --json fia_tpu/     # machine-readable
    python -m fia_tpu.analysis.lint --list-rules

``--select``/``--disable`` accept exact rule ids and family *prefixes*
(``FIA5`` expands to every registered FIA5xx rule), so ``make
lint-determinism`` stays correct as the family grows.

``--self-check`` lints the repo's own blessed surface (``fia_tpu/``,
``scripts/``, ``bench.py``, resolved relative to the installed package)
and must come back clean — it is wired into ``make lint``,
``scripts/tier1.sh`` (fatal), and ``bench.py --lint``.

Baseline workflow (landing a new rule warn-first)::

    python -m fia_tpu.analysis.lint --self-check --write-baseline b.json
    ...                      # existing findings snapshotted, not fixed
    python -m fia_tpu.analysis.lint --self-check --baseline b.json
    # exit 0: only pre-existing findings;  exit 1: NEW findings appeared

Baseline keys are line-number-insensitive (rule, path, message with
digit runs collapsed), so pure code motion doesn't churn the snapshot;
genuinely new findings in a file do.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

from fia_tpu.analysis.core import LintResult, all_rules, lint_paths
from fia_tpu.analysis.reporters import (
    json_report,
    rule_catalog,
    terminal_report,
)

_DIGITS_RE = re.compile(r"\d+")


def _baseline_key(f) -> str:
    """Line-insensitive identity of a finding for baseline matching."""
    return f"{f.rule}|{f.path}|{_DIGITS_RE.sub('#', f.message)}"


def _baseline_counts(findings) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        k = _baseline_key(f)
        out[k] = out.get(k, 0) + 1
    return dict(sorted(out.items()))


def write_baseline(path: str, result: LintResult) -> None:
    doc = {"version": 1, "counts": _baseline_counts(result.findings)}
    # fialint: disable=FIA101 -- the baseline snapshot is the linter's own state file; the linter must not import the (numpy-using) atomic-io layer
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)  # fialint: disable=FIA101 -- same linter state file write
        fh.write("\n")


def apply_baseline(path: str, result: LintResult):
    """Split current findings against a snapshot.

    Returns ``(new_findings, new_groups, resolved_groups)``: findings
    whose group has MORE occurrences than the snapshot recorded (the
    whole group is shown when its count grew — the engine cannot know
    which member is the new one), plus group-level new/resolved counts.
    """
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    base = doc.get("counts", {})
    groups: dict[str, list] = {}
    for f in result.findings:
        groups.setdefault(_baseline_key(f), []).append(f)
    new_findings, new_groups = [], 0
    for k, fs in sorted(groups.items()):
        if len(fs) > base.get(k, 0):
            new_groups += 1
            new_findings.extend(fs)
    resolved = sum(1 for k, n in base.items()
                   if n > len(groups.get(k, [])))
    return new_findings, new_groups, resolved


def self_check_paths() -> tuple[list[str], str]:
    """The repo's own lint surface, anchored at the package location."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    paths = [pkg_dir]
    for extra in ("scripts", "bench.py"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            paths.append(p)
    return paths, root


def _parse_rule_set(spec: list[str] | None) -> set[str] | None:
    if not spec:
        return None
    requested: set[str] = set()
    for chunk in spec:
        requested.update(r.strip() for r in chunk.split(",") if r.strip())
    known = set(all_rules())
    out: set[str] = set()
    unknown: set[str] = set()
    for rid in requested:
        if rid in known:
            out.add(rid)
            continue
        # family prefix: FIA5 -> every registered FIA5xx rule
        family = {k for k in known if k.startswith(rid)} if (
            re.fullmatch(r"FIA\d{1,2}", rid)
        ) else set()
        if family:
            out |= family
        else:
            unknown.add(rid)
    if unknown:
        raise SystemExit(
            f"fialint: unknown rule id(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(known))})"
        )
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fia_tpu.analysis.lint",
        description="Repo-native static analysis for fia_tpu "
                    "(see docs/lint.md).",
    )
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    ap.add_argument("--json", action="store_true",
                    help="emit the JSON report instead of terminal lines")
    ap.add_argument("--select", action="append", metavar="RULES",
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--disable", action="append", metavar="RULES",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--self-check", action="store_true",
                    help="lint the repo's own fia_tpu/, scripts/, bench.py")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", metavar="PATH",
                    help="fail only on findings NOT in this snapshot")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="snapshot current findings to PATH and exit 0")
    args = ap.parse_args(argv)

    if args.baseline and args.write_baseline:
        print("fialint: --baseline and --write-baseline are mutually "
              "exclusive", file=sys.stderr)
        return 2

    if args.list_rules:
        print(rule_catalog())
        return 0

    root = None
    paths = list(args.paths)
    if args.self_check:
        sc_paths, root = self_check_paths()
        paths.extend(sc_paths)
    if not paths:
        ap.print_usage(sys.stderr)
        print("fialint: no paths given (or use --self-check)",
              file=sys.stderr)
        return 2

    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"fialint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    result = lint_paths(
        paths,
        select=_parse_rule_set(args.select),
        disable=_parse_rule_set(args.disable),
        root=root,
    )

    if args.write_baseline:
        write_baseline(args.write_baseline, result)
        print(f"fialint: baseline written to {args.write_baseline} "
              f"({len(result.findings)} finding(s) snapshotted)")
        return 0

    if args.baseline:
        try:
            new, new_groups, resolved = apply_baseline(
                args.baseline, result
            )
        except (OSError, ValueError) as e:
            print(f"fialint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        delta = LintResult(
            findings=new, suppressed=result.suppressed,
            files_checked=result.files_checked, root=result.root,
        )
        print(json_report(delta) if args.json else terminal_report(delta))
        print(f"fialint: baseline {args.baseline}: {new_groups} new "
              f"finding group(s), {resolved} resolved "
              f"({len(result.findings)} total current)",
              file=sys.stderr)
        return 0 if not new else 1

    print(json_report(result) if args.json else terminal_report(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
