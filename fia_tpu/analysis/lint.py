"""CLI entry point: ``python -m fia_tpu.analysis.lint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage error.

Common invocations::

    python -m fia_tpu.analysis.lint fia_tpu/            # lint the package
    python -m fia_tpu.analysis.lint --self-check        # the tier-1 gate
    python -m fia_tpu.analysis.lint --select FIA101 ... # one rule family
    python -m fia_tpu.analysis.lint --json fia_tpu/     # machine-readable
    python -m fia_tpu.analysis.lint --list-rules

``--self-check`` lints the repo's own blessed surface (``fia_tpu/``,
``scripts/``, ``bench.py``, resolved relative to the installed package)
and must come back clean — it is wired into ``make lint``,
``scripts/tier1.sh`` (fatal), and ``bench.py --lint``.
"""

from __future__ import annotations

import argparse
import os
import sys

from fia_tpu.analysis.core import all_rules, lint_paths
from fia_tpu.analysis.reporters import (
    json_report,
    rule_catalog,
    terminal_report,
)


def self_check_paths() -> tuple[list[str], str]:
    """The repo's own lint surface, anchored at the package location."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    paths = [pkg_dir]
    for extra in ("scripts", "bench.py"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            paths.append(p)
    return paths, root


def _parse_rule_set(spec: list[str] | None) -> set[str] | None:
    if not spec:
        return None
    out: set[str] = set()
    for chunk in spec:
        out.update(r.strip() for r in chunk.split(",") if r.strip())
    known = set(all_rules())
    unknown = out - known
    if unknown:
        raise SystemExit(
            f"fialint: unknown rule id(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(known))})"
        )
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fia_tpu.analysis.lint",
        description="Repo-native static analysis for fia_tpu "
                    "(see docs/lint.md).",
    )
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    ap.add_argument("--json", action="store_true",
                    help="emit the JSON report instead of terminal lines")
    ap.add_argument("--select", action="append", metavar="RULES",
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--disable", action="append", metavar="RULES",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--self-check", action="store_true",
                    help="lint the repo's own fia_tpu/, scripts/, bench.py")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(rule_catalog())
        return 0

    root = None
    paths = list(args.paths)
    if args.self_check:
        sc_paths, root = self_check_paths()
        paths.extend(sc_paths)
    if not paths:
        ap.print_usage(sys.stderr)
        print("fialint: no paths given (or use --self-check)",
              file=sys.stderr)
        return 2

    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"fialint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    result = lint_paths(
        paths,
        select=_parse_rule_set(args.select),
        disable=_parse_rule_set(args.disable),
        root=root,
    )
    print(json_report(result) if args.json else terminal_report(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
