"""FIA402 — bare ``print(`` in library code.

Everything under ``fia_tpu/`` except the CLI mains writes no stdout:
stdout is the machine-readable surface (the bench JSON line, the serve
CLI's response stream), and a stray ``print`` in library code either
corrupts that stream or vanishes into a log nobody reads. Human-facing
diagnostics route through :func:`fia_tpu.obs.diag` instead — one call
lands the note on stderr, bumps ``diag_total{channel=...}`` in the
metrics registry, and attaches a span event to the active trace, so
the message survives in every export the obs spine has.

Deliberate stdout contracts (the trainer's interactive step progress,
the reference-format model-eval report) carry an inline justified
suppression — which doubles as documentation of *why* that line owns
stdout.
"""

from __future__ import annotations

import ast

from fia_tpu.analysis import config
from fia_tpu.analysis.core import FileRule, Finding, SourceFile, register
from fia_tpu.analysis.visitor import call_name


@register
class BarePrintRule(FileRule):
    """Bare print() in fia_tpu/ library code (diagnostics go via obs)."""

    id = "FIA402"
    name = "bare-print-in-library"

    def check(self, sf: SourceFile):
        rel = sf.rel
        if not rel.startswith(config.OBS_PRINT_SCOPE):
            return []
        if any(rel.startswith(p)
               for p in config.OBS_PRINT_EXEMPT_PREFIXES):
            return []
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and call_name(node) == "print":
                findings.append(Finding(
                    self.id, rel, node.lineno, node.col_offset,
                    "bare print() in library code — route diagnostics "
                    "through fia_tpu.obs.diag (stderr + metrics counter "
                    "+ span event) or the JSONL event stream; stdout "
                    "belongs to CLI mains",
                ))
        return findings
