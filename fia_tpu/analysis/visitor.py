"""Visitor framework and shared AST helpers for fialint rules.

Rules subclass :class:`RuleVisitor` (an ``ast.NodeVisitor`` carrying
the :class:`~fia_tpu.analysis.core.SourceFile` and a findings sink) or
use the free helpers directly. The jit-scope machinery lives here too
because three trace-hygiene rules share it.
"""

from __future__ import annotations

import ast

from fia_tpu.analysis.core import Finding, SourceFile


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's callee, else None (lambdas, subscripts)."""
    return dotted_name(node.func)


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def literal_or_none(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


class RuleVisitor(ast.NodeVisitor):
    """NodeVisitor with the source file and a findings sink attached."""

    def __init__(self, rule_id: str, sf: SourceFile):
        self.rule_id = rule_id
        self.sf = sf
        self.findings: list[Finding] = []

    def flag(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            self.rule_id, self.sf.rel,
            getattr(node, "lineno", 1), getattr(node, "col_offset", 0),
            message,
        ))

    def run(self) -> list[Finding]:
        if self.sf.tree is not None:
            self.visit(self.sf.tree)
        return self.findings


# ---------------------------------------------------------------------
# jit-scope detection (shared by the FIA2xx trace-hygiene rules)
# ---------------------------------------------------------------------

_JIT_CALLEES = {
    "jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit",
    "jax.experimental.pjit.pjit",
}
# wrappers whose first positional argument is (eventually) the traced fn
_UNWRAP_CALLEES = {
    "jax.vmap", "vmap", "jax.pmap", "pmap", "partial",
    "functools.partial", "jax.grad", "grad", "jax.value_and_grad",
    "value_and_grad", "jax.checkpoint", "jax.remat",
}


def _terminal_fn_name(node: ast.AST) -> str | None:
    """Unwrap ``vmap(partial(self._f, ...))`` chains to the innermost
    function's bare name (``_f``)."""
    while isinstance(node, ast.Call):
        cn = call_name(node)
        if cn in _UNWRAP_CALLEES and node.args:
            node = node.args[0]
            continue
        return None
    name = dotted_name(node)
    if name is None:
        return None
    return name.split(".")[-1]


def _static_argnums_of(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = literal_or_none(kw.value)
            if isinstance(v, int):
                return (v,)
            if isinstance(v, (tuple, list)):
                return tuple(int(x) for x in v if isinstance(x, int))
    return ()


class JitIndex:
    """Which function defs in a module are jit-traced, and with which
    static argument positions.

    Detected, in one AST pass over the module:

    - defs decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``;
    - defs whose *name* is passed (possibly through ``vmap`` /
      ``partial`` / ``grad`` wrappers) into a ``jax.jit(...)`` call
      anywhere in the module (the engine's ``self._jitted[k] =
      jax.jit(fn)`` idiom);
    - names registered in ``config.REGISTERED_JIT_ENTRY_POINTS`` for
      this file — entry points reached through indirection the AST
      cannot see (e.g. a method referenced only inside a ``vmap``
      assigned to a local that a later jitted closure calls).
    """

    def __init__(self, sf: SourceFile):
        from fia_tpu.analysis import config

        self.jitted_names: dict[str, tuple[int, ...]] = {}
        for entry in config.REGISTERED_JIT_ENTRY_POINTS:
            suffix, name = entry[0], entry[1]
            # optional third element: explicit static positions; default
            # (0,) covers the bound-method case (self static)
            statics = tuple(entry[2]) if len(entry) > 2 else (0,)
            if sf.rel.endswith(suffix):
                self.jitted_names[name] = statics
        if sf.tree is None:
            return
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and call_name(node) in _JIT_CALLEES:
                if node.args:
                    fn = _terminal_fn_name(node.args[0])
                    if fn:
                        self.jitted_names.setdefault(
                            fn, _static_argnums_of(node)
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    statics: tuple[int, ...] | None = None
                    if isinstance(dec, ast.Call):
                        cn = call_name(dec)
                        if cn in _JIT_CALLEES:
                            statics = _static_argnums_of(dec)
                        elif cn in ("partial", "functools.partial") and (
                            dec.args
                            and dotted_name(dec.args[0]) in _JIT_CALLEES
                        ):
                            statics = _static_argnums_of(dec)
                    elif dotted_name(dec) in _JIT_CALLEES:
                        statics = ()
                    if statics is not None:
                        self.jitted_names[node.name] = statics

    def is_jitted(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
        return fn.name in self.jitted_names

    def traced_params(
        self, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> set[str]:
        """Parameter names traced at call time: positional/kw params
        minus ``self`` and the declared static positions. Vararg packs
        are traced (``fn(*a)`` receives traced operands)."""
        statics = set(self.jitted_names.get(fn.name, ()))
        params = list(fn.args.posonlyargs) + list(fn.args.args)
        traced: set[str] = set()
        for i, a in enumerate(params):
            if i in statics or a.arg == "self":
                continue
            traced.add(a.arg)
        for a in fn.args.kwonlyargs:
            traced.add(a.arg)
        if fn.args.vararg:
            traced.add(fn.args.vararg.arg)
        return traced


def iter_jitted_defs(sf: SourceFile):
    """Yield ``(funcdef, jit_index, enclosing_funcdef_or_None)`` for
    every jit-traced def in the file."""
    idx = JitIndex(sf)
    if sf.tree is None or not idx.jitted_names:
        return

    def walk(node, enclosing):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if idx.is_jitted(child):
                    yield child, idx, enclosing
                yield from walk(child, child)
            else:
                yield from walk(child, enclosing)

    yield from walk(sf.tree, None)
