"""Project-wide call graph over the lint file set.

The FIA2xx family polices *one function at a time*; the FIA5xx
determinism family needs to follow a value from the function where a
nondeterministic read happens to the (possibly distant) function where
its result is byte-pinned. This module builds the structure that makes
that possible without importing anything: a name-resolution index per
module (imports, from-imports, defs, classes, jit/partial aliases) and
a resolver that turns an ``ast.Call`` inside a known function into
either a project-internal :class:`FuncDef` or a *canonical* external
dotted name (``np.random.rand`` → ``numpy.random.rand``,
``_time.monotonic`` → ``time.monotonic``).

Resolution is deliberately the same shape the FIA2xx machinery uses —
``jax.jit(fn)`` / ``vmap(partial(self._f, ...))`` wrapper chains are
unwrapped to the terminal function (``visitor._terminal_fn_name``'s
logic, generalised to return the full dotted target), so a jit-wrapped
sink is still a sink and a vmapped source still a source.

Known limits (documented, not silent): attribute calls on arbitrary
objects (``self.journal.record``) resolve only to their bare attribute
name; instance state (``self.x = ...`` in one method, read in another)
is not tracked; subscripted callees (``self._jitted[k](...)``) do not
resolve. The dataflow layer treats unresolved calls conservatively
(argument taint passes through to the result).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from fia_tpu.analysis.core import SourceFile
from fia_tpu.analysis.visitor import _JIT_CALLEES, _UNWRAP_CALLEES, dotted_name


@dataclass
class FuncDef:
    """One function/method definition in the project."""

    rel: str            # repo-relative file
    qualpath: str       # "fn", "Class.method", "outer.inner", "<module>"
    node: ast.AST       # FunctionDef/AsyncFunctionDef, or Module for
                        # the synthetic top-level pseudo-function
    sf: SourceFile
    class_name: str | None = None

    @property
    def qual(self) -> str:
        return f"{self.rel}::{self.qualpath}"

    @property
    def display(self) -> str:
        return self.qualpath if self.qualpath != "<module>" else "<module>"

    def body_statements(self) -> list[ast.stmt]:
        if isinstance(self.node, ast.Module):
            return [
                s for s in self.node.body
                if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Import,
                                      ast.ImportFrom))
            ]
        return list(self.node.body)

    def param_names(self) -> list[str]:
        if isinstance(self.node, ast.Module):
            return []
        a = self.node.args
        names = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        names += [p.arg for p in a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


@dataclass
class ModuleInfo:
    """Per-module name-resolution tables."""

    rel: str
    sf: SourceFile
    dotted: str                                  # "fia_tpu.serve.cache"
    imports: dict[str, str] = field(default_factory=dict)      # alias -> module
    from_imports: dict[str, tuple[str, str]] = field(
        default_factory=dict)                    # name -> (module, attr)
    defs: dict[str, FuncDef] = field(default_factory=dict)     # qualpath -> def
    classes: dict[str, dict[str, FuncDef]] = field(
        default_factory=dict)                    # class -> {method -> def}
    bases: dict[str, list[str]] = field(default_factory=dict)  # class -> bases
    aliases: dict[str, str] = field(default_factory=dict)      # name -> qualpath


def module_dotted(rel: str) -> str:
    """Repo-relative path → importable dotted module name."""
    p = rel[:-3] if rel.endswith(".py") else rel
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


_WRAPPERS = _JIT_CALLEES | _UNWRAP_CALLEES


def unwrap_wrapped(node: ast.AST) -> ast.AST:
    """Strip ``jit``/``vmap``/``partial``/``grad`` wrapper calls down to
    the terminal callee expression: ``jax.jit(vmap(partial(f, a)))``
    → the ``f`` node. Non-wrapper nodes pass through unchanged."""
    while isinstance(node, ast.Call):
        cn = dotted_name(node.func)
        if cn in _WRAPPERS and node.args:
            node = node.args[0]
            continue
        break
    return node


class CallGraph:
    """Name resolution across every module in one lint invocation."""

    def __init__(self, files: list[SourceFile]):
        self.modules: dict[str, ModuleInfo] = {}      # rel -> info
        self.by_dotted: dict[str, ModuleInfo] = {}    # dotted -> info
        self.functions: list[FuncDef] = []
        for sf in files:
            if sf.tree is None or not sf.rel.endswith(".py"):
                continue
            mi = self._index_module(sf)
            self.modules[sf.rel] = mi
            self.by_dotted[mi.dotted] = mi

    # -- indexing ------------------------------------------------------

    def _index_module(self, sf: SourceFile) -> ModuleInfo:
        mi = ModuleInfo(rel=sf.rel, sf=sf, dotted=module_dotted(sf.rel))
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue  # relative imports: not used in this repo
                for a in node.names:
                    mi.from_imports[a.asname or a.name] = (
                        node.module, a.name
                    )
        self._index_defs(mi, sf.tree, prefix="", class_name=None)
        # synthetic pseudo-function for module-level statements
        top = FuncDef(rel=sf.rel, qualpath="<module>", node=sf.tree, sf=sf)
        mi.defs["<module>"] = top
        self.functions.append(top)
        # module-level aliases: NAME = jax.jit(fn) / NAME = fn
        for stmt in sf.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                target = unwrap_wrapped(stmt.value)
                tn = dotted_name(target)
                if tn and tn in mi.defs:
                    mi.aliases[stmt.targets[0].id] = tn
        return mi

    def _index_defs(self, mi: ModuleInfo, node: ast.AST, prefix: str,
                    class_name: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qp = f"{prefix}{child.name}"
                fd = FuncDef(rel=mi.rel, qualpath=qp, node=child,
                             sf=mi.sf, class_name=class_name)
                mi.defs[qp] = fd
                self.functions.append(fd)
                if class_name is not None and prefix.count(".") == 1:
                    mi.classes.setdefault(class_name, {})[child.name] = fd
                self._index_defs(mi, child, prefix=f"{qp}.",
                                 class_name=class_name)
            elif isinstance(child, ast.ClassDef):
                mi.classes.setdefault(child.name, {})
                mi.bases[child.name] = [
                    b for b in (dotted_name(x) for x in child.bases) if b
                ]
                self._index_defs(mi, child, prefix=f"{child.name}.",
                                 class_name=child.name)
            else:
                self._index_defs(mi, child, prefix=prefix,
                                 class_name=class_name)

    # -- resolution ----------------------------------------------------

    def canonical(self, mi: ModuleInfo, dotted: str) -> str:
        """Rewrite a dotted name through the module's import tables:
        ``np.random.rand`` → ``numpy.random.rand``, a bare from-import
        → its defining module's dotted path."""
        parts = dotted.split(".")
        root = parts[0]
        if root in mi.from_imports:
            module, attr = mi.from_imports[root]
            return ".".join([module, attr] + parts[1:])
        if root in mi.imports:
            return ".".join([mi.imports[root]] + parts[1:])
        return dotted

    def _lookup_local(self, mi: ModuleInfo, caller: FuncDef,
                      name: str) -> FuncDef | None:
        """Bare-name lookup: nested def of the caller, then module
        scope, then module-level jit/partial aliases."""
        if caller.qualpath != "<module>":
            nested = mi.defs.get(f"{caller.qualpath}.{name}")
            if nested is not None:
                return nested
        fd = mi.defs.get(name)
        if fd is not None:
            return fd
        alias = mi.aliases.get(name)
        if alias is not None:
            return mi.defs.get(alias)
        return None

    def _lookup_method(self, mi: ModuleInfo, class_name: str,
                       method: str, _depth: int = 0) -> FuncDef | None:
        """Method lookup in a class, then single-inheritance walk up
        base classes resolvable within the project."""
        if _depth > 4:
            return None
        methods = mi.classes.get(class_name)
        if methods and method in methods:
            return methods[method]
        for base in mi.bases.get(class_name, []):
            base_mi, base_cls = self._resolve_class(mi, base)
            if base_mi is not None:
                fd = self._lookup_method(base_mi, base_cls, method,
                                         _depth + 1)
                if fd is not None:
                    return fd
        return None

    def _resolve_class(self, mi: ModuleInfo,
                       name: str) -> tuple[ModuleInfo | None, str]:
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in mi.classes:
                return mi, parts[0]
            if parts[0] in mi.from_imports:
                module, attr = mi.from_imports[parts[0]]
                target = self.by_dotted.get(module)
                if target is not None and attr in target.classes:
                    return target, attr
        elif len(parts) == 2 and parts[0] in mi.imports:
            target = self.by_dotted.get(mi.imports[parts[0]])
            if target is not None and parts[1] in target.classes:
                return target, parts[1]
        return None, ""

    def resolve_call(
        self, caller: FuncDef, call: ast.Call,
        local_aliases: dict[str, FuncDef] | None = None,
    ) -> tuple[FuncDef | None, str | None]:
        """Resolve a call inside ``caller``.

        Returns ``(funcdef, canonical_name)``: a project-internal
        target when resolution succeeds (jit/vmap/partial wrappers
        unwrapped), plus the import-canonicalised dotted name for
        source/sink matching against external registries. Either half
        may be None.
        """
        mi = self.modules.get(caller.rel)
        if mi is None:
            return None, None
        func = unwrap_wrapped(call.func) if isinstance(
            call.func, ast.Call) else call.func
        name = dotted_name(func)
        if name is None:
            return None, None
        parts = name.split(".")
        root = parts[0]
        canonical = self.canonical(mi, name)

        # local alias bound to a known def (g = jax.jit(self._f); g(x))
        if local_aliases and len(parts) == 1 and root in local_aliases:
            return local_aliases[root], canonical

        # self.method() (and single-level base classes)
        if root == "self" and caller.class_name and len(parts) == 2:
            fd = self._lookup_method(mi, caller.class_name, parts[1])
            return fd, canonical

        # bare name: nested def / module def / module alias, then a
        # from-imported function defined in another project module
        if len(parts) == 1:
            fd = self._lookup_local(mi, caller, root)
            if fd is None and root in mi.from_imports:
                module, attr = mi.from_imports[root]
                target = self.by_dotted.get(module)
                if target is not None:
                    fd = target.defs.get(attr)
            return fd, canonical

        # imported-module attribute: inject.fire(...) / io.save_json_atomic
        if root in mi.imports:
            target = self.by_dotted.get(mi.imports[root])
            if target is not None:
                return target.defs.get(".".join(parts[1:])), canonical
        # from-imported name with attribute tail: Klass.method / mod.fn
        if root in mi.from_imports:
            module, attr = mi.from_imports[root]
            target = self.by_dotted.get(module)
            if target is not None:
                return (
                    target.defs.get(".".join([attr] + parts[1:])),
                    canonical,
                )
            # ``from pkg import module`` style: pkg.module may itself
            # be a project module
            target = self.by_dotted.get(f"{module}.{attr}")
            if target is not None:
                return target.defs.get(".".join(parts[1:])), canonical
        return None, canonical

    def resolve_value(
        self, caller: FuncDef, node: ast.AST,
    ) -> FuncDef | None:
        """Resolve a non-call expression that names a function — the
        alias-building half (``g = jax.jit(self._f)`` needs ``_f``)."""
        mi = self.modules.get(caller.rel)
        if mi is None:
            return None
        node = unwrap_wrapped(node)
        name = dotted_name(node)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] == "self" and caller.class_name and len(parts) == 2:
            return self._lookup_method(mi, caller.class_name, parts[1])
        if len(parts) == 1:
            fd = self._lookup_local(mi, caller, parts[0])
            if fd is None and parts[0] in mi.from_imports:
                module, attr = mi.from_imports[parts[0]]
                target = self.by_dotted.get(module)
                if target is not None:
                    fd = target.defs.get(attr)
            return fd
        if parts[0] in mi.imports:
            target = self.by_dotted.get(mi.imports[parts[0]])
            if target is not None:
                return target.defs.get(".".join(parts[1:]))
        if parts[0] in mi.from_imports:
            module, attr = mi.from_imports[parts[0]]
            target = self.by_dotted.get(module)
            if target is not None:
                return target.defs.get(".".join([attr] + parts[1:]))
        return None
