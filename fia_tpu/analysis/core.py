"""Lint-engine core: findings, rule registry, suppressions, driver.

The engine is deliberately dependency-free (stdlib ``ast`` only) so it
can run before anything heavy imports — `bench.py --lint` uses it as a
preflight without paying a JAX import, and `make lint` gates tier-1.

Architecture:

- :class:`SourceFile` — one parsed file: source text, AST, and the
  per-line suppression map parsed from ``# fialint:`` comments.
- :class:`FileRule` — checks one file at a time (most rules).
- :class:`ProjectRule` — checks cross-file invariants (site registry
  vs docs, emitted metrics schema vs consumer) and runs once per
  invocation over every collected file plus the repo root.
- :func:`lint_paths` — the driver: collect, parse, run rules, apply
  suppressions, return a :class:`LintResult`.

Suppression syntax (justification REQUIRED)::

    risky_call()  # fialint: disable=FIA101 -- one-line justification

or, when the justification doesn't fit inline, as a standalone comment
line immediately above the flagged statement::

    # fialint: disable=FIA101 -- one-line justification
    risky_call()

A suppression with no ``-- justification`` tail, an unknown rule id,
or an empty justification is itself a finding (``FIA001``) — the
acceptance bar is "clean modulo *justified* suppressions", so the
engine enforces the justification, not convention.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

# Rule ids the engine itself emits (not registered rules).
PARSE_ERROR = "FIA000"
BAD_SUPPRESSION = "FIA001"

_SUPPRESS_RE = re.compile(
    r"#\s*fialint:\s*disable=(?P<rules>[A-Za-z0-9_,\s]*?)"
    r"(?:\s*--\s*(?P<why>.*?))?\s*$"
)

_RULE_ID_RE = re.compile(r"^FIA\d{3}$")


@dataclass(frozen=True)
class Finding:
    """One lint violation, anchored to a repo-relative location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class SourceFile:
    """A parsed source file plus its suppression map."""

    path: str  # absolute
    rel: str  # repo-relative, posix separators
    text: str
    tree: ast.AST | None
    # line -> rule ids suppressed on that line
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    # engine-level findings discovered while parsing (FIA000/FIA001)
    engine_findings: list[Finding] = field(default_factory=list)


class Rule:
    """Base: ``id`` like ``FIA101``, ``name`` a short kebab slug."""

    id: str = ""
    name: str = ""

    def describe(self) -> str:
        return (self.__doc__ or "").strip().splitlines()[0]


class FileRule(Rule):
    def check(self, sf: SourceFile) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


class ProjectRule(Rule):
    def check_project(
        self, files: list[SourceFile], root: str
    ) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: add a rule to the global registry (id-unique)."""
    inst = rule_cls()
    if not _RULE_ID_RE.match(inst.id):
        raise ValueError(f"bad rule id {inst.id!r} on {rule_cls.__name__}")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return rule_cls


def all_rules() -> dict[str, Rule]:
    _load_builtin_rules()
    return dict(_REGISTRY)


_loaded = False


def _load_builtin_rules() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    # import for registration side effects
    from fia_tpu.analysis import (  # noqa: F401
        rules_determinism,
        rules_io,
        rules_obs,
        rules_schema,
        rules_sites,
        rules_trace,
    )


def _comment_tokens(text: str):
    """(lineno, comment_text, standalone) for every real COMMENT token —
    docstrings and string literals that merely *mention* fialint don't
    count. ``standalone`` is True when the comment is the whole line
    (nothing but whitespace before it)."""
    lines = text.splitlines()
    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                lineno, col = tok.start
                before = lines[lineno - 1][:col] if lineno <= len(lines) else ""
                yield lineno, tok.string, not before.strip()
    except (tokenize.TokenizeError, IndentationError, SyntaxError):
        return  # the parse-error finding already covers unreadable files


def parse_suppressions(sf: SourceFile) -> None:
    """Fill ``sf.suppressions`` and emit FIA001 for malformed ones."""
    for lineno, line, standalone in _comment_tokens(sf.text):
        if "fialint" not in line:
            continue
        m = _SUPPRESS_RE.search(line)
        if not m:
            sf.engine_findings.append(Finding(
                BAD_SUPPRESSION, sf.rel, lineno, 0,
                "unparseable fialint comment (expected "
                "'# fialint: disable=RULEID -- justification')",
            ))
            continue
        ids = [r.strip() for r in m.group("rules").split(",") if r.strip()]
        why = (m.group("why") or "").strip()
        bad = [r for r in ids if not _RULE_ID_RE.match(r)
               or r not in all_rules()]
        if not ids:
            sf.engine_findings.append(Finding(
                BAD_SUPPRESSION, sf.rel, lineno, 0,
                "suppression lists no rule ids",
            ))
            continue
        if bad:
            sf.engine_findings.append(Finding(
                BAD_SUPPRESSION, sf.rel, lineno, 0,
                f"suppression names unknown rule(s): {', '.join(bad)}",
            ))
            continue
        if not why:
            sf.engine_findings.append(Finding(
                BAD_SUPPRESSION, sf.rel, lineno, 0,
                "suppression carries no justification "
                "(append ' -- why this line is exempt')",
            ))
            continue
        sf.suppressions.setdefault(lineno, set()).update(ids)
        if standalone:
            # a comment-only line shields the statement below it
            sf.suppressions.setdefault(lineno + 1, set()).update(ids)


SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "output",
             "build", "dist"}


def collect_files(paths: list[str]) -> list[str]:
    """Expand files/dirs into a sorted, de-duplicated list of .py files."""
    out: set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.add(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in SKIP_DIRS and not d.startswith(".")
                )
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.add(os.path.join(dirpath, fn))
    return sorted(out)


def find_root(paths: list[str]) -> str:
    """Repo root = nearest ancestor of the first path with pyproject.toml."""
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    d = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return (os.path.abspath(paths[0]) if paths else os.getcwd())
        d = parent


def _relpath(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:  # different drive (windows)
        rel = path
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


def load_source_file(path: str, root: str) -> SourceFile:
    with open(path, encoding="utf-8", errors="replace") as fh:
        text = fh.read()
    rel = _relpath(path, root)
    sf = SourceFile(path=path, rel=rel, text=text, tree=None)
    try:
        sf.tree = ast.parse(text, filename=rel)
    except SyntaxError as e:
        sf.engine_findings.append(Finding(
            PARSE_ERROR, rel, e.lineno or 1, e.offset or 0,
            f"syntax error: {e.msg}",
        ))
    parse_suppressions(sf)
    return sf


class LintContext:
    """Per-invocation shared state: the parsed-module cache and rule
    memos.

    Before this existed every ProjectRule that needed a registry module
    (FIA301/FIA303 both parse ``sites.py``, FIA401 parses the metrics
    module, the obs schema and every consumer) re-opened and re-parsed
    it from disk once per rule invocation — even though the very same
    file was already sitting, parsed, in the invocation's file list.
    The context indexes the collected :class:`SourceFile` set by
    repo-relative path and lazily loads (then caches) anything outside
    it, so one ``make lint`` parses each file exactly once. ``memo``
    gives expensive cross-rule artifacts (the FIA5xx call-graph +
    dataflow run, shared by six rules) the same once-per-invocation
    lifetime.
    """

    def __init__(self, files: list[SourceFile], root: str):
        self.files = files
        self.root = root
        self._by_rel: dict[str, SourceFile] = {sf.rel: sf for sf in files}
        self._memos: dict[str, object] = {}

    def module(self, rel: str) -> SourceFile | None:
        """The parsed module at repo-relative ``rel`` (cached), or None
        when the file is missing. Parse failures return the SourceFile
        with ``tree=None`` — callers distinguish missing from broken."""
        sf = self._by_rel.get(rel)
        if sf is None:
            path = os.path.join(self.root, rel.replace("/", os.sep))
            if not os.path.isfile(path):
                return None
            sf = load_source_file(path, self.root)
            self._by_rel[rel] = sf
        return sf

    def memo(self, key: str, build):
        """``build()`` once per invocation, cached under ``key``."""
        if key not in self._memos:
            self._memos[key] = build()
        return self._memos[key]


# The active invocation's context. lint_paths installs one for the
# duration of the rule run; rules reach it via current_context() so the
# ProjectRule.check_project(files, root) signature stays stable.
_CONTEXT: LintContext | None = None


def current_context() -> LintContext | None:
    return _CONTEXT


def parsed_module(root: str, rel: str) -> ast.AST | None:
    """Parsed AST of the module at ``rel`` under ``root``, or None when
    missing/unparseable. Served from the active invocation's parse
    cache when one is installed (the ``lint_paths`` rule run), so
    registry modules already collected for linting are never re-read
    from disk; falls back to a direct load for standalone callers."""
    ctx = _CONTEXT
    if ctx is not None and ctx.root == root:
        sf = ctx.module(rel)
        return sf.tree if sf is not None else None
    path = os.path.join(root, rel.replace("/", os.sep))
    if not os.path.isfile(path):
        return None
    return load_source_file(path, root).tree


@dataclass
class LintResult:
    findings: list[Finding]
    suppressed: list[Finding]
    files_checked: int
    root: str

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "files_checked": self.files_checked,
            "counts": _counts(self.findings),
            "suppressed_counts": _counts(self.suppressed),
            "findings": [f.as_dict() for f in self.findings],
        }


def _counts(findings: list[Finding]) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return dict(sorted(out.items()))


def _sort_key(f: Finding):
    return (f.path, f.line, f.col, f.rule, f.message)


def lint_paths(
    paths: list[str],
    select: set[str] | None = None,
    disable: set[str] | None = None,
    root: str | None = None,
) -> LintResult:
    """Run the registered rules over ``paths``.

    ``select``: run only these rule ids. ``disable``: skip these.
    Engine findings (FIA000 parse errors, FIA001 bad suppressions) are
    always reported and never suppressible.
    """
    rules = all_rules()
    active = {
        rid: r for rid, r in rules.items()
        if (select is None or rid in select)
        and (disable is None or rid not in disable)
    }
    root = root or find_root(paths)
    files = [load_source_file(p, root) for p in collect_files(paths)]

    global _CONTEXT
    prev_ctx, _CONTEXT = _CONTEXT, LintContext(files, root)
    try:
        raw: list[Finding] = []
        for sf in files:
            raw.extend(sf.engine_findings)
            if sf.tree is None:
                continue
            for rule in active.values():
                if isinstance(rule, FileRule):
                    raw.extend(rule.check(sf))
        for rule in active.values():
            if isinstance(rule, ProjectRule):
                raw.extend(rule.check_project(files, root))
    finally:
        _CONTEXT = prev_ctx

    supp_map = {sf.rel: sf.suppressions for sf in files}
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        if f.rule in (PARSE_ERROR, BAD_SUPPRESSION):
            kept.append(f)
            continue
        ids = supp_map.get(f.path, {}).get(f.line, set())
        (suppressed if f.rule in ids else kept).append(f)
    return LintResult(
        findings=sorted(set(kept), key=_sort_key),
        suppressed=sorted(set(suppressed), key=_sort_key),
        files_checked=len(files),
        root=root,
    )
