"""FIA401 — serving metrics schema consistency.

``serve/metrics.py`` declares the stable event schema (``SCHEMA``:
event name → field names) that operators build dashboards on;
``scripts/latency_report.py`` declares what it reads (``CONSUMES``).
This rule cross-checks the two against each other and against the
actual ``EventLog.log(...)`` call sites in ``fia_tpu/serve/``, so a
renamed field or a new event can't silently decouple the producer
from the report:

- every event literal logged under ``fia_tpu/serve/`` must be a
  ``SCHEMA`` key;
- statically visible keyword fields at those call sites must be
  declared for that event;
- every event/field in ``CONSUMES`` must exist in ``SCHEMA``.

Since the obs spine landed the checked schema is the UNION of the
serving ``SCHEMA`` and the obs event schema
(``fia_tpu/obs/events.py`` ``SCHEMA`` — the ``obs.span`` /
``obs.metrics`` lines the service mirrors into the same JSONL
stream), and the consumer side covers every declared consumer
(``config.OBS_CONSUMERS``: the latency report plus the
``fia_tpu.cli.obs`` reader), in BOTH directions:

- each consumer's ``CONSUMES`` ⊆ the union schema (a renamed field
  breaks the reader loudly);
- every ``obs.*`` event in the obs schema is consumed by at least one
  declared consumer (an event nobody reads is dead weight on the hot
  path).

Extra consumers and the obs schema are checked only when their files
exist under the lint root, so foreign/synthetic trees lint clean.

``t`` and ``event`` are implicit (EventLog stamps them on every
record).
"""

from __future__ import annotations

import ast
import os

from fia_tpu.analysis import config, core
from fia_tpu.analysis.core import Finding, ProjectRule, SourceFile, register
from fia_tpu.analysis.visitor import const_str, literal_or_none


def _load_decl(root: str, rel: str, name: str):
    """literal_eval a module-level ``NAME = {...}`` declaration.

    Returns ``(mapping, lineno)`` or ``(None, reason)``. The module
    comes from the invocation parse cache (``core.parsed_module``) —
    the schema/consumer files are already in the lint file set, so
    this never re-parses them from disk.
    """
    tree = core.parsed_module(root, rel)
    if tree is None:
        return None, f"{rel} missing or unparseable"
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            value = literal_or_none(node.value)
            if isinstance(value, dict):
                return (
                    {str(k): frozenset(v) for k, v in value.items()},
                    node.lineno,
                )
            return None, f"{rel}:{node.lineno} {name} is not a literal dict"
    return None, f"{rel} declares no module-level {name}"


def _log_calls(sf: SourceFile):
    """(node, event_literal, visible_kwarg_names) for EventLog-style
    ``*.log("event.name", field=...)`` calls."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "log"):
            continue
        if not node.args:
            continue
        event = const_str(node.args[0])
        if event is None or "." not in event:
            continue  # not a schema'd serving event
        kwargs = {kw.arg for kw in node.keywords if kw.arg is not None}
        yield node, event, kwargs


@register
class MetricsSchemaRule(ProjectRule):
    """Emitted serving events and the latency report must agree."""

    id = "FIA401"
    name = "metrics-schema-drift"

    def check_project(self, files: list[SourceFile], root: str):
        findings: list[Finding] = []
        in_scope = [
            sf for sf in files
            if sf.tree is not None and config.METRICS_SCOPE in sf.rel
        ]
        schema, schema_ref = _load_decl(
            root, config.METRICS_MODULE, "SCHEMA"
        )
        if schema is None:
            # only demand the declaration when serving code is actually
            # being linted — foreign trees have no serving schema
            if in_scope:
                findings.append(Finding(
                    self.id, config.METRICS_MODULE, 1, 0,
                    "missing serving metrics schema declaration: "
                    f"{schema_ref}",
                ))
            return findings
        implicit = config.METRICS_IMPLICIT_FIELDS

        # obs extension: union in the tracing/metrics event schema when
        # present (absent in synthetic/foreign trees — plain serve-only
        # checking then)
        obs_schema: dict = {}
        if os.path.exists(os.path.join(root, config.OBS_MODULE)):
            obs_schema, obs_ref = _load_decl(
                root, config.OBS_MODULE, "SCHEMA"
            )
            if obs_schema is None:
                findings.append(Finding(
                    self.id, config.OBS_MODULE, 1, 0,
                    f"missing obs event schema declaration: {obs_ref}",
                ))
                obs_schema = {}
        union = {**schema, **obs_schema}

        # producer side: every .log("x.y", ...) in fia_tpu/serve/
        for sf in in_scope:
            for node, event, kwargs in _log_calls(sf):
                if event not in union:
                    findings.append(Finding(
                        self.id, sf.rel, node.lineno, node.col_offset,
                        f"event {event!r} is not declared in "
                        f"{config.METRICS_MODULE} SCHEMA (or the obs "
                        f"schema at {config.OBS_MODULE})",
                    ))
                    continue
                undeclared = sorted(kwargs - union[event] - implicit)
                if undeclared:
                    findings.append(Finding(
                        self.id, sf.rel, node.lineno, node.col_offset,
                        f"event {event!r} emits undeclared field(s) "
                        f"{', '.join(undeclared)} (add to SCHEMA or drop)",
                    ))

        # consumer side: each declared consumer's CONSUMES ⊆ the union
        # schema. The latency report is mandatory (the original
        # contract); extra obs consumers are checked when present.
        consumers = [config.METRICS_CONSUMER] + [
            c for c in config.OBS_CONSUMERS
            if c != config.METRICS_CONSUMER
            and os.path.exists(os.path.join(root, c))
        ]
        consumed_events: set[str] = set()
        for rel in consumers:
            consumes, c_ref = _load_decl(root, rel, "CONSUMES")
            if consumes is None:
                findings.append(Finding(
                    self.id, rel, 1, 0,
                    f"missing consumer declaration: {c_ref}",
                ))
                continue
            consumed_events |= set(consumes)
            for event, fields in sorted(consumes.items()):
                if event not in union:
                    findings.append(Finding(
                        self.id, rel, 1, 0,
                        f"consumer {rel} reads unknown event {event!r}",
                    ))
                    continue
                missing = sorted(set(fields) - union[event] - implicit)
                if missing:
                    findings.append(Finding(
                        self.id, rel, 1, 0,
                        f"consumer {rel} reads field(s) "
                        f"{', '.join(missing)} that {event!r} does not "
                        f"emit (SCHEMA at "
                        f"{config.METRICS_MODULE}:{schema_ref})",
                    ))

        # reverse direction: every obs.* event someone emits must have
        # at least one declared reader — an exported event nobody
        # consumes is hot-path weight with no dashboard behind it
        for event in sorted(set(obs_schema) - consumed_events):
            findings.append(Finding(
                self.id, config.OBS_MODULE, 1, 0,
                f"obs event {event!r} is declared but no consumer "
                f"({', '.join(consumers)}) reads it — wire it into a "
                "CONSUMES or drop the event",
            ))
        return findings
