"""`fialint` — repo-native static analysis for fia_tpu.

An AST-based lint engine (`python -m fia_tpu.analysis.lint`) whose
rules encode the invariants this repo actually rides on and that no
general-purpose linter knows about:

- **FIA101 raw-write discipline** — every persisted byte goes through
  `utils/io.py` / `reliability/artifacts.py` (the fsync'd-atomic +
  checksummed-manifest path); a raw `open(.., "w")` elsewhere is how
  caches get torn.
- **FIA201/202/203 trace hygiene** — host syncs, Python control flow
  on traced values, and array closure capture inside jit-traced
  functions are the recompile/constant-baking hazards that wreck the
  serving path's latency.
- **FIA301/302/303 fault-site integrity** — injection-site literals
  must resolve to the checked-in registry
  (`reliability/sites.py`), reliability-layer raises must be
  classifiable, and `docs/reliability.md` must document every site.
- **FIA401 metrics schema consistency** — the serving events emitted
  by `serve/metrics.py` and the fields `scripts/latency_report.py`
  consumes are cross-checked against one declared schema.

See `docs/lint.md` for the rule catalog and suppression syntax
(`# fialint: disable=FIA101 -- justification`).
"""

from fia_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintResult,
    all_rules,
    lint_paths,
)
