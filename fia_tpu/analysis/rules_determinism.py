"""FIA501–FIA506 — the call-graph determinism family.

The repo's headline guarantees are bitwise: sharded == replicated to
the last mantissa bit, artifacts carry canonical fingerprints, cache
keys and journal entries replay byte-identically. Those contracts die
quietly when a nondeterministic value — an unseeded RNG draw, a
wall-clock read, an arbitrary listing order — leaks into something
byte-pinned, often through two or three intermediate calls where no
single function looks wrong. These six rules run the interprocedural
taint engine (:mod:`fia_tpu.analysis.dataflow`) over the project call
graph (:mod:`fia_tpu.analysis.callgraph`) and flag only *completed*
source→sink flows, with the call chain in the message:

- **FIA501 unseeded-rng-to-sink** — draws through numpy's legacy
  global generator (``np.random.rand``...), the stdlib ``random``
  module's hidden global state, zero-argument
  ``default_rng()``/``RandomState()``/``Random()``, and entropy reads
  (``uuid.uuid4``, ``os.urandom``, ``secrets.*``) reaching a sink.
- **FIA502 wallclock-to-sink** — ``time.*``/``datetime.now`` reads
  outside the injectable Clock seam (``reliability/policy.py``)
  reaching a *byte-pinned* sink. Metrics events are exempt for this
  rule: timestamps in the event stream ARE the observability contract.
- **FIA503 fs-order-to-sink** — ``os.listdir``/``glob.glob``/
  ``Path.iterdir`` enumeration order (filesystem-dependent) reaching a
  sink unsorted. ``sorted()`` on the listing kills the taint.
- **FIA504 unsorted-json-keys** — ``json.dump`` without
  ``sort_keys=True`` (flagged directly: it writes persisted bytes by
  definition), and ``json.dumps`` without it whose string reaches a
  sink.
- **FIA505 set-order-to-sink** — iteration order of a ``set`` (hash-
  seed dependent) reaching a sink; ``sorted(the_set)`` is the fix.
- **FIA506 identity-ordering-to-sink** — ``id()``/``hash()``-derived
  values or ``sorted(..., key=id)`` orderings reaching a sink.

All six share ONE call-graph + dataflow run per lint invocation
(memoized on the :class:`~fia_tpu.analysis.core.LintContext`).

Findings anchor at the SOURCE line, so a single justified
``# fialint: disable=FIA50x`` at the nondeterministic read suppresses
every chain that starts there — "suppress at the source propagates to
the chain". When only the *sink* line carries the suppression, the
finding re-anchors there instead, so either end of a flow is a valid
place to take responsibility for it.
"""

from __future__ import annotations

import ast

from fia_tpu.analysis import core
from fia_tpu.analysis.core import Finding, ProjectRule, SourceFile, register
from fia_tpu.analysis.dataflow import DataflowEngine, Flow, has_sort_keys
from fia_tpu.analysis.visitor import dotted_name


def _dataflow(files: list[SourceFile], root: str):
    """The invocation-shared (engine, flows) pair — one call-graph
    build and one taint fixpoint no matter how many FIA5xx rules run."""
    def build():
        engine = DataflowEngine(
            [sf for sf in files if sf.tree is not None], root
        )
        return engine, engine.run()

    ctx = core.current_context()
    if ctx is not None and ctx.root == root:
        return ctx.memo("determinism-dataflow", build)
    return build()


class _FlowRule(ProjectRule):
    """Shared driver: filter the flow set down to this rule's id and
    render findings with the source→sink chain in the message."""

    def check_project(self, files: list[SourceFile], root: str):
        _, flows = _dataflow(files, root)
        supp = {sf.rel: sf.suppressions for sf in files}
        return [
            self._finding(fl, supp) for fl in flows if fl.rule == self.id
        ]

    def _finding(self, fl: Flow, supp: dict) -> Finding:
        path, line, col = fl.source_rel, fl.source_line, fl.source_col
        at_source = self.id in supp.get(
            fl.source_rel, {}).get(fl.source_line, set())
        at_sink = self.id in supp.get(
            fl.sink_rel, {}).get(fl.sink_line, set())
        if at_sink and not at_source:
            # the sink line took responsibility for the flow: anchor
            # there so the core suppression machinery sees it
            path, line, col = fl.sink_rel, fl.sink_line, 0
        chain = " -> ".join(fl.chain)
        return Finding(
            self.id, path, line, col,
            f"{fl.desc} reaches {fl.sink_desc} at "
            f"{fl.sink_rel}:{fl.sink_line} (chain: {chain})",
        )


@register
class UnseededRngRule(_FlowRule):
    """Global/unseeded RNG draws must not reach byte-pinned outputs."""

    id = "FIA501"
    name = "unseeded-rng-to-sink"


@register
class WallclockRule(_FlowRule):
    """Wall-clock reads outside the Clock seam must not be byte-pinned."""

    id = "FIA502"
    name = "wallclock-to-sink"


@register
class FsOrderRule(_FlowRule):
    """Filesystem enumeration order must be sorted before it is pinned."""

    id = "FIA503"
    name = "fs-order-to-sink"


@register
class JsonSortKeysRule(_FlowRule):
    """Persisted JSON must pin key order with sort_keys=True."""

    id = "FIA504"
    name = "unsorted-json-keys"

    def check_project(self, files: list[SourceFile], root: str):
        # taint half: json.dumps strings that reach a sink
        findings = super().check_project(files, root)
        # direct half: json.dump writes persisted bytes by definition —
        # no flow analysis needed, the call site IS the sink
        engine, _ = _dataflow(files, root)
        for sf in files:
            if sf.tree is None:
                continue
            mi = engine.graph.modules.get(sf.rel)
            if mi is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                if (engine.graph.canonical(mi, name) == "json.dump"
                        and not has_sort_keys(node)):
                    findings.append(Finding(
                        self.id, sf.rel, node.lineno, node.col_offset,
                        "json.dump without sort_keys=True — persisted "
                        "JSON key order follows dict construction order "
                        "and breaks byte-stable fingerprints",
                    ))
        return findings


@register
class SetOrderRule(_FlowRule):
    """Set iteration order (hash-seed dependent) must not be pinned."""

    id = "FIA505"
    name = "set-order-to-sink"


@register
class IdentityOrderRule(_FlowRule):
    """id()/hash()-derived orderings must not reach pinned outputs."""

    id = "FIA506"
    name = "identity-ordering-to-sink"
