"""Source→sink determinism dataflow over the project call graph.

Every headline contract in this repo is a *determinism* contract —
sharded-vs-replicated bitwise identity, chunking-invariant reverse
top-k, replayable brownout ladders, pair-keyed reproducible sampling.
This engine is the static half of those contracts: it follows a value
from a nondeterministic read (an unseeded RNG draw, a wall-clock read
outside the Clock seam, an arbitrarily-ordered directory listing, a
``set`` iteration, an ``id()``/``hash()``-derived ordering) to a place
where the repo byte-pins bytes (published artifacts, journal and cache
fingerprints, metrics SCHEMA events, dispatch-path return values), and
reports the full call chain in between. A source that never reaches a
sink is *not* a finding — timing a solve for a log line is fine;
letting that timestamp into a fingerprinted artifact is not.

Architecture (docs/design.md §24):

- per-function **taint pass**: a source-order walk of one function
  body tracking which local names carry which taints. Assignments
  propagate, ``sorted()``/``min``/``len``-style calls sanitize
  (order-taints die at ``sorted``, value-taints like an RNG draw
  survive it), container mutations (``xs.append(tainted)``) taint the
  container.
- per-function **summary**: which taints escape through ``return``,
  which parameters pass through to the return value, and which
  parameters reach a sink inside the function (transitively).
- **fixpoint** over the call graph: summaries start empty and the
  passes repeat until no summary changes, so a chain
  ``a() → b() → c() → publish`` converges regardless of definition
  order. Flows are collected on the stable final pass.

Taint *kinds*: ``order`` (FIA503/505 and the ``key=id`` half of 506 —
the multiset of values is fine, their order is not; killed by
``sorted()``) and ``value`` (FIA501/502/504/506 — the bytes themselves
vary; survive sorting).

Known limits, by design (the engine is stdlib-``ast`` only, no type
inference): instance attributes are tracked only as whole-``self``
taint, implicit flows through comparisons/branch conditions are not
tracked, and subscripted callees (``self._jitted[k](...)``) do not
resolve — the conservative fallback passes argument taint through
unresolved calls.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, replace

from fia_tpu.analysis import config
from fia_tpu.analysis.callgraph import CallGraph, FuncDef
from fia_tpu.analysis.core import SourceFile
from fia_tpu.analysis.visitor import dotted_name

MAX_PASSES = 8
MAX_CHAIN = 8

ORDER = "order"
VALUE = "value"


@dataclass(frozen=True)
class Tag:
    """One live taint: which rule, where it was born, how it travelled."""

    rule: str
    kind: str            # ORDER or VALUE
    origin_rel: str
    origin_line: int
    origin_col: int
    desc: str            # e.g. "numpy.random.rand (global RNG draw)"
    via: tuple[str, ...]  # function displays, origin first


@dataclass(frozen=True)
class ParamTaint:
    """Placeholder taint seeded on parameter ``index`` to discover
    passthrough and param→sink behavior for the summary."""

    index: int


@dataclass(frozen=True)
class SinkRef:
    """A sink reachable from a parameter, for function summaries."""

    desc: str
    rel: str
    line: int
    path: tuple[str, ...]  # function displays from the summarised fn in


@dataclass(frozen=True)
class Summary:
    returns: frozenset      # of Tag
    passthrough: frozenset  # of int (param index flows to return)
    param_sinks: frozenset  # of (int, SinkRef)


EMPTY_SUMMARY = Summary(frozenset(), frozenset(), frozenset())


@dataclass(frozen=True)
class Flow:
    """One complete source→sink path (pre-Finding; the FIA5xx rules
    convert these, choosing the anchor line suppression-aware)."""

    rule: str
    source_rel: str
    source_line: int
    source_col: int
    desc: str
    sink_desc: str
    sink_rel: str
    sink_line: int
    chain: tuple[str, ...]


def has_sort_keys(call: ast.Call) -> bool:
    """True when a json.dump/json.dumps call pins key order."""
    for kw in call.keywords:
        if kw.arg == "sort_keys":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
        if kw.arg is None:
            return True  # **kwargs may carry it: benefit of the doubt
    return False


def _extend_via(tag: Tag, name: str) -> Tag:
    if tag.via and tag.via[-1] == name:
        return tag
    if len(tag.via) >= MAX_CHAIN:
        return tag
    return replace(tag, via=tag.via + (name,))


def _real(tags) -> set:
    return {t for t in tags if isinstance(t, Tag)}


def _dedup_tags(tags) -> frozenset:
    """Collapse via-variants of the same logical taint to the shortest
    chain. Without this, call cycles mint a new variant per fixpoint
    round and summaries never stabilise."""
    best: dict[tuple, Tag] = {}
    for t in tags:
        k = (t.rule, t.kind, t.origin_rel, t.origin_line,
             t.origin_col, t.desc)
        cur = best.get(k)
        if cur is None or (len(t.via), t.via) < (len(cur.via), cur.via):
            best[k] = t
    return frozenset(best.values())


def _dedup_sinks(pairs) -> frozenset:
    """Same normalisation for (param index, SinkRef) summary entries."""
    best: dict[tuple, tuple] = {}
    for i, s in pairs:
        k = (i, s.desc, s.rel, s.line)
        cur = best.get(k)
        if cur is None or (
            (len(s.path), s.path) < (len(cur[1].path), cur[1].path)
        ):
            best[k] = (i, s)
    return frozenset(best.values())


class _FunctionPass:
    """One taint pass over one function body."""

    def __init__(self, engine: "DataflowEngine", fd: FuncDef,
                 collect: bool):
        self.e = engine
        self.fd = fd
        self.collect = collect
        self.env: dict[str, set] = {}
        self.set_vars: set[str] = set()
        self.local_aliases: dict[str, FuncDef] = {}
        self.returns: set = set()
        self.passthrough: set[int] = set()
        self.param_sinks: set[tuple[int, SinkRef]] = set()
        self.flows: list[Flow] = []
        params = fd.param_names()
        self.param_index = {p: i for i, p in enumerate(params)}
        for p, i in self.param_index.items():
            self.env[p] = {ParamTaint(i)}

    # -- driver --------------------------------------------------------

    def run(self) -> Summary:
        for stmt in self.fd.body_statements():
            self.stmt(stmt)
        return Summary(
            returns=_dedup_tags(_real(self.returns)),
            passthrough=frozenset(
                t.index for t in self.returns if isinstance(t, ParamTaint)
            ),
            param_sinks=_dedup_sinks(self.param_sinks),
        )

    # -- statements ----------------------------------------------------

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # analysed as their own FuncDefs
        if isinstance(node, ast.Assign):
            taints = self.expr(node.value)
            is_set = self.expr_is_set(node.value)
            for tgt in node.targets:
                self.assign_target(tgt, taints, is_set, strong=True)
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                target_fd = self.e.graph.resolve_value(self.fd, node.value)
                if target_fd is not None:
                    self.local_aliases[node.targets[0].id] = target_fd
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self.assign_target(node.target, self.expr(node.value),
                               self.expr_is_set(node.value), strong=True)
        elif isinstance(node, ast.AugAssign):
            taints = self.expr(node.value)
            self.assign_target(node.target, taints,
                               self.expr_is_set(node.value), strong=False)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                taints = self.expr(node.value)
                self.returns |= taints
                self.check_return_sink(node, taints)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            taints = self.expr(node.iter) | self.iteration_tags(node.iter)
            self.assign_target(node.target, taints, False, strong=False)
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, ast.While):
            self.expr(node.test)
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, ast.If):
            self.expr(node.test)
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                taints = self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self.assign_target(item.optional_vars, taints, False,
                                       strong=True)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Try):
            for s in node.body:
                self.stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
            for s in node.orelse + node.finalbody:
                self.stmt(s)
        elif isinstance(node, ast.Expr):
            self.expr(node.value)
        elif isinstance(node, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)

    def assign_target(self, tgt: ast.AST, taints: set, is_set: bool,
                      strong: bool) -> None:
        if isinstance(tgt, ast.Name):
            if strong:
                self.env[tgt.id] = set(taints)
                self.set_vars.discard(tgt.id)
            else:
                self.env.setdefault(tgt.id, set()).update(taints)
            if is_set:
                self.set_vars.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self.assign_target(el, taints, False, strong=strong)
        elif isinstance(tgt, ast.Starred):
            self.assign_target(tgt.value, taints, False, strong=strong)
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            # self.x = tainted / d[k] = tainted: taint the container
            root = tgt
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name):
                self.env.setdefault(root.id, set()).update(taints)

    # -- expressions ---------------------------------------------------

    def expr(self, node: ast.AST) -> set:
        if node is None:
            return set()
        if isinstance(node, ast.Call):
            return self.call(node)
        if isinstance(node, ast.Name):
            return set(self.env.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            return self.expr(node.value)
        if isinstance(node, ast.Await):
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value) | self.expr(node.slice)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) | self.expr(node.right)
        if isinstance(node, ast.BoolOp):
            out: set = set()
            for v in node.values:
                out |= self.expr(v)
            return out
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.Compare):
            # a comparison's bool is insensitive to order/identity
            # taint; still walk operands so nested calls are processed
            self.expr(node.left)
            for c in node.comparators:
                self.expr(c)
            return set()
        if isinstance(node, ast.IfExp):
            self.expr(node.test)
            return self.expr(node.body) | self.expr(node.orelse)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            out = set()
            for el in node.elts:
                out |= self.expr(el)
            return out
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                if k is not None:
                    out |= self.expr(k)
            for v in node.values:
                out |= self.expr(v)
            return out
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            out = set()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    out |= self.expr(child)
            return out
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            out = set()
            for gen in node.generators:
                taints = self.expr(gen.iter) | self.iteration_tags(gen.iter)
                self.assign_target(gen.target, taints, False, strong=False)
                out |= taints
                for cond in gen.ifs:
                    self.expr(cond)
            if isinstance(node, ast.DictComp):
                out |= self.expr(node.key) | self.expr(node.value)
            else:
                out |= self.expr(node.elt)
            return out
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.NamedExpr):
            taints = self.expr(node.value)
            self.assign_target(node.target, taints,
                               self.expr_is_set(node.value), strong=True)
            return taints
        if isinstance(node, (ast.Constant, ast.Lambda)):
            return set()
        # conservative default: union over expression children
        out = set()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                out |= self.expr(child)
        return out

    def expr_is_set(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.set_vars
        if isinstance(node, ast.Call):
            cn = self.canonical_name(node)
            return cn in ("set", "frozenset")
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
        ):
            return (self.expr_is_set(node.left)
                    or self.expr_is_set(node.right))
        return False

    def iteration_tags(self, iter_expr: ast.AST) -> set:
        """Extra taint born from *iterating* the expression: set
        iteration order is interpreter/hash-seed dependent. (Dict
        iteration is insertion-ordered and therefore fine; fs-listing
        calls tag their result at the call itself.)"""
        if self.expr_is_set(iter_expr):
            return {self.tag("FIA505", ORDER, iter_expr,
                             "set iteration order")}
        return set()

    def canonical_name(self, call: ast.Call) -> str | None:
        mi = self.e.graph.modules.get(self.fd.rel)
        name = dotted_name(call.func)
        if name is None or mi is None:
            return name
        return self.e.graph.canonical(mi, name)

    def tag(self, rule: str, kind: str, node: ast.AST, desc: str) -> Tag:
        return Tag(
            rule=rule, kind=kind, origin_rel=self.fd.rel,
            origin_line=getattr(node, "lineno", 1),
            origin_col=getattr(node, "col_offset", 0),
            desc=desc, via=(self.fd.display,),
        )

    # -- calls ---------------------------------------------------------

    def call(self, call: ast.Call) -> set:
        arg_taints = [self.expr(a) for a in call.args]
        kw_taints = {kw.arg: self.expr(kw.value) for kw in call.keywords}
        all_args: set = set()
        for t in arg_taints:
            all_args |= t
        for t in kw_taints.values():
            all_args |= t
        obj_taints: set = set()
        if isinstance(call.func, ast.Attribute):
            obj_taints = self.expr(call.func.value)

        target_fd, canonical = self.e.graph.resolve_call(
            self.fd, call, self.local_aliases
        )
        if target_fd is not None:
            # reverse edge for the worklist: when the callee's summary
            # changes, this function must be re-analysed
            self.e.callers.setdefault(target_fd.qual, set()).add(
                self.fd.qual
            )
        attr = call.func.attr if isinstance(
            call.func, ast.Attribute) else None

        # sources -----------------------------------------------------
        src = self.source_tag(call, canonical, attr)
        if src is not None:
            return all_args | obj_taints | {src}

        # sanitizers --------------------------------------------------
        if canonical in config.SANITIZE_ALL_CALLS:
            return set()
        if canonical in config.SANITIZE_ORDER_CALLS or attr == "sort":
            out = {t for t in all_args | obj_taints
                   if not (isinstance(t, Tag) and t.kind == ORDER)}
            key_tag = self.key_identity_tag(call)
            if key_tag is not None:
                out.add(key_tag)
                if attr == "sort":
                    self.mutate_object(call, {key_tag})
            return out

        # container mutation: xs.append(tainted) taints xs ------------
        if attr in ("append", "add", "extend", "insert", "update",
                    "setdefault", "appendleft", "push"):
            self.mutate_object(call, all_args)

        result = all_args | obj_taints

        # project-internal summary application ------------------------
        if target_fd is not None:
            summary = self.e.summaries.get(target_fd.qual, EMPTY_SUMMARY)
            params = target_fd.param_names()
            offset = 1 if params[:1] == ["self"] else 0
            index_of = {p: i for i, p in enumerate(params)}
            mapped: dict[int, set] = {}
            for pos, taints in enumerate(arg_taints):
                mapped[pos + offset] = taints
            for name, taints in kw_taints.items():
                if name in index_of:
                    mapped[index_of[name]] = taints
            # resolved calls get the precise summary, not the blanket
            # arg passthrough (obj taint stays: instance state)
            result = set(obj_taints)
            for t in summary.returns:
                result.add(_extend_via(t, self.fd.display))
            for i in summary.passthrough:
                result |= mapped.get(i, set())
            for i, sink in summary.param_sinks:
                for t in mapped.get(i, ()):
                    if isinstance(t, Tag):
                        self.emit(t, sink.desc, sink.rel, sink.line,
                                  extra_path=sink.path)
                    elif isinstance(t, ParamTaint):
                        # cycle/depth guard: don't extend a path that
                        # already passed through this function or is
                        # at the chain cap
                        if (self.fd.display in sink.path
                                or len(sink.path) >= MAX_CHAIN):
                            continue
                        self.param_sinks.add((t.index, SinkRef(
                            desc=sink.desc, rel=sink.rel, line=sink.line,
                            path=(self.fd.display,) + sink.path,
                        )))

        # sink checks -------------------------------------------------
        sink_desc = self.sink_desc(target_fd, canonical)
        if sink_desc is not None:
            self.record_sink_args(call, all_args, sink_desc)
        event = self.metrics_event(call)
        if event is not None:
            self.record_sink_args(
                call, all_args, f"metrics event {event!r}",
                rules=config.METRICS_EVENT_SINK_RULES,
            )
        return result

    def source_tag(self, call: ast.Call, canonical: str | None,
                   attr: str | None) -> Tag | None:
        if canonical is None:
            if attr in config.FS_ORDER_METHOD_ATTRS:
                return self.tag("FIA503", ORDER, call,
                                f".{attr}() listing order")
            return None
        if canonical in config.ALWAYS_RANDOM_CALLS:
            return self.tag("FIA501", VALUE, call,
                            f"{canonical} (entropy read)")
        if canonical.startswith("numpy.random."):
            tail = canonical.rsplit(".", 1)[-1]
            if tail not in config.NP_RANDOM_DETERMINISTIC_ATTRS:
                return self.tag("FIA501", VALUE, call,
                                f"{canonical} (global RNG draw)")
        if (canonical in config.RNG_SEEDED_CONSTRUCTORS
                and not call.args and not call.keywords):
            return self.tag("FIA501", VALUE, call,
                            f"{canonical}() without a seed")
        if (canonical.count(".") == 1
                and canonical.startswith("random.")
                and canonical.split(".")[1] in config.RANDOM_MODULE_FNS):
            return self.tag("FIA501", VALUE, call,
                            f"{canonical} (global RNG draw)")
        if canonical in config.WALLCLOCK_CALLS:
            if not self.fd.rel.endswith(config.WALLCLOCK_SEAM_FILES):
                return self.tag("FIA502", VALUE, call,
                                f"{canonical} (wall-clock read)")
        if canonical in config.FS_ORDER_CALLS:
            return self.tag("FIA503", ORDER, call,
                            f"{canonical} (filesystem enumeration "
                            "order)")
        if canonical in config.ID_HASH_CALLS and call.args:
            return self.tag("FIA506", VALUE, call,
                            f"{canonical}() (process-varying value)")
        if canonical == "json.dumps" and not has_sort_keys(call):
            return self.tag("FIA504", VALUE, call,
                            "json.dumps without sort_keys=True")
        return None

    def key_identity_tag(self, call: ast.Call) -> Tag | None:
        """``sorted(xs, key=id)`` orders by process-varying identity."""
        for kw in call.keywords:
            if kw.arg == "key" and dotted_name(kw.value) in (
                config.ID_HASH_CALLS
            ):
                return self.tag(
                    "FIA506", ORDER, call,
                    f"ordering by {dotted_name(kw.value)}()",
                )
        return None

    def mutate_object(self, call: ast.Call, taints: set) -> None:
        root = call.func
        while isinstance(root, (ast.Attribute, ast.Subscript)):
            root = root.value
        if isinstance(root, ast.Name) and taints:
            self.env.setdefault(root.id, set()).update(taints)

    def sink_desc(self, target_fd: FuncDef | None,
                  canonical: str | None) -> str | None:
        if target_fd is not None:
            desc = self.e.sink_functions.get(target_fd.qual)
            if desc is not None:
                return desc
            return None
        if canonical is not None:
            tail = canonical.rsplit(".", 1)[-1]
            return config.DETERMINISM_SINK_CALL_NAMES.get(tail)
        return None

    @staticmethod
    def metrics_event(call: ast.Call) -> str | None:
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "log" and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
                and "." in call.args[0].value):
            return call.args[0].value
        return None

    def record_sink_args(self, call: ast.Call, all_args: set,
                         desc: str, rules=None) -> None:
        for t in all_args:
            if isinstance(t, Tag):
                if rules is not None and t.rule not in rules:
                    continue
                self.emit(t, desc, self.fd.rel, call.lineno)
            elif isinstance(t, ParamTaint) and rules is None:
                self.param_sinks.add((t.index, SinkRef(
                    desc=desc, rel=self.fd.rel, line=call.lineno,
                    path=(self.fd.display,),
                )))

    def check_return_sink(self, node: ast.Return, taints: set) -> None:
        if self.fd.qual not in self.e.return_sinks:
            return
        desc = (f"byte-pinned return of dispatch-path function "
                f"{self.fd.display!r}")
        for t in taints:
            if isinstance(t, Tag):
                self.emit(t, desc, self.fd.rel, node.lineno)
            elif isinstance(t, ParamTaint):
                self.param_sinks.add((t.index, SinkRef(
                    desc=desc, rel=self.fd.rel, line=node.lineno,
                    path=(self.fd.display,),
                )))

    def emit(self, tag: Tag, sink_desc: str, sink_rel: str,
             sink_line: int, extra_path: tuple[str, ...] = ()) -> None:
        if not self.collect:
            return
        chain = tag.via
        for name in (self.fd.display,) + extra_path:
            if not chain or chain[-1] != name:
                chain = chain + (name,)
        self.flows.append(Flow(
            rule=tag.rule, source_rel=tag.origin_rel,
            source_line=tag.origin_line, source_col=tag.origin_col,
            desc=tag.desc, sink_desc=sink_desc, sink_rel=sink_rel,
            sink_line=sink_line, chain=chain[:MAX_CHAIN],
        ))


class DataflowEngine:
    """The fixpoint driver: summaries to a fixpoint via a worklist,
    then one collecting pass."""

    def __init__(self, files: list[SourceFile], root: str):
        self.graph = CallGraph(files)
        self.root = root
        self.summaries: dict[str, Summary] = {}
        self.callers: dict[str, set[str]] = {}  # callee qual -> callers
        self.sink_functions: dict[str, str] = {}
        self.return_sinks: set[str] = set()
        for fd in self.graph.functions:
            for path, qual, desc in config.DETERMINISM_SINK_FUNCTIONS:
                if fd.rel.endswith(path) and fd.qualpath == qual:
                    self.sink_functions[fd.qual] = desc
            for path, name in config.DETERMINISM_SINK_RETURNS:
                if fd.rel.endswith(path) and (
                    fd.qualpath.rsplit(".", 1)[-1] == name
                ):
                    self.return_sinks.add(fd.qual)

    def run(self) -> list[Flow]:
        # phase 1: summaries to a fixpoint. The worklist starts with
        # every function once and re-enqueues only the callers of a
        # function whose summary changed — most summaries stabilise on
        # the first visit, so this beats whole-project passes by a
        # large margin on a real tree.
        by_qual = {fd.qual: fd for fd in self.graph.functions}
        work = deque(self.graph.functions)
        queued = {fd.qual for fd in self.graph.functions}
        budget = len(self.graph.functions) * MAX_PASSES
        while work and budget > 0:
            budget -= 1
            fd = work.popleft()
            queued.discard(fd.qual)
            summary = _FunctionPass(self, fd, collect=False).run()
            if self.summaries.get(fd.qual, EMPTY_SUMMARY) != summary:
                self.summaries[fd.qual] = summary
                for caller in self.callers.get(fd.qual, ()):
                    if caller not in queued and caller in by_qual:
                        queued.add(caller)
                        work.append(by_qual[caller])
        # phase 2: one collecting pass with stable summaries
        flows: list[Flow] = []
        for fd in self.graph.functions:
            fp = _FunctionPass(self, fd, collect=True)
            fp.run()
            flows.extend(fp.flows)
        seen = set()
        out = []
        for f in flows:
            key = (f.rule, f.source_rel, f.source_line, f.source_col,
                   f.sink_rel, f.sink_line, f.sink_desc)
            if key not in seen:
                seen.add(key)
                out.append(f)
        out.sort(key=lambda f: (f.source_rel, f.source_line,
                                f.source_col, f.rule, f.sink_rel,
                                f.sink_line))
        return out


def analyze(files: list[SourceFile], root: str) -> list[Flow]:
    """All source→sink determinism flows in the file set."""
    return DataflowEngine(files, root).run()
