"""Repo-specific lint configuration.

fialint is a *repo-native* linter: its rules encode this codebase's
invariants, so the allowlists naming which modules own which privilege
live here — in code, reviewed like code — rather than in an external
config file.
"""

from __future__ import annotations

# FIA101: the only modules allowed to perform raw persisted writes.
# utils/io.py owns the fsync'd-atomic primitives; reliability/artifacts.py
# owns the checksummed-manifest publish built on top.
RAW_WRITE_ALLOWED = (
    "fia_tpu/utils/io.py",
    "fia_tpu/reliability/artifacts.py",
)

# FIA2xx: jit entry points reached through indirection the AST cannot
# follow (a method captured inside a ``vmap``/``partial`` assigned to a
# local, then called from a jitted closure). Each entry is
# (path suffix, bare function name) with an optional third element
# naming the static argument positions; when omitted, position 0 is
# static (the bound-method ``self`` case).
REGISTERED_JIT_ENTRY_POINTS = (
    # InfluenceEngine._query_one: vmapped via partial into the padded
    # per-bucket closures that _batched/_batched_packed jit.
    ("fia_tpu/influence/engine.py", "_query_one"),
    # Fused score-kernel dispatch (influence/kernels): called from the
    # engine's jitted _flat_fn/_bank_fn closures through the package
    # dispatch table. ``model`` and the resolved ``variant`` string are
    # trace-static — both are folded into the engine's jit cache key.
    ("fia_tpu/influence/kernels/__init__.py", "fused_scores", (0, 1)),
    ("fia_tpu/influence/kernels/__init__.py", "row_grads", (0, 1)),
    # Per-geometry kernel wrappers (model static) and the Pallas kernel
    # bodies themselves (every positional arg is a VMEM Ref; the
    # geometry ints ride as keyword-only partial bindings).
    ("fia_tpu/influence/kernels/mf.py", "fused_scores", (0,)),
    ("fia_tpu/influence/kernels/mf.py", "_kernel", ()),
    ("fia_tpu/influence/kernels/ncf.py", "fused_scores", (0,)),
    ("fia_tpu/influence/kernels/ncf.py", "_kernel", ()),
    # Shared kernel-body helpers and the pallas_call harness
    # (kernel_body / shape ints / block_specs builder are static).
    ("fia_tpu/influence/kernels/common.py", "onehot_fetch", (2,)),
    ("fia_tpu/influence/kernels/common.py", "score_epilogue", (4,)),
    ("fia_tpu/influence/kernels/common.py", "run_tiled", (0, 1, 2, 4)),
)

# FIA204: the registered dispatch hot path. These functions sit between
# "a batch of queries exists on the host" and "one fused device program
# runs"; the mega-batch design (docs/design.md §14) moves data
# host→device once per *dispatch*, never once per *query*. A transfer
# call lexically inside a Python loop in one of them reintroduces the
# per-query dispatch wall the fused path exists to kill. Entries are
# (path suffix, bare function name), like REGISTERED_JIT_ENTRY_POINTS.
DISPATCH_PATH_FUNCTIONS = (
    # _dispatch_flat / drain are thin obs-span wrappers since the obs
    # spine landed; the registered names stay (the wrappers must remain
    # transfer-free too) and the *_inner/_impl bodies are policed.
    ("fia_tpu/influence/engine.py", "_dispatch_flat"),
    ("fia_tpu/influence/engine.py", "_dispatch_flat_inner"),
    ("fia_tpu/influence/engine.py", "_finalize_flat"),
    ("fia_tpu/influence/engine.py", "query_many"),
    ("fia_tpu/influence/engine.py", "_query_bank_hits"),
    ("fia_tpu/serve/service.py", "_dispatch_misses"),
    ("fia_tpu/serve/service.py", "drain"),
    ("fia_tpu/serve/service.py", "_drain_impl"),
    # Host-sharded dispatch (docs/design.md §25): the per-host shard
    # compute and the coordinator's journal merge sit on the same
    # "queries exist → fused program runs" path, one pod level up; a
    # per-row transfer inside either would reintroduce the dispatch
    # wall across EVERY host at once.
    ("fia_tpu/serve/service.py", "_dispatch_hostshard"),
    ("fia_tpu/serve/hostshard.py", "dispatch_local_shard"),
    ("fia_tpu/serve/hostshard.py", "merge_host_shards"),
    # The sharded hot path's one sanctioned cross-device fetch: the
    # masked-gather + psum collective that pulls per-query block rows
    # out of the row-sharded tables (docs/design.md §20). Registered so
    # a per-table host transfer or a bare un-placed device_put inside
    # it is a lint finding, not a silent re-replication.
    # (shard_model_params is deliberately NOT here: it is a cold-path
    # placement loop, and its per-leaf put_global is the point.)
    ("fia_tpu/parallel/sharded.py", "gather_table_rows"),
)

# Call names FIA204 treats as host→device transfer initiators when they
# appear inside a loop on the dispatch path. jnp.asarray/jnp.array on
# host data IS a transfer (plus a possible copy); put_global is the
# mesh-aware equivalent.
DISPATCH_TRANSFER_CALLS = frozenset({
    "jax.device_put",
    "device_put",
    "put_global",
    "jnp.asarray",
    "jnp.array",
    "jax.numpy.asarray",
    "jax.numpy.array",
})

# FIA205: mesh-aware placement discipline on the same dispatch path.
# An un-sharded ``jax.device_put(x)`` of a batch-axis array in a
# registered dispatch-path function lands the WHOLE batch on device 0 —
# under a mesh that serializes every shard's work through one device
# and silently un-does the query-axis sharding (docs/design.md §15).
# Per-shard placement must go through the fia_tpu/parallel helpers
# below (which attach the mesh's NamedSharding, single- and
# multi-process alike) or pass an explicit placement operand.
MESH_PLACEMENT_HELPERS = frozenset({
    "put_global",
    "shard_along",
    "replicate",
})
# device_put spellings FIA205 inspects for a missing placement operand.
UNSHARDED_TRANSFER_CALLS = frozenset({
    "jax.device_put",
    "device_put",
})

# FIA302 applies to files whose repo-relative path starts with:
RELIABILITY_PREFIX = "fia_tpu/reliability/"

# FIA302: exception types the reliability layer may raise. The four
# reliability-owned types are taxonomy-classifiable (or ARE the
# taxonomy); the builtins are programmer-contract errors that indicate
# a bug at the call site, not a runtime fault to classify.
RELIABILITY_RAISABLE = frozenset({
    "DeadlineExpired",
    "NanPayload",
    "ArtifactIntegrityError",
    "JournalMismatch",
    "UnfiredFaultError",
    "ValueError",
    "TypeError",
    "KeyError",
    "NotImplementedError",
    "AssertionError",
})

# FIA301/FIA303: where the site registry and its documentation live.
SITES_MODULE = "fia_tpu/reliability/sites.py"
SITES_DOC = "docs/reliability.md"

# FIA401: the emitted-schema and consumer declarations.
METRICS_MODULE = "fia_tpu/serve/metrics.py"
METRICS_CONSUMER = "scripts/latency_report.py"
# Event-log calls checked against the schema are restricted to this
# subtree (EventLog is also used for training curves / bench logs whose
# ad-hoc events are not part of the serving contract).
METRICS_SCOPE = "fia_tpu/serve/"
# Fields every EventLog record carries implicitly.
METRICS_IMPLICIT_FIELDS = frozenset({"t", "event"})

# FIA401 (obs extension): the tracing/metrics event schema
# (fia_tpu/obs/events.py SCHEMA) is unioned with the serving SCHEMA for
# the producer-side checks, and every consumer below must declare a
# CONSUMES literal checked against that union. Consumers are checked
# only when the file exists under the lint root, so synthetic/foreign
# trees lint clean without them; the reverse direction (every obs.*
# event consumed by at least one consumer) runs only when the obs
# schema itself was loaded.
OBS_MODULE = "fia_tpu/obs/events.py"
OBS_CONSUMERS = (
    "scripts/latency_report.py",
    "fia_tpu/cli/obs.py",
)

# ---------------------------------------------------------------------
# FIA5xx — call-graph determinism family (docs/lint.md, docs/design.md
# §24). Sources are nondeterministic reads; sinks are the things the
# repo byte-pins (published artifacts, fingerprints, cache keys,
# metrics events, dispatch-path return values). The dataflow engine
# (analysis/dataflow.py) flags a source only when its value *reaches*
# a sink through the project call graph.
# ---------------------------------------------------------------------

# FIA501: draws through numpy's legacy global generator
# (np.random.rand & friends). The new-style Generator API is exempt
# when seeded — these attrs construct deterministic streams.
NP_RANDOM_DETERMINISTIC_ATTRS = frozenset({
    "default_rng", "Generator", "Philox", "PCG64", "PCG64DXSM",
    "MT19937", "SFC64", "SeedSequence", "BitGenerator", "RandomState",
})
# constructors that are deterministic ONLY when given a seed argument;
# the zero-argument form seeds from the OS and is a source.
RNG_SEEDED_CONSTRUCTORS = frozenset({
    "numpy.random.default_rng", "numpy.random.RandomState",
    "random.Random",
})
# stdlib ``random`` module-level draws (the hidden global Mersenne
# state; ``random.Random(seed).x()`` through an instance is fine).
RANDOM_MODULE_FNS = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "sample", "shuffle", "getrandbits", "gauss", "normalvariate",
    "betavariate", "expovariate", "triangular", "vonmisesvariate",
    "paretovariate", "weibullvariate", "lognormvariate", "randbytes",
})
# unconditionally nondeterministic value reads.
ALWAYS_RANDOM_CALLS = frozenset({
    "uuid.uuid4", "uuid.uuid1", "secrets.token_bytes",
    "secrets.token_hex", "secrets.token_urlsafe", "secrets.randbelow",
    "os.urandom",
})

# FIA502: wall-clock reads. Production time flows through the
# injectable Clock seam (reliability/policy.py WALL/VirtualClock);
# reads through a clock *object* don't match here by construction
# (they resolve to the object attribute, not the time module), and the
# seam module itself — the one sanctioned place that touches
# time.monotonic — is exempted below.
WALLCLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})
WALLCLOCK_SEAM_FILES = ("fia_tpu/reliability/policy.py",)

# FIA503: arbitrarily-ordered filesystem enumerations (os.listdir
# order is filesystem-dependent; glob inherits it).
FS_ORDER_CALLS = frozenset({
    "os.listdir", "os.scandir", "os.walk", "glob.glob", "glob.iglob",
})
FS_ORDER_METHOD_ATTRS = frozenset({"iterdir", "rglob"})

# FIA506: process-varying identity/ordering primitives.
ID_HASH_CALLS = frozenset({"id", "hash"})

# Sanitizers. KILL_ORDER: result independent of argument *order*
# (kills FIA503/505/506-order taint, keeps FIA501/502 value taint —
# a sorted list of random numbers is still random). KILL_ALL: result
# deterministic regardless (shape/structure probes).
SANITIZE_ORDER_CALLS = frozenset({
    "sorted", "min", "max", "sum", "any", "all", "set", "frozenset",
    "collections.Counter", "Counter",
})
SANITIZE_ALL_CALLS = frozenset({
    "len", "isinstance", "hasattr", "callable", "type",
})

# Sink *functions*: project-internal defs whose arguments end up
# byte-pinned — matched through the call graph, so a jit wrapper, an
# import alias or a cross-module from-import still resolves to the
# registered sink. Entries are (path suffix, qualpath, description).
DETERMINISM_SINK_FUNCTIONS = (
    ("fia_tpu/utils/io.py", "save_npz_atomic", "published artifact"),
    ("fia_tpu/utils/io.py", "save_json_atomic", "published artifact"),
    ("fia_tpu/utils/io.py", "save_text_atomic", "published artifact"),
    ("fia_tpu/utils/io.py", "savetxt_atomic", "published artifact"),
    ("fia_tpu/reliability/artifacts.py", "publish_npz",
     "published artifact"),
    ("fia_tpu/reliability/artifacts.py", "canonical_fingerprint",
     "artifact fingerprint"),
    ("fia_tpu/reliability/artifacts.py", "rewrite_fingerprint",
     "artifact fingerprint"),
    ("fia_tpu/reliability/journal.py", "Journal.record",
     "journal entry"),
    ("fia_tpu/reliability/journal.py", "Journal.open",
     "journal fingerprint"),
    ("fia_tpu/serve/cache.py", "disk_put", "disk cache entry"),
    ("fia_tpu/serve/cache.py", "disk_entry_path", "cache key path"),
    ("fia_tpu/serve/cache.py", "disk_fingerprint", "cache fingerprint"),
    ("fia_tpu/train/checkpoint.py", "save", "checkpoint artifact"),
    ("fia_tpu/train/checkpoint.py", "save_rotated",
     "checkpoint artifact"),
)

# Name-based sink fallback for calls the graph cannot resolve to a
# project def (fixture trees without the io module, attribute calls on
# objects). Keys are the *last* component of the canonical callee.
DETERMINISM_SINK_CALL_NAMES = {
    "save_npz_atomic": "published artifact",
    "save_json_atomic": "published artifact",
    "save_text_atomic": "published artifact",
    "savetxt_atomic": "published artifact",
    "publish_npz": "published artifact",
    "canonical_fingerprint": "artifact fingerprint",
    "disk_put": "disk cache entry",
}

# Functions whose RETURN VALUE is byte-pinned (the sharded-vs-
# replicated identity contract pins exact bytes out of the dispatch
# path): a tainted return is a finding. Seeded from the FIA204/205
# dispatch registry; (path suffix, name) pairs.
DETERMINISM_SINK_RETURNS = DISPATCH_PATH_FUNCTIONS

# Metrics SCHEMA events (``*.log("event.name", field=...)``) are sinks
# for the ORDER/RNG rules only — wall-clock values flowing into events
# are the observability contract itself (``t`` is an implicit field),
# so FIA502 does not treat event emission as a sink.
METRICS_EVENT_SINK_RULES = frozenset({
    "FIA501", "FIA503", "FIA505", "FIA506",
})

# FIA402: bare ``print(`` is banned in library code under this prefix —
# stdout belongs to CLI mains (machine-readable JSON lines), and
# human-facing diagnostics must ride the obs spine (fia_tpu.obs.diag:
# stderr + counter + span event) so they are never lost. Exemptions:
# CLI entry points own stdout; the linter's own terminal reporter is a
# CLI in all but path.
OBS_PRINT_SCOPE = "fia_tpu/"
OBS_PRINT_EXEMPT_PREFIXES = (
    "fia_tpu/cli/",
    "fia_tpu/analysis/lint.py",
)
