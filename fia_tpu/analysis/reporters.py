"""Finding reporters: terminal lines and machine-readable JSON."""

from __future__ import annotations

import json

from fia_tpu.analysis.core import LintResult, all_rules


def terminal_report(result: LintResult) -> str:
    """One `path:line:col: RULE message` line per finding + summary."""
    lines = [f.render() for f in result.findings]
    counts = ", ".join(
        f"{rid}={n}" for rid, n in sorted(
            result.as_dict()["counts"].items()
        )
    )
    if result.findings:
        lines.append(
            f"fialint: {len(result.findings)} finding(s) in "
            f"{result.files_checked} file(s) [{counts}]"
            + (f"; {len(result.suppressed)} suppressed"
               if result.suppressed else "")
        )
    else:
        lines.append(
            f"fialint: OK ({result.files_checked} file(s) clean"
            + (f", {len(result.suppressed)} justified suppression(s)"
               if result.suppressed else "")
            + ")"
        )
    return "\n".join(lines)


def json_report(result: LintResult) -> str:
    """Deterministic JSON document (stable key order, sorted findings)."""
    return json.dumps(result.as_dict(), indent=1, sort_keys=True)


def rule_catalog() -> str:
    """`RULEID name — summary` lines for --list-rules."""
    out = []
    for rid, rule in sorted(all_rules().items()):
        out.append(f"{rid} {rule.name} — {rule.describe()}")
    return "\n".join(out)
