"""FIA101 — raw-write discipline.

Every persisted byte in this repo goes through ``utils/io.py`` (the
fsync'd atomic-rename primitives) or ``reliability/artifacts.py`` (the
checksummed-manifest publish on top). A raw ``open(.., "w")`` anywhere
else is exactly how the r5 chain lost artifacts: a kill mid-write
leaves a torn file at the published name and the reader trusts it.

This rule replaces ``scripts/check_raw_writes.sh`` (a grep over two
byte patterns) with precise AST detection over the full raw-write
surface:

- ``open(path, "w"/"wb"/"w+"/"wb+"/"xb"/"x")`` (positional or
  ``mode=``). Append mode (``"a"``) is allowed by design: append-only
  JSONL event logs and journals are the repo's crash-tolerant logging
  idiom (a torn tail line is detected and skipped by every reader).
- ``np.save`` / ``np.savez`` / ``np.savez_compressed`` / ``np.savetxt``
- ``json.dump`` (to a file handle; ``json.dumps`` is a string, fine)
- ``pickle.dump``
- ``<path>.write_text(...)`` / ``<path>.write_bytes(...)``
- ``os.fdopen(fd, "w"/"wb")`` (the mkstemp-then-fdopen variant)
"""

from __future__ import annotations

import ast

from fia_tpu.analysis import config
from fia_tpu.analysis.core import FileRule, SourceFile, register
from fia_tpu.analysis.visitor import RuleVisitor, call_name, const_str

_WRITE_MODES = {"w", "wb", "w+", "wb+", "w+b", "x", "xb", "wt"}

_NP_WRITERS = {"save", "savez", "savez_compressed", "savetxt"}

_ROUTE = ("; route through fia_tpu.utils.io (save_npz_atomic / "
          "save_json_atomic / savetxt_atomic) or "
          "reliability.artifacts.publish_npz")


def _mode_arg(node: ast.Call, pos: int) -> str | None:
    """The literal mode argument of an open()-style call, if any."""
    if len(node.args) > pos:
        return const_str(node.args[pos])
    for kw in node.keywords:
        if kw.arg == "mode":
            return const_str(kw.value)
    return None


class _IoVisitor(RuleVisitor):
    def visit_Call(self, node: ast.Call) -> None:
        cn = call_name(node)
        if cn == "open":
            mode = _mode_arg(node, 1)
            if mode in _WRITE_MODES:
                self.flag(node, f"raw open(.., {mode!r}) write" + _ROUTE)
        elif cn == "os.fdopen":
            mode = _mode_arg(node, 1)
            if mode in _WRITE_MODES:
                self.flag(node, f"raw os.fdopen(.., {mode!r}) write" + _ROUTE)
        elif cn in ("json.dump", "pickle.dump"):
            self.flag(node, f"raw {cn} to a file handle" + _ROUTE)
        elif cn is not None and cn.split(".", 1)[0] in ("np", "numpy"):
            tail = cn.split(".")[-1]
            if tail in _NP_WRITERS and len(cn.split(".")) == 2:
                self.flag(node, f"raw {cn} write" + _ROUTE)
        elif isinstance(node.func, ast.Attribute) and node.func.attr in (
            "write_text", "write_bytes"
        ):
            self.flag(
                node, f"raw .{node.func.attr}(...) write" + _ROUTE
            )
        self.generic_visit(node)


@register
class RawWriteRule(FileRule):
    """Persisted writes must go through the artifact integrity layer."""

    id = "FIA101"
    name = "raw-write"

    def check(self, sf: SourceFile):
        if sf.rel.endswith(config.RAW_WRITE_ALLOWED):
            return []
        return _IoVisitor(self.id, sf).run()
