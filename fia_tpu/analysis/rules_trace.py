"""FIA201/202/203/204/205 — trace and dispatch hygiene.

FIA201–203 police jit-traced function bodies; FIA204 and FIA205 police
the *host-side* dispatch path (the registered functions that pack a
batch and launch one fused device program), where the hazards are
per-query host→device transfers (204) and un-sharded placement that
lands a batch-axis array on device 0 under a mesh (205) rather than
trace-time syncs.

The serving path's latency contract rests on the pad-bucket discipline:
every hot dispatch reuses a compiled program. The three ways that
silently breaks are all *visible in the AST* of a traced function:

- **FIA201 host sync** — ``float()`` / ``.item()`` / ``np.*`` /
  ``print`` on a traced value blocks the host on the device (or worse,
  silently constant-folds at trace time and ships a stale value).
- **FIA202 traced branch** — Python ``if``/``while`` on an
  array-valued expression either raises a ``TracerBoolConversionError``
  on device or — when the value happens to be concrete at trace time —
  bakes one branch into the compiled program and recompiles when the
  operand bucket changes.
- **FIA203 array closure capture** — a numpy array captured by a
  jitted closure is baked into the executable as a constant: a new
  compile (and a duplicated on-device buffer) per distinct captured
  array, which is exactly the recompile storm the engine's
  ``_jitted[pad]`` cache exists to prevent. Arrays must flow through
  the traced argument list.

Jit scopes are detected per the module docstring of
:class:`fia_tpu.analysis.visitor.JitIndex`; entry points reached
through indirection are registered in ``config``. Detection is
necessarily heuristic (no type inference): names assigned from
``jnp.*``/``jax.*`` expressions or derived from traced parameters are
treated as traced. False positives are suppressed inline with a
justification, which doubles as documentation of *why* the flagged
line is actually safe.
"""

from __future__ import annotations

import ast

from fia_tpu.analysis import config
from fia_tpu.analysis.core import FileRule, Finding, SourceFile, register
from fia_tpu.analysis.visitor import (
    call_name,
    dotted_name,
    iter_jitted_defs,
)

_ARRAY_MODULES = ("jnp", "jax", "lax")
_HOST_MODULES = ("np", "numpy", "onp")


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_none_check(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` — a static-arg idiom, never a
    device sync (a traced operand cannot be None)."""
    return (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], (ast.Is, ast.IsNot))
    )


def _roots(node: ast.AST) -> set[str]:
    """Root names of every Name/Attribute chain in an expression."""
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


class _TraceScope:
    """Dataflow over one jitted def: which local names are traced."""

    def __init__(self, fn: ast.FunctionDef, traced_params: set[str]):
        self.fn = fn
        self.traced: set[str] = set(traced_params)

    def expr_is_traced(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self.traced:
                return True
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn and cn.split(".", 1)[0] in _ARRAY_MODULES:
                    return True
        return False

    def note_assign(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign) and self.expr_is_traced(node.value):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        self.traced.add(n.id)
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ):
            if self.expr_is_traced(node.value) or (
                node.target.id in self.traced
            ):
                self.traced.add(node.target.id)


def _walk_in_order(fn: ast.FunctionDef):
    """Source-order walk of a def's body, skipping nested defs (they
    get their own scope when jitted; when not jitted they still trace,
    but their params shadow — handled conservatively by skipping)."""
    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from rec(child)
    for stmt in fn.body:
        yield stmt
        yield from rec(stmt)


@register
class HostSyncRule(FileRule):
    """Host-sync hazards inside jit-traced functions."""

    id = "FIA201"
    name = "host-sync-in-jit"

    def check(self, sf: SourceFile):
        findings: list[Finding] = []
        for fn, idx, _ in iter_jitted_defs(sf):
            scope = _TraceScope(fn, idx.traced_params(fn))
            for node in _walk_in_order(fn):
                scope.note_assign(node)
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                msg = None
                if cn == "print":
                    msg = ("print() inside a jit-traced function runs at "
                           "trace time only (use jax.debug.print)")
                elif cn in ("float", "int", "bool") and node.args and (
                    scope.expr_is_traced(node.args[0])
                ):
                    msg = (f"{cn}() on a traced value forces a host sync "
                           "(TracerConversionError on device)")
                elif cn and cn.split(".", 1)[0] in _HOST_MODULES:
                    msg = (f"host numpy call {cn}() inside a jit-traced "
                           "function (constant-folds at trace time or "
                           "fails on tracers; use jnp)")
                elif cn in ("jax.device_get",):
                    msg = "jax.device_get inside a jit-traced function"
                elif isinstance(node.func, ast.Attribute) and (
                    node.func.attr in ("item", "tolist",
                                       "block_until_ready")
                    and not node.args
                ):
                    msg = (f".{node.func.attr}() forces a host sync "
                           "inside a jit-traced function")
                if msg:
                    findings.append(Finding(
                        self.id, sf.rel, node.lineno, node.col_offset, msg
                    ))
        return findings


@register
class TracedBranchRule(FileRule):
    """Python control flow on traced values inside jit scopes."""

    id = "FIA202"
    name = "traced-branch"

    def check(self, sf: SourceFile):
        findings: list[Finding] = []
        for fn, idx, _ in iter_jitted_defs(sf):
            scope = _TraceScope(fn, idx.traced_params(fn))
            for node in _walk_in_order(fn):
                scope.note_assign(node)
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                test = node.test
                if _is_none_check(test):
                    continue
                if isinstance(test, ast.Call) and call_name(test) in (
                    "isinstance", "hasattr", "callable", "len"
                ):
                    continue
                hits = sorted(_roots(test) & scope.traced)
                if hits:
                    kw = "while" if isinstance(node, ast.While) else "if"
                    findings.append(Finding(
                        self.id, sf.rel, node.lineno, node.col_offset,
                        f"Python `{kw}` on traced value(s) "
                        f"{', '.join(hits)} (bakes one branch into the "
                        "compiled program; use jnp.where/lax.cond)",
                    ))
        return findings


def _enclosing_array_bindings(enclosing: ast.FunctionDef) -> set[str]:
    """Names bound in the enclosing scope whose value is (or derives
    from) a host numpy call — the constant-baking capture hazard."""
    out: set[str] = set()
    for node in ast.walk(enclosing):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node is not enclosing
        ):
            continue
        if isinstance(node, ast.Assign):
            derives = False
            for n in ast.walk(node.value):
                if isinstance(n, ast.Call):
                    cn = call_name(n)
                    if cn and cn.split(".", 1)[0] in _HOST_MODULES:
                        derives = True
                if isinstance(n, ast.Name) and n.id in out:
                    derives = True
            if derives:
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
    return out


@register
class ClosureCaptureRule(FileRule):
    """Numpy arrays captured by jitted closures get baked as constants."""

    id = "FIA203"
    name = "array-closure-capture"

    def check(self, sf: SourceFile):
        findings: list[Finding] = []
        for fn, idx, enclosing in iter_jitted_defs(sf):
            if enclosing is None:
                continue
            local: set[str] = idx.traced_params(fn) | {"self"}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                local.add(n.id)
            hazards = _enclosing_array_bindings(enclosing)
            flagged: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    if (node.id in hazards and node.id not in local
                            and node.id not in flagged):
                        flagged.add(node.id)
            if flagged:
                # one finding per closure, anchored at its def line, so
                # a single justified suppression covers the whole
                # capture set when the baking is deliberate
                findings.append(Finding(
                    self.id, sf.rel, fn.lineno, fn.col_offset,
                    f"jitted closure {fn.name!r} captures host "
                    f"array(s) {', '.join(sorted(flagged))} from the "
                    "enclosing scope — baked into the compiled program "
                    "as constants (a recompile + duplicated device "
                    "buffer per distinct array); pass them as traced "
                    "arguments",
                ))
        return findings


def _body_calls(fn: ast.FunctionDef):
    """Calls lexically inside ``fn``, skipping nested defs/lambdas (the
    same deferred-code carve-out as :func:`_loop_body_calls`)."""
    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from rec(child)
    yield from rec(fn)


def _loop_body_calls(fn: ast.FunctionDef):
    """Calls lexically inside a loop body of ``fn``, skipping nested
    defs/lambdas: a closure built in a loop is deferred code (the
    serving path stores retry thunks that way), not a per-iteration
    transfer, so flagging it would punish the escape hatch the rule
    wants to preserve."""
    def rec(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            inner = in_loop or isinstance(child, (ast.For, ast.While))
            if in_loop and isinstance(child, ast.Call):
                yield child
            yield from rec(child, inner)
    yield from rec(fn, False)


@register
class DispatchTransferRule(FileRule):
    """Per-query host transfers inside registered dispatch-path loops."""

    id = "FIA204"
    name = "per-query-transfer-in-dispatch"

    def check(self, sf: SourceFile):
        wanted = {
            name for path, name in config.DISPATCH_PATH_FUNCTIONS
            if sf.rel.endswith(path)
        }
        if not wanted:
            return []
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.FunctionDef)
                    and node.name in wanted):
                continue
            for call in _loop_body_calls(node):
                cn = call_name(call)
                if cn in config.DISPATCH_TRANSFER_CALLS:
                    findings.append(Finding(
                        self.id, sf.rel, call.lineno, call.col_offset,
                        f"host→device transfer {cn}() inside a loop in "
                        f"dispatch-path function {node.name!r} — the "
                        "fused mega-batch contract (docs/design.md §14) "
                        "is one transfer per dispatch, never per query; "
                        "hoist it above the loop or pack the batch "
                        "first",
                    ))
        return findings


@register
class UnshardedTransferRule(FileRule):
    """Un-sharded ``jax.device_put`` on the registered dispatch path."""

    id = "FIA205"
    name = "unsharded-transfer-in-dispatch"

    def check(self, sf: SourceFile):
        wanted = {
            name for path, name in config.DISPATCH_PATH_FUNCTIONS
            if sf.rel.endswith(path)
        }
        if not wanted:
            return []
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.FunctionDef)
                    and node.name in wanted):
                continue
            for call in _body_calls(node):
                cn = call_name(call)
                if cn not in config.UNSHARDED_TRANSFER_CALLS:
                    continue
                placed = len(call.args) >= 2 or any(
                    kw.arg in ("device", "sharding", "src")
                    for kw in call.keywords
                )
                if placed:
                    continue
                helpers = "/".join(sorted(config.MESH_PLACEMENT_HELPERS))
                findings.append(Finding(
                    self.id, sf.rel, call.lineno, call.col_offset,
                    f"un-sharded {cn}() in dispatch-path function "
                    f"{node.name!r} lands the whole batch on device 0 — "
                    "under a mesh this serializes every shard through "
                    "one device (docs/design.md §15); route placement "
                    f"through fia_tpu/parallel ({helpers}) or pass an "
                    "explicit sharding operand",
                ))
        return findings
