"""Observability CLI: render the obs event stream a service wrote.

Reads the JSONL file a :class:`~fia_tpu.serve.service.InfluenceService`
(or bench) produced — ``obs.span`` lines interleaved with the
``serve.*`` stream, plus the final ``obs.metrics`` registry snapshot —
and renders it three ways:

- ``report PATH`` — trace-completeness audit (every ok ``serve.request``
  root must carry its full admit→queue→batch→dispatch→solver chain)
  plus a registry summary: per-solver-rung and per-mode latency
  percentiles, counters, gauges. Exits nonzero on incomplete chains —
  ``scripts/obs_smoke.sh`` gates on that.
- ``trace PATH [--last N] [--out FILE]`` — Chrome/Perfetto
  ``trace_event`` JSON (open in ui.perfetto.dev); ``--last N`` keeps
  only the N most recent traces by first-span time.
- ``prom PATH`` — Prometheus text exposition of the last
  ``obs.metrics`` snapshot in the file.

Run:  python -m fia_tpu.cli.obs report output/serve-MF-synthetic.jsonl
      python -m fia_tpu.cli.obs trace output/serve-MF-synthetic.jsonl \\
          --last 20 --out /tmp/trace.json
      python -m fia_tpu.cli.obs prom output/serve-MF-synthetic.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys

from fia_tpu.obs.export import perfetto, prometheus, read_spans
from fia_tpu.obs.registry import percentile_from_snapshot

# What this CLI reads, per event — cross-checked against the emitted
# schemas (serve/metrics.py SCHEMA ∪ obs/events.py SCHEMA) by lint
# rule FIA401, both directions. Keep it a literal dict.
CONSUMES = {
    "obs.span": ("trace", "span", "parent", "name", "t0", "dur_us",
                 "attrs", "events"),
    "obs.metrics": ("snapshot",),
}

# The complete span chain every served (ok) request must carry,
# in seq order under the serve.request root (serve/service.py
# _trace_request); rejected requests stop after serve.queue.
REQUEST_CHAIN = ("serve.request", "serve.admit", "serve.queue",
                 "serve.batch", "serve.dispatch", "serve.solver")
REJECT_CHAIN = REQUEST_CHAIN[:3]


def last_snapshot(path: str) -> dict | None:
    """The final ``obs.metrics`` snapshot in the file (the service
    writes one on close; later ones supersede earlier)."""
    snap = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a killed process
            if d.get("event") == "obs.metrics":
                snap = d.get("snapshot")
    return snap


def audit_chains(spans: list[dict]) -> dict:
    """Group request spans by trace and check chain completeness."""
    by_trace: dict[str, dict[str, dict]] = {}
    for s in spans:
        if s["name"].startswith("serve.") and s["name"] in REQUEST_CHAIN:
            by_trace.setdefault(s["trace"], {})[s["name"]] = s
    ok = rejected = incomplete = 0
    broken: list[str] = []
    for trace, chain in by_trace.items():
        root = chain.get("serve.request")
        if root is None:
            incomplete += 1
            broken.append(trace)
            continue
        want = (REQUEST_CHAIN
                if (root.get("attrs") or {}).get("status") == "ok"
                else REJECT_CHAIN)
        if all(n in chain for n in want):
            if len(want) == len(REQUEST_CHAIN):
                ok += 1
            else:
                rejected += 1
        else:
            incomplete += 1
            broken.append(trace)
    return {"requests": len(by_trace), "ok_complete": ok,
            "rejected_complete": rejected, "incomplete": incomplete,
            "broken_traces": broken[:10]}


def _hist_rows(snap: dict, prefix: str) -> list[tuple[str, dict]]:
    return [(k, h) for k, h in snap.get("histograms", {}).items()
            if k.startswith(prefix)]


def _print_hist_block(title: str, rows: list[tuple[str, dict]]) -> None:
    if not rows:
        return
    print(title)
    for key, h in rows:
        label = key.split("{", 1)[1][:-1] if "{" in key else key
        p50 = percentile_from_snapshot(h, 50) / 1e3
        p99 = percentile_from_snapshot(h, 99) / 1e3
        print(f"  {label:<24} n={h['count']:<6} "
              f"p50={p50:.2f}ms  p99={p99:.2f}ms")


def cmd_report(args) -> int:
    spans = read_spans(args.path)
    snap = last_snapshot(args.path)
    if not spans and snap is None:
        print(f"no obs events in {args.path}", file=sys.stderr)
        return 1
    audit = audit_chains(spans)
    print(f"spans: {len(spans)}  request traces: {audit['requests']}  "
          f"ok-complete: {audit['ok_complete']}  "
          f"rejected-complete: {audit['rejected_complete']}  "
          f"incomplete: {audit['incomplete']}")
    if audit["broken_traces"]:
        print(f"  broken: {', '.join(audit['broken_traces'])}")
    if snap is not None:
        _print_hist_block("solve by solver rung:",
                          _hist_rows(snap, "serve.solve_by_solver_us"))
        _print_hist_block("solve by serving mode:",
                          _hist_rows(snap, "serve.solve_by_mode_us"))
        _print_hist_block("queue wait by mode:",
                          _hist_rows(snap, "serve.queue_wait_us"))
        counters = snap.get("counters", {})
        if counters:
            print("counters:")
            for k in sorted(counters):
                print(f"  {k} = {counters[k]:g}")
        gauges = snap.get("gauges", {})
        if gauges:
            print("gauges:")
            for k in sorted(gauges):
                print(f"  {k} = {gauges[k]:g}")
    return 1 if audit["incomplete"] else 0


def cmd_trace(args) -> int:
    spans = read_spans(args.path)
    if not spans:
        print(f"no obs.span lines in {args.path}", file=sys.stderr)
        return 1
    if args.last:
        first_t0: dict[str, float] = {}
        for s in spans:
            tid = s["trace"]
            if tid not in first_t0 or s["t0"] < first_t0[tid]:
                first_t0[tid] = s["t0"]
        keep = set(sorted(first_t0, key=first_t0.get)[-args.last:])
        spans = [s for s in spans if s["trace"] in keep]
    doc = perfetto(spans)
    if args.out:
        from fia_tpu.utils.io import save_json_atomic

        save_json_atomic(args.out, doc)
        print(f"{len(doc['traceEvents'])} trace events -> {args.out}",
              file=sys.stderr)
    else:
        print(json.dumps(doc))
    return 0


def cmd_prom(args) -> int:
    snap = last_snapshot(args.path)
    if snap is None:
        print(f"no obs.metrics snapshot in {args.path}", file=sys.stderr)
        return 1
    sys.stdout.write(prometheus(snap))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fia_tpu.cli.obs",
        description=__doc__.split("\n\n", 1)[0],
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("report", help="chain audit + registry summary")
    p.add_argument("path")
    p.set_defaults(fn=cmd_report)
    p = sub.add_parser("trace", help="Perfetto trace_event JSON")
    p.add_argument("path")
    p.add_argument("--last", type=int, default=0,
                   help="keep only the N most recent traces")
    p.add_argument("--out", default="",
                   help="write JSON here instead of stdout")
    p.set_defaults(fn=cmd_trace)
    p = sub.add_parser("prom", help="Prometheus text snapshot")
    p.add_argument("path")
    p.set_defaults(fn=cmd_prom)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
