"""RQ2 driver: wall-clock cost of influence queries.

Equivalent of reference ``src/scripts/RQ2.py`` + ``RQ2.sh`` (the
embed-size sweep that the reference's inert argparse silently dropped
works here). Prints the reference's timer lines plus a JSON summary with
throughput numbers.

Run:  python -m fia_tpu.cli.rq2 --dataset synthetic --model MF \
        --num_steps_train 2000 --num_test 64
"""

from __future__ import annotations

import json

import numpy as np

from fia_tpu.cli import common


def main(argv=None):
    args = common.base_parser(__doc__).parse_args(argv)
    common.apply_backend(args)

    from fia_tpu.eval.rq2 import time_influence_queries
    from fia_tpu.influence.engine import InfluenceEngine

    splits = common.load_splits(args)
    train, test = splits["train"], splits["test"]
    model, params = common.build_model(args, splits)
    mesh = common.mesh_for(args)
    log = common.event_log_for(args, "rq2")
    log.log("run_start", driver="rq2", **{
        k: v for k, v in vars(args).items() if not k.startswith("_")
    })
    trainer, state, batch = common.train_or_load(
        args, model, params, splits, event_log=log, mesh=mesh
    )

    engine = InfluenceEngine(
        model, state.params, train, mesh=mesh,
        cache_dir=args.train_dir,
        model_name=common.model_name_for(args, splits=splits),
        **common.engine_kwargs(args),
    )

    test_idx = common.explicit_test_indices(args, test)
    if test_idx is None:
        rng = np.random.default_rng(args.seed + 17)
        n_queries = max(args.num_test, 1)
        test_idx = rng.choice(test.num_examples, size=n_queries,
                              replace=False)
    points = test.x[test_idx]

    timing = time_influence_queries(
        engine, points, batch_queries=args.query_batch or None
    )
    # reference-format lines (matrix_factorization.py:225, 249-250)
    print(f"Inverse HVP + scoring for {timing.num_queries} queries took "
          f"{timing.total_time_s} sec")
    print(f"Multiplying by {timing.num_scores} train examples took "
          f"{timing.total_time_s} sec (fused)")
    print(f"Total time is {timing.total_time_s} sec")
    print(json.dumps({"model": args.model, "dataset": args.dataset,
                      "embed_size": args.embed_size, **timing.json()}))
    log.log("query_batch", model=args.model, dataset=args.dataset,
            embed_size=args.embed_size, **timing.json())
    log.close()
    return timing


if __name__ == "__main__":
    main()
