"""Chaos engine driver: seeded schedules, smoke/soak sweeps, replay.

Modes (mutually exclusive, checked in this order):

- ``--smoke``: the CI gate (``make chaos-smoke``). Fixed seed, benign
  schedules (bit-identity-preserving fault kinds only) across the
  selftest + three end-to-end scenarios; every run must reproduce its
  golden run bit-identically and satisfy the full oracle battery.
  Deterministic and CPU-bounded (≤60 s).
- ``--soak LO:HI``: a seed-range sweep (``make chaos-soak``) over the
  full fault domain with ``--faults`` faults per schedule — the
  long-running fuzz mode; NOT part of tier-1.
- ``--replay repro.json``: re-run a shrunk repro schedule emitted by a
  failing run. Exits nonzero iff the failure reproduces — the repro
  file is a failing test you can hand to whoever owns the bug.
- default: one schedule for ``--scenario``/``--seed``/``--faults``
  (``--all_kinds`` switches from the benign to the full domain), with
  automatic ddmin shrinking + repro emission on oracle failure.

Run:  python -m fia_tpu.cli.chaos --smoke
      python -m fia_tpu.cli.chaos --scenario train_resume --seed 7 --faults 3
      python -m fia_tpu.cli.chaos --replay /tmp/chaos/repro-*.json
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile

from fia_tpu.chaos.runner import ChaosEngine
from fia_tpu.chaos.scenarios import SCENARIO_NAMES

# The smoke battery: the jax-free selftest plus the end-to-end
# scenarios, two benign seeded schedules each. serve_stream_mesh needs
# multiple devices to exercise sharded dispatch (scripts/chaos_smoke.sh
# forces 8 virtual CPU devices); on a 1-device host it degrades to the
# single-device workload rather than failing.
SMOKE_SCENARIOS = ("selftest", "train_resume", "query_cache",
                   "serve_stream", "serve_stream_mesh",
                   "device_loss_recovery", "host_loss_recovery",
                   "factor_bank",
                   "update_while_serving", "unlearn_while_serving",
                   "serve_brownout", "serve_multitenant")
SMOKE_SEEDS_PER_SCENARIO = 2
SMOKE_FAULTS = 3


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m fia_tpu.cli.chaos",
        description="seeded fault schedules against end-to-end scenarios",
    )
    p.add_argument("--smoke", action="store_true",
                   help="fixed-seed benign battery (the tier-1 gate)")
    p.add_argument("--soak", type=str, default=None, metavar="LO:HI",
                   help="seed-range sweep over the full fault domain")
    p.add_argument("--replay", type=str, default=None, metavar="REPRO",
                   help="re-run a repro JSON; nonzero exit iff it "
                        "still fails")
    p.add_argument("--scenario", action="append",
                   choices=list(SCENARIO_NAMES), default=None,
                   help="scenario(s) to run (repeatable; default: the "
                        "smoke set)")
    p.add_argument("--seed", type=int, default=0,
                   help="schedule seed (soak: offset added to the range)")
    p.add_argument("--faults", type=int, default=SMOKE_FAULTS,
                   help="faults per generated schedule")
    p.add_argument("--all_kinds", action="store_true",
                   help="draw from the full fault domain (kill kinds, "
                        "solver escalation) instead of the benign one; "
                        "bit-identity is then checked only on served "
                        "answers, not whole outcomes")
    p.add_argument("--no_shrink", action="store_true",
                   help="skip ddmin shrinking on failure")
    p.add_argument("--workdir", type=str, default=None,
                   help="root for run dirs + repro files (default: a "
                        "fresh temp dir)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-run progress lines")
    return p


def _engine(args) -> ChaosEngine:
    root = args.workdir or tempfile.mkdtemp(prefix="fia-chaos-")
    return ChaosEngine(root, verbose=not args.quiet)


def _finish(reports, eng: ChaosEngine, label: str) -> int:
    failed = [r for r in reports if not r.passed]
    summary = {
        "mode": label,
        "runs": len(reports),
        "passed": len(reports) - len(failed),
        "failed": [r.to_dict() for r in failed],
        "workdir": eng.root,
    }
    print(json.dumps(summary, indent=2, default=str))
    if failed:
        for r in failed:
            if r.repro_path:
                print(f"[chaos] repro: python -m fia_tpu.cli.chaos "
                      f"--replay {r.repro_path}", file=sys.stderr)
        return 1
    return 0


def run_smoke(args) -> int:
    eng = _engine(args)
    names = args.scenario or list(SMOKE_SCENARIOS)
    reports = []
    for name in names:
        for i in range(SMOKE_SEEDS_PER_SCENARIO):
            reports.append(eng.run(
                name, seed=args.seed + i, n_faults=args.faults,
                benign=True, shrink=not args.no_shrink))
    return _finish(reports, eng, "smoke")


def run_soak(args) -> int:
    try:
        lo, hi = (int(v) for v in args.soak.split(":"))
    except ValueError:
        print(f"--soak wants LO:HI, got {args.soak!r}", file=sys.stderr)
        return 2
    eng = _engine(args)
    names = args.scenario or list(SMOKE_SCENARIOS)
    reports = []
    for seed in range(lo, hi):
        for name in names:
            reports.append(eng.run(
                name, seed=args.seed + seed, n_faults=args.faults,
                benign=not args.all_kinds, shrink=not args.no_shrink))
    return _finish(reports, eng, "soak")


def run_replay(args) -> int:
    eng = _engine(args)
    report = eng.replay(args.replay)
    print(json.dumps(report.to_dict(), indent=2, default=str))
    if report.failures:
        print(f"[chaos] failure REPRODUCED "
              f"({', '.join(f.oracle for f in report.failures)})",
              file=sys.stderr)
        return 1
    print("[chaos] schedule passed — the repro no longer fails",
          file=sys.stderr)
    return 0


def run_single(args) -> int:
    eng = _engine(args)
    names = args.scenario or list(SMOKE_SCENARIOS)
    reports = [
        eng.run(name, seed=args.seed, n_faults=args.faults,
                benign=not args.all_kinds, shrink=not args.no_shrink)
        for name in names
    ]
    return _finish(reports, eng, "single")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.smoke:
        return run_smoke(args)
    if args.soak:
        return run_soak(args)
    if args.replay:
        return run_replay(args)
    return run_single(args)


if __name__ == "__main__":
    raise SystemExit(main())
