"""Offline factor-bank builder (the ``precomputed`` solver's artifact).

Selects the hot (user, item) pairs from the trained model's interaction
index, computes their damped block Hessians in fused mega-batch
dispatches (the flat program's ``hessian`` stage — AOT/mesh machinery
included), factorizes them (batched Cholesky, clamped-eigendecomposition
inverse fallback, optional Schulz polish), and publishes the bank
through the artifact integrity layer under the engine's canonical path
(``<train_dir>/factor/<model>-bank.npz``). A ``solver=precomputed``
engine over the same train_dir then answers banked queries with one
triangular solve / matvec; everything else falls through the solver
ladder unchanged (docs/design.md §16).

Run:  python -m fia_tpu.cli.factor --dataset synthetic --model MF \
        --num_steps_train 300 --bank_entries 256

``--verify`` additionally serves a small stream against the published
bank IN-PROCESS and exits nonzero unless (a) the bank loaded, (b) the
hit rate over banked pairs is positive with scores matching the direct
solver, and (c) a miss falls through bitwise-identically to a bank-less
ladder engine. This is the CI gate (``make factor-smoke``).
"""

from __future__ import annotations

import json
import sys

import numpy as np

from fia_tpu.cli import common
from fia_tpu.influence import factor as fbank
from fia_tpu.influence.engine import InfluenceEngine


def add_factor_flags(p):
    p.add_argument("--bank_entries", type=int, default=1024,
                   help="max (user, item) pairs to precompute")
    p.add_argument("--bank_top_users", type=int, default=64,
                   help="user head size for hot-pair selection")
    p.add_argument("--bank_top_items", type=int, default=64,
                   help="item head size for hot-pair selection")
    p.add_argument("--bank_batch", type=int, default=512,
                   help="pairs per fused Hessian dispatch")
    p.add_argument("--schulz_polish", type=int, default=0,
                   help="1: Newton-Schulz refine the eigendecomposition "
                        "fallback inverses (HyperINF-style)")
    p.add_argument("--verify", action="store_true",
                   help="after publishing, serve a smoke stream against "
                        "the bank in-process; nonzero exit on any "
                        "accuracy/fall-through failure")
    return p


def build_engine(args):
    """Model + trained params + engine from the shared CLI plumbing."""
    common.apply_backend(args)
    splits = common.load_splits(args)
    model, params = common.build_model(args, splits)
    name = common.model_name_for(args, splits=splits)
    _, state, _ = common.train_or_load(args, model, params, splits,
                                       verbose=False)
    mesh = common.mesh_for(args)
    kwargs = common.engine_kwargs(args)
    # the builder needs the hessian stage, not a serving solver; the
    # precomputed rung only means something to the engine that LOADS
    # the bank afterwards
    kwargs["solver"] = "direct"
    engine = InfluenceEngine(
        model, state.params, splits["train"],
        cache_dir=args.train_dir, model_name=name,
        mesh=mesh, **kwargs,
    )
    return engine, splits, name


def build_and_publish(engine, args, name) -> dict:
    pairs = fbank.select_hot_pairs(
        engine.index, max_entries=args.bank_entries,
        top_users=args.bank_top_users, top_items=args.bank_top_items,
    )
    bank = fbank.build_bank(engine, pairs, batch_queries=args.bank_batch,
                            schulz_polish=bool(args.schulz_polish))
    path = engine.factor_bank_path()
    fp = fbank.bank_fingerprint(name, engine.model.block_size,
                                engine.damping, *engine._train_host)
    fbank.publish_bank(bank, path, fp)
    return {
        "event": "factor.publish",
        "path": path,
        "entries": len(bank),
        "cholesky": int(np.count_nonzero(bank.kind == fbank.KIND_CHOLESKY)),
        "inverse": int(np.count_nonzero(bank.kind == fbank.KIND_INVERSE)),
        "block_d": bank.block_d,
    }


def run_verify(engine, args, name, summary) -> int:
    """In-process smoke against the just-published bank."""
    from scipy.stats import spearmanr

    model = engine.model
    train_host = engine._train_host
    from fia_tpu.data.dataset import RatingDataset

    train = RatingDataset(*train_host)
    mk = lambda solver, cache: InfluenceEngine(
        model, engine.params, train, damping=engine.damping,
        solver=solver, cache_dir=args.train_dir if cache else None,
        model_name=name, lissa_depth=min(engine.lissa_depth, 200),
    )
    eng = mk("precomputed", cache=True)
    failures = []
    n_loaded = eng.ensure_factor_bank()
    if n_loaded <= 0:
        failures.append("published bank failed verified load")
    else:
        pairs = eng._bank.pairs[: min(16, n_loaded)]
        res = eng.query_batch(np.asarray(pairs, np.int64))
        st = eng.bank_stats()
        if st["hits"] <= 0:
            failures.append("no bank hits over banked pairs")
        ref = mk("direct", cache=False)
        res_ref = ref.query_batch(np.asarray(pairs, np.int64))
        worst = 1.0
        for t in range(len(pairs)):
            a, b = res.scores_of(t), res_ref.scores_of(t)
            if len(a) > 1 and (np.std(a) > 0 or np.std(b) > 0):
                worst = min(worst, float(spearmanr(a, b).statistic))
        if not (worst >= 0.999):
            failures.append(f"hit-path Spearman vs direct {worst} < 0.999")
        # miss fall-through: a pair outside the bank must answer
        # bitwise-identically to a bank-less engine on the same ladder
        banked = {tuple(p) for p in eng._bank.pairs.tolist()}
        x = train_host[0]
        miss = next(
            (
                (int(u), int(i))
                for u, i in zip(x[:, 0], x[:, 1])
                if (int(u), int(i)) not in banked
            ),
            None,
        )
        if miss is not None:
            from fia_tpu.reliability import policy as rpolicy

            mq = np.asarray([miss], np.int64)
            a = eng.query_batch(mq).scores_of(0)
            b = mk(rpolicy.next_solver("precomputed") or "direct",
                   cache=False).query_batch(mq).scores_of(0)
            if not np.array_equal(a, b):
                failures.append("miss fall-through not bitwise-identical "
                                "to the bank-less ladder")
        else:
            failures.append("no miss pair available to check fall-through")
        summary["verify"] = {
            "loaded": n_loaded, "spearman_worst": worst,
            **{k: st[k] for k in ("hits", "misses", "dropped_stale")},
        }
    for f in failures:
        print(f"FACTOR VERIFY FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"factor verify ok: {n_loaded} entries, "
              f"hits {summary['verify']['hits']}, "
              f"worst Spearman {summary['verify']['spearman_worst']:.6f}")
    return 1 if failures else 0


def main(argv=None) -> int:
    p = add_factor_flags(common.base_parser(__doc__))
    args = p.parse_args(argv)
    engine, _splits, name = build_engine(args)
    summary = build_and_publish(engine, args, name)
    rc = 0
    if args.verify:
        rc = run_verify(engine, args, name, summary)
    print(json.dumps(summary))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
