"""Data-debugging driver: reverse sweep → plan → fidelity gate → apply.

The audit subsystem's end-to-end CLI (docs/design.md §23). One run:

1. trains (or restores) a model, optionally planting label corruption
   first so there are genuinely harmful rows to find;
2. runs the batched reverse top-k sweep (:mod:`fia_tpu.audit.reverse`)
   over the audited test set — journaled, resumable with ``--resume``;
3. builds a removal/reweighting :class:`UnlearnPlan` and publishes it
   as a checksummed artifact;
4. verifies the plan's predicted deltas against real leave-rows-out
   retraining (:mod:`fia_tpu.audit.verify`) and holds them to the
   fidelity gate (sign agreement AND Spearman ≥ ``--gate``);
5. with ``--apply 1``, flows the plan live through the epoch-fenced
   unlearning loop (refused if the gate failed, unless ``--force_apply``).

``--gate_demo`` presets the committed-recipe configuration (small
planted-corruption synthetic problem whose gate artifact lives in
``output/``):

    python -m fia_tpu.cli.debug_data --gate_demo

Plain runs compose with every shared knob, e.g.::

    python -m fia_tpu.cli.debug_data --dataset synthetic \
        --num_steps_train 3000 --topk 64 --plan_action reweight \
        --reweight 0.3 --verify 0 --apply 1
"""

from __future__ import annotations

import os

import numpy as np

from fia_tpu.cli import common
from fia_tpu.utils import io
from fia_tpu.reliability import policy as rpolicy
from fia_tpu.reliability.journal import Journal


def add_audit_args(p):
    """The audit-specific knobs, on top of ``common.base_parser``."""
    p.add_argument("--topk", type=int, default=32,
                   help="reverse-sweep candidate rows to rank")
    p.add_argument("--audit_points", type=int, default=0,
                   help="audited test points sampled from the test "
                        "split (0 = the whole split)")
    p.add_argument("--chunk_points", type=int, default=0,
                   help="outer sweep chunking (throughput knob; the "
                        "result is bitwise identical for any value)")
    p.add_argument("--plan_action", choices=["remove", "reweight"],
                   default="remove")
    p.add_argument("--plan_rows", type=int, default=8,
                   help="cap on rows in the plan (after the "
                        "negative-influence filter)")
    p.add_argument("--reweight", type=float, default=0.5,
                   help="label weight w for --plan_action reweight")
    p.add_argument("--verify", type=int, default=1,
                   help="1: retrain-and-compare the plan against the "
                        "fidelity gate before anything is applied")
    p.add_argument("--verify_steps", type=int, default=3000,
                   help="gentle retraining steps per verify lane")
    p.add_argument("--verify_lr", type=float, default=1e-3)
    p.add_argument("--controls", type=int, default=-1,
                   help="most-POSITIVE sweep rows appended to the "
                        "verified slice as spread controls "
                        "(-1 = match the plan slice)")
    p.add_argument("--gate", type=float, default=0.9,
                   help="fidelity threshold for sign agreement AND "
                        "Spearman")
    p.add_argument("--apply", type=int, default=0,
                   help="1: apply the plan live through the epoch-"
                        "fenced unlearning loop")
    p.add_argument("--apply_steps", type=int, default=100,
                   help="fine-tune steps inside the fenced apply")
    p.add_argument("--force_apply", action="store_true",
                   help="apply even when the fidelity gate failed")
    p.add_argument("--corrupt_rows", type=int, default=0,
                   help="plant label corruption (y -> 6-y) on this "
                        "many off-center train rows before training — "
                        "the data-debugging demo the sweep should "
                        "catch")
    p.add_argument("--corrupt_seed", type=int, default=7)
    p.add_argument("--split_seed", type=int, default=None,
                   help="synthetic split seed when it must differ from "
                        "the model seed (default: --seed)")
    p.add_argument("--json_out", type=str, default="",
                   help="write the run summary as JSON here")
    p.add_argument("--gate_demo", action="store_true",
                   help="preset the committed fidelity-gate recipe "
                        "(see module doc)")
    return p


def apply_gate_demo(args) -> None:
    """The committed-recipe preset: a planted-corruption problem small
    enough for CPU where the gate provably passes (the artifact in
    ``output/`` was produced by exactly this configuration)."""
    args.dataset = "synthetic"
    args.synth_stream = "zipf"
    args.synth_users, args.synth_items = 60, 40
    args.synth_train, args.synth_test = 2000, 50
    args.split_seed, args.seed = 3, 0
    args.model, args.embed_size = "MF", 4
    args.weight_decay, args.damping = 1e-3, 1e-3
    args.lr, args.batch_size = 1e-2, 200
    args.num_steps_train = 1500
    args.solver = "direct"
    args.corrupt_rows, args.corrupt_seed = 80, 7
    args.topk = 32
    args.audit_points = 0
    args.plan_action, args.plan_rows = "remove", 8
    args.verify, args.controls = 1, 8
    args.verify_steps, args.verify_lr = 3000, 1e-3
    args.retrain_times = 3


def plant_corruption(splits, n: int, seed: int) -> np.ndarray:
    """Invert ``n`` off-center train labels (y -> 6-y) in place.

    Only rows with ``|y - 3| >= 1`` are eligible: inverting a
    mid-scale rating barely moves it, and the demo needs rows that
    genuinely hurt the test set so the sweep has something real to
    find."""
    from fia_tpu.data.dataset import RatingDataset

    train = splits["train"]
    y = np.array(train.y, np.float32, copy=True)
    eligible = np.flatnonzero(np.abs(y - 3.0) >= 1.0)
    if len(eligible) < n:
        raise SystemExit(
            f"--corrupt_rows {n}: only {len(eligible)} off-center rows"
        )
    rng = np.random.default_rng(seed)
    rows = np.sort(rng.choice(eligible, size=n, replace=False))
    y[rows] = 6.0 - y[rows]
    splits["train"] = RatingDataset(np.asarray(train.x), y)
    return rows


def load_splits(args):
    """``common.load_splits`` with the split seed decoupled from the
    model seed (the gate recipe plants corruption on a seed-3 stream
    but trains a seed-0 model)."""
    if args.split_seed is None:
        return common.load_splits(args)
    saved = args.seed
    args.seed = args.split_seed
    try:
        return common.load_splits(args)
    finally:
        args.seed = saved


def build_fia_model(args, splits, corrupt_tag: str):
    """The api-level :class:`FIAModel` (the audit subsystem operates on
    the full model wrapper: engine + fenced apply + event routing)."""
    from fia_tpu.api import FIAModel

    num_users = max(int(np.max(s.x[:, 0])) + 1 for s in splits.values())
    num_items = max(int(np.max(s.x[:, 1])) + 1 for s in splits.values())
    name = common.model_name_for(args, splits=splits) + corrupt_tag
    return FIAModel(
        args.model, num_users, num_items, args.embed_size,
        args.weight_decay,
        batch_size=common.batch_size_for(args, splits["train"]),
        data_sets=splits, initial_learning_rate=args.lr,
        damping=args.damping, avextol=args.avextol,
        train_dir=args.train_dir, model_name=name,
        solver=args.solver, seed=args.seed, mesh=common.mesh_for(args),
    )


def main(argv=None):
    args = add_audit_args(common.base_parser(__doc__)).parse_args(argv)
    if args.gate_demo:
        apply_gate_demo(args)
    common.apply_backend(args)

    from fia_tpu.audit import build_plan, save_plan
    from fia_tpu.audit.reverse import reverse_topk, sweep_fingerprint
    from fia_tpu.audit.verify import verify_fingerprint, verify_plan

    splits = load_splits(args)
    corrupt_tag = ""
    planted = np.zeros(0, np.int64)
    if args.corrupt_rows:
        planted = plant_corruption(splits, args.corrupt_rows,
                                   args.corrupt_seed)
        # corruption changes the train stream: its own checkpoint/
        # artifact namespace, or a clean run would restore a corrupted
        # model (and vice versa)
        corrupt_tag = f"_corrupt{args.corrupt_rows}s{args.corrupt_seed}"

    model = build_fia_model(args, splits, corrupt_tag)
    log = common.event_log_for(args, "debug_data")
    log.log("run_start", driver="debug_data", **{
        k: v for k, v in vars(args).items() if not k.startswith("_")
    })

    from fia_tpu.train import checkpoint

    steps = args.num_steps_train
    restore = (steps - 1 if args.load_checkpoint
               and checkpoint.exists(model._checkpoint_path(steps - 1))
               else 0)
    model.train(steps, save_checkpoints=True, verbose=False,
                load_checkpoints=restore)
    print(f"model {model.model_name} @ step {int(model.state.step)} "
          f"(train rows {model.num_train_examples})")

    test = splits["test"]
    if args.audit_points and args.audit_points < test.num_examples:
        sel = np.sort(np.random.default_rng(args.seed).choice(
            test.num_examples, size=args.audit_points, replace=False))
    else:
        sel = np.arange(test.num_examples)
    tp = np.asarray(test.x, np.int64)[sel]
    ty = np.asarray(test.y, np.float32)[sel]

    engine = model.engine(args.solver)
    deadline = rpolicy.Deadline(args.deadline)
    chunk_points = args.chunk_points or None
    batch_queries = args.query_batch or 256
    jpath = os.path.join(
        args.train_dir, f".debug-data-{model.model_name}.journal.jsonl")
    fp = sweep_fingerprint(engine, tp, ty, k=args.topk,
                           batch_queries=batch_queries,
                           chunk_points=chunk_points)
    with Journal.open(jpath, fp, resume=args.resume) as journal:
        sweep = reverse_topk(
            model, tp, ty, k=args.topk, engine=engine,
            batch_queries=batch_queries, chunk_points=chunk_points,
            journal=journal, deadline=deadline,
        )
    print(f"sweep {sweep.sweep_id}: {sweep.rows_scored} row-scores in "
          f"{sweep.seconds:.1f}s ({sweep.rows_per_s:,.0f} rows/s)")
    if len(planted):
        hits = np.isin(sweep.row_ids, planted)
        print(f"planted-corruption hit rate: {hits.mean():.2f} "
              f"({int(hits.sum())}/{len(hits)} of top-{len(hits)} "
              f"are planted rows)")

    plan = build_plan(model, sweep, action=args.plan_action,
                      max_rows=args.plan_rows, reweight=args.reweight)
    plan_path = os.path.join(
        args.train_dir, f"{model.model_name}-plan-{plan.plan_id}.npz")
    save_plan(plan, plan_path)
    print(f"plan {plan.plan_id} [{plan.action}]: {plan.rows} rows, "
          f"predicted test-SSE delta {plan.predicted_delta:+.4f} "
          f"-> {plan_path}")

    summary = {
        "model_key": model.model_name, "sweep_id": sweep.sweep_id,
        "rows_scored": int(sweep.rows_scored),
        "rows_per_s": round(sweep.rows_per_s, 1),
        "plan_id": plan.plan_id, "plan_action": plan.action,
        "plan_rows": int(plan.rows),
        "predicted_delta": float(plan.predicted_delta),
        "planted_hit_rate": (float(np.isin(sweep.row_ids, planted).mean())
                             if len(planted) else None),
        "plan_path": plan_path,
    }

    verdict = None
    if args.verify:
        n_ctl = args.plan_rows if args.controls < 0 else args.controls
        control_rows = control_deltas = None
        if n_ctl:
            # spread controls: the sweep's most-positive rows (verify.py
            # module doc) — value-descending with row-id ascending on
            # ties, deterministic like the sweep itself
            g = sweep.group_scores
            order = np.argsort(-g.astype(np.float64), kind="stable")
            control_rows = order[:n_ctl].astype(np.int64)
            control_deltas = g[control_rows].astype(np.float64)
        vfp = verify_fingerprint(
            model, plan, tp, num_steps=args.verify_steps,
            batch_size=common.batch_size_for(args, splits["train"]),
            learning_rate=args.verify_lr,
            retrain_times=args.retrain_times, seed=args.seed,
            max_rows=args.plan_rows, control_rows=control_rows,
        )
        vjpath = os.path.join(
            args.train_dir,
            f".debug-data-verify-{plan.plan_id}.journal.jsonl")
        vart = os.path.join(
            args.train_dir, f"{model.model_name}-verify-{plan.plan_id}.npz")
        with Journal.open(vjpath, vfp, resume=args.resume) as vj:
            verdict = verify_plan(
                model, plan, tp, ty, num_steps=args.verify_steps,
                batch_size=common.batch_size_for(args, splits["train"]),
                learning_rate=args.verify_lr,
                retrain_times=args.retrain_times,
                lane_chunk=args.lane_chunk, max_rows=args.plan_rows,
                seed=args.seed, control_rows=control_rows,
                control_deltas=control_deltas, gate=args.gate,
                journal=vj, artifact_path=vart, mesh=model.mesh,
            )
        state = "PASS" if verdict.passed else "FAIL"
        print(f"fidelity gate [{state}]: sign agreement "
              f"{verdict.sign_agreement:.3f}, spearman "
              f"{verdict.spearman:.3f} (gate {args.gate:g}, "
              f"{verdict.plan_rows} plan rows + "
              f"{len(verdict.row_ids) - verdict.plan_rows} controls) "
              f"-> {vart}")
        log.log("fidelity_gate", passed=verdict.passed,
                sign_agreement=float(verdict.sign_agreement),
                spearman=float(verdict.spearman), gate=float(args.gate))
        summary.update(
            gate_passed=bool(verdict.passed),
            sign_agreement=float(verdict.sign_agreement),
            spearman=float(verdict.spearman),
            verify_artifact=vart,
        )

    if args.apply:
        if verdict is not None and not verdict.passed \
                and not args.force_apply:
            print("apply refused: fidelity gate failed "
                  "(--force_apply overrides)")
            summary["apply_status"] = "refused"
        else:
            from fia_tpu.audit import apply_plan

            res = apply_plan(model, plan, steps=args.apply_steps)
            print(f"apply [{res.status}]: {plan.rows} rows "
                  f"{plan.action} in {res.seconds:.1f}s "
                  f"(touched {res.touched_users} users / "
                  f"{res.touched_items} items)"
                  + (f" reason={res.reason}" if res.reason else ""))
            summary["apply_status"] = res.status
            summary["apply_seconds"] = round(res.seconds, 3)

    log.log("run_done", **{k: v for k, v in summary.items()
                           if not isinstance(v, np.ndarray)})
    log.close()
    if args.json_out:
        io.save_json_atomic(args.json_out,
                            dict(sorted(summary.items())), indent=2)
        print(f"summary -> {args.json_out}")
    return summary


if __name__ == "__main__":
    main()
