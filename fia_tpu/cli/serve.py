"""Online influence-query serving driver.

Turns a trained model into a stdin/stdout JSONL service backed by
:class:`fia_tpu.serve.InfluenceService`: one request object per input
line (``{"user": u, "item": i, "id": ..., "deadline_s": ...}`` — bare
``u i`` pairs are accepted too), one response object per output line
(the ``serve.request`` schema of fia_tpu/serve/metrics.py plus the
score payload).

Modes (checked in this order; ``--warmup`` composes with the others):

- ``--warmup N``: AOT pre-lower + compile the flat dispatch geometries
  of the micro-batches the scheduler would plan for N representative
  test points, then dispatch those batches once (autotune warm). Exits
  nonzero when any planned geometry is left uncompiled — a cold bucket
  would otherwise pay its compile inside someone's latency budget.
  Standalone it reports and exits; combined with ``--smoke_requests``
  or the stdin loop it arms the caches first and the traffic mode runs
  on a warm, never-compiling hot path.
- ``--smoke_requests N``: self-contained synthetic open-loop stream — N
  queries over the test split with a repeat-heavy hot set — then a
  latency/cache report. Exits nonzero unless every request either
  succeeded or was rejected with a classified reason, and the hot tier
  actually absorbed repeats. This is the CI gate (``make serve-smoke``).
- default: the stdin loop, draining after every ``--drain_every`` lines
  (micro-batching needs a queue; a pipe full of requests provides one).

Run:  python -m fia_tpu.cli.serve --dataset synthetic --model MF \
        --num_steps_train 300 --warmup 32
"""

from __future__ import annotations

import json
import sys

import numpy as np

from fia_tpu.cli import common
from fia_tpu.influence.engine import InfluenceEngine
from fia_tpu.reliability import taxonomy
from fia_tpu.serve import InfluenceService, Request, ServeConfig


def add_serve_flags(p):
    p.add_argument("--max_batch", type=int, default=1024,
                   help="mega-batch coalescing cap per device dispatch "
                        "(big fused dispatches amortize the host "
                        "dispatch wall; dial down when p50 latency "
                        "matters more than throughput)")
    p.add_argument("--max_queue", type=int, default=4096,
                   help="admission bound: queued requests beyond this "
                        "are rejected with reason 'overload'")
    p.add_argument("--cache_entries", type=int, default=1024,
                   help="hot-block LRU capacity (solved (u,i) blocks)")
    p.add_argument("--coalesce", choices=["bucket", "fifo"],
                   default="bucket",
                   help="dispatch order: pad-bucket sorted or arrival")
    p.add_argument("--request_deadline", type=float, default=0.0,
                   help="default per-request budget in seconds "
                        "(0 = unbounded); expired requests are rejected "
                        "with reason 'deadline'")
    p.add_argument("--disk_cache", type=int, default=1,
                   help="1: verified on-disk tier under --train_dir")
    p.add_argument("--metrics", type=str, default="auto",
                   help="serving metrics JSONL path; 'auto' derives one "
                        "under --train_dir, 'none' disables")
    p.add_argument("--drain_every", type=int, default=32,
                   help="stdin mode: drain the queue every N lines")
    p.add_argument("--warmup", type=int, default=0,
                   help="AOT-precompile the planned dispatch "
                        "geometries over N test points (nonzero exit "
                        "when a planned bucket is left uncompiled); "
                        "alone: report and exit, with a traffic mode: "
                        "arm first, then serve warm")
    p.add_argument("--smoke_requests", type=int, default=0,
                   help="run an N-request synthetic smoke stream, "
                        "report, exit (nonzero on failure)")
    p.add_argument("--smoke_hot_frac", type=float, default=0.5,
                   help="smoke stream: fraction of requests drawn from "
                        "a small hot set of repeated queries")
    p.add_argument("--class_quota", action="append", default=None,
                   metavar="CLASS=FRAC",
                   help="per-class queue quota as a fraction of "
                        "--max_queue (repeatable, e.g. "
                        "--class_quota scavenger=0.25); defaults keep "
                        "interactive/batch at 1.0 and scavenger at 0.5")
    p.add_argument("--class_weight", action="append", default=None,
                   metavar="CLASS=W",
                   help="fair-queueing DRR weight per class "
                        "(repeatable; defaults interactive=8 batch=3 "
                        "scavenger=1)")
    p.add_argument("--smoke_class_mix", type=str, default="",
                   help="smoke stream tenant mix, e.g. "
                        "'interactive=0.2,batch=0.5,scavenger=0.3' "
                        "(empty = unclassed legacy stream)")
    p.add_argument("--trace", type=int, default=0,
                   help="1: per-request span tracing — obs.span lines "
                        "interleave into the metrics JSONL; render with "
                        "python -m fia_tpu.cli.obs "
                        "(docs/observability.md)")
    return p


def _parse_class_kv(pairs, cast) -> dict | None:
    """``["scavenger=0.25", ...]`` → {"scavenger": 0.25} (None in/out
    passthrough; validation happens in the serve layer)."""
    if not pairs:
        return None
    out = {}
    for kv in pairs:
        k, _, v = kv.partition("=")
        out[k.strip()] = cast(v)
    return out


def build_service(args):
    """Model + engine + service from the shared CLI plumbing."""
    if getattr(args, "trace", 0):
        from fia_tpu import obs

        obs.configure(trace=True)
    common.apply_backend(args)
    splits = common.load_splits(args)
    model, params = common.build_model(args, splits)
    name = common.model_name_for(args, splits=splits)
    _, state, _ = common.train_or_load(args, model, params, splits,
                                       verbose=False)
    mesh = common.mesh_for(args)
    engine = InfluenceEngine(
        model, state.params, splits["train"],
        cache_dir=args.train_dir, model_name=name,
        mesh=mesh, **common.engine_kwargs(args),
    )
    metrics = args.metrics
    if metrics == "none":
        metrics = None
    elif metrics == "auto":
        import os

        metrics = os.path.join(
            args.train_dir, f"serve-{args.model}-{args.dataset}.jsonl"
        )
    cfg = ServeConfig(
        max_batch=args.max_batch, max_queue=args.max_queue,
        cache_entries=args.cache_entries, coalesce=args.coalesce,
        default_deadline_s=args.request_deadline or None,
        disk_cache=bool(args.disk_cache), metrics_path=metrics,
        mesh=mesh,
        class_quotas=_parse_class_kv(
            getattr(args, "class_quota", None), float),
        class_weights=_parse_class_kv(
            getattr(args, "class_weight", None), int),
    )
    try:
        svc = InfluenceService(engine=engine, config=cfg)
    except Exception as e:
        # construction validates mesh liveness + fingerprint; report a
        # classified failure as an operator-readable line and a clean
        # nonzero exit, never a raw backend traceback
        kind = taxonomy.classify(e)
        if kind is None:
            raise
        line = {"event": "serve.construct_failed", "kind": kind,
                "error": str(e)}
        # the liveness probe attaches exactly which mesh members failed
        # (device ids always, whole hosts when an entire process's
        # devices are dark) — surfaced so the operator knows what to
        # rebuild around, not just that construction failed
        if getattr(e, "devices", None):
            line["devices"] = [int(d) for d in e.devices]
        if getattr(e, "hosts", None):
            line["hosts"] = [int(h) for h in e.hosts]
        print(json.dumps(line), file=sys.stderr)
        raise SystemExit(1)
    return svc, splits


def parse_request(line: str) -> Request | None:
    """One stdin line → Request (JSON object or bare ``u i``), None on
    blank lines."""
    line = line.strip()
    if not line:
        return None
    if line.startswith("{"):
        d = json.loads(line)
        kw = {}
        if d.get("class") is not None:
            kw["cls"] = str(d["class"])
        if d.get("tenant") is not None:
            kw["tenant"] = str(d["tenant"])
        return Request(user=int(d["user"]), item=int(d["item"]),
                       id=d.get("id"), deadline_s=d.get("deadline_s"),
                       **kw)
    parts = line.split()
    return Request(user=int(parts[0]), item=int(parts[1]))


def smoke_stream(test_x, n: int, hot_frac: float, seed: int,
                 class_mix: str = ""):
    """A repeat-heavy synthetic request stream over the test split:
    ``hot_frac`` of requests revisit a small hot set (what a real
    serving workload looks like, and what makes hot-tier hits
    assertable). ``class_mix`` ('cls=frac,...') samples a priority
    class per request from the given distribution; empty keeps the
    unclassed legacy stream."""
    rng = np.random.default_rng(seed)
    hot = test_x[rng.choice(len(test_x), size=max(4, n // 25),
                            replace=False)]
    classes, probs = None, None
    if class_mix:
        mix = _parse_class_kv(class_mix.split(","), float)
        classes = list(mix)
        total = sum(mix.values())
        probs = [mix[c] / total for c in classes]
    out = []
    for k in range(n):
        if rng.random() < hot_frac:
            u, i = hot[rng.integers(len(hot))]
        else:
            u, i = test_x[rng.integers(len(test_x))]
        kw = {}
        if classes:
            kw["cls"] = classes[int(rng.choice(len(classes), p=probs))]
            kw["tenant"] = f"t-{kw['cls']}"
        out.append(Request(user=int(u), item=int(i), id=f"smoke{k}",
                           **kw))
    return out


def run_smoke(svc: InfluenceService, splits, args) -> int:
    reqs = smoke_stream(np.asarray(splits["test"].x), args.smoke_requests,
                        args.smoke_hot_frac, args.seed,
                        class_mix=getattr(args, "smoke_class_mix", ""))
    responses = svc.run(reqs, drain_every=args.max_batch)
    report = svc.close()
    print(json.dumps({"event": "serve.smoke", **report}))

    failures = []
    unreasoned = [r for r in responses
                  if not r.ok and not r.reason]
    unresolved = len(reqs) - len(responses)
    if unreasoned or unresolved:
        failures.append(
            f"{len(unreasoned)} rejected without reason, "
            f"{unresolved} never resolved"
        )
    if svc.cache.stats.hits_hot <= 0:
        failures.append("hot-block cache never hit on a repeat-heavy "
                        "stream")
    if report["ok"] + sum(report["rejected"].values()) != len(reqs):
        failures.append("request accounting does not add up")
    for cls, lane in report.get("classes", {}).items():
        if lane["ok"] + sum(lane["rejected"].values()) != lane["requests"]:
            failures.append(f"class {cls!r} accounting does not add up")
    for f in failures:
        print(f"SMOKE FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"serve smoke ok: {report['ok']}/{len(reqs)} served, "
              f"hot hits {svc.cache.stats.hits_hot}, "
              f"p95 solve {report['solve_ms']['p95']}ms")
    return 1 if failures else 0


def run_warmup(svc: InfluenceService, splits, args) -> int:
    pts = np.asarray(splits["test"].x[: args.warmup], np.int64)
    info = svc.warmup(pts)
    print(json.dumps({"event": "serve.warmup", **info}))
    if not info["all_planned_compiled"]:
        print("WARMUP FAIL: planned dispatch geometries left "
              f"uncompiled (planned {info['planned_geometries']}, "
              f"aot {info['aot']})", file=sys.stderr)
        return 1
    return 0


def run_stdin(svc: InfluenceService, args) -> int:
    n = 0
    for line in sys.stdin:
        req = parse_request(line)
        if req is None:
            continue
        r = svc.submit(req)
        if r is not None:  # immediate rejection
            print(json.dumps(r.json()), flush=True)
        n += 1
        if args.drain_every and n % args.drain_every == 0:
            for resp in svc.drain():
                print(json.dumps(resp.json()), flush=True)
    for resp in svc.drain():
        print(json.dumps(resp.json()), flush=True)
    report = svc.close()
    print(json.dumps({"event": "serve.rollup.final", **report}),
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = add_serve_flags(common.base_parser(__doc__))
    args = p.parse_args(argv)
    svc, splits = build_service(args)
    if args.warmup:
        rc = run_warmup(svc, splits, args)
        if rc or not args.smoke_requests:
            return rc
    if args.smoke_requests:
        return run_smoke(svc, splits, args)
    return run_stdin(svc, args)


if __name__ == "__main__":
    raise SystemExit(main())
