"""RQ1 driver: influence-vs-retraining fidelity.

Equivalent of reference ``src/scripts/RQ1.py`` (+ ``RQ1.sh``), with the
argparse flags actually wired up. Outputs the same artifact fields —
actual_loss_diffs, predicted_loss_diffs, indices_to_remove — as
``output/RQ1-<model>-<dataset>.npz`` and prints the Pearson correlation.

Run:  python -m fia_tpu.cli.rq1 --dataset synthetic --model MF \
        --num_steps_train 3000 --num_steps_retrain 1500 --num_test 2
"""

from __future__ import annotations

import os

import numpy as np

from fia_tpu.cli import common
from fia_tpu.reliability import policy as rpolicy
from fia_tpu.reliability.journal import Journal
from fia_tpu.reliability.artifacts import publish_npz


def artifact_path(train_dir, model, dataset, args, test_indices, tag,
                  model_key=""):
    """Where this run banks its npz rows.

    The canonical reference-shaped name is RQ1-<model>-<dataset>.npz.
    Two divert rules keep hours of banked chip time safe from
    clobbering:

    - ``--test_indices`` resume runs always divert to a -pt<ids>
      suffix (merge via scripts/merge_rq1.py); an occupied -pt path
      banked under a different protocol/config ladders further to
      -pt<ids>-<protocol>[-m<digest>] instead of clobbering.
    - Any other run that finds an existing artifact written under a
      DIFFERENT protocol, train stream, or model config diverts to a
      protocol-suffixed name. "Same protocol" covers the retrain
      budget, removals, num_test, maxinf, seed, stream tag (stored in
      the npz since r4) AND, since r5, a model_key folding in the
      training hyperparameters (num_steps_train, lr, embed_size,
      damping, weight_decay via common.model_name_for) — runs
      differing only in those used to compare equal and overwrite the
      canonical artifact in place despite measuring different
      influence values. Same-protocol re-runs still overwrite in
      place, which is what makes chain retries idempotent. Artifacts
      predating any provenance field are treated as different
      (divert).
    """
    proto = (args.num_steps_retrain, args.retrain_times,
             args.num_to_remove, args.num_test, int(args.maxinf),
             args.seed, tag or "")

    def occupied_by_other(path):
        """True when ``path`` exists and was banked by a run with a
        different protocol, stream, or model config (or predates the
        provenance fields — treated as different, never clobbered)."""
        if not os.path.exists(path):
            return False
        try:
            with np.load(path, allow_pickle=False) as z:
                old = tuple(z["protocol"]) + (str(z["stream_tag"]),)
                old_key = (str(z["model_key"]) if "model_key" in z.files
                           else None)
        except Exception:
            return True
        return not (old == (*(int(x) for x in proto[:6]), proto[6])
                    and old_key == model_key)

    pstr = (f"{'' if not proto[6] else proto[6] + '-'}"
            f"r{proto[0]}x{proto[1]}n{proto[3]}rm{proto[2]}"
            + (f"-maxinf" if proto[4] else "")
            + (f"-seed{proto[5]}" if proto[5] else ""))

    def digested(path):
        """Last rung of the divert ladder: suffix the model_key digest.

        The digested path is checked for occupancy too — it is 8 hex
        chars of sha1(model_key), so two different model configs CAN
        collide there. A collision means every rung of the ladder is
        occupied by some other run; clobbering silently at the bottom
        rung would be exactly the artifact-loss bug class the ladder
        exists to prevent, so fail loudly instead (r5 advisor finding).
        """
        import hashlib

        digest = hashlib.sha1(model_key.encode()).hexdigest()[:8]
        dpath = path[: -len(".npz")] + f"-m{digest}.npz"
        if occupied_by_other(dpath):
            raise SystemExit(
                f"artifact ladder exhausted: {dpath} is already banked "
                f"by a different run (model_key digest collision at "
                f"m{digest}). Refusing to clobber hours of banked rows "
                "— move the existing artifact aside or change "
                "--train_dir."
            )
        return dpath

    canonical = os.path.join(train_dir, f"RQ1-{model}-{dataset}.npz")
    if args.test_indices:
        # resume runs never claim the canonical name; their -pt path
        # gets the same occupied-by-other laddering as any divert
        # (two resumes at the same indices but different retrain
        # protocol or training config must not clobber each other)
        suffix = "-".join(str(int(t)) for t in test_indices)
        pt = os.path.join(train_dir, f"RQ1-{model}-{dataset}-pt{suffix}.npz")
        if not occupied_by_other(pt):
            return pt
        ptp = pt[: -len(".npz")] + f"-{pstr}.npz"
        return ptp if not occupied_by_other(ptp) else digested(ptp)
    if not os.path.exists(canonical) or not occupied_by_other(canonical):
        return canonical
    divert = os.path.join(train_dir, f"RQ1-{model}-{dataset}-{pstr}.npz")
    # the divert name encodes the retrain protocol but not the model
    # config; two same-protocol runs differing only in training
    # hyperparameters would compute the SAME divert path
    return divert if not occupied_by_other(divert) else digested(divert)


def main(argv=None):
    args = common.base_parser(__doc__).parse_args(argv)
    common.apply_backend(args)

    from fia_tpu.eval.metrics import pearson, spearman
    from fia_tpu.eval.rq1 import test_retraining
    from fia_tpu.influence.engine import InfluenceEngine

    splits = common.load_splits(args)
    train, test = splits["train"], splits["test"]
    model, params = common.build_model(args, splits)
    print(f"users={model.num_users} items={model.num_items} "
          f"train={train.num_examples} test={test.num_examples} "
          f"params={model.num_params()}")

    mesh = common.mesh_for(args)
    log = common.event_log_for(args, "rq1")
    log.log("run_start", driver="rq1", **{
        k: v for k, v in vars(args).items() if not k.startswith("_")
    })
    trainer, state, batch = common.train_or_load(
        args, model, params, splits, event_log=log, mesh=mesh
    )

    engine = InfluenceEngine(
        model, state.params, train, mesh=mesh,
        cache_dir=args.train_dir,
        model_name=common.model_name_for(args, splits=splits),
        **common.engine_kwargs(args),
    )
    test_indices = common.pick_test_points(args, splits, engine.index)
    print(f"test indices: {list(map(int, test_indices))}")

    # Never clobber a banked artifact from a different run: resume
    # runs and different-protocol/stream runs divert to suffixed
    # paths; only same-protocol re-runs overwrite (idempotent chain
    # retries). See artifact_path.
    tag = common.synth_tag_for(args, splits)
    # model_key folds the training hyperparameters into provenance;
    # lr/num_steps_train are not in model_name_for's checkpoint key, so
    # append them explicitly (ADVICE r4: two runs differing only in
    # training config must not overwrite each other's artifact)
    model_key = (f"{common.model_name_for(args, splits=splits)}"
                 f"_steps{args.num_steps_train}_lr{args.lr:g}")
    art_path = artifact_path(
        args.train_dir, args.model, args.dataset, args, test_indices, tag,
        model_key=model_key,
    )
    if os.path.basename(art_path) != f"RQ1-{args.model}-{args.dataset}.npz":
        print(f"existing artifact kept; rows -> {art_path}")

    # Resumable chain (fia_tpu/reliability): each completed test point is
    # journaled next to its artifact with the exact arrays the npz rows
    # are built from, so a killed chain restarted with --resume recomputes
    # ZERO completed points and emits a byte-identical npz. The journal
    # fingerprint binds the rows to this exact run (model config, retrain
    # protocol, stream, test indices) — a mismatched --resume fails loudly
    # (JournalMismatch) rather than stitching rows from a different run.
    jpath = os.path.join(
        args.train_dir,
        "." + os.path.basename(art_path)[: -len(".npz")] + ".journal.jsonl",
    )
    fingerprint = {
        "kind": "rq1-chain",
        "model_key": model_key,
        "protocol": [args.num_steps_retrain, args.retrain_times,
                     args.num_to_remove, args.num_test, int(args.maxinf),
                     args.seed],
        "stream_tag": tag or "",
        "test_indices": [int(i) for i in test_indices],
    }
    deadline = rpolicy.Deadline(args.deadline)

    actuals, predictions, removed = [], [], []
    repeat_rows, drift_rows, y0s = [], [], []

    def bank_rows():
        # per-test-point rows can be ragged (a test point's related set
        # may hold fewer than num_to_remove rows), so stack as flat
        # arrays plus per-row test-point ids rather than a (T, R) matrix.
        # repeat_y rows align with actual_loss_diffs rows; the per-point
        # drift lane and original prediction ride alongside so the
        # noise-floor decomposition (scripts/fidelity_spread.py) can run
        # from the artifact alone
        # published through the integrity layer: the npz bytes stay
        # identical to a plain savez (resume byte-identity contract),
        # and the sidecar manifest binds the rows to the same journal
        # fingerprint that guards --resume
        publish_npz(
            art_path,
            dict(
                actual_loss_diffs=np.concatenate(actuals),
                predicted_loss_diffs=np.concatenate(predictions),
                indices_to_remove=np.concatenate(removed),
                test_index_of_row=np.repeat(
                    [int(i) for i in test_indices[: len(actuals)]],
                    [len(a) for a in actuals],
                ),
                repeat_y=np.concatenate(repeat_rows),
                drift_repeat_y=np.stack(drift_rows),
                y0_of_point=np.asarray(y0s, np.float32),
                # provenance (r4): lets artifact_path distinguish a
                # same-protocol re-run (overwrite) from a different run
                # (divert), and lets post-processing label rows
                protocol=np.asarray([args.num_steps_retrain,
                                     args.retrain_times, args.num_to_remove,
                                     args.num_test, int(args.maxinf),
                                     args.seed], np.int64),
                stream_tag=np.asarray(tag),
                model_key=np.asarray(model_key),
            ),
            fingerprint=fingerprint,
        )

    saved = False
    with Journal.open(jpath, fingerprint, resume=args.resume) as journal:
        for t in test_indices:
            point_key = f"point:{int(t)}"
            if journal.done(point_key):
                p = journal.get(point_key)
                actuals.append(p["actual_y_diffs"])
                predictions.append(p["predicted_y_diffs"])
                removed.append(p["indices_to_remove"])
                repeat_rows.append(p["per_repeat_y"][:-1])
                drift_rows.append(p["per_repeat_y"][-1])
                y0s.append(p["y0"])
                print(f"test {int(t)}: restored from journal "
                      f"(pearson r = {p['pearson']:.4f})")
                log.log("test_point_restored", test_idx=int(t),
                        pearson=float(p["pearson"]))
                continue
            # a spent wall-clock budget stops the chain cleanly BETWEEN
            # points — but never before at least one point is banked, so
            # every run makes forward progress for --resume to build on
            if deadline.expired() and actuals:
                print(f"[reliability] deadline ({args.deadline:g}s) "
                      f"reached after {len(actuals)} point(s); rerun "
                      "with --resume to continue")
                log.log("deadline_stop", points_done=len(actuals))
                break
            res = test_retraining(
                engine, train, test, int(t),
                num_to_remove=args.num_to_remove,
                num_steps=args.num_steps_retrain,
                batch_size=batch,
                learning_rate=args.lr,
                retrain_times=args.retrain_times,
                remove_type="maxinf" if args.maxinf else "random",
                lane_chunk=args.lane_chunk,
                steps_per_dispatch=args.steps_per_dispatch,
                mesh=mesh, event_log=log,
            )
            r = pearson(res.actual_y_diffs, res.predicted_y_diffs)
            print(f"test {int(t)}: pearson r = {r:.4f} "
                  f"(bias_retrain {res.bias_retrain:+.5f})")
            log.log("test_point_done", test_idx=int(t), pearson=float(r),
                    bias_retrain=float(res.bias_retrain))
            actuals.append(res.actual_y_diffs)
            predictions.append(res.predicted_y_diffs)
            removed.append(res.indices_to_remove)
            repeat_rows.append(res.per_repeat_y[:-1])
            drift_rows.append(res.per_repeat_y[-1])
            y0s.append(res.y0)

            bank_rows()
            saved = True
            # journal AFTER the npz save: a crash between the two leaves
            # the point un-journaled and it is simply recomputed (and the
            # npz idempotently rewritten) on --resume
            journal.record(point_key, {
                "actual_y_diffs": np.asarray(res.actual_y_diffs),
                "predicted_y_diffs": np.asarray(res.predicted_y_diffs),
                "indices_to_remove": np.asarray(res.indices_to_remove),
                "per_repeat_y": np.asarray(res.per_repeat_y),
                "y0": float(res.y0),
                "pearson": float(r),
                "bias_retrain": float(res.bias_retrain),
            })
    if actuals and not saved:
        # every point came from the journal (e.g. the killed run died
        # after its last point's journal append but before exit, or the
        # artifact was removed) — rewrite the npz from the restored rows
        bank_rows()

    a = np.concatenate(actuals)
    p = np.concatenate(predictions)
    print(f"Correlation is {pearson(a, p):.6f} (spearman {spearman(a, p):.6f})")
    log.log("run_done", pearson=float(pearson(a, p)),
            spearman=float(spearman(a, p)))
    log.close()
    return pearson(a, p)


if __name__ == "__main__":
    main()
