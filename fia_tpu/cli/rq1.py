"""RQ1 driver: influence-vs-retraining fidelity.

Equivalent of reference ``src/scripts/RQ1.py`` (+ ``RQ1.sh``), with the
argparse flags actually wired up. Outputs the same artifact fields —
actual_loss_diffs, predicted_loss_diffs, indices_to_remove — as
``output/RQ1-<model>-<dataset>.npz`` and prints the Pearson correlation.

Run:  python -m fia_tpu.cli.rq1 --dataset synthetic --model MF \
        --num_steps_train 3000 --num_steps_retrain 1500 --num_test 2
"""

from __future__ import annotations

import os

import numpy as np

from fia_tpu.cli import common
from fia_tpu.utils.io import save_npz_atomic


def artifact_path(train_dir, model, dataset, args, test_indices, tag,
                  model_key=""):
    """Where this run banks its npz rows.

    The canonical reference-shaped name is RQ1-<model>-<dataset>.npz.
    Two divert rules keep hours of banked chip time safe from
    clobbering:

    - ``--test_indices`` resume runs always divert to a -pt<ids>
      suffix (merge via scripts/merge_rq1.py); an occupied -pt path
      banked under a different protocol/config ladders further to
      -pt<ids>-<protocol>[-m<digest>] instead of clobbering.
    - Any other run that finds an existing artifact written under a
      DIFFERENT protocol, train stream, or model config diverts to a
      protocol-suffixed name. "Same protocol" covers the retrain
      budget, removals, num_test, maxinf, seed, stream tag (stored in
      the npz since r4) AND, since r5, a model_key folding in the
      training hyperparameters (num_steps_train, lr, embed_size,
      damping, weight_decay via common.model_name_for) — runs
      differing only in those used to compare equal and overwrite the
      canonical artifact in place despite measuring different
      influence values. Same-protocol re-runs still overwrite in
      place, which is what makes chain retries idempotent. Artifacts
      predating any provenance field are treated as different
      (divert).
    """
    proto = (args.num_steps_retrain, args.retrain_times,
             args.num_to_remove, args.num_test, int(args.maxinf),
             args.seed, tag or "")

    def occupied_by_other(path):
        """True when ``path`` exists and was banked by a run with a
        different protocol, stream, or model config (or predates the
        provenance fields — treated as different, never clobbered)."""
        if not os.path.exists(path):
            return False
        try:
            with np.load(path, allow_pickle=False) as z:
                old = tuple(z["protocol"]) + (str(z["stream_tag"]),)
                old_key = (str(z["model_key"]) if "model_key" in z.files
                           else None)
        except Exception:
            return True
        return not (old == (*(int(x) for x in proto[:6]), proto[6])
                    and old_key == model_key)

    pstr = (f"{'' if not proto[6] else proto[6] + '-'}"
            f"r{proto[0]}x{proto[1]}n{proto[3]}rm{proto[2]}"
            + (f"-maxinf" if proto[4] else "")
            + (f"-seed{proto[5]}" if proto[5] else ""))

    def digested(path):
        import hashlib

        digest = hashlib.sha1(model_key.encode()).hexdigest()[:8]
        return path[: -len(".npz")] + f"-m{digest}.npz"

    canonical = os.path.join(train_dir, f"RQ1-{model}-{dataset}.npz")
    if args.test_indices:
        # resume runs never claim the canonical name; their -pt path
        # gets the same occupied-by-other laddering as any divert
        # (two resumes at the same indices but different retrain
        # protocol or training config must not clobber each other)
        suffix = "-".join(str(int(t)) for t in test_indices)
        pt = os.path.join(train_dir, f"RQ1-{model}-{dataset}-pt{suffix}.npz")
        if not occupied_by_other(pt):
            return pt
        ptp = pt[: -len(".npz")] + f"-{pstr}.npz"
        return ptp if not occupied_by_other(ptp) else digested(ptp)
    if not os.path.exists(canonical) or not occupied_by_other(canonical):
        return canonical
    divert = os.path.join(train_dir, f"RQ1-{model}-{dataset}-{pstr}.npz")
    # the divert name encodes the retrain protocol but not the model
    # config; two same-protocol runs differing only in training
    # hyperparameters would compute the SAME divert path
    return divert if not occupied_by_other(divert) else digested(divert)


def main(argv=None):
    args = common.base_parser(__doc__).parse_args(argv)
    common.apply_backend(args)

    from fia_tpu.eval.metrics import pearson, spearman
    from fia_tpu.eval.rq1 import test_retraining
    from fia_tpu.influence.engine import InfluenceEngine

    splits = common.load_splits(args)
    train, test = splits["train"], splits["test"]
    model, params = common.build_model(args, splits)
    print(f"users={model.num_users} items={model.num_items} "
          f"train={train.num_examples} test={test.num_examples} "
          f"params={model.num_params()}")

    mesh = common.mesh_for(args)
    log = common.event_log_for(args, "rq1")
    log.log("run_start", driver="rq1", **{
        k: v for k, v in vars(args).items() if not k.startswith("_")
    })
    trainer, state, batch = common.train_or_load(
        args, model, params, splits, event_log=log, mesh=mesh
    )

    engine = InfluenceEngine(
        model, state.params, train, mesh=mesh,
        cache_dir=args.train_dir,
        model_name=common.model_name_for(args, splits=splits),
        **common.engine_kwargs(args),
    )
    test_indices = common.pick_test_points(args, splits, engine.index)
    print(f"test indices: {list(map(int, test_indices))}")

    # Never clobber a banked artifact from a different run: resume
    # runs and different-protocol/stream runs divert to suffixed
    # paths; only same-protocol re-runs overwrite (idempotent chain
    # retries). See artifact_path.
    tag = common.synth_tag_for(args, splits)
    # model_key folds the training hyperparameters into provenance;
    # lr/num_steps_train are not in model_name_for's checkpoint key, so
    # append them explicitly (ADVICE r4: two runs differing only in
    # training config must not overwrite each other's artifact)
    model_key = (f"{common.model_name_for(args, splits=splits)}"
                 f"_steps{args.num_steps_train}_lr{args.lr:g}")
    art_path = artifact_path(
        args.train_dir, args.model, args.dataset, args, test_indices, tag,
        model_key=model_key,
    )
    if os.path.basename(art_path) != f"RQ1-{args.model}-{args.dataset}.npz":
        print(f"existing artifact kept; rows -> {art_path}")

    actuals, predictions, removed = [], [], []
    repeat_rows, drift_rows, y0s = [], [], []
    for t in test_indices:
        res = test_retraining(
            engine, train, test, int(t),
            num_to_remove=args.num_to_remove,
            num_steps=args.num_steps_retrain,
            batch_size=batch,
            learning_rate=args.lr,
            retrain_times=args.retrain_times,
            remove_type="maxinf" if args.maxinf else "random",
            lane_chunk=args.lane_chunk,
            steps_per_dispatch=args.steps_per_dispatch,
            mesh=mesh, event_log=log,
        )
        r = pearson(res.actual_y_diffs, res.predicted_y_diffs)
        print(f"test {int(t)}: pearson r = {r:.4f} "
              f"(bias_retrain {res.bias_retrain:+.5f})")
        log.log("test_point_done", test_idx=int(t), pearson=float(r),
                bias_retrain=float(res.bias_retrain))
        actuals.append(res.actual_y_diffs)
        predictions.append(res.predicted_y_diffs)
        removed.append(res.indices_to_remove)
        repeat_rows.append(res.per_repeat_y[:-1])
        drift_rows.append(res.per_repeat_y[-1])
        y0s.append(res.y0)

        # per-test-point rows can be ragged (a test point's related set
        # may hold fewer than num_to_remove rows), so stack as flat
        # arrays plus per-row test-point ids rather than a (T, R) matrix.
        # repeat_y rows align with actual_loss_diffs rows; the per-point
        # drift lane and original prediction ride alongside so the
        # noise-floor decomposition (scripts/fidelity_spread.py) can run
        # from the artifact alone
        save_npz_atomic(
            art_path,
            actual_loss_diffs=np.concatenate(actuals),
            predicted_loss_diffs=np.concatenate(predictions),
            indices_to_remove=np.concatenate(removed),
            test_index_of_row=np.repeat(
                [int(i) for i in test_indices[: len(actuals)]],
                [len(a) for a in actuals],
            ),
            repeat_y=np.concatenate(repeat_rows),
            drift_repeat_y=np.stack(drift_rows),
            y0_of_point=np.asarray(y0s, np.float32),
            # provenance (r4): lets artifact_path distinguish a
            # same-protocol re-run (overwrite) from a different run
            # (divert), and lets post-processing label rows
            protocol=np.asarray([args.num_steps_retrain,
                                 args.retrain_times, args.num_to_remove,
                                 args.num_test, int(args.maxinf),
                                 args.seed], np.int64),
            stream_tag=np.asarray(tag),
            model_key=np.asarray(model_key),
        )

    a = np.concatenate(actuals)
    p = np.concatenate(predictions)
    print(f"Correlation is {pearson(a, p):.6f} (spearman {spearman(a, p):.6f})")
    log.log("run_done", pearson=float(pearson(a, p)),
            spearman=float(spearman(a, p)))
    log.close()
    return pearson(a, p)


if __name__ == "__main__":
    main()
