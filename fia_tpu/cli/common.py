"""Shared CLI plumbing for the RQ drivers.

The reference wrote an argparse block and then commented it out, so its
shell sweeps silently all ran one hardcoded config (SURVEY.md §2.3).
Here the same knob names (``RQ1.py:18-34``) are real flags, plus
``--backend`` (north-star requirement) and the solver/scale knobs this
framework adds.
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from fia_tpu.data.loaders import load_dataset
from fia_tpu.data.synthetic import synthetic_splits
from fia_tpu.models import MODELS
from fia_tpu.train.trainer import Trainer, TrainConfig
from fia_tpu.train import checkpoint

# Reference batch sizes: exact divisors of the train-set sizes
# (RQ1.py:68, 71).
BATCH_SIZES = {"movielens": 3020, "yelp": 3009}


def base_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    # reference knobs (names preserved)
    p.add_argument("--avextol", type=float, default=1e-3,
                   help="solver tolerance for the influence solve")
    p.add_argument("--damping", type=float, default=1e-6)
    p.add_argument("--weight_decay", type=float, default=1e-3)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--embed_size", type=int, default=16)
    p.add_argument("--maxinf", type=int, default=1,
                   help="1: remove most-influential rows; 0: random")
    p.add_argument("--dataset", type=str, default="movielens",
                   choices=["movielens", "yelp", "synthetic"])
    p.add_argument("--model", type=str, default="MF", choices=["MF", "NCF"])
    p.add_argument("--num_test", type=int, default=5)
    p.add_argument("--test_indices", type=int, nargs="+", default=None,
                   help="explicit test-split row indices; overrides the "
                        "num_test sampler (resume a truncated run's "
                        "missing points, or probe a specific query)")
    p.add_argument("--num_steps_train", type=int, default=80_000)
    p.add_argument("--num_steps_retrain", type=int, default=24_000)
    p.add_argument("--reset_adam", type=int, default=0)
    p.add_argument("--load_checkpoint", type=int, default=1)
    p.add_argument("--retrain_times", type=int, default=4)
    p.add_argument("--num_to_remove", type=int, default=50,
                   help="training rows removed per test point for RQ1 "
                        "ground truth (experiments.py:18 default; the "
                        "reference RQ1 driver passes 1)")
    p.add_argument("--lane_chunk", type=int, default=32,
                   help="LOO retraining lanes per device dispatch; lower "
                        "for big models on fragile tunnel workers")
    p.add_argument("--steps_per_dispatch", type=int, default=2000,
                   help="max retraining steps per device dispatch")
    p.add_argument("--sort_test_case", type=int, default=0,
                   help="1: pick the least-supported test points")
    # framework knobs
    p.add_argument("--backend", type=str, default=None,
                   choices=[None, "tpu", "cpu"],
                   help="force a JAX platform (default: auto)")
    p.add_argument("--solver", type=str, default="direct",
                   choices=["direct", "cg", "lissa", "schulz",
                            "precomputed", "sampled"])
    p.add_argument("--sampled_cap", type=int, default=None,
                   help="sampled-rung Hessian sample cap per query "
                        "(docs/design.md §22; default: the engine's "
                        "DEFAULT_CAP). Queries with fewer related rows "
                        "are exact (err_bound 0)")
    p.add_argument("--sampled_tol", type=float, default=None,
                   help="sampled-rung certificate tolerance: queries "
                        "whose err_bound exceeds it escalate one ladder "
                        "rung (default: inf — always serve sampled)")
    p.add_argument("--cg_maxiter", type=int, default=100,
                   help="CG iteration cap (reference fmin_ncg maxiter, "
                        "matrix_factorization.py:431)")
    p.add_argument("--lissa_depth", type=int, default=10_000,
                   help="LiSSA recursion depth (reference default, "
                        "genericNeuralNet.py:544)")
    p.add_argument("--lissa_scale", type=float, default=10.0,
                   help="LiSSA scale (reference genericNeuralNet.py:511)")
    p.add_argument("--impl", type=str, default="auto",
                   choices=["auto", "flat", "padded"],
                   help="query implementation: flat segment-sum or "
                        "padded per-query vmap")
    p.add_argument("--mesh", type=int, default=0,
                   help="shard query batches, training and LOO retraining "
                        "over an N-device 'data' mesh (0 = single device)")
    p.add_argument("--model_parallel", type=int, default=1,
                   help="row-shard the embedding tables over a 'model' "
                        "mesh axis of this size (must divide --mesh; 1 = "
                        "replicated tables). >1 builds a 2-D "
                        "('data','model') mesh and turns on the engine's "
                        "shard_tables placement — for tables too large "
                        "for one device's HBM (docs/design.md §20)")
    p.add_argument("--log_file", type=str, default="auto",
                   help="JSONL event log path; 'auto' derives one under "
                        "--train_dir, 'none' disables")
    p.add_argument("--pad_policy", type=str, default="batch",
                   choices=["batch", "dataset"],
                   help="pad queries to the batch max (least compute) or "
                        "the dataset ceiling (one compile for any batch)")
    p.add_argument("--data_dir", type=str, default="data")
    p.add_argument("--train_dir", type=str, default="output")
    p.add_argument("--batch_size", type=int, default=0,
                   help="0 = reference default for the dataset")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--calibrate", type=int, default=1,
                   help="1: synthesize missing train splits calibrated to "
                        "the real valid/test marginals; 0: generic Zipf "
                        "generator (the round-1 measurement stream)")
    p.add_argument("--cal_rev", choices=["cal2", "cal3"], default="cal2",
                   help="calibrated-stream revision: cal2 (the r3/r4 "
                        "measurement stream) or cal3 (saturation-"
                        "compensated item head, r4); tags flow into "
                        "checkpoint names so streams never share "
                        "checkpoints")
    # synthetic scale (used when --dataset synthetic)
    p.add_argument("--synth_users", type=int, default=600)
    p.add_argument("--synth_items", type=int, default=400)
    p.add_argument("--synth_train", type=int, default=50_000)
    p.add_argument("--synth_test", type=int, default=500)
    p.add_argument("--query_batch", type=int, default=0,
                   help="cap queries per device dispatch (0 = all at "
                        "once); >0 routes through the pipelined "
                        "query_many — e.g. 32 for the k=256 sweep "
                        "point whose 64-query dispatch kills the TPU "
                        "worker (BASELINE §4.1)")
    p.add_argument("--synth_stream", choices=["zipf", "cal"],
                   default="zipf",
                   help="synthetic train stream: 'zipf' (r1 generator) "
                        "or 'cal' (cal2-style waterfilled unique pairs "
                        "— scales with no reference heldout, e.g. "
                        "ML-20M fidelity rows)")
    # reliability (fia_tpu/reliability): preemption-tolerant execution
    p.add_argument("--resume", action="store_true",
                   help="resume an interrupted chain from its progress "
                        "journal: completed test points are loaded, not "
                        "recomputed (journal fingerprint must match — a "
                        "mismatch fails loudly rather than stitching "
                        "rows from a different run)")
    p.add_argument("--deadline", type=float, default=0.0,
                   help="wall-clock budget in seconds (0 = none); the "
                        "chain stops cleanly between test points when "
                        "the budget is spent, with all completed points "
                        "journaled for --resume")
    p.add_argument("--checkpoint_every", type=int, default=0,
                   help="publish a rotated training checkpoint every N "
                        "steps so a killed run auto-resumes from the "
                        "last good generation (0 = auto: num_steps/4; "
                        "-1 disables periodic checkpointing)")
    p.add_argument("--checkpoint_keep", type=int, default=3,
                   help="rotated checkpoint generations to retain")
    return p


def engine_kwargs(args) -> dict:
    """Solver/impl engine kwargs shared by every driver.

    The solver name routes through the one ladder-aware resolution path
    (reliability/policy.resolve_solver) — the same call api.FIAModel
    makes — so a CLI run and the library agree on what a configured
    solver means."""
    from fia_tpu.reliability.policy import resolve_solver

    kw = dict(
        damping=args.damping,
        solver=resolve_solver(args.solver),
        pad_policy=args.pad_policy,
        cg_tol=cg_tol_for(args),
        cg_maxiter=args.cg_maxiter,
        lissa_depth=args.lissa_depth,
        lissa_scale=args.lissa_scale,
        impl=args.impl,
        shard_tables=getattr(args, "model_parallel", 1) > 1,
    )
    if getattr(args, "sampled_cap", None) is not None:
        kw["sampled_cap"] = args.sampled_cap
    if getattr(args, "sampled_tol", None) is not None:
        kw["sampled_tol"] = args.sampled_tol
    return kw


def mesh_for(args):
    """A Mesh over the first --mesh devices (None when 0): 1-D 'data'
    by default, 2-D ('data','model') when --model_parallel > 1."""
    if not getattr(args, "mesh", 0):
        if getattr(args, "model_parallel", 1) > 1:
            raise SystemExit("--model_parallel > 1 requires --mesh N")
        return None
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < args.mesh:
        raise SystemExit(
            f"--mesh {args.mesh} requested but only {len(devs)} devices "
            "are visible (set XLA_FLAGS=--xla_force_host_platform_device_"
            "count=N for a virtual CPU mesh)"
        )
    mp = int(getattr(args, "model_parallel", 1))
    if mp > 1:
        from fia_tpu.parallel.sharded import make_2d_mesh

        try:
            return make_2d_mesh(args.mesh, model_parallel=mp)
        except ValueError as e:
            raise SystemExit(str(e))
    return Mesh(np.asarray(devs[: args.mesh]), ("data",))


def event_log_for(args, driver: str):
    """EventLog from --log_file ('auto' derives a per-run path)."""
    from fia_tpu.utils.logging import EventLog

    path = args.log_file
    if path == "none":
        path = None
    elif path == "auto":
        path = os.path.join(
            args.train_dir, f"events-{driver}-{args.model}-{args.dataset}.jsonl"
        )
    return EventLog(path)


def cg_tol_for(args) -> float:
    """Engine cg_tol from the reference's --avextol knob.

    fmin_ncg's avextol bounds the change in the quadratic objective; the
    CG loop stops on the squared-residual ratio, so the scale differs —
    1e-6·avextol reproduces the reference's effective accuracy at its
    default avextol=1e-3. One mapping shared by all drivers.
    """
    return args.avextol * 1e-6


def apply_backend(args) -> None:
    if args.backend not in ("cpu", "tpu"):
        return
    # jax is already imported by this module's own imports, so the env var
    # alone is too late (it is read once at jax import); jax.config still
    # takes effect as long as no backend has been initialised yet. The env
    # var is set too so spawned subprocesses inherit the choice.
    os.environ["JAX_PLATFORMS"] = args.backend
    import jax

    jax.config.update("jax_platforms", args.backend)


def explicit_test_indices(args, test):
    """Validated ``--test_indices`` as an int64 array, or None when the
    flag is unset. The single source of truth for every driver (rq1 via
    pick_test_points, rq2 directly); load_splits also calls it so a
    typo'd index fails BEFORE the training phase, which can cost hours
    on a resumed full protocol."""
    vals = getattr(args, "test_indices", None)
    if not vals:
        return None
    idx = np.asarray(vals, dtype=np.int64)
    if idx.min() < 0 or idx.max() >= test.num_examples:
        raise SystemExit(
            f"--test_indices out of range [0, {test.num_examples})"
        )
    return idx


def load_splits(args):
    if args.dataset == "synthetic":
        if getattr(args, "synth_stream", "zipf") == "cal":
            from fia_tpu.data.synthetic import calibrated_splits

            splits = calibrated_splits(
                args.synth_users, args.synth_items, args.synth_train,
                args.synth_test, seed=args.seed,
            )
            # tag checkpoints so a cal-stream run never loads a
            # Zipf-stream checkpoint (and vice versa)
            args._synth_tag = "calsynth"
        else:
            splits = synthetic_splits(
                args.synth_users, args.synth_items, args.synth_train,
                args.synth_test, seed=args.seed,
            )
    else:
        splits = load_dataset(args.dataset, args.data_dir,
                              synthesize_train=True, synth_seed=args.seed,
                              calibrate=bool(getattr(args, "calibrate", 1)),
                              cal_rev=getattr(args, "cal_rev", "cal2"))
        # generator tag flows into checkpoint/model names (model_name_for):
        # a calibrated-split run must never load a Zipf-split checkpoint
        args._synth_tag = getattr(splits["train"], "synth_tag", "")
    explicit_test_indices(args, splits["test"])  # fail fast, all paths
    return splits


def batch_size_for(args, train) -> int:
    if args.batch_size:
        return args.batch_size
    if args.dataset in BATCH_SIZES:
        return BATCH_SIZES[args.dataset]
    return max(1, min(3000, train.num_examples // 10))


def synth_tag_for(args, splits=None) -> str:
    """The train stream's generator tag ('cal2', 'cal3', 'calsynth',
    '' for real/Zipf streams). Pass ``splits`` whenever they are in
    hand: the tag is read directly from the train split, so it cannot
    silently drop when a caller never went through load_splits (which
    stashes the same tag on args as a fallback for split-free paths).
    The single resolver for checkpoint names AND artifact provenance —
    two sites disagreeing here would let a cal3 run load a cal2
    checkpoint or clobber its artifact."""
    if splits is not None:
        return getattr(splits["train"], "synth_tag", "")
    return getattr(args, "_synth_tag", "")


def model_name_for(args, wd=None, splits=None) -> str:
    """Checkpoint/model-name key (see synth_tag_for on the tag)."""
    wd = args.weight_decay if wd is None else wd
    tag = synth_tag_for(args, splits)
    return (
        f"{args.dataset}_{args.model}_explicit_damping{args.damping:.0e}"
        f"_avextol{args.avextol:.0e}_embed{args.embed_size}"
        f"_maxinf{args.maxinf}_wd{wd:.0e}"
        + (f"_{tag}" if tag else "")
    )


def build_model(args, splits):
    import jax

    train = splits["train"]
    num_users = max(int(np.max(s.x[:, 0])) + 1 for s in splits.values())
    num_items = max(int(np.max(s.x[:, 1])) + 1 for s in splits.values())
    model = MODELS[args.model](
        num_users=num_users, num_items=num_items,
        embedding_size=args.embed_size, weight_decay=args.weight_decay,
    )
    params = model.init_params(jax.random.PRNGKey(args.seed))
    return model, params


def train_fingerprint(args, name, num_steps, batch) -> dict:
    """The training-run config fingerprint stamped on checkpoint
    manifests. One resolver for terminal AND rotated generations, so a
    checkpoint from a different config (seed, step budget, lr) is
    rejected at restore time rather than silently trusted."""
    return {
        "kind": "train-ckpt",
        "model_key": name,
        "seed": int(args.seed),
        "num_steps": int(num_steps),
        "batch": int(batch),
        "lr": float(args.lr),
    }


def train_or_load(args, model, params, splits, num_steps=None, verbose=True,
                  event_log=None, mesh=None):
    """Reference RQ2.py:102-109 train-or-load behavior, crash-safe.

    Restore ladder: (1) the terminal checkpoint when valid; (2) the
    newest valid rotated generation from a prior killed run (training
    resumes from its step, not step 0); (3) train from scratch. Training
    publishes rotated generations every --checkpoint_every steps, and a
    corrupt/mismatched terminal checkpoint falls through this ladder
    instead of crashing the driver.
    """
    from fia_tpu.reliability.artifacts import ArtifactIntegrityError
    from fia_tpu.train.trainer import TrainState
    from fia_tpu.utils.io import sweep_stale_tmps

    num_steps = num_steps or args.num_steps_train
    train = splits["train"]
    batch = batch_size_for(args, train)
    cfg = TrainConfig(batch_size=batch, num_steps=num_steps,
                      learning_rate=args.lr, seed=args.seed,
                      log_every=10_000 if verbose else 0)
    trainer = Trainer(model, cfg, event_log=event_log, mesh=mesh)
    state = trainer.init_state(params)

    name = model_name_for(args, splits=splits)
    ckpt = os.path.join(args.train_dir, f"{name}-checkpoint-{num_steps - 1}")
    fp = train_fingerprint(args, name, num_steps, batch)
    sweep_stale_tmps(args.train_dir)

    if args.load_checkpoint and checkpoint.exists(ckpt):
        print(f"Checkpoint found, loading {ckpt}")
        try:
            p, o, step = checkpoint.load(ckpt, state.params, state.opt_state)
            return trainer, TrainState(
                p, o if o is not None else state.opt_state, step
            ), batch
        except (ArtifactIntegrityError, ValueError) as e:
            # corrupt terminal checkpoint: quarantined by the integrity
            # layer; fall through to rotated generations / retraining
            print(f"Terminal checkpoint rejected ({e}); falling back")

    ckpter = None
    every = int(getattr(args, "checkpoint_every", 0))
    if every == 0:
        every = max(1, num_steps // 4)
    if every > 0:
        ckpter = checkpoint.PeriodicCheckpointer(
            os.path.join(args.train_dir, f"{name}-ckpts"),
            every=every, keep=int(getattr(args, "checkpoint_keep", 3)),
            fingerprint=fp,
        )

    if args.load_checkpoint and ckpter is not None:
        restored = checkpoint.restore_latest_valid(
            ckpter.dir_path, state.params, state.opt_state,
            fingerprint=fp, verbose=verbose,
        )
        if restored is not None:
            p, o, step = restored
            state = TrainState(
                p, o if o is not None else state.opt_state, step
            )
            ckpter._last_step = step

    remaining = num_steps - state.step
    if remaining > 0:
        if verbose:
            what = "Resuming" if state.step else "Training"
            print(f"{what} {args.model} at step {state.step}/{num_steps} "
                  f"(batch {batch})")
        state = trainer.fit(state, train.x, train.y, num_steps=remaining,
                            checkpointer=ckpter)
    os.makedirs(args.train_dir, exist_ok=True)
    checkpoint.save(ckpt, state.params, state.opt_state, state.step,
                    fingerprint=fp)
    if verbose:
        print(f"Saved checkpoint {ckpt}")
    return trainer, state, batch


def pick_test_points(args, splits, engine_index):
    """Random test points, or the least-supported ones when
    sort_test_case=1 (reference RQ1.py:130-137)."""
    test = splits["test"]
    idx = explicit_test_indices(args, test)
    if idx is not None:
        return idx
    rng = np.random.default_rng(args.seed)
    if args.sort_test_case:
        counts = np.array(
            [engine_index.related_count(int(u), int(i)) for u, i in test.x]
        )
        return np.argsort(counts)[: args.num_test]
    return rng.choice(test.num_examples, size=args.num_test, replace=False)
