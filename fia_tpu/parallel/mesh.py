"""Device-mesh helpers.

The reference has no distributed machinery at all (single tf.Session,
pinned device — SURVEY.md §2.4). The TPU-native scaling axes for this
workload are:

  - ``data``: test-query batches (influence) and train-row shards (full
    HVP accumulation) — collectives ride ICI via XLA-inserted psums.
  - ``model``: optional row-sharding of the user/item embedding tables
    for the scaled stress configs.

All entry points accept an optional Mesh; everything degrades to single
device when the mesh is None or trivial.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: int | None = None,
    axis_names: tuple[str, ...] = ("data",),
    shape: tuple[int, ...] | None = None,
) -> Mesh:
    """Build a Mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names)


def mesh_fingerprint(mesh: Mesh | None):
    """Hashable identity of a mesh layout, ``None`` for no mesh.

    Keys every compiled-executable cache that must distinguish device
    topologies (the engine's AOT geometry keys, serve-config/engine
    consistency checks): same axis names, same shape, same devices in
    the same order ⇒ same lowered shardings ⇒ reusable executable.
    """
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def shard_along(mesh: Mesh, tree, axis: str = "data", dim: int = 0):
    """Shard every leaf's ``dim`` dimension along a mesh axis."""

    def put(x):
        spec = [None] * x.ndim
        spec[dim] = axis
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(put, tree)


def replicate(mesh: Mesh, tree):
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
