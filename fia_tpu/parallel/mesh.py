"""Device-mesh helpers.

The reference has no distributed machinery at all (single tf.Session,
pinned device — SURVEY.md §2.4). The TPU-native scaling axes for this
workload are:

  - ``data``: test-query batches (influence) and train-row shards (full
    HVP accumulation) — collectives ride ICI via XLA-inserted psums.
  - ``model``: optional row-sharding of the user/item embedding tables
    for the scaled stress configs.

All entry points accept an optional Mesh; everything degrades to single
device when the mesh is None or trivial.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: int | None = None,
    axis_names: tuple[str, ...] = ("data",),
    shape: tuple[int, ...] | None = None,
) -> Mesh:
    """Build a Mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names)


def mesh_fingerprint(mesh: Mesh | None):
    """Hashable identity of a mesh layout, ``None`` for no mesh.

    Keys every compiled-executable cache that must distinguish device
    topologies (the engine's AOT geometry keys, serve-config/engine
    consistency checks): same axis names, same shape, same devices in
    the same order ⇒ same lowered shardings ⇒ reusable executable.
    """
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def live_device_ids() -> frozenset:
    """Ids of the devices the backend can see right now.

    The liveness baseline for device-loss handling: a mesh referencing
    an id outside this set is serving on a dead device. On a healthy
    host this is just ``jax.devices()``; when the backend itself is
    unreachable the empty set is returned (every mesh device then
    counts as lost, which is the honest answer).
    """
    try:
        return frozenset(int(d.id) for d in jax.devices())
    except Exception:
        return frozenset()


def lost_device_ids(mesh: Mesh | None) -> tuple[int, ...]:
    """Mesh device ids no longer visible to the backend (sorted)."""
    if mesh is None:
        return ()
    live = live_device_ids()
    return tuple(sorted(
        int(d.id) for d in mesh.devices.flat if int(d.id) not in live
    ))


def surviving_mesh(mesh: Mesh, lost_ids=()) -> Mesh | None:
    """The shrunk mesh after device loss: survivors, original order.

    ``lost_ids``: device ids known dead (:func:`lost_device_ids`). When
    empty — a dispatch fault classified ``device_lost`` without naming
    the culprit, the common case for injected losses and terse backend
    errors — the LAST mesh device is dropped: deterministic, and the
    *identity* of the dropped device never matters for results (every
    mesh size serves bit-identically, docs/design.md §15); only the
    shrink itself does. Returns ``None`` when no device would survive
    (or nothing would shrink — a named loss set disjoint from the
    mesh), so callers shed classified instead of rebuilding in place.

    A 2-D mesh with model parallelism keeps its trailing axis sizes
    when enough survivors remain to fill whole 'model' groups (excess
    survivors past the last full group are dropped too): a
    ``shard_tables`` engine re-placed on the shrunk mesh then stays
    row-sharded instead of silently re-replicating tables that may not
    fit one device. Only when survivors cannot fill even one group
    does the mesh collapse to trailing-axis size 1 (the engine's
    ``_sharded_now`` degrades to replicated placement — last resort
    over dying).
    """
    devs = list(mesh.devices.flat)
    lost = frozenset(int(i) for i in lost_ids)
    if lost:
        keep = [d for d in devs if int(d.id) not in lost]
        if len(keep) == len(devs):
            return None
    else:
        keep = devs[:-1]
    if not keep:
        return None
    tail = tuple(int(mesh.shape[a]) for a in mesh.axis_names[1:])
    mp = 1
    for t in tail:
        mp *= t
    if mp > 1 and len(keep) >= mp:
        keep = keep[: (len(keep) // mp) * mp]
        shape = (len(keep) // mp,) + tail
    else:
        shape = (len(keep),) + (1,) * (len(mesh.axis_names) - 1)
    return Mesh(np.asarray(keep).reshape(shape), tuple(mesh.axis_names))


def shard_along(mesh: Mesh, tree, axis: str = "data", dim: int = 0):
    """Shard every leaf's ``dim`` dimension along a mesh axis."""

    def put(x):
        spec = [None] * x.ndim
        spec[dim] = axis
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(put, tree)


def replicate(mesh: Mesh, tree):
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
