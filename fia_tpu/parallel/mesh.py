"""Device-mesh helpers.

The reference has no distributed machinery at all (single tf.Session,
pinned device — SURVEY.md §2.4). The TPU-native scaling axes for this
workload are:

  - ``data``: test-query batches (influence) and train-row shards (full
    HVP accumulation) — collectives ride ICI via XLA-inserted psums.
  - ``model``: optional row-sharding of the user/item embedding tables
    for the scaled stress configs.

All entry points accept an optional Mesh; everything degrades to single
device when the mesh is None or trivial.

Hosts: on a real pod every device carries the ``process_index`` of the
host that owns it, and host loss (all of one process's devices dying at
once) is a distinct failure granularity from device loss —
:func:`lost_host_ids` is the liveness probe, :func:`surviving_mesh`
accepts whole-host drops, and :func:`mesh_fingerprint` keys on the
host layout so a mesh rebuilt over a different host assignment never
reuses another topology's executables. CI runs single-process with
virtual CPU devices, so :func:`virtual_hosts` lets tests overlay a
device→host map and exercise every host-granularity path without a
second process.
"""

from __future__ import annotations

from contextlib import contextmanager

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Armed by virtual_hosts()/set_virtual_hosts(): device id -> host index.
# None means "trust the backend" (d.process_index). Process-global like
# the backend topology it stands in for; arm it from the test thread.
_VIRTUAL_HOSTS: dict[int, int] | None = None


def set_virtual_hosts(mapping: dict[int, int] | None) -> None:
    """Overlay a device-id→host-index map (None restores the backend).

    Single-process CI has every virtual device on process 0, which
    makes host-granularity code untestable. With a map armed,
    :func:`host_index` (and everything built on it: host fingerprints,
    host liveness, host-granular mesh shrinks) sees the overlay
    topology instead. Devices absent from the map fall back to their
    real ``process_index``.
    """
    global _VIRTUAL_HOSTS
    _VIRTUAL_HOSTS = None if mapping is None else {
        int(k): int(v) for k, v in mapping.items()
    }


@contextmanager
def virtual_hosts(mapping: dict[int, int]):
    """Scoped :func:`set_virtual_hosts` for tests and chaos scenarios."""
    global _VIRTUAL_HOSTS
    prev = _VIRTUAL_HOSTS
    set_virtual_hosts(mapping)
    try:
        yield
    finally:
        _VIRTUAL_HOSTS = prev


def host_index(device) -> int:
    """The host (process) index that owns ``device``.

    Honors an armed :func:`virtual_hosts` overlay; otherwise the
    backend's ``process_index``.
    """
    if _VIRTUAL_HOSTS is not None:
        h = _VIRTUAL_HOSTS.get(int(device.id))
        if h is not None:
            return h
    return int(device.process_index)


def mesh_hosts(mesh: Mesh | None) -> tuple[int, ...]:
    """Sorted distinct host indices a mesh spans (empty for no mesh)."""
    if mesh is None:
        return ()
    return tuple(sorted({host_index(d) for d in mesh.devices.flat}))


def init_pod_mesh(
    axis_names: tuple[str, ...] = ("data",),
    shape: tuple[int, ...] | None = None,
    **distributed_kwargs,
) -> Mesh:
    """Initialize the multi-host runtime and build a global pod mesh.

    Wraps :func:`fia_tpu.parallel.distributed.initialize` (idempotent;
    a no-op single-process) and lays *all* global devices — every
    host's, in backend order — into one mesh. Single-process this is
    exactly :func:`make_mesh` over the local devices, so callers write
    one code path for laptop CI and pod serving.
    """
    from fia_tpu.parallel import distributed

    distributed.initialize(**distributed_kwargs)
    return make_mesh(axis_names=axis_names, shape=shape)


def make_mesh(
    n_devices: int | None = None,
    axis_names: tuple[str, ...] = ("data",),
    shape: tuple[int, ...] | None = None,
) -> Mesh:
    """Build a Mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names)


def mesh_fingerprint(mesh: Mesh | None):
    """Hashable identity of a mesh layout, ``None`` for no mesh.

    Keys every compiled-executable cache that must distinguish device
    topologies (the engine's AOT geometry keys, serve-config/engine
    consistency checks): same axis names, same shape, same devices in
    the same order, same device→host assignment ⇒ same lowered
    shardings ⇒ reusable executable. The host layout is part of the
    identity because cross-host meshes lower to different collectives
    (DCN vs ICI links) than single-host ones with identical device ids
    — and it is stable across process restarts: a restarted coordinator
    rebuilding the same mesh over the same pod computes the same
    fingerprint and resumes its journals/AOT caches.
    """
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(int(d.id) for d in mesh.devices.flat),
        tuple(host_index(d) for d in mesh.devices.flat),
    )


def live_device_ids() -> frozenset:
    """Ids of the devices the backend can see right now.

    The liveness baseline for device-loss handling: a mesh referencing
    an id outside this set is serving on a dead device. On a healthy
    host this is just ``jax.devices()``; when the backend itself is
    unreachable the empty set is returned (every mesh device then
    counts as lost, which is the honest answer).
    """
    try:
        return frozenset(int(d.id) for d in jax.devices())
    except Exception:
        return frozenset()


def lost_device_ids(mesh: Mesh | None) -> tuple[int, ...]:
    """Mesh device ids no longer visible to the backend (sorted)."""
    if mesh is None:
        return ()
    live = live_device_ids()
    return tuple(sorted(
        int(d.id) for d in mesh.devices.flat if int(d.id) not in live
    ))


def lost_host_ids(mesh: Mesh | None) -> tuple[int, ...]:
    """Hosts *all* of whose mesh devices are dead (sorted).

    The host-granularity liveness probe: a collective timing out says
    "some peer is gone" without naming it, so recovery asks the backend
    which devices still answer and promotes a loss to host granularity
    only when an entire process's devices went dark together. A host
    with any surviving device is NOT listed — that is device loss, and
    the finer-grained shrink handles it.
    """
    if mesh is None:
        return ()
    live = live_device_ids()
    by_host: dict[int, list[bool]] = {}
    for d in mesh.devices.flat:
        by_host.setdefault(host_index(d), []).append(int(d.id) in live)
    return tuple(sorted(h for h, alive in by_host.items() if not any(alive)))


def surviving_mesh(
    mesh: Mesh, lost_ids=(), lost_hosts=(), unnamed: str = "device"
) -> Mesh | None:
    """The shrunk mesh after device or host loss: survivors, original
    order.

    ``lost_ids``: device ids known dead (:func:`lost_device_ids`).
    ``lost_hosts``: host indices known dead (:func:`lost_host_ids`) —
    every device they own is dropped, unioned with ``lost_ids``. When
    both are empty — a dispatch fault classified ``device_lost`` /
    ``host_lost`` without naming the culprit, the common case for
    injected losses and terse backend errors — a deterministic victim
    is dropped: the LAST mesh device (``unnamed="device"``) or the
    whole host owning the last mesh device (``unnamed="host"``).
    Deterministic, and the *identity* of the dropped unit never matters
    for results (every mesh size serves bit-identically,
    docs/design.md §15); only the shrink itself does. Returns ``None``
    when no device would survive (or nothing would shrink — a named
    loss set disjoint from the mesh), so callers shed classified
    instead of rebuilding in place.

    A 2-D mesh with model parallelism keeps its trailing axis sizes
    when enough survivors remain to fill whole 'model' groups (excess
    survivors past the last full group are dropped too): a
    ``shard_tables`` engine re-placed on the shrunk mesh then stays
    row-sharded instead of silently re-replicating tables that may not
    fit one device. Only when survivors cannot fill even one group
    does the mesh collapse to trailing-axis size 1 (the engine's
    ``_sharded_now`` degrades to replicated placement — last resort
    over dying). Host drops go through the same group math: losing a
    host is just losing its devices, one level up.
    """
    devs = list(mesh.devices.flat)
    lost = frozenset(int(i) for i in lost_ids)
    dead_hosts = frozenset(int(h) for h in lost_hosts)
    if dead_hosts:
        lost = lost | frozenset(
            int(d.id) for d in devs if host_index(d) in dead_hosts
        )
    if lost:
        keep = [d for d in devs if int(d.id) not in lost]
        if len(keep) == len(devs):
            return None
    elif unnamed == "host":
        victim = host_index(devs[-1])
        keep = [d for d in devs if host_index(d) != victim]
    else:
        keep = devs[:-1]
    if not keep:
        return None
    tail = tuple(int(mesh.shape[a]) for a in mesh.axis_names[1:])
    mp = 1
    for t in tail:
        mp *= t
    if mp > 1 and len(keep) >= mp:
        keep = keep[: (len(keep) // mp) * mp]
        shape = (len(keep) // mp,) + tail
    else:
        shape = (len(keep),) + (1,) * (len(mesh.axis_names) - 1)
    return Mesh(np.asarray(keep).reshape(shape), tuple(mesh.axis_names))


def shard_along(mesh: Mesh, tree, axis: str = "data", dim: int = 0):
    """Shard every leaf's ``dim`` dimension along a mesh axis."""

    def put(x):
        spec = [None] * x.ndim
        spec[dim] = axis
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(put, tree)


def replicate(mesh: Mesh, tree):
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
