from fia_tpu.parallel.mesh import make_mesh, shard_along, replicate  # noqa: F401
