from fia_tpu.parallel.mesh import make_mesh, shard_along, replicate  # noqa: F401
from fia_tpu.parallel.distributed import (  # noqa: F401
    initialize,
    runtime_info,
    make_hybrid_mesh,
    global_batch,
    process_local_rows,
)
