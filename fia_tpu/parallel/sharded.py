"""Model-parallel (sharded embedding table) configuration.

The scaling axes of this workload are #users/#items (embedding-table
rows) and #queries/#train-rows (data) — there is no sequence dimension
(SURVEY.md §2.4). For stress configs whose tables exceed one device's
HBM (e.g. MovieLens-20M at large k), tables are row-sharded over a
'model' mesh axis while queries/batches shard over 'data'; XLA inserts
the gather/psum collectives over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: param names holding per-user/per-item rows, per model class name
TABLE_PARAMS = {
    "MF": ("P", "Q", "bu", "bi"),
    "NCF": ("P_mlp", "Q_mlp", "P_gmf", "Q_gmf"),
}


def make_2d_mesh(n_devices: int | None = None, model_parallel: int = 2) -> Mesh:
    """('data', 'model') mesh; ``model_parallel`` must divide the device
    count — raises rather than silently unsharding the tables (a config
    that asked for table sharding because they exceed one device's HBM
    must not fall back to full replication; same contract as
    ``distributed.make_hybrid_mesh``)."""
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    n = len(devs)
    if n % model_parallel:
        raise ValueError(
            f"model_parallel={model_parallel} does not divide the device "
            f"count {n}"
        )
    mp = model_parallel
    return Mesh(np.asarray(devs).reshape(n // mp, mp), ("data", "model"))


def shard_model_params(mesh: Mesh, params, model, axis: str = "model"):
    """Row-shard the embedding tables over ``axis``; replicate the rest.

    Row counts not divisible by the axis size are handled by XLA's
    implicit padding of sharded dimensions. Multi-process meshes are
    supported via ``distributed.put_global`` (each process serves the
    shards its devices own).
    """
    from fia_tpu.parallel.distributed import put_global

    names = TABLE_PARAMS.get(type(model).__name__, ())
    out = {}
    for k, v in params.items():
        if k in names:
            spec = P(axis, *([None] * (v.ndim - 1)))
        else:
            spec = P()
        out[k] = put_global(mesh, v, spec)
    return out


def replicate_rest(mesh: Mesh, tree):
    return jax.tree_util.tree_map(
        lambda v: jax.device_put(v, NamedSharding(mesh, P())), tree
    )
