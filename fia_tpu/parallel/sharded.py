"""Model-parallel (sharded embedding table) configuration.

The scaling axes of this workload are #users/#items (embedding-table
rows) and #queries/#train-rows (data) — there is no sequence dimension
(SURVEY.md §2.4). For stress configs whose tables exceed one device's
HBM (e.g. MovieLens-20M at large k), tables are row-sharded over a
'model' mesh axis while queries/batches shard over 'data'.

Two sharded regimes coexist:

- the padded per-query path leaves the gathers to GSPMD, which inserts
  collectives wherever a sharded table is indexed;
- the flat hot path (engine ``shard_tables=True``) gathers the exact
  per-query block rows ONCE per dispatch through
  :func:`gather_table_rows` — an explicit masked-local-gather + psum
  over the 'model' axis — and runs every downstream per-query op on
  locally-resident rows, so the fused score program never touches a
  table again (docs/design.md §20).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fia_tpu import obs

#: param names holding per-user/per-item rows, per model class name
TABLE_PARAMS = {
    "MF": ("P", "Q", "bu", "bi"),
    "NCF": ("P_mlp", "Q_mlp", "P_gmf", "Q_gmf"),
}

#: which id axis indexes each table's rows, aligned with TABLE_PARAMS
TABLE_ROW_AXES = {
    "MF": ("user", "item", "user", "item"),
    "NCF": ("user", "item", "user", "item"),
}


def table_names(model) -> tuple[str, ...]:
    return TABLE_PARAMS.get(type(model).__name__, ())


def padded_rows(n: int, parts: int) -> int:
    """Smallest multiple of ``parts`` >= ``n`` — the physical row count
    of a table row-sharded over ``parts`` devices by
    :func:`gather_table_rows` (shard_map needs divisible globals)."""
    return -(-int(n) // int(parts)) * int(parts)


def make_2d_mesh(n_devices: int | None = None, model_parallel: int = 2) -> Mesh:
    """('data', 'model') mesh; ``model_parallel`` must divide the device
    count — raises rather than silently unsharding the tables (a config
    that asked for table sharding because they exceed one device's HBM
    must not fall back to full replication; same contract as
    ``distributed.make_hybrid_mesh``)."""
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    n = len(devs)
    if n % model_parallel:
        raise ValueError(
            f"model_parallel={model_parallel} does not divide the device "
            f"count {n}"
        )
    mp = model_parallel
    return Mesh(np.asarray(devs).reshape(n // mp, mp), ("data", "model"))


def shard_model_params(mesh: Mesh, params, model, axis: str = "model",
                       pad_rows: bool = True):
    """Row-shard the embedding tables over ``axis``; replicate the rest.

    Row counts not divisible by the axis size are zero-padded on the
    leading dim to a :func:`padded_rows` multiple before placement
    (``pad_rows``, on by default): ``device_put`` with a NamedSharding
    requires divisible global dims — there is no implicit padding on
    the placement path — and the flat hot path's ``shard_map`` gather
    needs divisible global shapes anyway. Real row ids never reach the
    pad rows (they are beyond ``num_users``/``num_items``), so
    predictions, regularizer sums, and the engine's per-leaf sum/norm
    params fingerprint are all exactly unchanged (appended zeros
    contribute +0.0). ``pad_rows=False`` is for divisible-by-
    construction configs that must keep the logical shape. Multi-
    process meshes are supported via ``distributed.put_global`` (each
    process serves the shards its devices own).
    """
    from fia_tpu.parallel.distributed import put_global

    names = table_names(model)
    parts = int(mesh.shape[axis])
    out = {}
    with obs.span("parallel.shard_params", tables=len(names),
                  parts=parts) as sp:
        for k, v in params.items():
            if k in names:
                if pad_rows:
                    pr = padded_rows(v.shape[0], parts)
                    if pr != int(v.shape[0]):
                        v = jnp.pad(
                            v, ((0, pr - int(v.shape[0])),)
                            + ((0, 0),) * (v.ndim - 1)
                        )
                spec = P(axis, *([None] * (v.ndim - 1)))
            else:
                spec = P()
            out[k] = put_global(mesh, v, spec)
        per_dev = per_device_table_bytes(out, model)
        obs.REGISTRY.gauge("parallel.table_bytes_per_device").set(per_dev)
        for k in names:
            if k in out:
                obs.REGISTRY.gauge(
                    "parallel.table_bytes", table=k
                ).set(int(np.prod(out[k].shape))
                      * out[k].dtype.itemsize)
        sp.set(per_device_bytes=per_dev)
    return out


def gather_table_rows(mesh: Mesh, model, params, uids, iids,
                      axis: str = "model"):
    """Gather per-row table slices from row-sharded tables.

    ``uids``/``iids`` are ``(ndev, S)`` int32 id arrays placed along the
    'data' axis (one query/flat-row shard per data row). Returns
    ``{table_name: (ndev, S, ...) rows}`` for every ``TABLE_PARAMS``
    entry of the model, each placed ``P('data', None, ...)``.

    The collective is one masked local gather + psum over ``axis``: the
    shard owning global row ``r`` (``r // rows_local``) contributes the
    real row, every other shard an exact ``+0.0`` (``jnp.where``, not a
    mask multiply — no ``-0.0`` sign surprises from ``0 * x``), so the
    psum reproduces the replicated gather bit-for-bit (``x + 0.0 == x``
    in IEEE-754 for every finite x; trained rows are never ``-0.0``).
    After this single collective, all per-query block math is local to
    the query's data shard — the fused kernels and the bitwise
    query-axis contract (docs/design.md §15) are untouched.

    Registered as a dispatch-path function for FIA204/FIA205: it IS the
    sanctioned cross-device fetch of the sharded hot path, and nothing
    in it may transfer from host or place un-sharded.
    """
    names = table_names(model)
    row_axes = TABLE_ROW_AXES[type(model).__name__]
    tabs = tuple(params[n] for n in names)
    # this runs at TRACE time when the caller is jitted, so no timing
    # span here — count tracings instead (a recompile-storm indicator)
    # and pin the event to whatever host span is open (precompile/query)
    obs.REGISTRY.counter("parallel.gather_traces_total").inc()
    obs.TRACER.current_span().event(
        "parallel.gather_table_rows", tables=len(names)
    )
    in_specs = (P("data", None), P("data", None)) + tuple(
        P(axis, *([None] * (t.ndim - 1))) for t in tabs
    )
    out_specs = {
        n: P("data", None, *([None] * (t.ndim - 1)))
        for n, t in zip(names, tabs)
    }

    def body(u_l, i_l, *tabs_l):
        k = jax.lax.axis_index(axis)
        out = {}
        for n, rax, tl in zip(names, row_axes, tabs_l):
            ids = u_l if rax == "user" else i_l
            rows_local = tl.shape[0]
            loc = ids - k * rows_local
            ok = (loc >= 0) & (loc < rows_local)
            r = jnp.take(tl, jnp.clip(loc, 0, rows_local - 1), axis=0)
            okb = ok.reshape(ok.shape + (1,) * (tl.ndim - 1))
            r = jnp.where(okb, r, jnp.zeros((), r.dtype))
            out[n] = jax.lax.psum(r, axis)
        return out

    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )(uids, iids, *tabs)


def per_device_table_bytes(params, model) -> int:
    """Max bytes of table rows any single device holds — the residency
    number the scale sweep / scale smoke report (shrinks ~linearly with
    ``model_parallel`` when tables are row-sharded, equals the full
    table footprint when replicated)."""
    per_dev: dict = {}
    for name in table_names(model):
        v = params.get(name)
        if v is None:
            continue
        shards = getattr(v, "addressable_shards", None)
        if shards:
            for sh in shards:
                d = sh.device.id
                per_dev[d] = per_dev.get(d, 0) + int(sh.data.nbytes)
        else:
            per_dev[0] = per_dev.get(0, 0) + int(np.asarray(v).nbytes)
    return max(per_dev.values(), default=0)


def replicate_rest(mesh: Mesh, tree):
    return jax.tree_util.tree_map(
        lambda v: jax.device_put(v, NamedSharding(mesh, P())), tree
    )
