"""Multi-host (multi-process) distributed runtime.

The reference is strictly single-process (one ``tf.Session``, one pinned
device — SURVEY.md §2.4: no NCCL/MPI/horovod anywhere). The TPU-native
counterpart of "a communication backend that scales out" is *not* an
explicit message-passing layer: processes join one JAX runtime, devices
form a global :class:`~jax.sharding.Mesh`, and XLA inserts the
collectives — riding ICI within a slice and DCN across slices/hosts.

This module holds the pieces of that story that are about *processes*
rather than devices:

  - :func:`initialize` — join the multi-process runtime (coordinator
    handshake), idempotent, no-op for single-process runs.
  - :func:`runtime_info` — process/device topology snapshot.
  - :func:`make_hybrid_mesh` — a ('data', 'model') mesh laid out so the
    'model' axis (embedding-table row sharding; heavy gather/psum
    traffic) stays within a host/slice on ICI, while the 'data' axis
    (query batches / train shards; one psum per step) spans hosts over
    DCN — the standard hybrid layout, cf. scaling-book recipe.
  - :func:`global_batch` — assemble per-process host arrays into one
    global sharded array (each process feeds only its local rows).
  - :func:`process_local_rows` — which slice of a global batch this
    process should load, so data loading scales with host count.

Everything degrades gracefully to single-process: the unit suite runs the
same code paths on the virtual 8-device CPU mesh, and a real multi-host
job only adds ``initialize(coordinator_address=...)`` up front.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.reliability import policy as rpolicy

# Per-array placement retry (see put_global): short delays — the engine's
# _reset_device_state already waited out the worker-restart window, this
# only covers the residual race at device_put time.
_PUT_RETRY = rpolicy.RetryPolicy(
    max_attempts=3, base_delay=0.1, max_delay=1.0, jitter=0.25
)

_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> None:
    """Join the multi-process JAX runtime (the distributed "backend").

    Wraps :func:`jax.distributed.initialize`: every process dials the
    coordinator, after which ``jax.devices()`` is the *global* device
    list and jitted collectives span hosts (DCN) transparently.

    Single-process runs (no coordinator address, no auto-detectable
    cluster) are a no-op, so drivers can call this unconditionally.
    Idempotent across repeated calls.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None:
        # No explicit cluster — stay single-process. (On managed TPU
        # pods jax.distributed.initialize() can auto-detect the cluster;
        # callers opt in by passing the coordinator explicitly so dev
        # boxes never block on a handshake.)
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


@dataclass(frozen=True)
class RuntimeInfo:
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int
    platform: str

    @property
    def is_multi_host(self) -> bool:
        return self.process_count > 1


def runtime_info() -> RuntimeInfo:
    return RuntimeInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
        platform=jax.default_backend(),
    )


def _granules(devs) -> list[list]:
    """Group devices into ICI granules (slices/hosts), DCN between them.

    TPU devices carry ``slice_index`` (multi-slice) — fall back to
    ``process_index`` (multi-host CPU/GPU), then to one granule
    (single-process dev box, virtual CPU mesh included).
    """
    for attr in ("slice_index", "process_index"):
        keys = {getattr(d, attr, None) for d in devs}
        if len(keys) > 1:
            by = {}
            for d in devs:
                by.setdefault(getattr(d, attr), []).append(d)
            return [by[k] for k in sorted(by)]
    return [list(devs)]


def make_hybrid_mesh(
    model_parallel: int = 1,
    axis_names: tuple[str, str] = ("data", "model"),
    devices=None,
    granules: list[list] | None = None,
) -> Mesh:
    """('data', 'model') mesh with DCN-aware axis placement.

    The 'model' axis (all-gathers of sharded embedding rows on every
    query — bandwidth-hungry) is laid out *within* an ICI granule
    (host/slice); the 'data' axis (one gradient/HVP psum per step)
    stacks granules, so only it crosses DCN. Same row-major layout
    ``mesh_utils.create_hybrid_device_mesh`` would produce for a
    (data, model) × (granules, 1) hybrid, built directly so it also
    works on device kinds without ``slice_index`` and is testable on
    the virtual CPU mesh (``granules`` override).

    Single-granule runs degrade to a plain local reshape. ``model_parallel``
    must divide the *per-granule* device count (a global-count check is
    not enough: 2 hosts x 2 devices cannot host model_parallel=4 without
    crossing DCN) — raises ``ValueError`` otherwise rather than silently
    unsharding the tables.
    """
    devs = list(jax.devices()) if devices is None else list(devices)
    groups = _granules(devs) if granules is None else [list(g) for g in granules]
    sizes = {len(g) for g in groups}
    if len(sizes) != 1:
        raise ValueError(f"granules must be equal-sized, got sizes {sorted(sizes)}")
    per = sizes.pop()
    if per % model_parallel:
        raise ValueError(
            f"model_parallel={model_parallel} does not divide the "
            f"per-granule device count {per}"
        )
    dev_arr = np.concatenate(
        [np.asarray(g, dtype=object).reshape(per // model_parallel, model_parallel)
         for g in groups],
        axis=0,
    )
    return Mesh(dev_arr, axis_names)


def process_local_rows(
    n_global: int, mesh: Mesh | None = None, axis: str = "data"
) -> slice:
    """The contiguous row range of a global batch this process feeds.

    ``NamedSharding`` supports only even partitions, so ``n_global``
    must divide the sharded axis — :func:`global_batch` would raise the
    same requirement from inside
    :func:`jax.make_array_from_process_local_data` anyway; callers pad
    batches to a mesh multiple first (the engine's query path does).
    With ``mesh``, the range is read off the actual sharding's
    device→index map (and the divisibility error surfaces here, early,
    with this guidance); without one, rows are split evenly over
    processes — equal to the sharding boundaries for every divisible
    count.
    """
    if mesh is not None:
        axis_size = mesh.shape[axis]
        if n_global % axis_size:
            raise ValueError(
                f"n_global={n_global} does not divide the '{axis}' axis "
                f"(size {axis_size}); NamedSharding supports only even "
                "partitions — pad the batch to a mesh multiple first"
            )
        sharding = NamedSharding(mesh, P(axis))
        me = jax.process_index()
        spans = [
            idx[0]
            for d, idx in sharding.devices_indices_map((n_global,)).items()
            if d.process_index == me
        ]
        # distinct spans only: along a replicated second axis (2-D mesh)
        # many local devices own the SAME row range
        uniq = {
            (0 if s.start is None else s.start,
             n_global if s.stop is None else s.stop)
            for s in spans
        }
        lo = min(a for a, _ in uniq)
        hi = max(b for _, b in uniq)
        if hi - lo != sum(b - a for a, b in uniq):
            # e.g. a mesh permutation interleaving this process's devices
            # with another's — a single slice would cover foreign rows
            raise ValueError(
                "this process's shard spans along the "
                f"'{axis}' axis are not contiguous ({sorted(uniq)}); "
                "process_local_rows cannot represent them as one slice — "
                "use a device-order mesh layout (make_hybrid_mesh) or feed "
                "rows per-device"
            )
        return slice(lo, hi)
    p, np_ = jax.process_index(), jax.process_count()
    base, extra = divmod(n_global, np_)
    start = p * base + min(p, extra)
    return slice(start, start + base + (1 if p < extra else 0))


def global_batch(
    mesh: Mesh, local_rows, axis: str = "data", global_rows: int | None = None
):
    """Assemble per-process host rows into one global sharded array.

    Each process passes only the rows :func:`process_local_rows` told it
    to load; :func:`jax.make_array_from_process_local_data` stitches the
    shards into a global array sharded along ``axis`` without any
    host-side all-gather. Works unchanged (and is the identity layout)
    for single-process runs.

    ``global_rows`` must be passed when the row count does not divide
    evenly over processes: without it each process infers the global
    shape by scaling its own local shape, and uneven
    :func:`process_local_rows` splits would disagree across processes.

    Accepts an array or a pytree of arrays sharing the leading dimension.
    """

    def put(x):
        x = np.asarray(x)
        spec = [None] * x.ndim
        spec[0] = axis
        sharding = NamedSharding(mesh, P(*spec))
        gshape = None if global_rows is None else (global_rows, *x.shape[1:])
        return jax.make_array_from_process_local_data(sharding, x, gshape)

    return jax.tree_util.tree_map(put, local_rows)


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh contains devices of more than one process."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def put_global(mesh: Mesh, tree, spec: P):
    """Place host arrays (identical on every process) onto a mesh sharding.

    Single-process (or local-only mesh): plain :func:`jax.device_put`.
    Multi-process: :func:`jax.make_array_from_callback` — each process
    serves only the index ranges its addressable devices own, which is
    the supported way to build an array over non-addressable devices
    (``device_put`` of host data onto a cross-process sharding is not).
    Every process must hold the same full host array (the replicated-
    input pattern: params, train tensors, query batches); use
    :func:`global_batch` when each process loads only its own rows.
    """
    sharding = NamedSharding(mesh, spec)

    local = not spans_processes(mesh)

    def put(x):
        def place():
            inject.fire(sites.DISTRIBUTED_PUT_GLOBAL)
            if local:
                # device_put reshards on-device; forcing np.asarray here
                # would round-trip already-device-resident params
                # through the host.
                return jax.device_put(x, sharding)
            xa = np.asarray(x)
            return jax.make_array_from_callback(
                xa.shape, sharding, lambda idx: xa[idx]
            )

        # Placement races a restarting/preempted worker (the r4 k=256
        # re-upload died at device_put); short bounded retries absorb
        # the window, anything else surfaces untouched.
        return _PUT_RETRY.run(place, retry_on=taxonomy.TRANSIENT)

    return jax.tree_util.tree_map(put, tree)
