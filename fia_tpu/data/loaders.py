"""Dataset loaders for the reference's TSV rating files.

Parity targets: reference ``src/scripts/load_movielens.py:6-25`` and
``load_yelp.py:6-23`` — tab-separated ``user \t item \t rating`` rows
loaded into train/validation/test datasets, with the reference's exact
row-count slicing preserved when the files have at least that many rows.

Because the reference training blobs are stripped from the repo, missing
train files are (optionally) synthesised at the dataset's published scale
(``synthesize_train=True``), keeping every valid/test user and item
covered so FIA queries have non-empty related sets.
"""

from __future__ import annotations

import os

import numpy as np

from fia_tpu.data import native
from fia_tpu.utils import io
from fia_tpu.data.dataset import RatingDataset
from fia_tpu.data.synthetic import synthesize_calibrated, synthesize_ratings

# Reference slice counts (load_movielens.py:12-17, load_yelp.py:12-16).
_SPECS = {
    "movielens": dict(
        prefix="ml-1m-ex", n_train=975_460, n_valid=12_074, n_test=12_074,
        num_users=6_040, num_items=3_706,
    ),
    "yelp": dict(
        prefix="yelp-ex", n_train=628_881, n_valid=51_354, n_test=51_153,
        num_users=25_677, num_items=25_815,
    ),
}


def _read_tsv(path: str, n_rows: int | None) -> RatingDataset:
    users, items, ratings = native.parse_tsv(path, max_rows=n_rows)
    return RatingDataset(np.stack([users, items], axis=1), ratings)


def save_tsv(ds: RatingDataset, path: str) -> None:
    out = np.concatenate([ds.x.astype(np.int64), ds.y.reshape(-1, 1)], axis=1)
    io.savetxt_atomic(path, out, fmt=["%d", "%d", "%g"], delimiter="\t")


def load_dataset(
    name: str,
    data_dir: str,
    synthesize_train: bool = True,
    synth_seed: int = 0,
    calibrate: bool = True,
    cal_rev: str = "cal2",
) -> dict[str, RatingDataset]:
    """Load {train, validation, test} RatingDatasets for a named dataset.

    A missing train file (stripped upstream) is synthesized; by default
    the generator is CALIBRATED to the real valid/test files (empirical
    item marginals, constrained lognormal user degrees, heldout-pair
    disjointness — ``synthesize_calibrated``). ``calibrate=False`` keeps
    the generic Zipf(0.8) generator the round-1 measurements used.
    ``cal_rev`` selects the calibrated-stream revision: ``"cal2"`` (the
    r3/r4 measurement stream) or ``"cal3"`` (saturation-compensated
    item head — ``head_fit``). The tag flows into checkpoint names so
    the two streams can never share checkpoints or influence caches.
    """
    if name not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; have {sorted(_SPECS)}")
    spec = _SPECS[name]
    paths = {
        split: os.path.join(data_dir, f"{spec['prefix']}.{short}.rating")
        for split, short in [("train", "train"), ("validation", "valid"), ("test", "test")]
    }

    valid = _read_tsv(paths["validation"], spec["n_valid"])
    test = _read_tsv(paths["test"], spec["n_test"])

    if os.path.exists(paths["train"]):
        train = _read_tsv(paths["train"], spec["n_train"])
    elif synthesize_train:
        cover = np.concatenate([valid.x, test.x], axis=0)
        if calibrate:
            if cal_rev not in ("cal2", "cal3"):
                raise ValueError(f"unknown cal_rev {cal_rev!r}")
            train = synthesize_calibrated(
                spec["num_users"], spec["num_items"], spec["n_train"],
                heldout_x=cover, seed=synth_seed,
                head_fit=(cal_rev == "cal3"),
            )
            # checkpoint/model names key on this tag so calibrated-split
            # checkpoints never collide with the older Zipf-split ones.
            # cal2 = cal1 + intra-train pair dedup + exact-fixed-point
            # degree floor (ADVICE r2); cal3 = cal2 + saturation-
            # compensated item head (r4). Rows in BASELINE.md stay
            # labelled with the stream they were measured on
            train.synth_tag = cal_rev
        else:
            train = synthesize_ratings(
                spec["num_users"], spec["num_items"], spec["n_train"],
                seed=synth_seed, ensure_cover=cover,
            )
    else:
        raise FileNotFoundError(
            f"{paths['train']} missing (stripped from the reference repo); "
            "pass synthesize_train=True to regenerate it"
        )
    return {"train": train, "validation": valid, "test": test}


def load_movielens(data_dir: str, **kw) -> dict[str, RatingDataset]:
    return load_dataset("movielens", data_dir, **kw)


def load_yelp(data_dir: str, **kw) -> dict[str, RatingDataset]:
    return load_dataset("yelp", data_dir, **kw)
