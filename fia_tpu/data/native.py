"""ctypes bindings for the native data-path library (native/fia_native.cpp).

Provides a fast TSV rating parser and CSR index builder; every entry
point has a numpy fallback so the framework runs without the shared
library (set ``FIA_NATIVE=0`` to force the fallback). The library is
built with ``make -C native`` and auto-built on first use when a
compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SO_PATH = os.path.join(_REPO_ROOT, "native", "libfia_native.so")

_lib = None
_tried = False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("FIA_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_SO_PATH):
        try:
            subprocess.run(
                ["make", "-C", os.path.join(_REPO_ROOT, "native")],
                capture_output=True, timeout=120, check=True,
            )
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        lib.fia_count_rows.restype = ctypes.c_int64
        lib.fia_count_rows.argtypes = [ctypes.c_char_p]
        lib.fia_parse_tsv.restype = ctypes.c_int64
        lib.fia_parse_tsv.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.fia_build_csr.restype = ctypes.c_int32
        lib.fia_build_csr.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def parse_tsv(path: str, max_rows: int | None = None):
    """(users, items, ratings) arrays from a ratings TSV file."""
    lib = _load()
    if lib is None:
        raw = np.loadtxt(path, dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(1, -1)
        if max_rows is not None:
            raw = raw[:max_rows]
        return (raw[:, 0].astype(np.int32), raw[:, 1].astype(np.int32),
                raw[:, 2].astype(np.float32))

    n = lib.fia_count_rows(path.encode())
    if n < 0:
        raise IOError(f"cannot read {path}")
    if max_rows is not None:
        n = min(n, max_rows)
    users = np.empty(n, np.int32)
    items = np.empty(n, np.int32)
    ratings = np.empty(n, np.float32)
    got = lib.fia_parse_tsv(
        path.encode(), n,
        users.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        items.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ratings.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if got < 0:
        raise IOError(f"cannot read {path}")
    return users[:got], items[:got], ratings[:got]


def build_csr(ids: np.ndarray, num_groups: int):
    """(indptr, indices) grouping row positions by id; stable order."""
    ids = np.ascontiguousarray(ids, np.int32)
    lib = _load()
    if lib is None:
        order = np.argsort(ids, kind="stable").astype(np.int64)
        counts = np.bincount(ids, minlength=num_groups)
        indptr = np.zeros(num_groups + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        return indptr, order
    indptr = np.empty(num_groups + 1, np.int64)
    indices = np.empty(len(ids), np.int64)
    rc = lib.fia_build_csr(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(ids), num_groups,
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        raise ValueError("id out of range in build_csr")
    return indptr, indices
