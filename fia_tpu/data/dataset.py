"""In-memory explicit-rating dataset.

Capability parity with the reference ``src/influence/dataset.py:5-70``
(``DataSet``: epoch-shuffled minibatching over a stable base array,
mutation helpers), re-designed for a JAX trainer: the host-side object is
numpy-backed for IO and mutation; batch *schedules* are materialised as
whole-epoch index permutations so the device-side training loop can
``lax.scan`` over exact-shape batches without host round-trips.
"""

from __future__ import annotations

import numpy as np


class RatingDataset:
    """(user, item) -> rating triples.

    Attributes:
      x: int32 array (N, 2) of (user_id, item_id).
      y: float32 array (N,) of ratings.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray):
        x = np.asarray(x)
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        self.x = np.ascontiguousarray(x, dtype=np.int32)
        self.y = np.ascontiguousarray(np.asarray(y).reshape(-1), dtype=np.float32)
        if self.x.shape[0] != self.y.shape[0]:
            raise ValueError(
                f"x and y disagree on N: {self.x.shape[0]} vs {self.y.shape[0]}"
            )
        self._order = np.arange(self.num_examples)
        self._cursor = 0
        self._epochs_completed = 0
        self._rng = np.random.default_rng(0)

    # -- basic protocol ----------------------------------------------------
    @property
    def num_examples(self) -> int:
        return self.x.shape[0]

    @property
    def num_users(self) -> int:
        return int(self.x[:, 0].max()) + 1 if self.num_examples else 0

    @property
    def num_items(self) -> int:
        return int(self.x[:, 1].max()) + 1 if self.num_examples else 0

    # labels alias for parity with the reference DataSet API
    @property
    def labels(self) -> np.ndarray:
        return self.y

    def __len__(self) -> int:
        return self.num_examples

    def __repr__(self) -> str:
        return (
            f"RatingDataset(N={self.num_examples}, users={self.num_users}, "
            f"items={self.num_items})"
        )

    # -- host-side minibatching (reference dataset.py:44-70 semantics) -----
    def reset_batch(self, seed: int = 0) -> None:
        """Reset the epoch cursor and the shuffle stream."""
        self._cursor = 0
        self._epochs_completed = 0
        self._order = np.arange(self.num_examples)
        self._rng = np.random.default_rng(seed)

    def next_batch(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        """Sequential minibatch; reshuffles on epoch wrap and truncates a
        ragged tail (reference ``dataset.py:49-70``)."""
        if batch_size > self.num_examples:
            raise ValueError("batch_size larger than the dataset")
        if self._cursor + batch_size > self.num_examples:
            self._epochs_completed += 1
            self._rng.shuffle(self._order)
            self._cursor = 0
        sel = self._order[self._cursor : self._cursor + batch_size]
        self._cursor += batch_size
        return self.x[sel], self.y[sel]

    def epoch_schedule(self, batch_size: int, seed: int) -> np.ndarray:
        """A full epoch of batch indices, shape (num_batches, batch_size).

        The ragged tail is dropped, matching the reference's tail
        truncation. This is the host-side companion of the device trainer:
        the returned index matrix is scanned on device.
        """
        order = np.random.default_rng(seed).permutation(self.num_examples)
        nb = self.num_examples // batch_size
        return order[: nb * batch_size].reshape(nb, batch_size)

    # -- mutation helpers (reference dataset.py:35-47, 73-90) --------------
    def append_one_case(self, x_row: np.ndarray, y_val: float) -> None:
        self.x = np.concatenate(
            [self.x, np.asarray(x_row, dtype=np.int32).reshape(1, -1)], axis=0
        )
        self.y = np.concatenate(
            [self.y, np.asarray([y_val], dtype=np.float32)], axis=0
        )
        self.reset_batch()

    def without(self, indices) -> "RatingDataset":
        """A copy with the given row indices removed (leave-one-out)."""
        keep = np.ones(self.num_examples, dtype=bool)
        keep[np.asarray(indices)] = False
        return RatingDataset(self.x[keep], self.y[keep])

    def subset(self, indices) -> "RatingDataset":
        idx = np.asarray(indices)
        return RatingDataset(self.x[idx], self.y[idx])


# -- module-level utilities (reference dataset.py:73-103) -------------------
def filter_dataset(
    x: np.ndarray, y: np.ndarray, pos_class, neg_class
) -> tuple[np.ndarray, np.ndarray]:
    """Restrict (x, y) to two label classes and relabel them ±1.

    Capability parity with the reference's module-level ``filter_dataset``
    (``src/influence/dataset.py:73-90``): rows whose label is neither
    ``pos_class`` nor ``neg_class`` are dropped; surviving labels map to
    +1 (pos) / -1 (neg). Unused by the rating workload (ratings are
    regression targets) but part of the dataset module's public surface.
    """
    x = np.asarray(x)
    y = np.asarray(y).astype(int)
    if x.shape[0] != y.shape[0] or y.ndim != 1:
        raise ValueError("x and y must align on N and y must be 1-D")
    pos = y == pos_class
    neg = y == neg_class
    keep = pos | neg
    out_y = np.where(pos, 1, -1)[keep]
    return x[keep], out_y


def find_distances(
    target: np.ndarray, x: np.ndarray, theta: np.ndarray | None = None
) -> np.ndarray:
    """Per-row distance from ``target``: L2, or |projection onto theta|.

    Parity with the reference's ``find_distances``
    (``src/influence/dataset.py:93-105``).
    """
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"x must be 2-D, got ndim={x.ndim}")
    target = np.asarray(target).reshape(-1)
    if x.shape[1] != target.shape[0]:
        raise ValueError(
            f"feature dims differ: x has {x.shape[1]}, target {target.shape[0]}"
        )
    diff = x - target
    if theta is None:
        return np.linalg.norm(diff, axis=1)
    return np.abs(diff @ np.asarray(theta).reshape(-1))
