"""CSR-style inverted index over (user, item) interactions.

The FIA related-set query — "all training rows whose user == u* OR item
== i*" (reference ``src/influence/matrix_factorization.py:315-322``) — is
a linear scan per test point in the reference. Here it is two O(1) CSR
row lookups. The index also provides padded/masked batched gathers so a
whole batch of test queries becomes rectangular device arrays suitable
for ``vmap``.

A native C++ builder (``native/``) can be swapped in for very large
datasets; the numpy path is the default and is already vectorised.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from fia_tpu.data import native


def _csr_from_ids(ids: np.ndarray, num_groups: int):
    """Group row positions by id. Returns (indptr, indices) CSR arrays.

    Uses the native counting-sort builder when libfia_native is
    available; numpy stable argsort otherwise (identical output)."""
    return native.build_csr(ids, num_groups)


def bucketed_pad(max_count: int, bucket: int, pad_to: int | None = None) -> int:
    """Pad length for ragged related sets, or ``pad_to`` verbatim after
    validating it fits.

    Rounds ``max_count`` up to a multiple of ``bucket``; past 16×bucket
    the granule grows geometrically (m/8, i.e. ~12.5% steps), so the
    number of distinct pad lengths — and hence jit recompilations across
    batches with different max related counts — is logarithmic, at
    ≤12.5% padding waste."""
    if pad_to is not None:
        if max_count > pad_to:
            raise ValueError(
                f"pad_to={pad_to} smaller than max related count {max_count}"
            )
        return int(pad_to)
    m = max(int(max_count), 1)
    granule = max(bucket, 1 << max(0, m.bit_length() - 4))
    return max(bucket, -(-m // granule) * granule)


class InteractionIndex:
    def __init__(self, x: np.ndarray, num_users: int | None = None,
                 num_items: int | None = None):
        x = np.asarray(x)
        self.num_users = int(num_users if num_users is not None else x[:, 0].max() + 1)
        self.num_items = int(num_items if num_items is not None else x[:, 1].max() + 1)
        self._u_indptr, self._u_rows = _csr_from_ids(x[:, 0], self.num_users)
        self._i_indptr, self._i_rows = _csr_from_ids(x[:, 1], self.num_items)
        # related() concatenation memo: a serving stream revisits hot
        # (u, i) pairs, and each visit re-allocated the concatenated
        # postings (the engine itself calls related() when attaching
        # result rows). Bounded LRU; entries are write-protected views
        # handed to multiple callers, so a consumer cannot corrupt them.
        self._related_memo: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._related_memo_cap = 4096
        self.memo_hits = 0
        self.memo_misses = 0
        # single-query related_padded memo: the online service pads the
        # same hot (u, i) pair to the same bucket over and over — the
        # (idx, mask) build is the per-query host cost on the padded
        # path. Keyed by pair + resolved pad so a bucket change misses.
        self._padded_memo: OrderedDict[tuple, tuple] = OrderedDict()
        self._padded_memo_cap = 1024

    def rows_of_user(self, u: int) -> np.ndarray:
        return self._u_rows[self._u_indptr[u] : self._u_indptr[u + 1]]

    def rows_of_item(self, i: int) -> np.ndarray:
        return self._i_rows[self._i_indptr[i] : self._i_indptr[i + 1]]

    def related(self, u: int, i: int) -> np.ndarray:
        """Training rows sharing user u or item i.

        Like the reference (``matrix_factorization.py:315-322``), rows
        matching both (the (u, i) interaction itself, if present in the
        training set) appear twice — user rows first, then item rows.

        Memoized (bounded LRU, read-only arrays): repeated queries for
        the same pair — the serving hot set — skip the concatenation.
        """
        key = (int(u), int(i))
        memo = self._related_memo
        hit = memo.get(key)
        if hit is not None:
            memo.move_to_end(key)
            self.memo_hits += 1
            return hit
        self.memo_misses += 1
        out = np.concatenate([self.rows_of_user(u), self.rows_of_item(i)])
        out.setflags(write=False)
        memo[key] = out
        if len(memo) > self._related_memo_cap:
            memo.popitem(last=False)
        return out

    def related_count(self, u: int, i: int) -> int:
        return int(
            self._u_indptr[u + 1] - self._u_indptr[u]
            + self._i_indptr[i + 1] - self._i_indptr[i]
        )

    def user_degrees(self) -> np.ndarray:
        """Interaction count per user id, (num_users,) int64 — the
        hotness signal the factor-bank selector ranks on."""
        return np.diff(self._u_indptr)

    def item_degrees(self) -> np.ndarray:
        """Interaction count per item id, (num_items,) int64."""
        return np.diff(self._i_indptr)

    def max_related_count(self) -> int:
        """Upper bound on any query's related-set size: the heaviest user
        degree plus the heaviest item degree. Padding to this ceiling
        makes every batch share one compiled program."""
        return int(
            np.diff(self._u_indptr).max(initial=0)
            + np.diff(self._i_indptr).max(initial=0)
        )

    def counts_batch(self, test_points: np.ndarray) -> np.ndarray:
        """Related-set sizes for a (T, 2) batch — O(T) indptr diffs, no
        gather."""
        test_points = np.asarray(test_points)
        u = test_points[:, 0]
        i = test_points[:, 1]
        return (
            self._u_indptr[u + 1] - self._u_indptr[u]
            + self._i_indptr[i + 1] - self._i_indptr[i]
        ).astype(np.int32)

    def postings(self):
        """The raw CSR arrays (u_indptr, u_rows, i_indptr, i_rows).

        Transferred to device once, these let the influence engine gather
        related sets *inside* the jitted query — the only per-batch
        host→device traffic is then the (T, 2) test points themselves
        (the padded (T, P) index/mask transfer dominated end-to-end query
        latency on interconnect-attached TPU hosts).
        """
        return self._u_indptr, self._u_rows, self._i_indptr, self._i_rows

    def related_padded(self, test_points: np.ndarray, pad_to: int | None = None,
                       bucket: int = 128):
        """Batched related sets as rectangular arrays.

        Args:
          test_points: (T, 2) int array of (u, i) pairs.
          pad_to: fixed row count; if None, the max count rounded up to a
            multiple of ``bucket`` (bucketing keeps the jit cache small).

        Returns:
          idx:   (T, P) int32 — related train-row ids, padded with 0.
          mask:  (T, P) bool  — True on real entries.
          count: (T,)   int32 — true related-set sizes.
        """
        test_points = np.asarray(test_points)
        if len(test_points) == 1:
            u, i = (int(v) for v in test_points[0])
            pad = bucketed_pad(self.related_count(u, i), bucket, pad_to)
            key = (u, i, pad)
            hit = self._padded_memo.get(key)
            if hit is not None:
                self._padded_memo.move_to_end(key)
                self.memo_hits += 1
                return hit
        lists = [self.related(int(u), int(i)) for u, i in test_points]
        counts = np.array([len(l) for l in lists], dtype=np.int32)
        pad_to = bucketed_pad(counts.max() if counts.size else 1, bucket, pad_to)
        idx = np.zeros((len(lists), pad_to), dtype=np.int32)
        mask = np.zeros((len(lists), pad_to), dtype=bool)
        for t, l in enumerate(lists):
            idx[t, : len(l)] = l
            mask[t, : len(l)] = True
        for a in (idx, mask, counts):
            a.setflags(write=False)
        if len(test_points) == 1:
            self._padded_memo[(int(test_points[0][0]),
                               int(test_points[0][1]), pad_to)] = (
                idx, mask, counts
            )
            if len(self._padded_memo) > self._padded_memo_cap:
                self._padded_memo.popitem(last=False)
        return idx, mask, counts
