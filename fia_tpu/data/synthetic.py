"""Synthetic explicit-rating generators.

The reference repo ships valid/test splits but its two training files are
stripped (``/root/reference/.MISSING_LARGE_BLOBS:1-2``), so end-to-end
runs regenerate a training split: ratings are sampled from a planted
low-rank MF model plus noise, quantised to the 1-5 star scale the real
files use. Deterministic given the seed.
"""

from __future__ import annotations

import numpy as np

from fia_tpu.data.dataset import RatingDataset


def synthesize_ratings(
    num_users: int,
    num_items: int,
    num_rows: int,
    seed: int = 0,
    rank: int = 8,
    noise: float = 0.4,
    ensure_cover: np.ndarray | None = None,
) -> RatingDataset:
    """Sample ``num_rows`` (user, item, rating) triples.

    Users/items are drawn from Zipf-ish popularity marginals (real rating
    data is heavy-tailed, and the FIA related-set sizes depend on that
    skew). ``ensure_cover`` is an optional (M, 2) array of (u, i) pairs —
    e.g. the test split — each of whose users and items is guaranteed at
    least one training interaction so every test query has a non-empty
    related set.
    """
    rng = np.random.default_rng(seed)

    def _zipf_choice(n, size):
        w = 1.0 / np.arange(1, n + 1) ** 0.8
        w /= w.sum()
        perm = rng.permutation(n)  # decouple popularity from id order
        return perm[rng.choice(n, size=size, p=w)]

    users = _zipf_choice(num_users, num_rows)
    items = _zipf_choice(num_items, num_rows)

    if ensure_cover is not None and len(ensure_cover):
        cover = np.asarray(ensure_cover)
        cu = np.unique(cover[:, 0])
        ci = np.unique(cover[:, 1])
        need = len(cu) + len(ci)
        if need > num_rows:
            raise ValueError("num_rows too small to cover the given pairs")
        users[: len(cu)] = cu
        items[: len(cu)] = rng.integers(0, num_items, size=len(cu))
        users[len(cu) : need] = rng.integers(0, num_users, size=len(ci))
        items[len(cu) : need] = ci

    ratings = _planted_ratings(users, items, num_users, num_items, rng,
                               rank=rank, noise=noise)

    x = np.stack([users, items], axis=1).astype(np.int32)
    return RatingDataset(x, ratings)


def _planted_ratings(users, items, num_users, num_items, rng,
                     rank: int = 8, noise: float = 0.4) -> np.ndarray:
    """Ratings from a planted MF model (r = clip(round(mu + b_u + b_i +
    p_u.q_i + eps), 1, 5)) — the 1-5 star scale of the real files."""
    num_rows = len(users)
    p = rng.normal(0, 1.0 / np.sqrt(rank), size=(num_users, rank))
    q = rng.normal(0, 1.0 / np.sqrt(rank), size=(num_items, rank))
    bu = rng.normal(0, 0.3, size=num_users)
    bi = rng.normal(0, 0.3, size=num_items)
    scores = (
        3.5
        + bu[users]
        + bi[items]
        + np.einsum("nk,nk->n", p[users], q[items])
        + rng.normal(0, noise, size=num_rows)
    )
    return np.clip(np.rint(scores), 1.0, 5.0).astype(np.float32)


def fit_user_degree_profile(
    num_users: int,
    num_rows: int,
    min_degree: int,
    rng,
    sigma: float = 1.0,
    max_degree: int | None = None,
) -> np.ndarray:
    """Per-user train degrees under the reference's leave-4-out protocol.

    The reference valid/test files hold EXACTLY 4 rows per user
    (measured: ml-1m-ex and yelp-ex both have every user at degree 4), so
    user marginals are NOT identifiable from the splits — only two facts
    are pinned down: every user has at least ``min_degree`` train rows
    (the source data's min-ratings filter minus the 4 held out) and the
    mean degree is num_rows/num_users. Within those constraints the
    profile is shifted-lognormal quantiles (σ=1 reproduces the
    magnitude/median/max shape of public MovieLens-1M user degrees),
    scaled exactly to num_rows by largest-remainder rounding and randomly
    permuted over user ids so popularity is decoupled from id order.

    ``max_degree`` caps the profile from above: a real user holds each
    item at most once, so no degree can exceed the item count (the σ=1
    tail overshoots it at ML-1M scale — quantile 6040/6040 lands at
    3833 > 3706 items, which would force duplicate pairs).
    """
    mean = num_rows / num_users
    if mean <= min_degree:
        raise ValueError(
            f"num_rows/num_users = {mean:.1f} <= min_degree {min_degree}"
        )
    if max_degree is not None and mean >= max_degree:
        raise ValueError(
            f"num_rows/num_users = {mean:.1f} >= max_degree {max_degree}"
        )
    from scipy.special import ndtri  # Phi^-1; scipy ships in the image

    mu = np.log(mean - min_degree) - 0.5 * sigma**2
    q = (np.arange(num_users) + 0.5) / num_users
    d = min_degree + np.exp(mu + sigma * ndtri(q))
    # Exact total via two-sided waterfilling: users pinned at the floor
    # (ceiling) take exactly min_degree (max_degree); the free users
    # scale to consume the remaining mass. A single clamp-then-rescale
    # pass can push clamped entries back outside the bounds (the rescale
    # moves everything), so iterate to the fixed point — it terminates
    # because the pinned sets only grow.
    hi = np.inf if max_degree is None else float(max_degree)
    lo_pin = np.zeros(num_users, bool)
    hi_pin = np.zeros(num_users, bool)
    while True:
        free = ~(lo_pin | hi_pin)
        if not free.any():
            raise ValueError("degree profile infeasible")
        # hi is inf when uncapped: inf * 0 = NaN, so the ceiling mass
        # must short-circuit while the hi_pin set is empty
        hi_mass = hi * hi_pin.sum() if hi_pin.any() else 0.0
        mass = num_rows - min_degree * lo_pin.sum() - hi_mass
        scale = mass / d[free].sum()
        new_lo = free & (d * scale < min_degree)
        new_hi = free & (d * scale > hi)
        if not (new_lo.any() or new_hi.any()):
            d = np.where(free, d * scale, np.where(lo_pin, float(min_degree), hi))
            break
        lo_pin |= new_lo
        hi_pin |= new_hi
    base = np.floor(d).astype(np.int64)
    short = num_rows - base.sum()
    order = np.argsort(d - base)[::-1]
    base[order[:short]] += 1
    if base.min() < min_degree or base.sum() != num_rows or (
        max_degree is not None and base.max() > max_degree
    ):
        raise AssertionError("degree profile violated its invariants")
    return base[rng.permutation(num_users)]


def _expected_unique_counts(
    p: np.ndarray, deg_vals: np.ndarray, deg_counts: np.ndarray,
    item_chunk: int = 4096,
) -> np.ndarray:
    """E[# distinct users holding item i] when each user of degree d
    draws d distinct items with marginal probabilities ``p``: the
    standard inclusion approximation 1 - (1-p_i)^d, summed over the
    degree histogram. Exact for with-replacement draws; a slight
    under-count for the generator's without-replacement draws, which
    the caller corrects by rescaling to the known total row count."""
    out = np.empty(len(p))
    l1p = np.log1p(-np.clip(p, 0.0, 1.0 - 1e-12))
    for s in range(0, len(p), item_chunk):
        e = min(s + item_chunk, len(p))
        out[s:e] = (
            deg_counts[None, :]
            * -np.expm1(l1p[s:e, None] * deg_vals[None, :])
        ).sum(axis=1)
    return out


def _dup_mask(users: np.ndarray, items: np.ndarray, num_items: int
              ) -> np.ndarray:
    """All-but-first occurrences of each duplicated (user, item) pair
    — shared by the generator's decollide loop and the head-fit draw
    simulator so their dedup semantics cannot drift apart."""
    codes = users * num_items + items
    order = np.argsort(codes, kind="stable")
    sc = codes[order]
    dup = np.zeros(len(users), bool)
    dup[order[1:]] = sc[1:] == sc[:-1]
    return dup


def _simulate_realized_counts(
    p: np.ndarray, degrees: np.ndarray, rng, rounds: int = 8
) -> np.ndarray:
    """Realized item counts of the generator's draw-then-dedup process
    (iid draws from ``p``, per-user duplicate resampling) — the cheap
    core of :func:`synthesize_calibrated`'s sampling, without the
    heldout-disjointness and coverage passes, which move the marginal
    by well under the head-fit tolerance."""
    num_items = len(p)
    users = np.repeat(np.arange(len(degrees), dtype=np.int64), degrees)
    items = rng.choice(num_items, size=users.size, p=p)
    for _ in range(rounds):
        dup = _dup_mask(users, items, num_items)
        if not dup.any():
            break
        items[dup] = rng.choice(num_items, size=int(dup.sum()), p=p)
    return np.bincount(items, minlength=num_items).astype(np.float64)


def _auto_smoothing(ic: np.ndarray, lo: float = 1e-3, hi: float = 4.0
                    ) -> float:
    """Count-smoothing pseudo-mass calibrated by zero-moment matching.

    Smoothing mass goes ONLY to unseen items (cal2 added +0.5 to every
    item, diluting the head shares it had just fit empirically). If the
    heldout is a fair M-row sample of the true train marginal, the
    number of items it misses pins the unseen-item mass: choose alpha
    so that an M-row multinomial downsample of p proportional to
    (ic + alpha*1{ic==0}) misses E = #(ic == 0) items, i.e. solve
    sum_i (1 - p_i(alpha))^M = E. A fixed 0.1-for-all undershot Yelp's
    low-count tail (scale-matched QQ r 0.9797 vs cal2's 0.9921) and a
    fixed 0.5-for-all re-diluted the head; the masked matched alpha
    tracks each dataset's own sparsity without touching seen shares."""
    M = float(ic.sum())
    z_target = float((ic == 0).sum())
    if z_target == 0:
        return lo

    unseen = ic == 0

    def zeros(alpha: float) -> float:
        p = ic + alpha * unseen
        p = p / p.sum()
        return float(np.exp(M * np.log1p(-np.minimum(p, 1 - 1e-12))).sum())

    if zeros(hi) > z_target:  # even max smoothing leaves more misses
        return hi
    for _ in range(40):
        mid = (lo * hi) ** 0.5
        if zeros(mid) > z_target:
            lo = mid  # too many misses -> unseen items need more mass
        else:
            hi = mid
    return (lo * hi) ** 0.5


def head_compensated_item_weights(
    ic: np.ndarray,
    degrees: np.ndarray,
    num_rows: int,
    smoothing: float | None = None,
    iters: int = 16,
    empirical_iters: int = 2,
) -> np.ndarray:
    """Item sampling weights whose REALIZED (post per-user-uniqueness)
    marginal matches the heldout counts ``ic`` — the cal3 stream fix.

    cal2 sampled items directly from ``ic + 0.5`` and measured a
    lighter head than the heldout ground truth (ML-1M top-1% item mass
    7.2% vs 10.8% — BASELINE §4.2 calibration-evidence row). Two
    mechanisms flatten the head, measured 2026-08-01: the +0.5
    smoothing dilutes ~0.7pp (mass flows to the many zero-count
    items), and per-user pair uniqueness saturates popular items for
    another ~2.9pp — a high-degree user re-drawing a head item keeps
    only one copy, and at train scale the top items' expected counts
    approach the user-count ceiling (ML-1M: 6,500 expected > 6,040
    users), so every overflow draw is redistributed down-tail.

    The fix inverts the saturation in two stages. First a damped
    multiplicative fixed point w <- w * (target / E[realized(w)])^0.7
    over the degree histogram (analytic; converges by ~16 iters —
    measured ML-1M top-1% realized mass 0.1065 vs target 0.1081 at
    iters 16/32/64 alike). The independent-inclusion model slightly
    overestimates head retention under the generator's actual
    draw-then-dedup process (measured draw: 0.0948), so
    ``empirical_iters`` refinement steps then correct against
    :func:`_simulate_realized_counts` with a PRIVATE fixed-seed rng —
    the caller's rng stream is never consumed, keeping cal2 rows
    byte-reproducible. Targets above the hard ceiling (the ML-1M top
    item) converge to partial compensation, the feasible optimum under
    uniqueness. ``smoothing=None`` calibrates the unseen-item
    pseudo-count per dataset by zero-moment matching
    (:func:`_auto_smoothing`); seen items keep their RAW heldout-count
    shares (cal2's +0.5-to-every-item diluted the head it had just
    fit)."""
    if smoothing is None:
        smoothing = _auto_smoothing(ic)
    target = ic.astype(np.float64) + smoothing * (ic == 0)
    target = target / target.sum() * num_rows
    deg_vals, deg_counts = np.unique(degrees, return_counts=True)
    deg_vals = deg_vals.astype(np.float64)
    deg_counts = deg_counts.astype(np.float64)
    w = target.copy()
    for _ in range(iters):
        p = w / w.sum()
        realized = _expected_unique_counts(p, deg_vals, deg_counts)
        realized *= num_rows / realized.sum()
        ratio = target / np.maximum(realized, 1e-9)
        w *= np.clip(ratio, 0.5, 2.0) ** 0.7
    sim_rng = np.random.default_rng(0xCA13)  # private; see docstring
    for _ in range(empirical_iters):
        realized = _simulate_realized_counts(w / w.sum(), degrees, sim_rng)
        ratio = target / np.maximum(realized, 1e-9)
        # a single draw is noisy at the tail (counts of 0/1); trust it
        # only where the target is big enough for relative error ~10%
        ratio = np.where(target >= 100.0, ratio, 1.0)
        w *= np.clip(ratio, 0.5, 2.0) ** 0.7
    return w / w.sum()


def synthesize_calibrated(
    num_users: int,
    num_items: int,
    num_rows: int,
    heldout_x: np.ndarray | None = None,
    seed: int = 0,
    min_degree: int = 16,
    rank: int = 8,
    noise: float = 0.4,
    item_zipf: float = 0.9,
    head_fit: bool = False,
) -> RatingDataset:
    """Train split calibrated to the reference's real valid/test files.

    ``heldout_x`` is the concatenated (valid+test) (M, 2) pair array.
    Item popularity is fit EMPIRICALLY from it (counts + 0.5 smoothing so
    items unseen in the 4-per-user holdout keep mass); user degrees come
    from :func:`fit_user_degree_profile`. Train pairs are kept disjoint
    from the heldout pairs (as the reference's real splits are — they
    were literally held out of train) AND unique among themselves (the
    real splits are sets of distinct (u, i) pairs; a duplicate would
    double-count its row in related sets and Hessians), and every
    heldout item is guaranteed at least one train row so FIA queries
    have non-empty related sets on both sides.

    ``heldout_x=None`` (scales with no reference split, e.g. ML-20M
    stress — r4): item popularity falls back to a permuted
    Zipf(``item_zipf``) profile; everything STRUCTURAL — waterfilled
    user degrees, unique pairs, exact row count — still holds, so the
    stream keeps cal2's realism guarantees minus the empirical item
    marginal (which no surviving data can pin at that scale).

    ``head_fit=True`` is the cal3 stream revision (r4): item weights
    are saturation-compensated against the uniqueness constraint so
    the REALIZED item-degree head matches the heldout counts
    (:func:`head_compensated_item_weights`). Consumes the rng stream
    identically to cal2, so cal2 rows stay reproducible.
    """
    rng = np.random.default_rng(seed)
    if heldout_x is None:
        ic = np.zeros(num_items, np.float64)
        p_item = 1.0 / np.arange(1, num_items + 1) ** item_zipf
        p_item = p_item[rng.permutation(num_items)]
        p_item /= p_item.sum()
        heldout_x = np.empty((0, 2), np.int64)
    else:
        heldout_x = np.asarray(heldout_x)
        ic = np.bincount(
            heldout_x[:, 1], minlength=num_items
        ).astype(np.float64)
        p_item = ic + 0.5
        p_item /= p_item.sum()

    # cap degrees at num_items - 8: a user holds each item at most once,
    # and ~4 items per user live in the heldout split (leave-4-out), so
    # the cap leaves slack for the disjointness constraint
    degrees = fit_user_degree_profile(
        num_users, num_rows, min_degree, rng, max_degree=num_items - 8
    )
    if head_fit and len(heldout_x):
        # cal3: replace the smoothed-count weights with saturation-
        # compensated ones (analytic — consumes no rng, so the draw
        # below sees the same rng state as a cal2 run)
        p_item = head_compensated_item_weights(ic, degrees, num_rows)
    users = np.repeat(np.arange(num_users, dtype=np.int64), degrees)
    items = rng.choice(num_items, size=num_rows, p=p_item)

    # Resample collisions with heldout pairs (the reference train never
    # contains them) and intra-train duplicates (the real splits hold
    # distinct pairs) in one loop; a handful of rounds clears the
    # few-per-mille hits. High-degree users on a skewed item marginal can
    # re-collide with their own rows indefinitely, so stubborn rows fall
    # through to an exact per-user weighted draw WITHOUT replacement
    # (Gumbel top-k over the items the user doesn't already hold).
    held_codes = np.unique(
        heldout_x[:, 0].astype(np.int64) * num_items + heldout_x[:, 1]
    )

    def _bad_mask():
        codes = users * num_items + items
        return np.isin(codes, held_codes) | _dup_mask(users, items,
                                                      num_items)

    for _ in range(16):
        bad = _bad_mask()
        if not bad.any():
            break
        items[bad] = rng.choice(num_items, size=int(bad.sum()), p=p_item)
    bad = _bad_mask()
    if bad.any():
        log_p = np.log(p_item)
        for u in np.unique(users[bad]):
            mine = users == u
            rows = np.flatnonzero(mine & bad)
            g = log_p + rng.gumbel(size=num_items)
            g[items[mine & ~bad]] = -np.inf  # items the user already holds
            lo = np.searchsorted(held_codes, u * num_items)
            hi = np.searchsorted(held_codes, (u + 1) * num_items)
            g[held_codes[lo:hi] - u * num_items] = -np.inf
            if np.isfinite(g).sum() < len(rows):
                raise RuntimeError("user degree exceeds available items")
            items[rows] = np.argpartition(-g, len(rows))[: len(rows)]
    if _bad_mask().any():
        raise RuntimeError("could not decollide train pairs")

    # cover heldout items that drew zero rows: overwrite the item of one
    # random row each (user degrees untouched). A live per-item count
    # guards the donor choice — stealing an item's SOLE row would
    # un-cover it (sparse marginals like yelp's have many 1-row items)
    live = np.bincount(items, minlength=num_items)
    need = np.flatnonzero((ic > 0) & (live == 0))
    if len(need):
        train_codes = np.sort(users * num_items + items)
        new_codes: set[int] = set()

        def _in_train(code: int) -> bool:
            j = np.searchsorted(train_codes, code)
            return (j < len(train_codes) and train_codes[j] == code) or (
                code in new_codes
            )

        cand = rng.permutation(num_rows)
        ci = 0
        for it in need:
            while ci < num_rows:
                r = cand[ci]
                ci += 1
                if live[items[r]] <= 1:
                    continue  # sole remaining row of its item
                code = users[r] * num_items + int(it)
                j = np.searchsorted(held_codes, code)
                # the donor row must not collide with heldout NOR
                # duplicate an existing (u, it) train pair
                if (
                    j == len(held_codes) or held_codes[j] != code
                ) and not _in_train(code):
                    live[items[r]] -= 1
                    items[r] = it
                    live[it] += 1
                    new_codes.add(code)
                    break
            else:
                raise RuntimeError("could not cover heldout items")

    ratings = _planted_ratings(users, items, num_users, num_items,
                               np.random.default_rng(seed + 1),
                               rank=rank, noise=noise)
    perm = rng.permutation(num_rows)
    x = np.stack([users, items], axis=1).astype(np.int32)[perm]
    return RatingDataset(x, ratings[perm])


def sample_heldout_pairs(
    train_x: np.ndarray,
    num_users: int,
    num_items: int,
    n: int,
    seed: int = 17,
) -> np.ndarray:
    """Sample ``n`` distinct (u, i) pairs absent from the training set.

    The benchmark/stress query protocol (mirroring the reference's RQ1/
    RQ2, whose test split is disjoint from train): a pair present in
    train couples its p_u/q_i blocks through the shared residual and can
    make the related-set block Hessian indefinite — a regime the
    reference never queries. Membership is tested against packed
    ``u * num_items + i`` codes so it stays cheap at ML-20M scale (a
    tuple set over 20M rows costs GBs).
    """
    rng = np.random.default_rng(seed)
    codes = np.sort(
        np.asarray(train_x[:, 0], np.int64) * num_items
        + np.asarray(train_x[:, 1], np.int64)
    )
    picked: set[int] = set()
    pts: list[tuple[int, int]] = []
    while len(pts) < n:
        u, i = int(rng.integers(0, num_users)), int(rng.integers(0, num_items))
        c = u * num_items + i
        if c in picked:
            continue
        j = np.searchsorted(codes, c)
        if j == len(codes) or codes[j] != c:
            picked.add(c)
            pts.append((u, i))
    return np.asarray(pts, dtype=np.int32)


def calibrated_splits(
    num_users: int,
    num_items: int,
    num_train: int,
    num_test: int,
    seed: int = 0,
    min_degree: int = 16,
    rank: int = 8,
    noise: float = 0.4,
) -> dict[str, RatingDataset]:
    """Train/valid/test splits on the cal2-style calibrated stream at
    scales with NO reference heldout files (ML-20M stress — r4).

    Train comes from :func:`synthesize_calibrated` (waterfilled unique
    pairs, Zipf item marginal); valid/test pairs are sampled DISJOINT
    from train (:func:`sample_heldout_pairs`) and rated by the SAME
    planted model as the train split: ``_planted_ratings`` draws the
    planted factors from its rng before any row-dependent consumption,
    so re-seeding ``seed + 1`` reproduces them exactly (only the
    per-row noise differs — as it should).
    """
    min_degree = min(min_degree, max(1, num_train // num_users - 1))
    train = synthesize_calibrated(
        num_users, num_items, num_train, heldout_x=None, seed=seed,
        min_degree=min_degree, rank=rank, noise=noise,
    )
    # checkpoint/cache names key on this tag (cli/common.py
    # model_name_for): a cal-stream run must never resume from or
    # share an influence cache with a Zipf-stream checkpoint
    train.synth_tag = "calsynth"
    pts = sample_heldout_pairs(
        train.x, num_users, num_items, 2 * num_test, seed=seed + 17
    )
    y = _planted_ratings(
        pts[:, 0].astype(np.int64), pts[:, 1].astype(np.int64),
        num_users, num_items, np.random.default_rng(seed + 1),
        rank=rank, noise=noise,
    )
    return {
        "train": train,
        "validation": RatingDataset(pts[:num_test], y[:num_test]),
        "test": RatingDataset(pts[num_test:], y[num_test:]),
    }


def synthetic_splits(
    num_users: int,
    num_items: int,
    num_train: int,
    num_test: int,
    seed: int = 0,
    **kw,
) -> dict[str, RatingDataset]:
    """Train/validation/test splits from one planted model.

    Valid/test pairs are kept DISJOINT from the training pairs (as the
    reference's real splits are): an in-train query pair appears twice in
    its own related set and couples the block through the shared residual
    — the regime ``sample_heldout_pairs`` documents as one the reference
    never queries, which the CLI drivers would otherwise hit at random.
    """
    margin = 4
    while True:
        full = synthesize_ratings(
            num_users, num_items, num_train + margin * num_test, seed=seed, **kw
        )
        train_x, train_y = full.x[:num_train], full.y[:num_train]
        codes = np.sort(
            np.asarray(train_x[:, 0], np.int64) * num_items
            + np.asarray(train_x[:, 1], np.int64)
        )
        rest_x, rest_y = full.x[num_train:], full.y[num_train:]
        rc = np.asarray(rest_x[:, 0], np.int64) * num_items + np.asarray(
            rest_x[:, 1], np.int64
        )
        if codes.size:
            j = np.clip(np.searchsorted(codes, rc), 0, len(codes) - 1)
            heldout = codes[j] != rc
        else:
            heldout = np.ones(len(rc), bool)
        if heldout.sum() >= 2 * num_test:
            rest_x, rest_y = rest_x[heldout], rest_y[heldout]
            break
        margin *= 2  # extremely dense configs: draw more candidates

    train = RatingDataset(train_x, train_y)
    valid = RatingDataset(rest_x[:num_test], rest_y[:num_test])
    test = RatingDataset(
        rest_x[num_test : 2 * num_test], rest_y[num_test : 2 * num_test]
    )
    return {"train": train, "validation": valid, "test": test}


#: scale-tier geometry for the table-sharding sweep (docs/design.md
#: §20): name -> (num_users, num_items, num_rows). User-table rows are
#: the scaling axis; train rows grow sublinearly (the hot path's cost
#: is per-query related-set work, not the raw row count).
SCALE_TIERS = {
    "100k": (100_000, 20_000, 400_000),
    "1m": (1_000_000, 100_000, 2_000_000),
    "5m": (5_000_000, 250_000, 4_000_000),
    "10m": (10_000_000, 500_000, 6_000_000),
}


def synthesize_scale(
    num_users: int,
    num_items: int,
    num_rows: int,
    seed: int = 0,
    item_zipf: float = 0.8,
) -> RatingDataset:
    """Streaming-cheap generator for the multi-million-user tiers.

    Unlike :func:`synthesize_ratings` there is no planted factor model —
    an ``(U, rank)`` table at the 10M-user tier would cost more to
    synthesize than the sweep it feeds. Users are uniform (every user
    row is equally likely to be resident-relevant, which is exactly the
    regime row-sharding targets); items follow the Zipf popularity real
    rating streams show, so popular-item queries carry the large
    related sets that stress ``s_pad``; ratings are i.i.d. 1-5 stars
    (score *values* are irrelevant to the perf sweep, and the 100k
    bit-identity stage only needs determinism, which the seed gives).
    """
    rng = np.random.default_rng(seed)
    users = rng.integers(0, num_users, size=num_rows)
    w = 1.0 / np.arange(1, num_items + 1) ** item_zipf
    w /= w.sum()
    perm = rng.permutation(num_items)  # decouple popularity from id order
    items = perm[rng.choice(num_items, size=num_rows, p=w)]
    y = rng.integers(1, 6, size=num_rows).astype(np.float32)
    x = np.stack([users, items], axis=1).astype(np.int32)
    return RatingDataset(x, y)
