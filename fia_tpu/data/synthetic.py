"""Synthetic explicit-rating generators.

The reference repo ships valid/test splits but its two training files are
stripped (``/root/reference/.MISSING_LARGE_BLOBS:1-2``), so end-to-end
runs regenerate a training split: ratings are sampled from a planted
low-rank MF model plus noise, quantised to the 1-5 star scale the real
files use. Deterministic given the seed.
"""

from __future__ import annotations

import numpy as np

from fia_tpu.data.dataset import RatingDataset


def synthesize_ratings(
    num_users: int,
    num_items: int,
    num_rows: int,
    seed: int = 0,
    rank: int = 8,
    noise: float = 0.4,
    ensure_cover: np.ndarray | None = None,
) -> RatingDataset:
    """Sample ``num_rows`` (user, item, rating) triples.

    Users/items are drawn from Zipf-ish popularity marginals (real rating
    data is heavy-tailed, and the FIA related-set sizes depend on that
    skew). ``ensure_cover`` is an optional (M, 2) array of (u, i) pairs —
    e.g. the test split — each of whose users and items is guaranteed at
    least one training interaction so every test query has a non-empty
    related set.
    """
    rng = np.random.default_rng(seed)

    def _zipf_choice(n, size):
        w = 1.0 / np.arange(1, n + 1) ** 0.8
        w /= w.sum()
        perm = rng.permutation(n)  # decouple popularity from id order
        return perm[rng.choice(n, size=size, p=w)]

    users = _zipf_choice(num_users, num_rows)
    items = _zipf_choice(num_items, num_rows)

    if ensure_cover is not None and len(ensure_cover):
        cover = np.asarray(ensure_cover)
        cu = np.unique(cover[:, 0])
        ci = np.unique(cover[:, 1])
        need = len(cu) + len(ci)
        if need > num_rows:
            raise ValueError("num_rows too small to cover the given pairs")
        users[: len(cu)] = cu
        items[: len(cu)] = rng.integers(0, num_items, size=len(cu))
        users[len(cu) : need] = rng.integers(0, num_users, size=len(ci))
        items[len(cu) : need] = ci

    # Planted MF structure: r = clip(round(mu + b_u + b_i + p_u.q_i + eps), 1, 5)
    p = rng.normal(0, 1.0 / np.sqrt(rank), size=(num_users, rank))
    q = rng.normal(0, 1.0 / np.sqrt(rank), size=(num_items, rank))
    bu = rng.normal(0, 0.3, size=num_users)
    bi = rng.normal(0, 0.3, size=num_items)
    scores = (
        3.5
        + bu[users]
        + bi[items]
        + np.einsum("nk,nk->n", p[users], q[items])
        + rng.normal(0, noise, size=num_rows)
    )
    ratings = np.clip(np.rint(scores), 1.0, 5.0).astype(np.float32)

    x = np.stack([users, items], axis=1).astype(np.int32)
    return RatingDataset(x, ratings)


def sample_heldout_pairs(
    train_x: np.ndarray,
    num_users: int,
    num_items: int,
    n: int,
    seed: int = 17,
) -> np.ndarray:
    """Sample ``n`` distinct (u, i) pairs absent from the training set.

    The benchmark/stress query protocol (mirroring the reference's RQ1/
    RQ2, whose test split is disjoint from train): a pair present in
    train couples its p_u/q_i blocks through the shared residual and can
    make the related-set block Hessian indefinite — a regime the
    reference never queries. Membership is tested against packed
    ``u * num_items + i`` codes so it stays cheap at ML-20M scale (a
    tuple set over 20M rows costs GBs).
    """
    rng = np.random.default_rng(seed)
    codes = np.sort(
        np.asarray(train_x[:, 0], np.int64) * num_items
        + np.asarray(train_x[:, 1], np.int64)
    )
    picked: set[int] = set()
    pts: list[tuple[int, int]] = []
    while len(pts) < n:
        u, i = int(rng.integers(0, num_users)), int(rng.integers(0, num_items))
        c = u * num_items + i
        if c in picked:
            continue
        j = np.searchsorted(codes, c)
        if j == len(codes) or codes[j] != c:
            picked.add(c)
            pts.append((u, i))
    return np.asarray(pts, dtype=np.int32)


def synthetic_splits(
    num_users: int,
    num_items: int,
    num_train: int,
    num_test: int,
    seed: int = 0,
    **kw,
) -> dict[str, RatingDataset]:
    """Train/validation/test splits from one planted model.

    Valid/test pairs are kept DISJOINT from the training pairs (as the
    reference's real splits are): an in-train query pair appears twice in
    its own related set and couples the block through the shared residual
    — the regime ``sample_heldout_pairs`` documents as one the reference
    never queries, which the CLI drivers would otherwise hit at random.
    """
    margin = 4
    while True:
        full = synthesize_ratings(
            num_users, num_items, num_train + margin * num_test, seed=seed, **kw
        )
        train_x, train_y = full.x[:num_train], full.y[:num_train]
        codes = np.sort(
            np.asarray(train_x[:, 0], np.int64) * num_items
            + np.asarray(train_x[:, 1], np.int64)
        )
        rest_x, rest_y = full.x[num_train:], full.y[num_train:]
        rc = np.asarray(rest_x[:, 0], np.int64) * num_items + np.asarray(
            rest_x[:, 1], np.int64
        )
        if codes.size:
            j = np.clip(np.searchsorted(codes, rc), 0, len(codes) - 1)
            heldout = codes[j] != rc
        else:
            heldout = np.ones(len(rc), bool)
        if heldout.sum() >= 2 * num_test:
            rest_x, rest_y = rest_x[heldout], rest_y[heldout]
            break
        margin *= 2  # extremely dense configs: draw more candidates

    train = RatingDataset(train_x, train_y)
    valid = RatingDataset(rest_x[:num_test], rest_y[:num_test])
    test = RatingDataset(
        rest_x[num_test : 2 * num_test], rest_y[num_test : 2 * num_test]
    )
    return {"train": train, "validation": valid, "test": test}
