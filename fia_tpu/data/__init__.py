from fia_tpu.data.dataset import (  # noqa: F401
    RatingDataset,
    filter_dataset,
    find_distances,
)
from fia_tpu.data.loaders import load_movielens, load_yelp, load_dataset  # noqa: F401
from fia_tpu.data.synthetic import synthesize_ratings  # noqa: F401
from fia_tpu.data.index import InteractionIndex  # noqa: F401
