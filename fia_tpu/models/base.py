"""Model interface for latent-factor recommenders.

The reference expresses models as TF1 graph-builder subclasses of a
template-method base (``src/influence/genericNeuralNet.py:82-180``) whose
parameters are flat 1-D variables sliced by hand for the FIA block
restriction (``matrix_factorization.py:152-162``). Here a model is a
small object exposing *pure functions* over a parameter pytree:

  - ``init_params(key)``       -> params pytree
  - ``predict(params, x)``     -> (B,) predicted ratings
  - ``loss(params, x, y)``     -> scalar total loss (masked-mean MSE + L2)
  - ``extract_block/with_block`` -> the FIA (user, item) parameter
    sub-block as a pytree, written back functionally so block-restricted
    gradients and Hessians fall out of ordinary AD instead of slicing.

Everything is jit/vmap/shard-friendly: (u, i) may be traced scalars.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

Params = Any  # pytree of jnp arrays
Block = Any  # pytree of jnp arrays (the FIA sub-block)


def truncated_normal(key, shape, stddev, dtype=jnp.float32):
    """TF-style truncated normal: resample beyond 2 sigma."""
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def _weighted_mean(err: jnp.ndarray, w) -> jnp.ndarray:
    """Plain mean, or masked mean sum(w·err)/max(sum(w), 1) when ``w`` is
    given — reproduces the reference's mean over whichever rows were fed
    (``matrix_factorization.py:122-132``) while letting padded callers
    mask rows out."""
    if w is None:
        return jnp.mean(err)
    w = w.astype(err.dtype)
    return jnp.sum(w * err) / jnp.maximum(jnp.sum(w), 1.0)


class LatentFactorModel:
    """Base class; subclasses define the forward pass and the FIA block."""

    #: params that carry L2 weight decay (reference
    #: ``genericNeuralNet.py:40-65``: wd * l2_loss = wd * 0.5 * sum(w^2)).
    decayed: tuple[str, ...] = ()

    #: flattening order of the FIA block — fixed explicitly so the flat
    #: inverse-HVP layout matches the reference's params_test order
    #: (e.g. [p_u, q_i, b_u, b_i] for MF, matrix_factorization.py:38-67)
    #: instead of the dict pytree's alphabetical order.
    block_keys: tuple[str, ...] = ()

    def __init__(self, num_users: int, num_items: int, embedding_size: int,
                 weight_decay: float):
        self.num_users = int(num_users)
        self.num_items = int(num_items)
        self.embedding_size = int(embedding_size)
        self.weight_decay = float(weight_decay)

    # -- subclass hooks ----------------------------------------------------
    def init_params(self, key) -> Params:
        raise NotImplementedError

    def predict(self, params: Params, x) -> jnp.ndarray:
        """x: (B, 2) int32 (user, item) -> (B,) float ratings."""
        raise NotImplementedError

    def extract_block(self, params: Params, u, i) -> Block:
        raise NotImplementedError

    def with_block(self, params: Params, block: Block, u, i) -> Params:
        raise NotImplementedError

    @property
    def block_size(self) -> int:
        raise NotImplementedError

    # -- generic functions -------------------------------------------------
    def reg_loss(self, params: Params) -> jnp.ndarray:
        reg = jnp.asarray(0.0, jnp.float32)
        for name in self.decayed:
            reg = reg + 0.5 * jnp.sum(jnp.square(params[name]))
        return self.weight_decay * reg

    def indiv_loss_from_pred(self, pred: jnp.ndarray, y) -> jnp.ndarray:
        """Per-example loss given predictions, (B,). The single hook a
        subclass overrides to change the per-example loss — both the
        training loss and the block-restricted influence loss route
        through it."""
        return jnp.square(pred - y)

    def indiv_loss(self, params: Params, x, y) -> jnp.ndarray:
        """Per-example loss, (B,)."""
        return self.indiv_loss_from_pred(self.predict(params, x), y)

    def loss(self, params: Params, x, y, w=None) -> jnp.ndarray:
        """Total training loss: (weighted-)mean squared error + L2.

        ``w`` is an optional (B,) weight/mask vector: the mean is then
        sum(w * err) / sum(w), which reproduces the reference's plain mean
        over whichever rows were fed (``matrix_factorization.py:122-132``)
        while letting padded/batched callers mask rows out.
        """
        err = self.indiv_loss(params, x, y)
        return _weighted_mean(err, w) + self.reg_loss(params)

    def loss_no_reg(self, params: Params, x, y, w=None) -> jnp.ndarray:
        return _weighted_mean(self.indiv_loss(params, x, y), w)

    def mae(self, params: Params, x, y) -> jnp.ndarray:
        """Reference 'accuracy' op (``matrix_factorization.py:134-146``)."""
        return jnp.mean(jnp.abs(self.predict(params, x) - y))

    def adversarial_loss(self, params: Params, x, y):
        """Adversarial-loss hook. ``None`` for rating regression.

        The reference base class carries a classification log(1-p) loss
        (``genericNeuralNet.py:481-494``, a Koh & Liang leftover), and both
        MF and NCF disable it by returning ``(None, None)``
        (``matrix_factorization.py:148-150``, ``NCF.py:177-179``); the
        ``loss_type='adversarial_loss'`` branches of their influence paths
        are commented out (``matrix_factorization.py:258-259``). Kept as an
        overridable hook so a classification model family can supply one.
        """
        return None, None

    def num_params(self) -> int:
        shapes = jax.eval_shape(self.init_params, jax.random.PRNGKey(0))
        return sum(math.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes))

    # -- block helpers -----------------------------------------------------
    def block_predict(self, params: Params, block: Block, u, i, x) -> jnp.ndarray:
        """Predict with the (u, i) block functionally substituted.

        Differentiating w.r.t. ``block`` yields exactly the reference's
        block-restricted gradients (its get_test_grad slicing,
        ``matrix_factorization.py:152-162``) because all other parameters
        are constants of the closure.
        """
        return self.predict(self.with_block(params, block, u, i), x)

    def block_reg(self, params: Params, block: Block, u, i) -> jnp.ndarray:
        """L2 regulariser with the (u, i) block substituted.

        Subclasses override with the scatter-free form
        ``reg(params) + wd/2 * (‖block rows‖² − ‖table rows‖²)`` — the
        full-table reduction is block-independent and stays unbatched
        under vmap, so only O(block) work is batched.
        """
        return self.reg_loss(self.with_block(params, block, u, i))

    #: optional closed-form block Hessian hook. When a subclass defines
    #: ``block_hessian(params, u, i, x, y, w) -> (d, d)`` (undamped), the
    #: influence engine's direct solver uses it instead of materialising
    #: the Hessian through ``block_size`` autodiff HVPs.
    block_hessian = None

    #: optional Gauss-Newton decomposition hooks, enabling the engine's
    #: flat segment-sum query path. They assert that the exact block
    #: Hessian of ``block_loss`` over rows (x, y, w) decomposes as
    #:   H = (2/n) Σ_j w_j (g_j g_jᵀ + a_j b_j e_j · C) + diag(r)
    #: with g_j = ∇_block r̂(z_j), e_j the residual, a_j/b_j the
    #: user/item match indicators, C = ``block_cross_const(params)``
    #: (∇²r̂ on rows equal to the query pair — constant in (u, i) for
    #: MF/NCF), and r = ``block_reg_diag(params)`` the L2 diagonal.
    #: Holds exactly when r̂ is piecewise-linear in the block except for
    #: bilinear terms joining the user and item rows (MF dot product,
    #: NCF GMF branch).
    block_cross_const = None
    block_reg_diag = None

    #: optional fused row-feature hooks, one step beyond
    #: ``block_row_grads``: ``build_row_features(params, x, y) -> (N, F)``
    #: packs every per-TRAIN-ROW quantity the flat influence program
    #: needs (the query-independent own-gradient components, the
    #: residual e_j, and the float-packed row ids) into ONE dense
    #: table, and ``grads_from_row_features(feat, u, i) ->
    #: (g (B, d), e (B,), a (B,), b (B,))`` recovers the per-row block
    #: gradients, residuals, and user/item match masks (``u``/``i``
    #: scalar or (B,) query ids) with masks only. Why: the flat program's cost is gather-tile traffic — each
    #: separate embedding/posting gather of a k=16 row reads a full
    #: (8, 128) TPU tile, and XLA's cost model put the MF grads stage
    #: at 39 GB accessed (73% of v5e HBM bandwidth) for ~100 MB of
    #: useful data (roofline_mf.json, r4). One wide gather from the
    #: fused table replaces ~8 scattered ones. The engine gates the
    #: table by size (it stores (N, ceil(F/128)·128) physically).
    build_row_features = None
    grads_from_row_features = None

    #: optional fast per-row block-Jacobian hook:
    #: ``block_row_grads(params, u, i, x) -> (B, d)`` with g_j =
    #: ∇_block r̂(z_j); ``u``/``i`` may be scalars or (B,) arrays aligned
    #: with ``x`` (the flat engine's per-row query ids). The generic
    #: path vmaps ``jax.grad`` over B single-row graphs — measured 92%
    #: of the MF flat query's device time (BENCH r4 device_split,
    #: 157 ms of 170 ms) for what is closed-form gathers (MF) or one
    #: batched backward (NCF): each row's prediction touches the query
    #: block only through its own gathered embeddings, so the stacked
    #: per-row own-input gradients of sum_j r̂_j, masked by the
    #: user/item match indicators, ARE the per-row block gradients.
    block_row_grads = None

    #: optional table-row gradient hook for the row-sharded flat path
    #: (``parallel/sharded.py``): ``grads_from_rows(params, rows, x, y,
    #: u, i) -> (g (B, d), e (B,))`` computes the per-row block
    #: gradients and residuals from *pre-gathered* table rows — ``rows``
    #: maps each of the model's ``TABLE_PARAMS`` entries to its values
    #: at the B flat rows' own (user, item) ids — instead of indexing
    #: the tables directly. Must be op-for-op the ``block_row_grads`` +
    #: ``predict`` pair so the sharded program (which fetches the rows
    #: once via collective) is bit-identical to the replicated one.
    grads_from_rows = None

    #: optional fused-score-kernel hooks (influence/kernels/): a model
    #: whose ``block_row_grads`` is closed-form over its own gathered
    #: embedding rows can let the Pallas score kernel re-form the
    #: per-row gradients inside VMEM instead of materialising an (S, d)
    #: matrix in HBM. ``kernel_family`` names the kernel body
    #: ("mf" / "ncf"); ``kernel_row_inputs(params, x) -> (B, R)``
    #: gathers the raw embedding rows the kernel's gradient form reads,
    #: in the layout that family documents; ``kernel_aux(params)``
    #: returns the (small, 2-D) non-embedding weight operands the
    #: kernel needs resident in VMEM (empty tuple when none).
    kernel_family: str | None = None
    kernel_row_inputs = None

    def kernel_aux(self, params: Params) -> tuple:
        return ()

    def block_loss(self, params: Params, block: Block, u, i, x, y, w=None):
        err = self.indiv_loss_from_pred(
            self.block_predict(params, block, u, i, x), y
        )
        return _weighted_mean(err, w) + self.block_reg(params, block, u, i)

    def flatten_block(self, block: Block) -> jnp.ndarray:
        keys = self.block_keys or tuple(sorted(block))
        return jnp.concatenate(
            [jnp.ravel(jnp.asarray(block[k])) for k in keys]
        )

    def unflatten_block(self, vec: jnp.ndarray, like: Block) -> Block:
        keys = self.block_keys or tuple(sorted(like))
        out, pos = {}, 0
        for k in keys:
            l = jnp.asarray(like[k])
            n = math.prod(l.shape)
            out[k] = jnp.reshape(vec[pos : pos + n], l.shape)
            pos += n
        return out
