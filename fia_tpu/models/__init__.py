from fia_tpu.models.base import LatentFactorModel  # noqa: F401
from fia_tpu.models.mf import MF  # noqa: F401
from fia_tpu.models.ncf import NCF  # noqa: F401

MODELS = {"MF": MF, "NCF": NCF}
