"""Biased matrix factorization.

Parity target: reference ``src/influence/matrix_factorization.py:21-146``
—  r̂(u, i) = p_u · q_i + b_u + b_i + b_g, squared-error loss with L2
weight decay on the two embedding tables only, embeddings initialised
truncated-normal with stddev 1/sqrt(k), biases zero.

TPU-native shape: parameters are dense (U, k)/(I, k) matrices (not the
reference's flat 1-D variables), so batched prediction is two gathers +
a fused elementwise reduction, and the FIA block is plain row indexing.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from fia_tpu.models.base import LatentFactorModel, truncated_normal


class MF(LatentFactorModel):
    decayed = ("P", "Q")
    block_keys = ("pu", "qi", "bu", "bi")

    def init_params(self, key):
        k = self.embedding_size
        kp, kq = jax.random.split(key)
        std = 1.0 / math.sqrt(k)
        return {
            "P": truncated_normal(kp, (self.num_users, k), std),
            "Q": truncated_normal(kq, (self.num_items, k), std),
            "bu": jnp.zeros((self.num_users,), jnp.float32),
            "bi": jnp.zeros((self.num_items,), jnp.float32),
            "bg": jnp.zeros((), jnp.float32),
        }

    def predict(self, params, x):
        u, i = x[:, 0], x[:, 1]
        dot = jnp.sum(params["P"][u] * params["Q"][i], axis=-1)
        return dot + params["bu"][u] + params["bi"][i] + params["bg"]

    # -- FIA block: [p_u (k), q_i (k), b_u, b_i] -> 2k + 2 params
    # (reference get_test_params, matrix_factorization.py:38-67; the global
    # bias is excluded there too).
    def extract_block(self, params, u, i):
        return {
            "pu": params["P"][u],
            "qi": params["Q"][i],
            "bu": params["bu"][u],
            "bi": params["bi"][i],
        }

    def with_block(self, params, block, u, i):
        return {
            "P": params["P"].at[u].set(block["pu"]),
            "Q": params["Q"].at[i].set(block["qi"]),
            "bu": params["bu"].at[u].set(block["bu"]),
            "bi": params["bi"].at[i].set(block["bi"]),
            "bg": params["bg"],
        }

    # Scatter-free block substitution: predictions gather only the rows
    # of ``x`` and select the block values where the row's user/item is
    # (u, i). Gradients w.r.t. the block are identical to substituting
    # into the full tables, but nothing table-sized is ever built inside
    # the vmapped influence query (the .at[u].set path materialises a
    # full (U, k) copy per vmap instance on TPU and OOMs at scale).
    def block_predict(self, params, block, u, i, x):
        xu, xi = x[:, 0], x[:, 1]
        mu = (xu == u)[:, None]
        mi = (xi == i)[:, None]
        pu = jnp.where(mu, block["pu"][None, :], params["P"][xu])
        qi = jnp.where(mi, block["qi"][None, :], params["Q"][xi])
        bu = jnp.where(xu == u, block["bu"], params["bu"][xu])
        bi = jnp.where(xi == i, block["bi"], params["bi"][xi])
        return jnp.sum(pu * qi, axis=-1) + bu + bi + params["bg"]

    def block_reg(self, params, block, u, i):
        corr = (
            jnp.sum(jnp.square(block["pu"]))
            - jnp.sum(jnp.square(params["P"][u]))
            + jnp.sum(jnp.square(block["qi"]))
            - jnp.sum(jnp.square(params["Q"][i]))
        )
        return self.reg_loss(params) + 0.5 * self.weight_decay * corr

    def block_hessian(self, params, u, i, x, y, w):
        """Closed-form damped-free block Hessian over rows (x, y, w).

        For the quadratic-in-block MF prediction the Hessian of
        block_loss has an exact masked-matmul form — a handful of MXU
        ops instead of ``block_size`` autodiff HVPs (the generic
        ``materialize_block_hessian`` path). With g_j = ∇_block pred_j =
        [a_j q_row; b_j p_row; a_j; b_j] (a_j = [user_j == u],
        b_j = [item_j == i]):

          H = (2/n) Σ_j w_j (g_j g_jᵀ + a_j b_j e_j [[0 I];[I 0]]) + wd·I

        where the e_j cross term comes from ∇²(pu·qi) on rows hitting
        both u and i (possible when a train row equals the query pair).
        Damping is added by the caller, as in the autodiff path.
        """
        k = self.embedding_size
        xu, xi = x[:, 0], x[:, 1]
        ma = (xu == u).astype(jnp.float32)
        mi = (xi == i).astype(jnp.float32)
        wf = w.astype(jnp.float32)
        a = wf * ma  # rows sharing the user
        b = wf * mi  # rows sharing the item
        n = jnp.maximum(jnp.sum(wf), 1.0)

        block = self.extract_block(params, u, i)
        p_row = jnp.where((xu == u)[:, None], block["pu"][None, :],
                          params["P"][xu])
        q_row = jnp.where((xi == i)[:, None], block["qi"][None, :],
                          params["Q"][xi])
        e = self.block_predict(params, block, u, i, x) - y

        c = 2.0 / n
        ab = wf * ma * mi  # rows equal to the query pair itself (w once)
        # g gᵀ accumulation, blockwise
        H_pp = c * (q_row.T * a) @ q_row + self.weight_decay * jnp.eye(k)
        H_qq = c * (p_row.T * b) @ p_row + self.weight_decay * jnp.eye(k)
        H_pq = c * ((q_row.T * ab) @ p_row + jnp.sum(ab * e) * jnp.eye(k))
        h_pbu = c * q_row.T @ a  # (k,)
        h_pbi = c * q_row.T @ ab
        h_qbu = c * p_row.T @ ab
        h_qbi = c * p_row.T @ b
        s_aa = c * jnp.sum(a)
        s_bb = c * jnp.sum(b)
        s_ab = c * jnp.sum(ab)

        top = jnp.concatenate(
            [
                jnp.concatenate([H_pp, H_pq], axis=1),
                jnp.concatenate([H_pq.T, H_qq], axis=1),
            ],
            axis=0,
        )  # (2k, 2k)
        cols_b = jnp.stack(
            [jnp.concatenate([h_pbu, h_qbu]), jnp.concatenate([h_pbi, h_qbi])],
            axis=1,
        )  # (2k, 2)
        corner = jnp.array([[s_aa, s_ab], [s_ab, s_bb]], jnp.float32)
        return jnp.concatenate(
            [
                jnp.concatenate([top, cols_b], axis=1),
                jnp.concatenate([cols_b.T, corner], axis=1),
            ],
            axis=0,
        )

    def block_row_grads(self, params, u, i, x):
        """Closed-form per-row block Jacobian (see base hook doc).

        g_j = [a_j q_row_j ; b_j p_row_j ; a_j ; b_j] with
        a_j = [user_j == u], b_j = [item_j == i] — the same form
        ``block_hessian``'s derivation uses. The row embeddings need no
        block substitution: where the row hits (u, i) the substituted
        value IS the current table row. Pure gathers + masks — the op
        the generic vmapped-autodiff path spent 92% of the MF flat
        query's device time emulating.
        """
        xu, xi = x[:, 0], x[:, 1]
        a = (xu == u).astype(jnp.float32)
        b = (xi == i).astype(jnp.float32)
        return jnp.concatenate(
            [
                a[:, None] * params["Q"][xi],
                b[:, None] * params["P"][xu],
                a[:, None],
                b[:, None],
            ],
            axis=1,
        )

    def grads_from_rows(self, params, rows, x, y, u, i):
        """(g, e) from pre-gathered table rows (see base hook doc):
        op-for-op ``block_row_grads`` + ``predict`` with every table
        index replaced by the corresponding gathered row, so the
        row-sharded flat path reproduces the replicated one bitwise."""
        xu, xi = x[:, 0], x[:, 1]
        a = (xu == u).astype(jnp.float32)
        b = (xi == i).astype(jnp.float32)
        g = jnp.concatenate(
            [
                a[:, None] * rows["Q"],
                b[:, None] * rows["P"],
                a[:, None],
                b[:, None],
            ],
            axis=1,
        )
        dot = jnp.sum(rows["P"] * rows["Q"], axis=-1)
        pred = dot + rows["bu"] + rows["bi"] + params["bg"]
        return g, pred - y

    # -- fused score-kernel hooks (see base doc + influence/kernels/mf.py):
    # the kernel re-forms g_j = [a Q[i_j]; b P[u_j]; a; b] in VMEM from
    # the raw rows, so the gather ships them in that order.
    kernel_family = "mf"

    def kernel_row_inputs(self, params, x):
        """(B, 2k) raw rows ``[Q[i_j] | P[u_j]]`` — the two embedding
        gathers the closed-form row gradient is built from."""
        return jnp.concatenate(
            [params["Q"][x[:, 1]], params["P"][x[:, 0]]], axis=1
        )

    # -- fused row-feature hooks (see base doc): one wide gather feeds
    # the flat influence program instead of ~8 tile-amplified ones.
    # Layout: [Q[i_j] (k) | P[u_j] (k) | e_j | u_j | i_j], F = 2k+3.
    # Ids are packed as float32 — exact below 2^24, which the engine
    # gates on.
    @property
    def row_feature_dim(self) -> int:
        return 2 * self.embedding_size + 3

    def build_row_features(self, params, x, y):
        xu, xi = x[:, 0], x[:, 1]
        e = self.predict(params, x) - y
        return jnp.concatenate(
            [
                params["Q"][xi],
                params["P"][xu],
                e[:, None],
                xu.astype(jnp.float32)[:, None],
                xi.astype(jnp.float32)[:, None],
            ],
            axis=1,
        )

    def grads_from_row_features(self, feat, u, i):
        """(g, e, a, b) for rows ``feat`` against query ids ``u``/``i``
        (scalar or per-row arrays) — same math as block_row_grads."""
        k = self.embedding_size
        a = (feat[:, 2 * k + 1] == u).astype(jnp.float32)
        b = (feat[:, 2 * k + 2] == i).astype(jnp.float32)
        g = jnp.concatenate(
            [
                a[:, None] * feat[:, :k],
                b[:, None] * feat[:, k: 2 * k],
                a[:, None],
                b[:, None],
            ],
            axis=1,
        )
        return g, feat[:, 2 * k], a, b

    def block_cross_const(self, params):
        """∇²r̂ on rows equal to the query pair: ∇²(pu·qi) = [[0 I];[I 0]]
        in the (pu, qi) blocks (see block_hessian's cross term)."""
        k = self.embedding_size
        d = self.block_size
        r = jnp.arange(k)
        C = jnp.zeros((d, d), jnp.float32)
        C = C.at[r, k + r].set(1.0)
        return C.at[k + r, r].set(1.0)

    def block_reg_diag(self, params):
        """L2 diagonal: wd on the embedding dims, none on the biases
        (only P/Q are decayed, reference matrix_factorization.py:92-97)."""
        k = self.embedding_size
        return jnp.concatenate(
            [jnp.full((2 * k,), self.weight_decay, jnp.float32),
             jnp.zeros((2,), jnp.float32)]
        )

    @property
    def block_size(self) -> int:
        return 2 * self.embedding_size + 2
