"""Biased matrix factorization.

Parity target: reference ``src/influence/matrix_factorization.py:21-146``
—  r̂(u, i) = p_u · q_i + b_u + b_i + b_g, squared-error loss with L2
weight decay on the two embedding tables only, embeddings initialised
truncated-normal with stddev 1/sqrt(k), biases zero.

TPU-native shape: parameters are dense (U, k)/(I, k) matrices (not the
reference's flat 1-D variables), so batched prediction is two gathers +
a fused elementwise reduction, and the FIA block is plain row indexing.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from fia_tpu.models.base import LatentFactorModel, truncated_normal


class MF(LatentFactorModel):
    decayed = ("P", "Q")
    block_keys = ("pu", "qi", "bu", "bi")

    def init_params(self, key):
        k = self.embedding_size
        kp, kq = jax.random.split(key)
        std = 1.0 / math.sqrt(k)
        return {
            "P": truncated_normal(kp, (self.num_users, k), std),
            "Q": truncated_normal(kq, (self.num_items, k), std),
            "bu": jnp.zeros((self.num_users,), jnp.float32),
            "bi": jnp.zeros((self.num_items,), jnp.float32),
            "bg": jnp.zeros((), jnp.float32),
        }

    def predict(self, params, x):
        u, i = x[:, 0], x[:, 1]
        dot = jnp.sum(params["P"][u] * params["Q"][i], axis=-1)
        return dot + params["bu"][u] + params["bi"][i] + params["bg"]

    # -- FIA block: [p_u (k), q_i (k), b_u, b_i] -> 2k + 2 params
    # (reference get_test_params, matrix_factorization.py:38-67; the global
    # bias is excluded there too).
    def extract_block(self, params, u, i):
        return {
            "pu": params["P"][u],
            "qi": params["Q"][i],
            "bu": params["bu"][u],
            "bi": params["bi"][i],
        }

    def with_block(self, params, block, u, i):
        return {
            "P": params["P"].at[u].set(block["pu"]),
            "Q": params["Q"].at[i].set(block["qi"]),
            "bu": params["bu"].at[u].set(block["bu"]),
            "bi": params["bi"].at[i].set(block["bi"]),
            "bg": params["bg"],
        }

    # Scatter-free block substitution: predictions gather only the rows
    # of ``x`` and select the block values where the row's user/item is
    # (u, i). Gradients w.r.t. the block are identical to substituting
    # into the full tables, but nothing table-sized is ever built inside
    # the vmapped influence query (the .at[u].set path materialises a
    # full (U, k) copy per vmap instance on TPU and OOMs at scale).
    def block_predict(self, params, block, u, i, x):
        xu, xi = x[:, 0], x[:, 1]
        mu = (xu == u)[:, None]
        mi = (xi == i)[:, None]
        pu = jnp.where(mu, block["pu"][None, :], params["P"][xu])
        qi = jnp.where(mi, block["qi"][None, :], params["Q"][xi])
        bu = jnp.where(xu == u, block["bu"], params["bu"][xu])
        bi = jnp.where(xi == i, block["bi"], params["bi"][xi])
        return jnp.sum(pu * qi, axis=-1) + bu + bi + params["bg"]

    def block_reg(self, params, block, u, i):
        corr = (
            jnp.sum(jnp.square(block["pu"]))
            - jnp.sum(jnp.square(params["P"][u]))
            + jnp.sum(jnp.square(block["qi"]))
            - jnp.sum(jnp.square(params["Q"][i]))
        )
        return self.reg_loss(params) + 0.5 * self.weight_decay * corr

    @property
    def block_size(self) -> int:
        return 2 * self.embedding_size + 2
