"""NeuMF-style neural collaborative filtering.

Parity target: reference ``src/influence/NCF.py:20-161`` — an MLP tower
over concatenated (user, item) MLP-embeddings (2k -> k relu -> k/2 relu),
a GMF branch p_u ⊙ q_i, concatenated and fused by one linear layer to a
scalar rating. Weight decay on all four embedding tables and the three
layer weight matrices (not the layer biases); embeddings and weights
truncated-normal with stddev 1/sqrt(fan_in), biases zero.

The FIA block for NCF is the four embedding rows only — the MLP weights
are deliberately excluded from the influence subspace (``NCF.py:43-66``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from fia_tpu.models.base import LatentFactorModel, truncated_normal


class NCF(LatentFactorModel):
    decayed = ("P_mlp", "Q_mlp", "P_gmf", "Q_gmf", "W1", "W2", "W3")
    block_keys = ("pu_mlp", "qi_mlp", "pu_gmf", "qi_gmf")

    def init_params(self, key):
        k = self.embedding_size
        k2 = k // 2
        keys = jax.random.split(key, 7)
        se = 1.0 / math.sqrt(k)
        return {
            "P_mlp": truncated_normal(keys[0], (self.num_users, k), se),
            "Q_mlp": truncated_normal(keys[1], (self.num_items, k), se),
            "P_gmf": truncated_normal(keys[2], (self.num_users, k), se),
            "Q_gmf": truncated_normal(keys[3], (self.num_items, k), se),
            "W1": truncated_normal(keys[4], (2 * k, k), 1.0 / math.sqrt(2 * k)),
            "b1": jnp.zeros((k,), jnp.float32),
            "W2": truncated_normal(keys[5], (k, k2), 1.0 / math.sqrt(k)),
            "b2": jnp.zeros((k2,), jnp.float32),
            "W3": truncated_normal(keys[6], (k2 + k, 1), 1.0 / math.sqrt(k2 + k)),
            "b3": jnp.zeros((1,), jnp.float32),
        }

    def predict(self, params, x):
        u, i = x[:, 0], x[:, 1]
        h_mlp = jnp.concatenate([params["P_mlp"][u], params["Q_mlp"][i]], axis=-1)
        h1 = jax.nn.relu(h_mlp @ params["W1"] + params["b1"])
        h2 = jax.nn.relu(h1 @ params["W2"] + params["b2"])
        h_gmf = params["P_gmf"][u] * params["Q_gmf"][i]
        h = jnp.concatenate([h2, h_gmf], axis=-1)
        return jnp.squeeze(h @ params["W3"] + params["b3"], axis=-1)

    # -- FIA block: 4 embedding rows, 4k params (NCF.py:43-66) -------------
    def extract_block(self, params, u, i):
        return {
            "pu_mlp": params["P_mlp"][u],
            "qi_mlp": params["Q_mlp"][i],
            "pu_gmf": params["P_gmf"][u],
            "qi_gmf": params["Q_gmf"][i],
        }

    def with_block(self, params, block, u, i):
        out = dict(params)
        out["P_mlp"] = params["P_mlp"].at[u].set(block["pu_mlp"])
        out["Q_mlp"] = params["Q_mlp"].at[i].set(block["qi_mlp"])
        out["P_gmf"] = params["P_gmf"].at[u].set(block["pu_gmf"])
        out["Q_gmf"] = params["Q_gmf"].at[i].set(block["qi_gmf"])
        return out

    # Scatter-free block substitution (see MF.block_predict): gather the
    # batch rows and select block values where the row hits (u, i) —
    # avoids materialising full (U, k) table copies per vmap instance.
    def block_predict(self, params, block, u, i, x):
        xu, xi = x[:, 0], x[:, 1]
        mu = (xu == u)[:, None]
        mi = (xi == i)[:, None]
        pm = jnp.where(mu, block["pu_mlp"][None, :], params["P_mlp"][xu])
        qm = jnp.where(mi, block["qi_mlp"][None, :], params["Q_mlp"][xi])
        pg = jnp.where(mu, block["pu_gmf"][None, :], params["P_gmf"][xu])
        qg = jnp.where(mi, block["qi_gmf"][None, :], params["Q_gmf"][xi])
        h1 = jax.nn.relu(jnp.concatenate([pm, qm], axis=-1) @ params["W1"] + params["b1"])
        h2 = jax.nn.relu(h1 @ params["W2"] + params["b2"])
        h = jnp.concatenate([h2, pg * qg], axis=-1)
        return jnp.squeeze(h @ params["W3"] + params["b3"], axis=-1)

    def block_reg(self, params, block, u, i):
        corr = (
            jnp.sum(jnp.square(block["pu_mlp"]))
            - jnp.sum(jnp.square(params["P_mlp"][u]))
            + jnp.sum(jnp.square(block["qi_mlp"]))
            - jnp.sum(jnp.square(params["Q_mlp"][i]))
            + jnp.sum(jnp.square(block["pu_gmf"]))
            - jnp.sum(jnp.square(params["P_gmf"][u]))
            + jnp.sum(jnp.square(block["qi_gmf"]))
            - jnp.sum(jnp.square(params["Q_gmf"][i]))
        )
        return self.reg_loss(params) + 0.5 * self.weight_decay * corr

    def block_hessian(self, params, u, i, x, y, w):
        """Exact (undamped) block Hessian via Gauss-Newton + the GMF
        bilinear correction.

        The NCF prediction is piecewise-linear in (pu_mlp, qi_mlp) — a
        relu MLP — and linear in each of pu_gmf / qi_gmf separately, so
        ∇²r̂ vanishes a.e. EXCEPT the GMF cross term on rows hitting both
        u and i (a train row equal to the query pair):
        ∂²r̂/∂pu_gmf ∂qi_gmf = diag(W3's gmf rows). Hence, with
        g_j = ∇_block r̂(z_j) (one vmapped AD pass, (B, 4k)):

          H = (2/n) Σ_j w_j (g_j g_jᵀ + a_j b_j e_j K) + wd·I

        — one MXU matmul instead of the generic path's 4k autodiff HVPs.
        Damping is added by the caller, as in the autodiff path.
        """
        from fia_tpu.influence.grads import per_example_block_prediction_grads

        xu, xi = x[:, 0], x[:, 1]
        wf = w.astype(jnp.float32)
        c = 2.0 / jnp.maximum(jnp.sum(wf), 1.0)

        block = self.extract_block(params, u, i)
        g = per_example_block_prediction_grads(self, params, u, i, x)
        e = self.block_predict(params, block, u, i, x) - y
        ab = wf * (xu == u).astype(jnp.float32) * (xi == i).astype(jnp.float32)
        return (
            c * (g.T * wf) @ g
            + c * jnp.sum(ab * e) * self.block_cross_const(params)
            + jnp.diag(self.block_reg_diag(params))
        )

    def _own_grads(self, params, xu, xi):
        """Per-row gradients of Σ_j r̂_j w.r.t. each row's OWN four
        gathered embedding rows — one batched forward+backward (each
        r̂_j touches only row j's inputs, so the stacked own-input
        gradients ARE the per-row gradients). The single place the NCF
        forward is re-derived for the fast-Jacobian paths; both
        block_row_grads and build_row_features route through it."""
        own = (params["P_mlp"][xu], params["Q_mlp"][xi],
               params["P_gmf"][xu], params["Q_gmf"][xi])
        return self._own_grads_from_rows(params, own)

    def _own_grads_from_rows(self, params, own):
        """The batched backward of :meth:`_own_grads` over
        already-gathered rows ``own = (pm, qm, pg, qg)`` — split out so
        the row-sharded path (rows arrive via collective gather) runs
        the identical gradient graph at the same batch shape."""

        def total(pm, qm, pg, qg):
            h1 = jax.nn.relu(
                jnp.concatenate([pm, qm], axis=-1) @ params["W1"]
                + params["b1"]
            )
            h2 = jax.nn.relu(h1 @ params["W2"] + params["b2"])
            h = jnp.concatenate([h2, pg * qg], axis=-1)
            return jnp.sum(h @ params["W3"] + params["b3"])

        return jax.grad(total, argnums=(0, 1, 2, 3))(*own)

    @staticmethod
    def _masked_block_concat(parts, a, b):
        """(B, 4k) block gradients from the four own-gradient pieces in
        block_keys order (pu_mlp, qi_mlp, pu_gmf, qi_gmf), masked by
        the user/item match indicators."""
        au, bi_ = a[:, None], b[:, None]
        return jnp.concatenate(
            [au * parts[0], bi_ * parts[1], au * parts[2], bi_ * parts[3]],
            axis=1,
        )

    def block_row_grads(self, params, u, i, x):
        """Per-row block Jacobian via ONE batched backward pass:
        ∂r̂_j/∂block = mask_j · ∂r̂_j/∂own_j (block substitution is
        the identity at the current params). Batched matmuls on the MXU
        replace B vmapped single-row autodiff graphs (see base hook
        doc — 92% of flat-query device time in the generic path).
        """
        xu, xi = x[:, 0], x[:, 1]
        return self._masked_block_concat(
            self._own_grads(params, xu, xi),
            (xu == u).astype(jnp.float32),
            (xi == i).astype(jnp.float32),
        )

    def grads_from_rows(self, params, rows, x, y, u, i):
        """(g, e) from pre-gathered table rows (see base hook doc):
        the ``_own_grads_from_rows`` backward plus the forward re-run
        with every table index replaced by its gathered row — the same
        graphs ``block_row_grads``/``predict`` build, so the
        row-sharded flat path is bitwise the replicated one."""
        xu, xi = x[:, 0], x[:, 1]
        pm, qm = rows["P_mlp"], rows["Q_mlp"]
        pg, qg = rows["P_gmf"], rows["Q_gmf"]
        g = self._masked_block_concat(
            self._own_grads_from_rows(params, (pm, qm, pg, qg)),
            (xu == u).astype(jnp.float32),
            (xi == i).astype(jnp.float32),
        )
        h1 = jax.nn.relu(
            jnp.concatenate([pm, qm], axis=-1) @ params["W1"] + params["b1"]
        )
        h2 = jax.nn.relu(h1 @ params["W2"] + params["b2"])
        h = jnp.concatenate([h2, pg * qg], axis=-1)
        pred = jnp.squeeze(h @ params["W3"] + params["b3"], axis=-1)
        return g, pred - y

    # -- fused score-kernel hooks (see base doc + influence/kernels/ncf.py):
    # the kernel replays the forward to the relu masks and runs the
    # closed-form backward per VMEM tile, so it needs the four raw rows
    # plus the (small) MLP weights as resident operands.
    kernel_family = "ncf"

    def kernel_row_inputs(self, params, x):
        """(B, 4k) raw rows
        ``[P_mlp[u_j] | Q_mlp[i_j] | P_gmf[u_j] | Q_gmf[i_j]]``."""
        xu, xi = x[:, 0], x[:, 1]
        return jnp.concatenate(
            [params["P_mlp"][xu], params["Q_mlp"][xi],
             params["P_gmf"][xu], params["Q_gmf"][xi]],
            axis=1,
        )

    def kernel_aux(self, params):
        """MLP weight operands for the kernel, biases lifted to 2-D
        (TPU Pallas wants >= 2-D VMEM operands)."""
        return (
            params["W1"], params["b1"][None, :],
            params["W2"], params["b2"][None, :],
            params["W3"],
        )

    # -- fused row-feature hooks (see base doc). Layout:
    # [g_pm (k) | g_qm (k) | g_pg (k) | g_qg (k) | e | u | i], F = 4k+3,
    # with the g_* the row's OWN-embedding prediction gradients (the
    # block_row_grads ingredients that don't depend on the query).
    @property
    def row_feature_dim(self) -> int:
        return 4 * self.embedding_size + 3

    def build_row_features(self, params, x, y):
        xu, xi = x[:, 0], x[:, 1]
        g = self._own_grads(params, xu, xi)
        e = self.predict(params, x) - y
        return jnp.concatenate(
            [g[0], g[1], g[2], g[3], e[:, None],
             xu.astype(jnp.float32)[:, None],
             xi.astype(jnp.float32)[:, None]],
            axis=1,
        )

    def grads_from_row_features(self, feat, u, i):
        k = self.embedding_size
        a = (feat[:, 4 * k + 1] == u).astype(jnp.float32)
        b = (feat[:, 4 * k + 2] == i).astype(jnp.float32)
        g = self._masked_block_concat(
            [feat[:, :k], feat[:, k: 2 * k],
             feat[:, 2 * k: 3 * k], feat[:, 3 * k: 4 * k]],
            a, b,
        )
        return g, feat[:, 4 * k], a, b

    def block_cross_const(self, params):
        """∇²r̂ on rows equal to the query pair: the GMF bilinear cross
        block diag(W3's gmf rows) (see block_hessian's derivation)."""
        k = self.embedding_size
        d = self.block_size
        r = jnp.arange(k)
        w3g = params["W3"][k // 2 :, 0]
        C = jnp.zeros((d, d), jnp.float32)
        C = C.at[2 * k + r, 3 * k + r].set(w3g)
        return C.at[3 * k + r, 2 * k + r].set(w3g)

    def block_reg_diag(self, params):
        """All four embedding rows are decayed (reference NCF.py:29-41)."""
        return jnp.full((self.block_size,), self.weight_decay, jnp.float32)

    @property
    def block_size(self) -> int:
        return 4 * self.embedding_size
