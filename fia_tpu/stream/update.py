"""The streaming update loop: append → fine-tune → project → swap.

``apply_updates(model, new_x, new_y, steps)`` is the one write path for
online model updates (``FIAModel.apply_updates`` delegates here). The
loop is crash-safe and epoch-fenced:

1. **Append + fine-tune.** The new interactions are appended to the
   train set and the model fine-tunes ``steps`` minibatch steps on the
   grown set through the ordinary :class:`~fia_tpu.train.trainer.Trainer`
   with a :class:`~fia_tpu.train.checkpoint.PeriodicCheckpointer` under
   ``<train_dir>/stream/upd-<id>/``. A mid-update kill leaves rotated
   generations behind; the next call with the same arguments resumes via
   ``restore_latest_valid`` and — thanks to the trainer's absolute-step
   epoch keys — converges bit-identically to an uninterrupted run.
2. **Local-update projection.** The fine-tuned parameters are projected
   onto the update's footprint (:mod:`fia_tpu.stream.footprint`):
   embedding/bias rows outside the touched user/item sets, and every
   global leaf, are pinned to their pre-update bytes. Untouched
   influence blocks therefore stay *bit-identical* — which is what makes
   surgical re-keying of caches sound (and what the factor bank's
   ``dep_crcs`` revalidation independently verifies).
3. **Epoch-fenced swap.** Each registered service fences its current
   (engine, fingerprint) under the serving epoch, the model state is
   swapped, the new engine is built (resident) and the factor bank
   surgically refreshed, then every service advances its epoch: queued
   tickets admitted before the swap resolve against the fenced old
   state, new tickets against the new, and only touched blocks are
   dropped from the hot/disk tiers — untouched entries are re-keyed to
   the new fingerprint without recompute.

A classified failure (taxonomy kind) at any point rolls the model back
to the fenced old state and returns ``status="rolled_back"`` — serving
never stops and never answers from a half-swapped state. Unclassified
failures surface. Fault sites: ``stream.update`` fires at the start of
every attempt, ``stream.swap`` immediately before the commit touches
any model state.

``apply_removal`` is the unlearning twin (docs/design.md §23): the
delta is rows *leaving* the train set (or having their labels softened
toward the model's prediction), but steps 1-3 are byte-for-byte the
same machinery — removed rows' users/items are the footprint, and the
``audit.apply`` site replaces ``stream.update`` at attempt start.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from fia_tpu import obs
from fia_tpu.data.dataset import RatingDataset
from fia_tpu.reliability import inject, sites, taxonomy
from fia_tpu.stream.footprint import Footprint, compute_footprint
from fia_tpu.train import checkpoint
from fia_tpu.train.trainer import TrainState


@dataclass
class UpdateResult:
    """Outcome of one :func:`apply_updates` call."""

    status: str  # "committed" | "rolled_back"
    update_id: str
    steps: int
    new_rows: int
    reason: str | None = None  # taxonomy kind on rollback
    base_step: int = 0
    resumed_step: int | None = None  # checkpoint step resumed from
    touched_users: int = 0
    touched_items: int = 0
    staleness_s: float = 0.0  # params-ready -> swap-complete window
    seconds: float = 0.0
    footprint: Footprint | None = None

    @property
    def committed(self) -> bool:
        return self.status == "committed"


def _leaf_tags(model, arr: np.ndarray) -> set:
    """The keying-axis tags ``factor._classify_leaves`` would assign."""
    tags = set()
    if arr.ndim >= 1 and arr.shape[0] == int(model.num_users):
        tags.add("user")
    if arr.ndim >= 1 and arr.shape[0] == int(model.num_items):
        tags.add("item")
    return tags or {"global"}


def project_params(model, old_host, new_host, fp: Footprint):
    """Project fine-tuned params onto the update footprint (host trees).

    Rows of user-keyed leaves outside ``fp.user_touched`` (and item-keyed
    outside ``fp.item_touched``) are restored to their pre-update bytes;
    global leaves are pinned entirely. An ambiguous leaf (leading dim
    matching BOTH table sizes) keeps a fine-tuned row only where user
    AND item are touched — a row visible to any untouched reader must
    not move (ambiguity costs update reach, never correctness, mirroring
    ``dep_crcs``' every-matching-axis hashing).

    The result is the strongest property surgical invalidation needs:
    every influence block outside the footprint computes bit-identically
    under the projected params.
    """

    def leaf(old, new):
        old = np.asarray(old)
        new = np.asarray(new)
        tags = _leaf_tags(model, old)
        if "global" in tags:
            return old
        if tags == {"user"}:
            keep_new = fp.user_touched
        elif tags == {"item"}:
            keep_new = fp.item_touched
        else:  # ambiguous: both axes must agree the row moved
            keep_new = fp.user_touched & fp.item_touched
        out = np.array(old)
        out[keep_new] = new[keep_new]
        return out

    return jax.tree_util.tree_map(leaf, old_host, new_host)


def _update_id(model, new_x: np.ndarray, new_y: np.ndarray,
               steps: int) -> str:
    """Deterministic id binding this update to (base params, rows, steps)
    — a killed attempt and its resuming retry agree on the checkpoint
    directory and fingerprint."""
    h = hashlib.sha1()
    h.update(str(int(model.state.step)).encode())
    for leaf in jax.tree_util.tree_leaves(model._host_params()):
        h.update(np.ascontiguousarray(leaf).tobytes())
    h.update(np.ascontiguousarray(new_x).tobytes())
    h.update(np.ascontiguousarray(new_y).tobytes())
    h.update(str(int(steps)).encode())
    return h.hexdigest()[:12]


def _coerce_rows(new_x, new_y):
    """Accept (N,2)+(N,), an (N,3) combined array, or a RatingDataset."""
    if isinstance(new_x, RatingDataset):
        return np.asarray(new_x.x, np.int32), np.asarray(new_x.y, np.float32)
    x = np.asarray(new_x)
    if new_y is None:
        if x.ndim != 2 or x.shape[1] != 3:
            raise ValueError(
                "without new_y, new_x must be (N, 3) [user, item, rating]"
            )
        return np.asarray(x[:, :2], np.int32), np.asarray(x[:, 2], np.float32)
    return (
        np.asarray(x, np.int32).reshape(-1, 2),
        np.asarray(new_y, np.float32).reshape(-1),
    )


def _apply_fenced(model, prepare, *, steps: int, uid: str, fp_kind: str,
                  entry_site: str, new_rows: int,
                  checkpoint_every: int | None = None,
                  keep_checkpoints: int = 3) -> UpdateResult:
    """The shared fine-tune → project → epoch-fenced-swap core.

    ``prepare()`` runs after the entry fault site fires (so a site
    fault rolls back before any work) and returns
    ``(new_train, footprint, warm_x)``: the post-delta train set, the
    invalidation footprint, and one (user, item) row to pre-warm the
    new engine's dispatch with. Both write paths — append
    (:func:`apply_updates`) and removal/reweight
    (:func:`apply_removal`) — differ only in that closure.
    """
    clock = model._trainer.clock
    t0 = clock.monotonic()
    old_state = model.state
    old_train = model.data_sets["train"]
    base_step = int(old_state.step)
    ckpt_dir = (
        os.path.join(model.train_dir, "stream", f"upd-{uid}")
        if model.train_dir else None
    )
    cfg = model._trainer.config
    saved_switches = (cfg.iter_to_switch_to_batch, cfg.iter_to_switch_to_sgd)
    mutated = False
    resumed_step = None
    footprint = None
    try:
        inject.fire(entry_site)
        new_train, footprint, warm_x = prepare()

        fp = {
            "kind": fp_kind,
            "model_key": model.model_name,
            "base_step": base_step,
            "steps": int(steps),
            "update_sha": uid,
        }
        state = old_state
        if ckpt_dir:
            restored = checkpoint.restore_latest_valid(
                ckpt_dir, old_state.params, old_state.opt_state,
                fingerprint=fp, verbose=False,
            )
            if restored is not None:
                p, o, s = restored
                state = TrainState(
                    jax.tree_util.tree_map(jnp.asarray, p),
                    jax.tree_util.tree_map(jnp.asarray, o),
                    int(s),
                )
                resumed_step = int(s)

        target_step = base_step + int(steps)
        remaining = target_step - int(state.step)
        if remaining > 0:
            ck = None
            if ckpt_dir:
                every = (max(1, int(steps) // 4) if checkpoint_every is None
                         else int(checkpoint_every))
                ck = checkpoint.PeriodicCheckpointer(
                    ckpt_dir, every=every, keep=keep_checkpoints,
                    fingerprint=fp,
                )
                ck._last_step = int(state.step)
            # incremental fine-tune is pure minibatch: a lingering
            # late-phase switch from a previous full train() must not
            # leak into the update (and must not vary across resumes)
            cfg.iter_to_switch_to_batch = None
            cfg.iter_to_switch_to_sgd = None
            with obs.span("stream.fit", trace_seed=f"update-{uid}",
                          update_id=uid, steps=remaining):
                state = model._trainer.fit(
                    state, new_train.x, new_train.y,
                    num_steps=remaining, checkpointer=ck,
                )

        # local-update projection: untouched blocks stay bit-identical
        old_host = model._host_params()
        new_host = jax.tree_util.tree_map(np.asarray, state.params)
        with obs.span("stream.project", trace_seed=f"update-{uid}",
                      update_id=uid):
            projected = project_params(model.model, old_host, new_host,
                                       footprint)
        t_ready = clock.monotonic()

        inject.fire(sites.STREAM_SWAP)  # last no-mutation-yet fault point
        mutated = True
        with obs.span("stream.fence_swap", trace_seed=f"update-{uid}",
                      update_id=uid, services=len(model._serving)):
            # fence first: each service pins its current (engine, fp)
            # under the serving epoch so queued tickets keep answering
            # on the state they were admitted against
            services = list(model._serving)
            for svc in services:
                svc.pin_epoch()
            model.state = TrainState(
                jax.tree_util.tree_map(jnp.asarray, projected),
                state.opt_state, target_step,
            )
            model.data_sets["train"] = new_train
            model._engines.clear()
            model.engine()  # new engine resident before any fence drops
            model._refresh_factor_bank()  # dep_crc survivors re-keyed
            for svc in services:
                # hand over a WARM engine: pre-lower/compile the new
                # engine's dispatch for the touched footprint while
                # queued tickets still answer on the fenced old state —
                # the first post-swap request must never pay a
                # trace/compile. A warmup failure means the new engine
                # cannot serve, so it (rightly) flows to the classified
                # rollback below.
                svc.warmup(warm_x)
            for svc in services:
                svc.advance_epoch(footprint)
        staleness_s = clock.monotonic() - t_ready
        if ckpt_dir:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        result = UpdateResult(
            status="committed", update_id=uid, steps=int(steps),
            new_rows=new_rows, base_step=base_step,
            resumed_step=resumed_step,
            touched_users=footprint.num_touched_users,
            touched_items=footprint.num_touched_items,
            staleness_s=staleness_s,
            seconds=clock.monotonic() - t0,
            footprint=footprint,
        )
    except Exception as e:
        kind = taxonomy.classify(e)
        if kind is None:
            raise
        # rollback: restore the fenced old state and keep serving on it.
        # Checkpoints stay on disk — a retry with the same arguments
        # resumes instead of restarting.
        if mutated:
            model.state = old_state
            model.data_sets["train"] = old_train
            model._engines.clear()
        result = UpdateResult(
            status="rolled_back", update_id=uid, steps=int(steps),
            new_rows=new_rows, reason=kind, base_step=base_step,
            resumed_step=resumed_step,
            touched_users=(footprint.num_touched_users if footprint else 0),
            touched_items=(footprint.num_touched_items if footprint else 0),
            seconds=clock.monotonic() - t0,
            footprint=footprint,
        )
    finally:
        cfg.iter_to_switch_to_batch = saved_switches[0]
        cfg.iter_to_switch_to_sgd = saved_switches[1]
    return result


def apply_updates(model, new_x, new_y=None, steps: int = 100,
                  checkpoint_every: int | None = None,
                  keep_checkpoints: int = 3) -> UpdateResult:
    """Run one streaming update against ``model`` (see module doc).

    ``checkpoint_every``: steps between rotated mid-update checkpoints
    (default ``max(1, steps // 4)``; saves land at the trainer's
    dispatch boundaries). Returns an :class:`UpdateResult`; a classified
    failure rolls back and reports, an unclassified one raises.
    """
    nx, ny = _coerce_rows(new_x, new_y)
    if len(nx) == 0:
        raise ValueError("apply_updates needs at least one new interaction")
    if nx[:, 0].min() < 0 or nx[:, 0].max() >= model.model.num_users or \
            nx[:, 1].min() < 0 or nx[:, 1].max() >= model.model.num_items:
        raise ValueError(
            "new interaction ids fall outside the model's user/item tables"
        )
    old_train = model.data_sets["train"]
    uid = _update_id(model, nx, ny, steps)

    def prepare():
        footprint = compute_footprint(
            np.asarray(old_train.x), nx,
            model.model.num_users, model.model.num_items,
        )
        new_train = RatingDataset(
            np.concatenate([np.asarray(old_train.x, np.int32), nx]),
            np.concatenate([np.asarray(old_train.y, np.float32), ny]),
        )
        return new_train, footprint, nx[:1]

    result = _apply_fenced(
        model, prepare, steps=steps, uid=uid, fp_kind="stream-update",
        entry_site=sites.STREAM_UPDATE, new_rows=len(nx),
        checkpoint_every=checkpoint_every,
        keep_checkpoints=keep_checkpoints,
    )
    model._log_event(
        "stream.update",
        update_id=result.update_id, status=result.status,
        reason=result.reason, steps=result.steps,
        new_rows=result.new_rows, base_step=result.base_step,
        resumed_step=result.resumed_step,
        touched_users=result.touched_users,
        touched_items=result.touched_items,
        staleness_ms=round(result.staleness_s * 1e3, 3),
        seconds=round(result.seconds, 3),
    )
    return result


def _removal_id(model, row_ids: np.ndarray, tag: str, steps: int) -> str:
    """Deterministic id binding a removal/reweight to (base params, rows,
    action, steps) — the resuming retry of a killed unlearning apply
    agrees on the checkpoint directory and fingerprint."""
    h = hashlib.sha1()
    h.update(str(int(model.state.step)).encode())
    for leaf in jax.tree_util.tree_leaves(model._host_params()):
        h.update(np.ascontiguousarray(leaf).tobytes())
    h.update(tag.encode())
    h.update(np.ascontiguousarray(row_ids).tobytes())
    h.update(str(int(steps)).encode())
    return h.hexdigest()[:12]


def apply_removal(model, row_ids, steps: int = 100,
                  reweight: float | None = None,
                  checkpoint_every: int | None = None,
                  keep_checkpoints: int = 3) -> UpdateResult:
    """Unlearn training rows through the same epoch-fenced loop.

    ``row_ids``: indices into the current train set. ``reweight=None``
    deletes the rows outright (the GDPR path); ``reweight=w`` with
    ``0 <= w < 1`` keeps them but softens each label toward the model's
    own prediction, ``y' = w*y + (1-w)*ŷ`` — at ``w=0`` the row carries
    no residual signal, so the label-noise-triage path shades into
    deletion continuously. Everything downstream is shared with
    :func:`apply_updates`: the removed rows' users/items are the
    footprint delta (second-order reach through the OLD adjacency,
    exactly the read set the removed rows participated in), fine-tuning
    runs on the shrunk set, untouched blocks are projected back to
    their pre-update bytes, and the swap is epoch-fenced with surgical
    invalidation and classified-failure rollback. Fault site:
    ``audit.apply`` fires at the start of every attempt (the swap keeps
    its own ``stream.swap`` site).
    """
    old_train = model.data_sets["train"]
    rows = np.unique(np.asarray(row_ids, np.int64).reshape(-1))
    if len(rows) == 0:
        raise ValueError("apply_removal needs at least one row to unlearn")
    if rows[0] < 0 or rows[-1] >= len(old_train.x):
        raise ValueError(
            "row ids fall outside the current train set "
            f"(0..{len(old_train.x) - 1})"
        )
    if reweight is not None and not (0.0 <= float(reweight) < 1.0):
        raise ValueError("reweight must be in [0, 1) — 1.0 is a no-op")
    tag = "remove" if reweight is None else f"reweight:{float(reweight)!r}"
    uid = _removal_id(model, rows, tag, steps)

    def prepare():
        old_x = np.asarray(old_train.x, np.int32)
        old_y = np.asarray(old_train.y, np.float32)
        removed_x = old_x[rows]
        footprint = compute_footprint(
            old_x, removed_x,
            model.model.num_users, model.model.num_items,
        )
        if reweight is None:
            keep = np.ones(len(old_x), bool)
            keep[rows] = False
            new_train = RatingDataset(old_x[keep], old_y[keep])
        else:
            w = np.float32(reweight)
            preds = np.asarray(model.model.predict(
                model.state.params, jnp.asarray(removed_x)), np.float32)
            new_y = np.array(old_y)
            new_y[rows] = w * old_y[rows] + (np.float32(1.0) - w) * preds
            new_train = RatingDataset(old_x, new_y)
        return new_train, footprint, removed_x[:1]

    return _apply_fenced(
        model, prepare, steps=steps, uid=uid, fp_kind="audit-apply",
        entry_site=sites.AUDIT_APPLY, new_rows=len(rows),
        checkpoint_every=checkpoint_every,
        keep_checkpoints=keep_checkpoints,
    )
