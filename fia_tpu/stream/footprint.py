"""Touched-block footprint of a streaming update.

An appended interaction batch directly perturbs the users ΔU and items
ΔI it names (their embedding rows fine-tune, their interaction lists
grow). But the (u, i) *influence block* reads more than u's and i's own
rows: the block Hessian gathers the P/Q rows of every counterparty in
the pair's related set (``factor.dep_crcs`` documents the exact read
set). So the blocks an update can reach are:

- ``user_touched[u]``: u ∈ ΔU, or u has an interaction with an item in
  ΔI (that item's Q row — which u's block Hessian reads — moved);
- ``item_touched[i]``: i ∈ ΔI, or i has an interaction with a user in
  ΔU;
- block (u, i) is touched iff ``user_touched[u] | item_touched[i]``.

Everything outside this footprint reads only parameter rows and train
rows the update provably did not change (the projection in
``stream.update`` pins them bit-identically), so untouched cache
entries can be re-keyed to the new params fingerprint without
recompute — the basis of surgical invalidation across the serve tiers.

The masks are computed over the OLD train set: appended rows connect
ΔU users only to ΔI items, both already first-order touched, so they
add no second-order reach beyond what the old adjacency gives.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class Footprint:
    """Boolean touch masks over the user/item id spaces."""

    user_touched: np.ndarray  # (num_users,) bool
    item_touched: np.ndarray  # (num_items,) bool
    delta_users: np.ndarray  # unique user ids named by the update
    delta_items: np.ndarray  # unique item ids named by the update

    def touched(self, user: int, item: int) -> bool:
        """Whether the (user, item) influence block is in the footprint."""
        return bool(self.user_touched[int(user)]) or bool(
            self.item_touched[int(item)]
        )

    def touched_pairs(self, pairs: np.ndarray) -> np.ndarray:
        """(N,) bool mask for an (N, 2) array of (user, item) pairs."""
        p = np.asarray(pairs, np.int64)
        return self.user_touched[p[:, 0]] | self.item_touched[p[:, 1]]

    @property
    def num_touched_users(self) -> int:
        return int(np.count_nonzero(self.user_touched))

    @property
    def num_touched_items(self) -> int:
        return int(np.count_nonzero(self.item_touched))


def compute_footprint(train_x, new_x, num_users: int,
                      num_items: int) -> Footprint:
    """The touched-block footprint of appending ``new_x`` to ``train_x``.

    ``train_x``: (N, 2) old interaction ids; ``new_x``: (M, 2) appended
    ids. Pure vectorized numpy — two scatter passes and two bincounts,
    no index structure required.
    """
    x = np.asarray(train_x, np.int64).reshape(-1, 2)
    nx = np.asarray(new_x, np.int64).reshape(-1, 2)
    du = np.unique(nx[:, 0])
    di = np.unique(nx[:, 1])

    in_du = np.zeros(int(num_users), bool)
    in_du[du] = True
    in_di = np.zeros(int(num_items), bool)
    in_di[di] = True

    # second-order reach through the old adjacency: a user is touched if
    # any of its rows names a ΔI item (it reads that item's moved Q
    # row); symmetrically for items.
    rows_hit_item = in_di[x[:, 1]]
    user_indirect = (
        np.bincount(x[rows_hit_item, 0], minlength=int(num_users)) > 0
    )
    rows_hit_user = in_du[x[:, 0]]
    item_indirect = (
        np.bincount(x[rows_hit_user, 1], minlength=int(num_items)) > 0
    )

    return Footprint(
        user_touched=in_du | user_indirect,
        item_touched=in_di | item_indirect,
        delta_users=du,
        delta_items=di,
    )
