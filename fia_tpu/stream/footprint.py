"""Touched-block footprint of a streaming update or removal.

A delta batch (appended interactions, or removed/reweighted rows)
directly perturbs the users ΔU and items ΔI it names. Two distinct
sets follow from that, and they are NOT the same set:

- **moved rows** (``user_touched`` / ``item_touched``) — the parameter
  rows the fine-tune is allowed to change. u moves if u ∈ ΔU or u has
  an interaction with a ΔI item (its embedding re-optimizes against
  that item's moved Q row); symmetrically for items. The projection in
  ``stream.update`` pins every row OUTSIDE this set to its pre-update
  bytes, which is what keeps the moved set from cascading further.
- **read-reached blocks** (``user_read`` / ``item_read``) — the blocks
  whose solve READS a moved row. The (u, i) block Hessian gathers the
  P/Q rows of every counterparty in the pair's related set
  (``factor.dep_crcs`` documents the exact read set), so a block whose
  own u/i rows are pinned still computes differently when any
  counterparty row moved: ``user_read[u]`` iff u moved or any of u's
  interactions names a moved item (and symmetrically). One extra
  adjacency hop past the moved set — and exactly one, because the
  projection froze the moved set.

``touched(u, i)`` — the predicate surgical cache invalidation keys on
— answers from the READ masks: everything outside it provably computes
bit-identically under the projected params, so untouched cache entries
re-key to the new fingerprint without recompute. The projection itself
keys on the MOVED masks. (Conflating the two was a real stale-bytes
bug: a block outside the moved set but inside the read set served
pre-update scores after a removal — caught by ``bench.py unlearn``'s
byte-level probe on an unstructured interaction graph; the community-
structured churn bench could never see it because there the two
closures coincide.)

The masks are computed over the OLD train set: a delta row names only
ΔU users and ΔI items, both already first-order touched, so it adds no
reach beyond what the old adjacency gives.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class Footprint:
    """Boolean touch masks over the user/item id spaces."""

    user_touched: np.ndarray  # (num_users,) bool — moved rows (projection)
    item_touched: np.ndarray  # (num_items,) bool
    delta_users: np.ndarray  # unique user ids named by the update
    delta_items: np.ndarray  # unique item ids named by the update
    # read-reach masks (invalidation); None falls back to the moved
    # masks — correct only when the caller guarantees the closures
    # coincide (e.g. hand-built fixtures)
    user_read: np.ndarray | None = None
    item_read: np.ndarray | None = None

    def touched(self, user: int, item: int) -> bool:
        """Whether the (user, item) block's SOLVE reads any moved row —
        the predicate cache invalidation must key on."""
        ur = self.user_read if self.user_read is not None else self.user_touched
        ir = self.item_read if self.item_read is not None else self.item_touched
        return bool(ur[int(user)]) or bool(ir[int(item)])

    def touched_pairs(self, pairs: np.ndarray) -> np.ndarray:
        """(N,) bool mask for an (N, 2) array of (user, item) pairs."""
        ur = self.user_read if self.user_read is not None else self.user_touched
        ir = self.item_read if self.item_read is not None else self.item_touched
        p = np.asarray(pairs, np.int64)
        return ur[p[:, 0]] | ir[p[:, 1]]

    @property
    def num_touched_users(self) -> int:
        return int(np.count_nonzero(self.user_touched))

    @property
    def num_touched_items(self) -> int:
        return int(np.count_nonzero(self.item_touched))


def compute_footprint(train_x, new_x, num_users: int,
                      num_items: int) -> Footprint:
    """The footprint of applying delta rows ``new_x`` against ``train_x``.

    ``train_x``: (N, 2) old interaction ids; ``new_x``: (M, 2) delta
    ids (appended interactions, or the rows being removed/reweighted —
    the reach analysis is identical). Pure vectorized numpy — scatter
    passes and bincounts, no index structure required.
    """
    x = np.asarray(train_x, np.int64).reshape(-1, 2)
    nx = np.asarray(new_x, np.int64).reshape(-1, 2)
    du = np.unique(nx[:, 0])
    di = np.unique(nx[:, 1])

    in_du = np.zeros(int(num_users), bool)
    in_du[du] = True
    in_di = np.zeros(int(num_items), bool)
    in_di[di] = True

    def _neighbors(endpoint_mask, src_col, dst_col, size):
        """Ids in ``dst_col`` sharing a row with a masked ``src_col`` id."""
        rows = endpoint_mask[x[:, src_col]]
        return np.bincount(x[rows, dst_col], minlength=size) > 0

    # moved rows: Δ plus one hop through the old adjacency (a user
    # re-optimizes against a ΔI item's moved Q row, and vice versa)
    user_moved = in_du | _neighbors(in_di, 1, 0, int(num_users))
    item_moved = in_di | _neighbors(in_du, 0, 1, int(num_items))

    # read reach: one further hop — a pinned user still serves changed
    # bytes when any counterparty item row it gathers has moved
    user_read = user_moved | _neighbors(item_moved, 1, 0, int(num_users))
    item_read = item_moved | _neighbors(user_moved, 0, 1, int(num_items))

    return Footprint(
        user_touched=user_moved,
        item_touched=item_moved,
        delta_users=du,
        delta_items=di,
        user_read=user_read,
        item_read=item_read,
    )
