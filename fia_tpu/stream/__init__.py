"""Streaming model updates: incremental train → surgical invalidate →
epoch-fenced refresh (docs/design.md §17).

:func:`~fia_tpu.stream.update.apply_updates` is the entry point
(``FIAModel.apply_updates`` delegates here);
:func:`~fia_tpu.stream.footprint.compute_footprint` derives the touched
(user, item) block set an appended interaction batch can reach through
the shared-row Hessian structure — the same read set the factor bank's
per-entry ``dep_crcs`` digest covers.
"""

from fia_tpu.stream.footprint import Footprint, compute_footprint
from fia_tpu.stream.update import UpdateResult, apply_updates, project_params

__all__ = [
    "Footprint",
    "compute_footprint",
    "UpdateResult",
    "apply_updates",
    "project_params",
]
