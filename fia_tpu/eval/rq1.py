"""RQ1: influence-vs-retraining fidelity.

Parity target: reference ``src/influence/experiments.py:17-150``
(``test_retraining``) driven by ``src/scripts/RQ1.py:142-165`` — for one
test interaction, predict the rating change from removing each selected
training row via influence, then measure the actual change by
leave-one-out retraining, and correlate.

TPU-native shape: the reference retrains sequentially (num_to_remove ×
retrain_times full training runs). Here every (removed row, repeat) pair
is one vmap lane of a single compiled retraining program, including the
no-removal drift-bias lanes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from fia_tpu import obs
from fia_tpu.data.dataset import RatingDataset
from fia_tpu.influence.engine import InfluenceEngine
from fia_tpu.train.trainer import loo_retrain_many


@dataclass
class RetrainResult:
    actual_y_diffs: np.ndarray  # (R,) retraining ground truth
    predicted_y_diffs: np.ndarray  # (R,) influence predictions
    indices_to_remove: np.ndarray  # (R,) positions into the related set
    removed_train_rows: np.ndarray  # (R,) train-row ids
    bias_retrain: float  # no-removal drift (subtracted from actuals)
    # raw per-repeat retrained predictions, (R+1, retrain_times): row r
    # holds lane r's repeats, the final row the no-removal drift lane.
    # Across-repeat variance measures RETRAINING noise directly — the
    # floor decomposition in scripts/fidelity_spread.py separates it
    # from influence-prediction error at zero extra device cost
    per_repeat_y: np.ndarray = None
    y0: float = 0.0  # original (pre-removal) prediction on the test point


def test_retraining(
    engine: InfluenceEngine,
    train: RatingDataset,
    test_ds: RatingDataset,
    test_idx: int,
    num_to_remove: int = 50,
    num_steps: int = 1000,
    batch_size: int = 100,
    learning_rate: float = 1e-3,
    retrain_times: int = 4,
    remove_type: str = "maxinf",
    random_seed: int = 17,
    clamp: float = 1.0,
    lane_chunk: int = 32,
    steps_per_dispatch: int = 2000,
    verbose: bool = True,
    mesh=None,
    event_log=None,
) -> RetrainResult:
    """Run the RQ1 experiment for one test point.

    remove_type: 'maxinf' picks the |influence|-largest related rows
    (reference ``experiments.py:36-48``); 'random' samples uniformly from
    the related set.

    ``verbose`` prints stage-boundary progress: full-protocol runs are
    hours of silent device work otherwise (hundreds of chunked
    retraining dispatches over a tunnel-attached chip).
    """
    import time

    def stage(msg):
        if verbose:
            obs.diag(
                "rq1",
                f"{time.strftime('%H:%M:%S')} test {test_idx}: {msg}",
            )
    model = engine.model
    params0 = engine.params
    rng = np.random.default_rng(random_seed)

    point = test_ds.x[test_idx]
    res = engine.query_batch(point[None, :])
    scores = res.scores_of(0)
    related = res.related_of(0)
    stage(f"influence query done ({len(related)} related rows)")
    if event_log is not None:
        event_log.log("influence_query", test_idx=int(test_idx),
                      related=int(len(related)))

    if remove_type == "maxinf":
        # descending |influence|, first num_to_remove — a [-n:] slice
        # would select EVERYTHING for n=0
        sel = np.argsort(np.abs(scores))[::-1][:num_to_remove].copy()
    elif remove_type == "random":
        sel = rng.choice(len(related), size=min(num_to_remove, len(related)),
                         replace=False)
    else:
        raise ValueError(f"remove_type {remove_type!r} not well specified")

    predicted = scores[sel]
    removed_rows = related[sel]

    # Original prediction on the test point.
    tx = jnp.asarray(point[None, :])
    y0 = float(model.predict(params0, tx)[0])

    # One vmapped program: (num_to_remove + 1) removal lanes x retrain_times
    # repeats; lane -1 removes nothing and measures retraining drift.
    lanes = np.concatenate([removed_rows, [-1]])
    all_removed = np.repeat(lanes, retrain_times)
    all_seeds = np.tile(
        random_seed + np.arange(retrain_times), len(lanes)
    ).astype(np.uint32)

    # Lanes run in fixed-size chunks: one bounded device program per
    # chunk (equal shapes reuse the compile), keeping peak memory and
    # single-dispatch runtime independent of num_to_remove x
    # retrain_times — a 100-lane x thousands-of-steps megaprogram can
    # exceed worker/interconnect dispatch budgets at ML-1M scale.
    lane_chunk = max(int(lane_chunk), 1)
    pred_fn = jax.jit(jax.vmap(lambda p: model.predict(p, tx)[0]))
    pad_lanes = (-len(all_removed)) % lane_chunk
    padded_removed = np.concatenate(
        [all_removed, np.full(pad_lanes, -1, all_removed.dtype)]
    )
    padded_seeds = np.concatenate(
        [all_seeds, np.full(pad_lanes, random_seed, all_seeds.dtype)]
    )
    chunks = []
    n_chunks = len(padded_removed) // lane_chunk
    stage(f"retraining {len(all_removed)} lanes x {num_steps} steps "
          f"({n_chunks} chunks of {lane_chunk})")
    for ci, c in enumerate(range(0, len(padded_removed), lane_chunk)):
        t0 = time.time()
        params_stack = loo_retrain_many(
            model, params0, train.x, train.y, padded_removed[c : c + lane_chunk],
            num_steps=num_steps, batch_size=batch_size,
            learning_rate=learning_rate, seeds=padded_seeds[c : c + lane_chunk],
            steps_per_dispatch=steps_per_dispatch, mesh=mesh,
        )
        chunks.append(np.asarray(pred_fn(params_stack)))
        stage(f"retrain chunk {ci + 1}/{n_chunks} done")
        if event_log is not None:
            event_log.log("retrain_chunk", test_idx=int(test_idx),
                          chunk=ci + 1, of=n_chunks, lanes=int(lane_chunk),
                          steps=int(num_steps), secs=round(time.time() - t0, 3))
    preds = np.concatenate(chunks)[: len(all_removed)]
    preds = preds.reshape(len(lanes), retrain_times)

    # NaN-robust means (reference drops NaN retrain outcomes,
    # experiments.py:136-137).
    with np.errstate(invalid="ignore"):
        lane_means = np.nanmean(preds, axis=1)
    bias = float(lane_means[-1] - y0)
    actual = lane_means[:-1] - y0 - bias

    # |predicted| > clamp is zeroed (reference experiments.py:139-140).
    predicted = np.where(np.abs(predicted) > clamp, 0.0, predicted)

    return RetrainResult(
        actual_y_diffs=np.asarray(actual),
        predicted_y_diffs=np.asarray(predicted),
        indices_to_remove=np.asarray(sel),
        removed_train_rows=np.asarray(removed_rows),
        bias_retrain=bias,
        per_repeat_y=np.asarray(preds, np.float32),
        y0=y0,
    )
