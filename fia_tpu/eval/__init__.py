from fia_tpu.eval.metrics import pearson, spearman  # noqa: F401
from fia_tpu.eval.rq1 import test_retraining, RetrainResult  # noqa: F401
from fia_tpu.eval.rq2 import time_influence_queries, TimingResult  # noqa: F401
