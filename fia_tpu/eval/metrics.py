"""Fidelity metrics: Pearson (reference RQ1.py:165) and Spearman (the
BASELINE.json north-star: rank correlation >= 0.99 vs the reference)."""

from __future__ import annotations

import numpy as np


def pearson(a, b) -> float:
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    mask = np.isfinite(a) & np.isfinite(b)
    a, b = a[mask], b[mask]
    if len(a) < 2:
        return float("nan")
    a = a - a.mean()
    b = b - b.mean()
    denom = np.sqrt((a * a).sum() * (b * b).sum())
    return float((a * b).sum() / denom) if denom else float("nan")


def _ranks(v: np.ndarray) -> np.ndarray:
    order = np.argsort(v, kind="stable")
    ranks = np.empty(len(v), np.float64)
    ranks[order] = np.arange(len(v))
    # average ties
    sv = v[order]
    i = 0
    while i < len(sv):
        j = i
        while j + 1 < len(sv) and sv[j + 1] == sv[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = (i + j) / 2.0
        i = j + 1
    return ranks


def spearman(a, b) -> float:
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    mask = np.isfinite(a) & np.isfinite(b)
    if mask.sum() < 2:
        return float("nan")
    return pearson(_ranks(a[mask]), _ranks(b[mask]))
