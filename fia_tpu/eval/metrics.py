"""Fidelity metrics: Pearson (reference RQ1.py:165) and Spearman (the
BASELINE.json north-star: rank correlation >= 0.99 vs the reference).

Thin finite-masking wrappers over scipy.stats — the reference itself
scores RQ1 with ``scipy.stats.pearsonr`` (RQ1.py:165), so delegating
keeps the metric definitions identical by construction.
"""

from __future__ import annotations

import numpy as np
from scipy import stats


def _masked(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    mask = np.isfinite(a) & np.isfinite(b)
    return a[mask], b[mask]


def pearson(a, b) -> float:
    a, b = _masked(a, b)
    if len(a) < 2 or np.ptp(a) == 0 or np.ptp(b) == 0:
        return float("nan")
    r, _ = stats.pearsonr(a, b)  # tuple unpack works on all scipy versions
    return float(r)


def spearman(a, b) -> float:
    a, b = _masked(a, b)
    if len(a) < 2 or np.ptp(a) == 0 or np.ptp(b) == 0:
        return float("nan")
    rho, _ = stats.spearmanr(a, b)
    return float(rho)
