"""RQ2: wall-clock cost of influence queries.

Parity target: reference ``src/scripts/RQ2.py`` + ``experiments.py:4-15``
(``record_time_cost``): time one influence query — inverse-HVP solve plus
scoring every related training row. The reference's printed timers ARE
its benchmark output (``matrix_factorization.py:225, 249-250``).

Here timing uses ``block_until_ready`` fences, separates compile from
steady state, and reports throughput (queries/sec and scores/sec, the
BASELINE.json primary metric) over a batch of test points.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from fia_tpu.influence.engine import InfluenceEngine


@dataclass
class TimingResult:
    num_queries: int
    num_scores: int  # total related rows scored
    compile_time_s: float
    total_time_s: float  # steady-state wall clock (excl. compile)
    queries_per_sec: float
    scores_per_sec: float
    per_query_ms: float
    repeats: int = 1
    times_s: list = field(default_factory=list)

    def json(self) -> dict:
        return {
            "num_queries": self.num_queries,
            "num_scores": self.num_scores,
            "compile_time_s": round(self.compile_time_s, 4),
            "total_time_s": round(self.total_time_s, 4),
            "queries_per_sec": round(self.queries_per_sec, 2),
            "scores_per_sec": round(self.scores_per_sec, 2),
            "per_query_ms": round(self.per_query_ms, 4),
        }


def time_influence_queries(
    engine: InfluenceEngine,
    test_points: np.ndarray,
    repeats: int = 3,
    pad_to: int | None = None,
) -> TimingResult:
    """Time batched influence queries over ``test_points`` (T, 2).

    The first call (compile + run) is measured separately; steady-state
    time is the best of ``repeats`` fenced runs, matching standard JAX
    benchmarking practice.
    """
    # pad_to=None lets the engine pick per its own pad_policy — its choice
    # is deterministic across repeats, so timing measures the same
    # compiled program production queries would use.
    test_points = np.asarray(test_points)

    t0 = time.perf_counter()
    res = engine.query_batch(test_points, pad_to=pad_to)
    compile_time = time.perf_counter() - t0

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = engine.query_batch(test_points, pad_to=pad_to)
        times.append(time.perf_counter() - t0)
    best = min(times)

    num_scores = int(res.counts.sum())
    return TimingResult(
        num_queries=len(test_points),
        num_scores=num_scores,
        compile_time_s=compile_time,
        total_time_s=best,
        queries_per_sec=len(test_points) / best,
        scores_per_sec=num_scores / best,
        per_query_ms=1e3 * best / len(test_points),
        repeats=repeats,
        times_s=times,
    )
