"""RQ2: wall-clock cost of influence queries.

Parity target: reference ``src/scripts/RQ2.py`` + ``experiments.py:4-15``
(``record_time_cost``): time one influence query — inverse-HVP solve plus
scoring every related training row. The reference's printed timers ARE
its benchmark output (``matrix_factorization.py:225, 249-250``).

Here timing uses ``block_until_ready`` fences, separates compile from
steady state, and reports throughput (queries/sec and scores/sec, the
BASELINE.json primary metric) over a batch of test points.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from fia_tpu.influence.engine import InfluenceEngine


@dataclass
class TimingResult:
    num_queries: int
    num_scores: int  # total related rows scored
    compile_time_s: float
    total_time_s: float  # steady-state wall clock (excl. compile)
    queries_per_sec: float
    scores_per_sec: float
    per_query_ms: float
    repeats: int = 1
    times_s: list = field(default_factory=list)

    def json(self) -> dict:
        return {
            "num_queries": self.num_queries,
            "num_scores": self.num_scores,
            "compile_time_s": round(self.compile_time_s, 4),
            "total_time_s": round(self.total_time_s, 4),
            "queries_per_sec": round(self.queries_per_sec, 2),
            "scores_per_sec": round(self.scores_per_sec, 2),
            "per_query_ms": round(self.per_query_ms, 4),
        }


def time_influence_queries(
    engine: InfluenceEngine,
    test_points: np.ndarray,
    repeats: int = 3,
    pad_to: int | None = None,
    batch_queries: int | None = None,
) -> TimingResult:
    """Time batched influence queries over ``test_points`` (T, 2).

    The first call (compile + run) is measured separately; steady-state
    time is the best of ``repeats`` fenced runs, matching standard JAX
    benchmarking practice.

    ``batch_queries``: cap the per-dispatch query count, routing through
    the engine's pipelined ``query_many``. The k=256 MF program kills
    the TPU worker at 64-query dispatches but runs at 32 (BASELINE
    §4.1, r3-r4) — the sweep's 64-query protocol then times as two
    windowed 32-query dispatches.
    """
    # pad_to=None lets the engine pick per its own pad_policy — its choice
    # is deterministic across repeats, so timing measures the same
    # compiled program production queries would use.
    test_points = np.asarray(test_points)
    if batch_queries is not None and batch_queries < 1:
        # a negative cap would make query_many's range() empty and
        # silently bank a zero-score "benchmark"
        raise ValueError(f"batch_queries must be >= 1, got {batch_queries}")

    def run():
        if batch_queries and batch_queries < len(test_points):
            return engine.query_many(
                test_points, batch_queries=batch_queries, pad_to=pad_to
            )
        return [engine.query_batch(test_points, pad_to=pad_to)]

    t0 = time.perf_counter()
    res = run()
    compile_time = time.perf_counter() - t0

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = run()
        times.append(time.perf_counter() - t0)
    best = min(times)

    num_scores = int(sum(int(r.counts.sum()) for r in res))
    return TimingResult(
        num_queries=len(test_points),
        num_scores=num_scores,
        compile_time_s=compile_time,
        total_time_s=best,
        queries_per_sec=len(test_points) / best,
        scores_per_sec=num_scores / best,
        per_query_ms=1e3 * best / len(test_points),
        repeats=repeats,
        times_s=times,
    )
