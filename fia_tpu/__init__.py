"""fia_tpu — a TPU-native Fast Influence Analysis framework.

A from-scratch JAX/XLA re-design of the capabilities of the FIA (KDD'19)
reference codebase (``zz9tf/FIA-KDD-19``): latent-factor recommenders (MF,
NCF) trained on explicit ratings, a generic influence-function engine
(per-example gradients, Hessian-vector products, inverse-HVP via CG /
LiSSA / direct solve), and the FIA block-restricted fast path that
computes the influence of training interactions on a test prediction in
the (user, item) embedding sub-block only.

Design notes (vs the reference, see SURVEY.md):
  - Parameters are pytrees of dense (U,k)/(I,k) matrices; the reference's
    flat-1D-variable + slice trick (reference
    ``src/influence/matrix_factorization.py:152-162``) becomes functional
    row indexing + AD.
  - The reference mutates its TF1 graph per test point (lazy op creation,
    ``matrix_factorization.py:183-198``); here an influence query is a pure
    jitted function of (u*, i*), compiled once and vmapped over test points.
  - Scoring (one sess.run per train row in the reference,
    ``matrix_factorization.py:240-246``) is a single vmapped per-example
    gradient batch followed by one matvec.
  - Scaling is expressed with ``jax.sharding`` over a device Mesh
    (data-parallel test-query batches, optionally sharded embedding
    tables) instead of any session/device pinning.
"""

__version__ = "0.1.0"

from fia_tpu.models import MF, NCF  # noqa: F401
from fia_tpu.influence.engine import InfluenceEngine  # noqa: F401
