"""Backend selector: the JAX/TPU engine is the product; the torch-CPU
reference engine mirrors the reference implementation's architecture
(autograd double-backprop + scipy fmin_ncg + per-row scoring loop) and
serves as the parity oracle and the benchmark baseline (BASELINE.md §3:
measure our own CPU baseline, report speedups against it)."""

from fia_tpu.backends.torch_ref import TorchRefMFEngine  # noqa: F401
